from .datasets import (
    CIFAR10Dataset,
    Dataset,
    ImageFolderDataset,
    LMDBDataset,
    MNISTDataset,
    SyntheticDataset,
    encode_datum,
    open_dataset,
    parse_datum,
)
from .feeder import Feeder, feeder_from_layer
from .transformer import DataTransformer
