"""WindowData pipeline — R-CNN-style fg/bg window sampling.

Reference: src/caffe/layers/window_data_layer.cpp: parses a "window file"
(per image: path, dims, and proposal windows with class + overlap), then per
batch samples `fg_fraction` foreground windows (overlap >= fg_threshold)
and the rest background (overlap in [0, bg_threshold)), crops each window
with `context_pad`, warps to crop_size x crop_size, mean-subtracts and
optionally mirrors.

Window file format (reference window_data_layer.cpp:72-120):

    # <image_index>
    <image_path>
    <channels> <height> <width>
    <num_windows>
    <class_index> <overlap> <x1> <y1> <x2> <y2>
    ...
"""

from __future__ import annotations

import os

import numpy as np

from ..proto.config import LayerParameter
from .transformer import DataTransformer


class WindowFile:
    def __init__(self, path: str, root: str = "",
                 fg_threshold: float = 0.5, bg_threshold: float = 0.5):
        self.images: list[str] = []
        self._records: list[tuple[int, int, float, int, int, int, int]] = []
        self._parse(path, root)
        # classified at load time like the reference (fg_threshold /
        # bg_threshold fixed per layer, window_data_layer.cpp:121-135)
        self.fg = [r for r in self._records if r[2] >= fg_threshold]
        self.bg = [r for r in self._records if 0 <= r[2] < bg_threshold]
        if not self.fg or not self.bg:
            raise ValueError(
                f"window file {path}: need both fg ({len(self.fg)}) and bg "
                f"({len(self.bg)}) windows at thresholds")

    def _parse(self, path: str, root: str) -> None:
        with open(path) as f:
            lines = [l.rstrip("\n") for l in f]
        i = 0
        while i < len(lines):
            if not lines[i].strip():
                i += 1
                continue
            if not lines[i].startswith("#"):
                raise ValueError(f"window file: expected '# index' at line {i}")
            img_path = lines[i + 1].strip()
            _c, _h, _w = (int(x) for x in lines[i + 2].split())
            num = int(lines[i + 3])
            img_id = len(self.images)
            self.images.append(os.path.join(root, img_path))
            for j in range(num):
                parts = lines[i + 4 + j].split()
                cls = int(parts[0])
                overlap = float(parts[1])  # lint: ok(host-sync) — text field
                # lint: ok(host-sync) — window-file text fields, host strings
                x1, y1, x2, y2 = (int(float(v)) for v in parts[2:6])
                self._records.append((img_id, cls, overlap, x1, y1, x2, y2))
            i += 4 + num


class WindowFeeder:
    """feed_fn for WindowData layers."""

    def __init__(self, lp: LayerParameter, phase: str, *, model_dir: str = "",
                 seed: int = 1701, rank: int = 0, world: int = 1):
        p = lp.window_data_param
        self.p = p
        self.tops = list(lp.top)
        self.phase = phase
        self.batch = p.batch_size
        tp = lp.transform_param
        self.crop = p.crop_size or (tp.crop_size if tp else 0)
        if not self.crop:
            raise ValueError(
                "WindowData requires crop_size (window_data_param or "
                "transform_param)")
        self.num_fg = int(round(p.batch_size * p.fg_fraction))
        self.wf = WindowFile(os.path.join(model_dir, p.source), p.root_folder,
                             p.fg_threshold, p.bg_threshold)
        # rank folded into the stream key: each rank samples distinct windows
        # (the reference stripes records per solver, data_reader.hpp:28-53)
        self.seed = seed
        self.rank, self.world = rank, world
        self.mean = None
        if tp is not None:
            tf = DataTransformer(tp, phase, model_dir=model_dir)
            self.mean = tf.mean
            self.mirror = tp.mirror
            self.scale = tp.scale
        else:
            self.mirror = bool(p.mirror)
            self.scale = p.scale
            if p.mean_file:
                from ..io import load_blob_binaryproto
                self.mean = load_blob_binaryproto(
                    os.path.join(model_dir, p.mean_file))
                if self.mean.ndim == 4:
                    self.mean = self.mean[0]
        if self.mean is not None and self.mean.shape[-1] > 1 \
                and self.mean.shape[-2:] != (self.crop, self.crop):
            # full-size mean: center-crop to the warped window size
            # (window_data_layer.cpp mean_off logic)
            mh = (self.mean.shape[-2] - self.crop) // 2
            mw = (self.mean.shape[-1] - self.crop) // 2
            if mh < 0 or mw < 0:
                raise ValueError("mean smaller than crop_size")
            self.mean = self.mean[:, mh:mh + self.crop, mw:mw + self.crop]
        self._img_cache: dict[int, np.ndarray] = {}

    def _load_image(self, img_id: int) -> np.ndarray:
        img = self._img_cache.get(img_id)
        if img is None:
            from PIL import Image
            arr = np.asarray(Image.open(self.wf.images[img_id]).convert("RGB"))
            img = arr[:, :, ::-1].astype(np.float32)  # BGR HWC
            if len(self._img_cache) > 64:
                self._img_cache.clear()
            self._img_cache[img_id] = img
        return img

    def _crop_window(self, rec, rng) -> np.ndarray:
        from PIL import Image
        img_id, cls, overlap, x1, y1, x2, y2 = rec
        img = self._load_image(img_id)
        h, w = img.shape[:2]
        if self.p.context_pad:
            # scale the context pad into window coordinates
            # (window_data_layer.cpp context_scale logic, crop_mode 'warp')
            cw, chh = x2 - x1 + 1, y2 - y1 + 1
            context_scale = self.crop / (self.crop - 2.0 * self.p.context_pad)
            pad_w = (context_scale * cw - cw) / 2.0
            pad_h = (context_scale * chh - chh) / 2.0
            x1, x2 = int(x1 - pad_w), int(x2 + pad_w)
            y1, y2 = int(y1 - pad_h), int(y2 + pad_h)
        x1c, y1c = max(x1, 0), max(y1, 0)
        x2c, y2c = min(x2, w - 1), min(y2, h - 1)
        window = img[y1c:y2c + 1, x1c:x2c + 1]
        pil = Image.fromarray(window.astype(np.uint8)[:, :, ::-1])
        warped = np.asarray(
            pil.resize((self.crop, self.crop), Image.BILINEAR))[:, :, ::-1]
        out = warped.transpose(2, 0, 1).astype(np.float32)
        if self.mean is not None:
            out = out - self.mean
        if self.mirror and self.phase == "TRAIN" and rng.integers(2):
            out = out[:, :, ::-1]
        return np.ascontiguousarray(out * self.scale)

    def __call__(self, it: int) -> dict[str, np.ndarray]:
        stream = it * self.world + self.rank
        rng = np.random.Generator(
            np.random.Philox(key=(self.seed << 32) ^ stream))
        data = np.empty((self.batch, 3, self.crop, self.crop), np.float32)
        labels = np.empty((self.batch,), np.int32)
        for slot in range(self.batch):
            if slot < self.num_fg:
                rec = self.wf.fg[int(rng.integers(len(self.wf.fg)))]
            else:
                rec = self.wf.bg[int(rng.integers(len(self.wf.bg)))]
                rec = (*rec[:1], 0, *rec[2:])  # bg windows are class 0
            data[slot] = self._crop_window(rec, rng)
            labels[slot] = rec[1]
        out = {self.tops[0]: data}
        if len(self.tops) > 1:
            out[self.tops[1]] = labels
        return out
