"""DataTransformer — Caffe's augmentation semantics on the host.

Reference: src/caffe/data_transformer.{cpp,cu} (753+268 LoC): mean-file /
per-channel mean-value subtraction, scale, random crop (train) vs center
crop (test), horizontal mirror, per-thread RNG with optional fixed seed.

The order of operations matches the reference exactly:
out = (pixel - mean) * scale, sampled from the (possibly mirrored) crop
window. When a C++ native transformer is built (caffe_mpi_tpu/native), the
inner loop dispatches there; the numpy path is the reference implementation
for it.
"""

from __future__ import annotations

import numpy as np

from ..proto.config import TransformationParameter


class DataTransformer:
    def __init__(self, tp: TransformationParameter | None, phase: str,
                 seed: int | None = None, model_dir: str = ""):
        import os
        self.tp = tp or TransformationParameter()
        self.phase = phase
        if seed is None and self.tp.random_seed >= 0:
            seed = self.tp.random_seed
        self.seed = seed
        # fallback RNG for single-threaded use; multi-threaded callers pass
        # a per-record rng to __call__ (the reference uses per-thread RNGs,
        # data_transformer.cpp; per-record keying is stronger: deterministic
        # regardless of thread scheduling)
        self.rng = np.random.default_rng(seed)
        self.mean: np.ndarray | None = None
        if self.tp.mean_file:
            from ..io import load_blob_binaryproto
            self.mean = load_blob_binaryproto(
                os.path.join(model_dir, self.tp.mean_file))
            if self.mean.ndim == 4:
                self.mean = self.mean[0]
        elif self.tp.mean_value:
            self.mean = np.asarray(self.tp.mean_value,
                                   np.float32)[:, None, None]

    def record_rng(self, record_index: int) -> np.random.Generator:
        """Deterministic per-record stream (counter-based Philox)."""
        return np.random.Generator(
            np.random.Philox(key=((self.seed or 0) << 32) ^ record_index))

    def output_shape(self, in_shape: tuple[int, int, int]) -> tuple[int, int, int]:
        c, h, w = in_shape
        if self.tp.force_color:
            c = 3
        elif self.tp.force_gray:
            c = 1
        crop = self.tp.crop_size
        return (c, crop, crop) if crop else (c, h, w)

    def __call__(self, img: np.ndarray,
                 rng: np.random.Generator | None = None) -> np.ndarray:
        """img: CHW uint8/float -> transformed float32 CHW."""
        if rng is None:
            rng = self.rng
        tp = self.tp
        c, h, w = img.shape
        if tp.force_color and c == 1:
            img = np.broadcast_to(img, (3, h, w))
            c = 3
        elif tp.force_gray and c == 3:
            # OpenCV BGR2GRAY weights (reference decodes via OpenCV)
            img = (0.114 * img[0] + 0.587 * img[1] + 0.299 * img[2])[None]
            c = 1
        out = img.astype(np.float32)

        crop = tp.crop_size
        if crop:
            if crop > h or crop > w:
                raise ValueError(f"crop_size {crop} exceeds image {h}x{w}")
            if self.phase == "TRAIN":
                off_h = int(rng.integers(0, h - crop + 1))
                off_w = int(rng.integers(0, w - crop + 1))
            else:  # center crop (data_transformer.cpp Transform)
                off_h = (h - crop) // 2
                off_w = (w - crop) // 2
            out = out[:, off_h:off_h + crop, off_w:off_w + crop]

        if self.mean is not None:
            mean = self.mean
            if crop and mean.shape[-2:] == (h, w):
                # full-size mean file: subtract at the same crop window
                # (data_transformer.cpp Transform)
                mean = mean[:, off_h:off_h + crop, off_w:off_w + crop]
            out = out - mean

        if tp.mirror and self.phase == "TRAIN" and rng.integers(2):
            out = out[:, :, ::-1]

        if tp.scale != 1.0:
            out = out * tp.scale
        return np.ascontiguousarray(out, np.float32)
