"""Host image-decode plane — policy, counters, and the PIL fallback for
the native JPEG/PNG decoder (ISSUE 10).

Reference: src/caffe/util/io.cpp DecodeDatumToCVMat (encoded Datum ->
cv::Mat, BGR) and ReadImageToCVMat (file -> optional is_color/resize ->
cv::Mat), both called per record from the C++ reader/transformer threads
(data_reader.cpp, data_transformer.cpp:40-118). The TPU-native design
moves the same work into native/decode.cc behind ctypes — the last
Python-held stage of the host pipeline — while this module owns:

  * the engagement policy: `CAFFE_NATIVE_DECODE` env — "0" forces the
    PIL path (bitwise-identical to the pre-native pipeline), "1" forces
    native (raising when the library is unbuilt — the A/B switch for
    tools/bench_data), unset = native when available;
  * the PIL fallback, which is also the behavioral reference: records
    the native plane declines (exotic variants: CMYK JPEG, alpha/16-bit
    PNG, GIF/BMP/...) decode here, so coverage never shrinks;
  * decode telemetry (`STATS`): per-path record counters read by
    tools/bench_data's stage breakdown, bench.py's `ingest` block, and
    tools/e2e_lmdb_train's run journal — and the counter the
    decoded-record cache tests assert against (epoch 2 must decode
    NOTHING).

Pixel contract everywhere: planar CHW, BGR channel order, uint8 —
matching the reference's OpenCV decode (datasets.parse_datum's
documented parity). PNG parity with PIL is bitwise (lossless format);
JPEG parity is within 1 LSB per pixel (IDCT variance between libjpeg
builds; on this image both link libjpeg-turbo and agree bitwise —
tests/test_native_decode.py pins the contract, docs/benchmarks.md
"Ingestion" documents it).
"""

from __future__ import annotations

import io
import os
import threading

import numpy as np


class DecodeStats:
    """Thread-safe decode-plane counters (Feeder pool workers decode
    concurrently; the cache tests need exact counts, not telemetry-grade
    approximations)."""

    _KEYS = ("native_records", "pil_records", "native_fallbacks",
             "fused_batches", "fused_records", "fused_fallback_records",
             "cache_hits", "cache_inserts", "cache_bytes")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            for k in self._KEYS:
                setattr(self, k, 0)

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, key, getattr(self, key) + n)

    def snapshot(self) -> dict:
        with self._lock:
            out = {k: getattr(self, k) for k in self._KEYS}
        # total image decodes actually performed, any path — per-record
        # native, per-record PIL, or inside a fused native batch (cache
        # hits perform none: the epoch-2 zero-decode assertion reads this)
        out["decode_calls"] = (out["native_records"] + out["pil_records"]
                               + out["fused_records"])
        return out


STATS = DecodeStats()


def native_mode() -> int:
    """CAFFE_NATIVE_DECODE policy: -1 forced PIL ("0"), +1 forced native
    ("1"), 0 auto (unset/other). Read per call — it is the bench A/B
    switch and tests flip it at runtime; the getenv cost is noise next
    to a decode."""
    v = os.environ.get("CAFFE_NATIVE_DECODE", "").strip()
    if v == "0":
        return -1
    if v == "1":
        return 1
    return 0


def native_enabled() -> bool:
    """True when records should try the native decoder first."""
    mode = native_mode()
    if mode < 0:
        return False
    from .. import native
    ok = native.available() and native.decode_available()
    if mode > 0 and not ok:
        raise RuntimeError(
            "CAFFE_NATIVE_DECODE=1 but the native decode plane is "
            "unavailable — build it with caffe_mpi_tpu/native/build.sh "
            "(requires libjpeg/libpng dev headers)")
    return ok


def _pil_decode(data: bytes) -> np.ndarray:
    """The reference path: PIL RGB -> BGR CHW (datasets.parse_datum's
    original decode, kept verbatim as fallback + behavioral oracle)."""
    from PIL import Image
    img = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    STATS.count("pil_records")
    # PIL gives RGB HWC; Caffe stores BGR — convert for parity with
    # the reference's OpenCV decode (io.cpp DecodeDatumToCVMat)
    return img[:, :, ::-1].transpose(2, 0, 1)


def decode_image(data: bytes) -> np.ndarray:
    """Encoded image bytes -> (3, h, w) planar BGR uint8. Native when
    enabled and the record is expressible there, else PIL; raises (PIL's
    decode error) when the bytes are no image at all — the caller
    (datasets._decode_verified / materialize_datum) converts that to
    RecordIntegrityError for the quarantine plane."""
    if native_enabled():
        from .. import native
        arr = native.decode_image_native(data)
        if arr is not None:
            STATS.count("native_records")
            return arr
        STATS.count("native_fallbacks")
    return _pil_decode(data)


def to_float_image(arr: np.ndarray) -> np.ndarray:
    """(3, h, w) planar BGR uint8 (this plane's pixel contract) -> HWC
    RGB float32 in [0,1] — the pycaffe load_image / web-upload
    convention. Bitwise what PIL's own decode-and-convert would produce
    for the same pixels (u8 -> f32 is exact, /255.0 is one IEEE divide),
    so callers can decode natively and still feed the classic float
    surfaces (ISSUE 14's serving fallback path, caffe_io.load_image)."""
    if arr.ndim != 3 or arr.shape[0] != 3:
        raise ValueError(f"expected (3, h, w) BGR uint8, got {arr.shape}")
    return arr[::-1].transpose(1, 2, 0).astype(np.float32) / 255.0


def decode_file(data: bytes, *, is_color: bool = True, new_h: int = 0,
                new_w: int = 0) -> np.ndarray:
    """File-read image bytes -> CHW uint8, with the ImageData layer's
    optional bilinear resize (reference io.cpp ReadImageToCVMat). The
    native path covers the color case — resize follows the reference's
    cv::resize INTER_LINEAR convention, where PIL's BILINEAR antialiases
    on downscale — grayscale stays on PIL (the "L" luma weights)."""
    if is_color and native_enabled():
        from .. import native
        if new_h and new_w:
            arr = native.decode_resize_native(data, new_h, new_w)
        else:
            arr = native.decode_image_native(data)
        if arr is not None:
            STATS.count("native_records")
            return arr
        STATS.count("native_fallbacks")
    from PIL import Image
    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if is_color else "L")
    if new_h and new_w:
        img = img.resize((new_w, new_h), Image.BILINEAR)
    arr = np.asarray(img)
    STATS.count("pil_records")
    if arr.ndim == 2:
        return arr[None, :, :]
    return arr[:, :, ::-1].transpose(2, 0, 1)  # RGB HWC -> BGR CHW
