"""Dataset backends — the reference's db abstraction + format-specific
readers, host-side.

Reference: include/caffe/util/db{,_lmdb,_leveldb}.hpp + src/caffe/util/db*.cpp
(cursor over key->Datum records), plus the dataset conversion tools
(tools/convert_imageset.cpp writes encoded/raw Datums into LMDB/LevelDB).

Here a dataset is random-access (`__len__` + `get(i) -> (chw_uint8, label)`),
which subsumes the reference's forward-only cursor and lets the deterministic
round-robin record partitioning of CursorManager (data_reader.hpp:28-53)
be an index calculation instead of a cursor-skipping protocol.

LMDB needs no third-party module: lmdb_io.py implements the on-disk B+tree
format directly (mmap reader + bulk writer), so LMDBs written by the
reference's convert_imageset load unchanged in this image; the python
`lmdb` module is used instead when it happens to be installed.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import NamedTuple, Protocol

import numpy as np

from ..io import _tag as _dfield, _varint as _dvarint
from ..utils.resilience import FAULTS, RecordIntegrityError
from .lmdb_io import LMDBError as LMDBIOError


class Dataset(Protocol):
    def __len__(self) -> int: ...
    def get(self, index: int) -> tuple[np.ndarray, int]:
        """Returns (CHW uint8 or float image, integer label)."""
        ...


# ---------------------------------------------------------------------------
# Datum wire format (reference caffe.proto Datum message, field numbers:
# 1=channels 2=height 3=width 4=data(bytes) 5=label 6=float_data(rep)
# 7=encoded(bool))
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


class DatumFields(NamedTuple):
    """Parsed-but-unmaterialized Datum: the wire fields with the image
    payload still in its stored form. The fused native ingestion path
    (feeder._build_batch_fused) consumes `data` bytes of encoded records
    directly — one ctypes call decodes a whole batch — while `get()`
    callers materialize per record via `materialize_datum`."""
    channels: int
    height: int
    width: int
    data: bytes
    label: int
    encoded: bool
    float_data: list[float]


def parse_datum_fields(buf: bytes) -> DatumFields:
    """Minimal protobuf-wire Datum parser (no protoc dependency)."""
    channels = height = width = label = 0
    data = b""
    float_data: list[float] = []
    encoded = False
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
            if field == 1:
                channels = val
            elif field == 2:
                height = val
            elif field == 3:
                width = val
            elif field == 5:
                label = val - (1 << 64) if val >= 1 << 63 else val
            elif field == 7:
                encoded = bool(val)
        elif wire == 2:
            size, pos = _read_varint(buf, pos)
            chunk = buf[pos:pos + size]
            pos += size
            if field == 4:
                data = chunk
            elif field == 6:  # packed float_data
                float_data.extend(struct.unpack(f"<{size // 4}f", chunk))
        elif wire == 5:
            if field == 6:
                float_data.append(struct.unpack("<f", buf[pos:pos + 4])[0])
            pos += 4
        elif wire == 1:
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
    return DatumFields(channels, height, width, data, label, encoded,
                       float_data)


def materialize_datum(f: DatumFields) -> tuple[np.ndarray, int]:
    """DatumFields -> (CHW array, label); encoded payloads route through
    the decode plane (data/decode.py: native libjpeg/libpng when
    enabled, PIL fallback — BGR CHW parity with the reference's OpenCV
    decode either way)."""
    if f.encoded:
        from .decode import decode_image
        arr = decode_image(f.data)
    elif f.data:
        arr = np.frombuffer(f.data, np.uint8).reshape(
            f.channels, f.height, f.width)
    else:
        arr = np.asarray(f.float_data, np.float32).reshape(
            f.channels, f.height, f.width)
    return arr, f.label


def parse_datum(buf: bytes) -> tuple[np.ndarray, int]:
    """Datum wire bytes -> (CHW array, label)."""
    return materialize_datum(parse_datum_fields(buf))


def _datum_header(c: int, h: int, w: int) -> bytearray:
    out = bytearray()
    out += _dfield(1, 0) + _dvarint(c)
    out += _dfield(2, 0) + _dvarint(h)
    out += _dfield(3, 0) + _dvarint(w)
    return out


def encode_datum(arr: np.ndarray, label: int) -> bytes:
    """Write a raw-bytes Datum (tools/convert_imageset parity, unencoded)."""
    c, h, w = arr.shape
    out = _datum_header(c, h, w)
    raw = arr.astype(np.uint8).tobytes()
    out += _dfield(4, 2) + _dvarint(len(raw)) + raw
    out += _dfield(5, 0) + _dvarint(label if label >= 0
                                    else label + (1 << 64))
    return bytes(out)


def encode_datum_image(arr: np.ndarray, label: int, codec: str = "jpeg",
                       quality: int = 95) -> bytes:
    """Datum carrying an ENCODED image (field 7 = true, data = JPEG/PNG
    bytes) — the reference's `convert_imageset -encoded` path
    (io.cpp EncodeDatum / tools/convert_imageset.cpp encode_type).
    `arr` is BGR CHW uint8, matching what parse_datum returns."""
    import io as _io

    from PIL import Image
    c, h, w = arr.shape
    if c != 3:
        raise ValueError("encoded datums are 3-channel BGR")
    rgb = np.ascontiguousarray(
        arr.astype(np.uint8)[::-1].transpose(1, 2, 0))  # BGR CHW -> RGB HWC
    buf = _io.BytesIO()
    if codec.lower() in ("jpeg", "jpg"):
        Image.fromarray(rgb).save(buf, "JPEG", quality=quality)
    elif codec.lower() == "png":
        Image.fromarray(rgb).save(buf, "PNG")
    else:
        raise ValueError(f"unknown codec {codec!r}")
    raw = buf.getvalue()
    out = _datum_header(c, h, w)
    out += _dfield(4, 2) + _dvarint(len(raw)) + raw
    out += _dfield(5, 0) + _dvarint(label if label >= 0
                                    else label + (1 << 64))
    out += _dfield(7, 0) + _dvarint(1)
    return bytes(out)


def encode_datum_float(arr: np.ndarray, label: int) -> bytes:
    """Datum carrying packed float_data (field 6) — the reference's float
    path (caffe.proto Datum.float_data, written by e.g. HDF5->datum
    converters and feature dumps)."""
    c, h, w = arr.shape
    out = _datum_header(c, h, w)
    raw = np.ascontiguousarray(arr, "<f4").tobytes()
    out += _dfield(6, 2) + _dvarint(len(raw)) + raw
    out += _dfield(5, 0) + _dvarint(label if label >= 0
                                    else label + (1 << 64))
    return bytes(out)


# ---------------------------------------------------------------------------
# Read-path integrity (ISSUE 4 data-integrity plane)
# ---------------------------------------------------------------------------

def _decode_verified(raw: bytes, index: int, source: str,
                     expect_crc: int | None = None,
                     actual_crc: int | None = None, *,
                     fields: bool = False):
    """Datum decode with integrity verification. `expect_crc` (from the
    LMDB crc sidecar / a format-level checksum) is compared against
    `actual_crc` — computed here over the fetched bytes when the caller
    did not already have one (the native LMDB path computes it in C
    over the mmap). Any mismatch or parse failure raises
    RecordIntegrityError, the deterministic-corruption signal the
    Feeder quarantines on (transient I/O errors stay OSError and keep
    their retry budget). The fault sites operate on the FETCHED bytes,
    zero cost when CAFFE_TPU_FAULTS is unset."""
    if FAULTS.active("record_corrupt") or FAULTS.active("record_decode"):
        poisoned = FAULTS.corrupt_bytes("record_corrupt", raw, index)
        poisoned = FAULTS.corrupt_bytes("record_decode", poisoned, index)
        if poisoned is not raw:
            raw, actual_crc = poisoned, None  # re-checksum injected rot
    if expect_crc is not None:
        if actual_crc is None:
            from .leveldb_io import crc32c
            actual_crc = crc32c(raw)
        if actual_crc != expect_crc:
            raise RecordIntegrityError(
                source, index,
                f"crc32c mismatch (sidecar {expect_crc:08x}, "
                f"computed {actual_crc:08x})")
    try:
        f = parse_datum_fields(raw)
        # fields=True defers image decode to the caller (the fused
        # native batch path); decode failures there re-enter the
        # quarantine plane through the per-record get() fallback
        return f if fields else materialize_datum(f)
    except Exception as e:
        raise RecordIntegrityError(
            source, index, f"undecodable Datum: {e!r}") from e


# ---------------------------------------------------------------------------
# Backends
# ---------------------------------------------------------------------------

class LMDBDataset:
    """Reads LMDBs written by the reference's convert_imageset
    (db_lmdb.cpp). Uses the python `lmdb` module when present, else the
    in-repo dependency-free B+tree reader (data/lmdb_io.py) — either way,
    reference-written LMDBs load unchanged.

    Integrity (ISSUE 4): when the crc sidecar our writers publish
    (`data.mdb.crc32c`, data/lmdb_io.py) is present, every value read
    — on all three cursor paths — verifies against its per-record
    crc32c; a mismatch raises RecordIntegrityError for the Feeder to
    quarantine. Sidecar-less (reference-written) DBs load unverified,
    as before; undecodable Datums quarantine either way."""

    def __init__(self, path: str):
        try:
            import lmdb
        except ImportError:
            lmdb = None
        self.path = path
        self.env = None
        self._reader = None
        self._native = None
        self._crcs = None
        # structural-corruption classes the get() path converts to the
        # quarantine signal — the lmdb module's own error hierarchy
        # joins in when that cursor is the one in use
        self._struct_errs: tuple = (LMDBIOError,)
        if lmdb is not None:
            self._struct_errs = (LMDBIOError, lmdb.Error)
            self.env = lmdb.open(path, readonly=True, lock=False,
                                 readahead=False, meminit=False)
            with self.env.begin() as txn:
                self.keys = [k for k, _ in txn.cursor()]
            self._load_sidecar(path)
            return
        try:  # native C++ mmap cursor when built
            from .. import native
            if native.available():
                self._native = native.NativeLMDB(path)
                # key-only scan: values stay untouched in the mmap
                self.keys = [self._native.key(i)
                             for i in range(len(self._native))]
                self._load_sidecar(path)
                return
        except (ImportError, ValueError, RuntimeError):
            self._native = None
        from .lmdb_io import LMDBReader
        self._reader = LMDBReader(path)
        self.keys = list(self._reader.keys())
        self._load_sidecar(path)

    def _load_sidecar(self, path: str) -> None:
        from .lmdb_io import read_crc_sidecar
        self._crcs = read_crc_sidecar(path, expect_count=len(self.keys))

    def __len__(self) -> int:
        return len(self.keys)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        return self._get(index, fields=False)

    def get_datum(self, index: int) -> DatumFields:
        """Verified wire fields WITHOUT materializing the image — the
        fused native ingestion path decodes encoded payloads batch-at-
        a-time (feeder._build_batch_fused). crc/structural verification
        is identical to get()."""
        return self._get(index, fields=True)

    def _get(self, index: int, fields: bool):
        expect = int(self._crcs[index]) if self._crcs is not None else None
        if self._native is not None:
            raw = self._native.value(index)
            # the C path checksums the value over the mmap — no second
            # pass over the bytes in Python (skipped while fault
            # injection is live: the injected rot lands on the FETCHED
            # copy, which the C reader cannot see)
            actual = (self._native.value_crc32c(index)
                      if expect is not None and not FAULTS.active(
                          "record_corrupt")
                      and not FAULTS.active("record_decode") else None)
            return _decode_verified(raw, index, self.path, expect, actual,
                                    fields=fields)
        try:
            if self._reader is not None:
                raw = self._reader.get(self.keys[index])
            else:
                with self.env.begin() as txn:
                    raw = txn.get(self.keys[index])
        except self._struct_errs as e:
            # structural rot (bad page flags, value beyond EOF): same
            # quarantine signal as a checksum mismatch
            raise RecordIntegrityError(self.path, index,
                                       f"structural: {e}") from e
        return _decode_verified(raw, index, self.path, expect,
                                fields=fields)


class LevelDBDataset:
    """Reads LevelDB datasets written by the reference's convert tools
    (db_leveldb.cpp) via the dependency-free SSTable reader
    (data/leveldb_io.py): all tables merged, key order, Datum values.

    Integrity (ISSUE 4): the SSTable format carries a masked crc32c per
    block, computed by every writer; the reader now verifies it on each
    block decode (leveldb_io._Table.read_block), so value fetches from
    a rotten block raise — converted here to RecordIntegrityError for
    the Feeder's quarantine. Undecodable Datums quarantine the same
    way."""

    def __init__(self, path: str):
        from .leveldb_io import LevelDBReader
        self.path = path
        self._reader = LevelDBReader(path)

    def __len__(self) -> int:
        return len(self._reader)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        return self._get(index, fields=False)

    def get_datum(self, index: int) -> DatumFields:
        """Verified wire fields without image materialization (fused
        native ingestion path); block-crc verification as in get()."""
        return self._get(index, fields=True)

    def _get(self, index: int, fields: bool):
        from .leveldb_io import LevelDBError
        try:
            # positional: values decode on demand from the mmap'd
            # tables, each block crc32c-verified on read
            raw = self._reader.value_at(index)
        except LevelDBError as e:
            raise RecordIntegrityError(self.path, index, str(e)) from e
        return _decode_verified(raw, index, self.path, fields=fields)


class ImageFolderDataset:
    """Reads an index file of `relative/path.jpg label` lines (the
    reference ImageData layer's source format, image_data_layer.cpp)."""

    def __init__(self, source: str, root: str = "", new_height: int = 0,
                 new_width: int = 0, is_color: bool = True):
        self.root = root
        self.new_hw = (new_height, new_width)
        self.is_color = is_color
        self.items: list[tuple[str, int]] = []
        with open(source) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                path, _, label = line.rpartition(" ")
                self.items.append((path, int(label)))

    def __len__(self) -> int:
        return len(self.items)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        # decode plane (ISSUE 10): native libjpeg/libpng decode +
        # bilinear resize when enabled (reference ReadImageToCVMat's
        # cv::resize INTER_LINEAR), PIL fallback kept
        from .decode import decode_file
        path, label = self.items[index]
        with open(os.path.join(self.root, path), "rb") as f:
            data = f.read()
        return decode_file(data, is_color=self.is_color,
                           new_h=self.new_hw[0], new_w=self.new_hw[1]), label


class MNISTDataset:
    """Raw idx-format MNIST files (the reference converts these to LMDB via
    examples/mnist/convert_mnist_data.cpp; here they are read directly)."""

    def __init__(self, images_path: str, labels_path: str):
        with open(images_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad MNIST image magic {magic}")
            self.images = np.frombuffer(f.read(), np.uint8).reshape(n, 1, rows, cols)
        with open(labels_path, "rb") as f:
            magic, n2 = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad MNIST label magic {magic}")
            self.labels = np.frombuffer(f.read(), np.uint8)
        if n != n2:
            raise ValueError("image/label count mismatch")

    def __len__(self) -> int:
        return len(self.labels)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        return self.images[index], int(self.labels[index])


class CIFAR10Dataset:
    """CIFAR-10 binary batches (examples/cifar10/convert_cifar_data.cpp
    reads the same 1+3072-byte record format)."""

    RECORD = 1 + 3 * 32 * 32

    def __init__(self, *batch_paths: str):
        blobs = []
        for p in batch_paths:
            with open(p, "rb") as f:
                raw = np.frombuffer(f.read(), np.uint8)
            if raw.size % self.RECORD:
                raise ValueError(f"{p}: not a CIFAR-10 binary batch")
            blobs.append(raw.reshape(-1, self.RECORD))
        self.records = np.concatenate(blobs, axis=0)

    def __len__(self) -> int:
        return len(self.records)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        rec = self.records[index]
        label = int(rec[0])
        img = rec[1:].reshape(3, 32, 32)  # CIFAR binary is RGB CHW
        return img[::-1], label  # -> BGR for Caffe parity


class CachedDataset:
    """Whole-dataset RAM cache (reference DataReader's DataCache,
    data_reader.hpp:55-101: optional cache of every record with epoch
    shuffling handled by the Feeder's permutations)."""

    def __init__(self, base: Dataset):
        self.records = [base.get(i) for i in range(len(base))]

    def __len__(self) -> int:
        return len(self.records)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        return self.records[index]


class DecodedCacheDataset:
    """Bounded decoded-record cache tier (ISSUE 10, solver knob
    `decoded_cache_mb` — docs/benchmarks.md "Ingestion").

    The reference DataCache (data_reader.hpp:55-101) caches every record
    whole; `data_param { cache: true }` / CachedDataset reproduces that.
    This tier is the bounded variant for datasets that don't fit RAM:
    post-decode, pre-augment CHW uint8 arrays are kept up to
    `budget_mb`, so every epoch after the first skips DB read, crc
    verification, AND image decode for the cached span — the expensive
    stages for JPEG/PNG-encoded DBs, which otherwise re-decode the whole
    dataset every epoch.

    Admission is first-fit and KEYED BY RECORD INDEX: once the budget is
    reached no entry is ever evicted or replaced, so under the Feeder's
    per-epoch permutations (epoch-shuffle semantics live upstream in
    `_record_index`) the same records hit every epoch — deterministic,
    and no LRU thrash when budget < dataset. Integrity is unchanged:
    misses go through the base dataset's crc/quarantine path, and only
    successfully decoded records are admitted (a corrupt record raises
    before insert, on first decode, exactly as uncached).

    Thread-safe: Feeder pool workers populate it concurrently. Cached
    arrays are marked read-only — every consumer copies (f32 cast,
    np.stack) before mutating."""

    def __init__(self, base: Dataset, budget_mb: float):
        self.base = base
        self.path = getattr(base, "path", "") or type(base).__name__
        self._budget = int(budget_mb * 2**20)
        self._bytes = 0
        self._full = False
        self._cache: dict[int, tuple[np.ndarray, int]] = {}
        self._lock = threading.Lock()
        base_datum = getattr(base, "get_datum", None)
        if base_datum is not None:
            # expose the fused-ingestion fields API only when the base
            # has it (the Feeder probes with getattr)
            self.get_datum = base_datum

    def __len__(self) -> int:
        return len(self.base)

    def lookup(self, index: int):
        """Cached (arr, label) or None — the Feeder's fused path asks
        before fetching encoded bytes."""
        with self._lock:
            hit = self._cache.get(index)
        if hit is not None:
            from .decode import STATS
            STATS.count("cache_hits")
        return hit

    def admitting(self) -> bool:
        """False once the budget has been hit — callers skip allocating
        decode side-buffers that could never be admitted."""
        return not self._full

    def insert(self, index: int, arr: np.ndarray, label: int) -> None:
        """Admit a decoded record (first-fit under the byte budget)."""
        if arr.dtype != np.uint8 or self._full:
            return
        arr = np.array(arr)  # own copy: cache entries are long-lived and
        #                      must not pin batch buffers or mmap views
        arr.setflags(write=False)
        with self._lock:
            if index in self._cache:
                return
            if self._bytes + arr.nbytes > self._budget:
                self._full = True
                return
            self._cache[index] = (arr, int(label))
            self._bytes += arr.nbytes
        from .decode import STATS
        STATS.count("cache_inserts")
        STATS.count("cache_bytes", arr.nbytes)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        hit = self.lookup(index)
        if hit is not None:
            return hit
        arr, label = self.base.get(index)
        self.insert(index, arr, label)
        return arr, label


class SyntheticDataset:
    """Deterministic class-template images — test/bench stand-in."""

    def __init__(self, num: int, shape=(3, 32, 32), classes: int = 10,
                 seed: int = 0, noise: float = 0.3):
        self.num = num
        self.classes = classes
        self.shape = shape
        self.noise = noise
        r = np.random.RandomState(seed)
        self.templates = r.randint(0, 256, (classes, *shape)).astype(np.uint8)

    def __len__(self) -> int:
        return self.num

    def get(self, index: int) -> tuple[np.ndarray, int]:
        label = index % self.classes
        r = np.random.RandomState(index)
        img = self.templates[label].astype(np.float32)
        img = img + self.noise * 255 * r.randn(*self.shape)
        return np.clip(img, 0, 255).astype(np.uint8), label


class DatumFileDataset:
    """Single-file Datum container. On-disk layout:
    MAGIC, raw back-to-back Datum messages, then an index:
    [int64 count][count x (int64 offset, int64 size)][int64 index_offset].
    Fills the gap when the lmdb module is unavailable; written by
    tools/convert_imageset with -backend datumfile."""

    MAGIC = b"CAFFEDATUMv1"

    def __init__(self, path: str):
        self.path = path  # names the file in quarantine journal entries
        self.f = open(path, "rb")
        self._fd = self.f.fileno()
        header = self.f.read(len(self.MAGIC))
        if header != self.MAGIC:
            raise ValueError(f"{path}: not a datumfile")
        self.f.seek(-8, os.SEEK_END)
        index_off = struct.unpack("<q", self.f.read(8))[0]
        self.f.seek(index_off)
        count = struct.unpack("<q", self.f.read(8))[0]
        self.offsets = np.frombuffer(self.f.read(count * 16), "<i8").reshape(-1, 2)

    def __len__(self) -> int:
        return len(self.offsets)

    def get(self, index: int) -> tuple[np.ndarray, int]:
        off, size = self.offsets[index]
        # pread: positioned read, safe under the Feeder's concurrent threads
        return _decode_verified(os.pread(self._fd, int(size), int(off)),
                                index, self.f.name)

    def get_datum(self, index: int) -> DatumFields:
        """Verified wire fields without image materialization (fused
        native ingestion path)."""
        off, size = self.offsets[index]
        return _decode_verified(os.pread(self._fd, int(size), int(off)),
                                index, self.f.name, fields=True)

    @classmethod
    def write(cls, path: str, records) -> int:
        """records: iterable of encoded Datum bytes."""
        offsets = []
        with open(path, "wb") as f:
            f.write(cls.MAGIC)
            for buf in records:
                offsets.append((f.tell(), len(buf)))
                f.write(buf)
            index_off = f.tell()
            f.write(struct.pack("<q", len(offsets)))
            f.write(np.asarray(offsets, "<i8").tobytes())
            f.write(struct.pack("<q", index_off))
        return len(offsets)


class _HybridDatumDataset:
    """Native mmap reader with per-record python fallback (encoded JPEG /
    float datums parse on the python path)."""

    def __init__(self, native_db, py_ds: DatumFileDataset):
        self.native = native_db
        self.py = py_ds

    def __len__(self) -> int:
        return len(self.py)

    def get(self, index: int):
        try:
            return self.native.get(index)
        except ValueError:
            return self.py.get(index)

    def get_datum(self, index: int) -> DatumFields:
        # encoded/float records live on the python reader either way
        return self.py.get_datum(index)


def open_dataset(backend: str, source: str, **kw) -> Dataset:
    """db::GetDB analogue (reference db.cpp factory)."""
    backend = backend.upper()
    if backend == "LMDB":
        return LMDBDataset(source)
    if backend == "DATUMFILE":
        py = DatumFileDataset(source)
        try:
            from .. import native
            if native.available():
                return _HybridDatumDataset(native.NativeDatumDB(source), py)
        except (ImportError, ValueError, RuntimeError):
            pass
        return py
    if backend == "LEVELDB":
        return LevelDBDataset(source)
    raise ValueError(f"unknown db backend {backend!r}")
