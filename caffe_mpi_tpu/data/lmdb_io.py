"""Dependency-free LMDB reader/writer (mmap'd B+tree pages).

Replaces: src/caffe/util/db_lmdb.{hpp,cpp} (the reference links liblmdb;
this image has neither liblmdb nor the python `lmdb` module). Rather than
gating LMDB support on an absent dependency, the on-disk format itself is
implemented here — it is a small, stable, well-documented B+tree layout
(LMDB 0.9.x "data version 1", the format every Caffe-era LMDB uses):

  page 0/1   meta pages (the one with the larger txnid wins)
  page N     branch pages (key -> child pgno), leaf pages (key -> value),
             overflow pages (values larger than ~2KB, F_BIGDATA nodes)

Struct layout follows mdb.c on LP64:
  MDB_page   u64 pgno | u16 pad | u16 flags | u16 lower | u16 upper | ptrs[]
  MDB_meta   u32 magic(0xBEEFC0DE) | u32 version(1) | u64 addr | u64 mapsize
             | MDB_db[2] | u64 last_pg | u64 txnid     (page psize is
             stored in mm_dbs[0].md_pad)
  MDB_db     u32 pad | u16 flags | u16 depth | u64 branch | u64 leaf
             | u64 overflow | u64 entries | u64 root
  MDB_node   u16 lo | u16 hi | u16 flags | u16 ksize | key | data
             (branch: child pgno = lo | hi<<16 | flags<<32;
              leaf: data size = lo | hi<<16, F_BIGDATA=0x01 means the data
              area holds a u64 overflow pgno)

The reader is read-only and zero-copy (memoryview slices of the mmap);
the writer is a bulk sorted-insert B+tree builder — exactly what
convert_imageset needs — not a transactional store.

TPU-native design note: data loading is host-side by construction (the
reference's DataReader threads feed GPUs; here records feed the jit'd
step via the feeder pipeline), so plain Python + mmap is the right tool —
the bytes go straight from page cache into the Datum wire parser.
"""

from __future__ import annotations

import logging
import mmap
import os
import struct

log = logging.getLogger("caffe_mpi_tpu.lmdb")

PAGEHDRSZ = 16
META_MAGIC = 0xBEEFC0DE
META_VERSION = 1
P_INVALID = 0xFFFFFFFFFFFFFFFF

P_BRANCH = 0x01
P_LEAF = 0x02
P_OVERFLOW = 0x04
P_META = 0x08

F_BIGDATA = 0x01

_META = struct.Struct("<IIQQ")          # magic, version, address, mapsize
_DB = struct.Struct("<IHHQQQQQ")        # pad, flags, depth, b, l, o, entries, root
_PAGEHDR = struct.Struct("<QHHHH")      # pgno, pad, flags, lower, upper
_NODEHDR = struct.Struct("<HHHH")       # lo, hi, flags, ksize


def _even(n: int) -> int:
    return (n + 1) & ~1


class LMDBError(RuntimeError):
    pass


class LMDBReader:
    """Read-only cursor over the main DB of an LMDB environment.

    `path` may be the environment directory (containing data.mdb) or the
    data file itself (MDB_NOSUBDIR layout). Iteration yields (key, value)
    bytes in key order — the order the reference's sequential cursor sees.
    """

    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, "data.mdb")
        self.path = path
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        self._view = memoryview(self._mm)
        meta = self._pick_meta()
        (self.psize, main_flags, self.depth, branch_pages, leaf_pages,
         overflow_pages, self.entries, self.root) = meta
        if main_flags:  # DUPSORT(0x04)/INTEGERKEY(0x08)/REVERSEKEY(0x02)
            # all change key comparison or node layout; Caffe main DBs
            # always have md_flags == 0
            raise LMDBError(
                f"unsupported main-DB flags 0x{main_flags:x} in {path}")

    # -- meta ------------------------------------------------------------
    def _parse_meta_at(self, off: int):
        hdr = _PAGEHDR.unpack_from(self._view, off)
        if not hdr[2] & P_META:
            raise LMDBError(f"page at {off} is not a meta page")
        magic, version, _addr, _mapsize = _META.unpack_from(
            self._view, off + PAGEHDRSZ)
        if magic != META_MAGIC:
            raise LMDBError(f"bad LMDB magic 0x{magic:x} in {self.path}")
        if version != META_VERSION:
            raise LMDBError(f"unsupported LMDB data version {version}")
        base = off + PAGEHDRSZ + _META.size
        free_db = _DB.unpack_from(self._view, base)
        main_db = _DB.unpack_from(self._view, base + _DB.size)
        last_pg, txnid = struct.unpack_from("<QQ", self._view,
                                            base + 2 * _DB.size)
        psize = free_db[0] or 4096  # mm_psize lives in mm_dbs[0].md_pad
        return txnid, (psize, main_db[1], main_db[2], main_db[3], main_db[4],
                       main_db[5], main_db[6], main_db[7])

    def _pick_meta(self):
        # meta 0 is at offset 0; meta 1 is at offset psize (mm_psize, read
        # from meta 0's mm_dbs[0].md_pad). Newest (larger txnid) wins.
        t0, m0 = self._parse_meta_at(0)
        try:
            t1, m1 = self._parse_meta_at(m0[0])
        except (LMDBError, struct.error):
            return m0
        return m1 if t1 > t0 else m0

    # -- pages -----------------------------------------------------------
    def _page(self, pgno: int):
        off = pgno * self.psize
        if off + self.psize > len(self._view):
            raise LMDBError(f"page {pgno} beyond EOF in {self.path}")
        pg, _pad, flags, lower, upper = _PAGEHDR.unpack_from(self._view, off)
        return off, flags, lower, upper

    def _nkeys(self, lower: int) -> int:
        return (lower - PAGEHDRSZ) >> 1

    def _node(self, page_off: int, i: int):
        (ptr,) = struct.unpack_from("<H", self._view,
                                    page_off + PAGEHDRSZ + 2 * i)
        noff = page_off + ptr
        lo, hi, flags, ksize = _NODEHDR.unpack_from(self._view, noff)
        return noff, lo, hi, flags, ksize

    def _node_key(self, noff: int, ksize: int) -> bytes:
        return bytes(self._view[noff + 8: noff + 8 + ksize])

    def _leaf_value(self, noff: int, lo: int, hi: int, flags: int,
                    ksize: int) -> bytes:
        dsize = lo | (hi << 16)
        doff = noff + 8 + ksize
        if flags & F_BIGDATA:
            (ovpgno,) = struct.unpack_from("<Q", self._view, doff)
            ooff, oflags, olower, oupper = self._page(ovpgno)
            if not oflags & P_OVERFLOW:
                raise LMDBError(f"page {ovpgno} is not an overflow page")
            # The value may span several overflow pages; _page() only
            # validated the first one (lmdb_reader.cc checks the full
            # extent the same way).
            if ooff + PAGEHDRSZ + dsize > len(self._view):
                raise LMDBError(
                    f"overflow value at page {ovpgno} extends beyond EOF "
                    f"in {self.path}")
            return bytes(self._view[ooff + PAGEHDRSZ:
                                    ooff + PAGEHDRSZ + dsize])
        return bytes(self._view[doff: doff + dsize])

    # -- public API ------------------------------------------------------
    def __len__(self) -> int:
        return self.entries

    def _walk(self, with_values: bool):
        """DFS over the B+tree in key order (LMDB has no leaf sibling
        links; the C cursor keeps the same page stack)."""
        if self.root == P_INVALID:
            return
        stack = [(self.root, 0)]
        while stack:
            pgno, i = stack.pop()
            off, flags, lower, _upper = self._page(pgno)
            n = self._nkeys(lower)
            if flags & P_LEAF:
                for j in range(n):
                    noff, lo, hi, nflags, ksize = self._node(off, j)
                    key = self._node_key(noff, ksize)
                    if with_values:
                        yield key, self._leaf_value(noff, lo, hi, nflags,
                                                    ksize)
                    else:
                        yield key
            elif flags & P_BRANCH:
                if i + 1 < n:
                    stack.append((pgno, i + 1))
                noff, lo, hi, nflags, _ksize = self._node(off, i)
                stack.append((lo | (hi << 16) | (nflags << 32), 0))
            else:
                raise LMDBError(f"unexpected page flags 0x{flags:x}")

    def items(self):
        return self._walk(with_values=True)

    def keys(self):
        # keys-only walk: touches page headers + key bytes, never copies
        # values (a multi-GB DB's key list costs MBs, not the whole file)
        return self._walk(with_values=False)

    def get(self, key: bytes):
        """Point lookup, binary search down the tree (mdb_cursor_set)."""
        if self.root == P_INVALID:
            return None
        pgno = self.root
        while True:
            off, flags, lower, _upper = self._page(pgno)
            n = self._nkeys(lower)
            if flags & P_LEAF:
                lo_i, hi_i = 0, n - 1
                while lo_i <= hi_i:
                    mid = (lo_i + hi_i) // 2
                    noff, lo, hi, nflags, ksize = self._node(off, mid)
                    k = self._node_key(noff, ksize)
                    if k == key:
                        return self._leaf_value(noff, lo, hi, nflags, ksize)
                    if k < key:
                        lo_i = mid + 1
                    else:
                        hi_i = mid - 1
                return None
            # branch: rightmost child whose separator <= key (node 0 is the
            # -inf child: its stored key, if any, is not consulted)
            child_i = 0
            lo_i, hi_i = 1, n - 1
            while lo_i <= hi_i:
                mid = (lo_i + hi_i) // 2
                noff, _lo, _hi, _f, ksize = self._node(off, mid)
                if self._node_key(noff, ksize) <= key:
                    child_i = mid
                    lo_i = mid + 1
                else:
                    hi_i = mid - 1
            noff, lo, hi, nflags, _ksize = self._node(off, child_i)
            pgno = lo | (hi << 16) | (nflags << 32)

    def close(self):
        self._view.release()
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Per-record integrity sidecar (ISSUE 4 data-integrity plane)
# ---------------------------------------------------------------------------
# The LMDB format itself carries no record checksums (mdb.c trusts the
# filesystem), so corruption inside a value is invisible to the B+tree
# walk: the page structure stays valid while the pixels rot. Our writer
# publishes a compact sidecar next to data.mdb — one crc32c per value,
# in key order, self-checksummed — and every read path (lmdb_io,
# native/lmdb_reader.cc, the python `lmdb` module) verifies against it
# when present. Reference-written LMDBs have no sidecar and load
# unverified, exactly as before.

CRC_SIDECAR_MAGIC = b"LMDBCRC1"
CRC_SIDECAR_SUFFIX = ".crc32c"


def crc_sidecar_path(data_path: str) -> str:
    """Sidecar path for a data file; accepts the env dir too."""
    if os.path.isdir(data_path):
        data_path = os.path.join(data_path, "data.mdb")
    return data_path + CRC_SIDECAR_SUFFIX


def write_crc_sidecar(data_path: str, crcs: list[int]) -> str:
    """Publish `<data.mdb>.crc32c`: magic | u64 count | u32 crc per
    record (key order) | u32 crc32c of the array — the trailing
    checksum means a rotten sidecar is detected and IGNORED (treated
    as absent) rather than quarantining the whole dataset."""
    from ..utils.resilience import atomic_output
    from .leveldb_io import crc32c
    path = crc_sidecar_path(data_path)
    body = struct.pack(f"<{len(crcs)}I", *crcs)
    # temp+rename like every other published integrity artifact: a
    # crash mid-publish must not leave a torn sidecar that silently
    # disables verification for the dataset forever
    with atomic_output(path) as tmp:
        with open(tmp, "wb") as f:
            f.write(CRC_SIDECAR_MAGIC)
            f.write(struct.pack("<Q", len(crcs)))
            f.write(body)
            f.write(struct.pack("<I", crc32c(body)))
    return path


def read_crc_sidecar(data_path: str, expect_count: int | None = None):
    """Load the sidecar's u32 crc array, or None when absent/invalid
    (a warning names WHY — count mismatch or self-checksum failure
    means the sidecar rotted, not the data)."""
    from .leveldb_io import crc32c
    path = crc_sidecar_path(data_path)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    hdr = len(CRC_SIDECAR_MAGIC)
    if len(raw) < hdr + 12 or raw[:hdr] != CRC_SIDECAR_MAGIC:
        log.warning("%s: not a crc sidecar; ignoring", path)
        return None
    (count,) = struct.unpack_from("<Q", raw, hdr)
    body = raw[hdr + 8:-4]
    (self_crc,) = struct.unpack_from("<I", raw, len(raw) - 4)
    if len(body) != 4 * count or crc32c(body) != self_crc:
        log.warning("%s: crc sidecar failed its self-checksum; record "
                    "verification disabled for this dataset", path)
        return None
    if expect_count is not None and count != expect_count:
        log.warning("%s: crc sidecar covers %d records but the DB has "
                    "%d; ignoring (stale sidecar?)", path, count,
                    expect_count)
        return None
    import numpy as _np
    return _np.frombuffer(body, "<u4")


# ---------------------------------------------------------------------------
# Writer: bulk sorted B+tree builder
# ---------------------------------------------------------------------------

class _PageBuf:
    def __init__(self, pgno: int, flags: int, psize: int):
        self.pgno = pgno
        self.flags = flags
        self.psize = psize
        self.ptrs: list[int] = []
        self.blobs: list[bytes] = []
        self.upper = psize

    def free(self) -> int:
        lower = PAGEHDRSZ + 2 * len(self.ptrs)
        return self.upper - lower

    def add(self, node: bytes) -> bool:
        need = _even(len(node)) + 2
        if need > self.free():
            return False
        self.upper -= _even(len(node))
        self.ptrs.append(self.upper)
        self.blobs.append(node)
        return True

    def render(self) -> bytes:
        buf = bytearray(self.psize)
        lower = PAGEHDRSZ + 2 * len(self.ptrs)
        _PAGEHDR.pack_into(buf, 0, self.pgno, 0, self.flags, lower,
                           self.upper)
        struct.pack_into(f"<{len(self.ptrs)}H", buf, PAGEHDRSZ, *self.ptrs)
        for ptr, blob in zip(self.ptrs, self.blobs):
            buf[ptr: ptr + len(blob)] = blob
        return bytes(buf)


def _leaf_node(key: bytes, value: bytes, big_pgno: int | None) -> bytes:
    dsize = len(value)
    if big_pgno is not None:
        return _NODEHDR.pack(dsize & 0xFFFF, dsize >> 16, F_BIGDATA,
                             len(key)) + key + struct.pack("<Q", big_pgno)
    return _NODEHDR.pack(dsize & 0xFFFF, dsize >> 16, 0, len(key)) + key + value


def _branch_node(key: bytes, pgno: int) -> bytes:
    return _NODEHDR.pack(pgno & 0xFFFF, (pgno >> 16) & 0xFFFF,
                         (pgno >> 32) & 0xFFFF, len(key)) + key


def write_lmdb(path: str, items, psize: int = 4096,
               subdir: bool = True, integrity: bool = True) -> str:
    """Write a fresh single-DB LMDB environment from (key, value) pairs.

    STREAMING: items may be any iterable; keys must arrive in ascending
    order (convert_imageset's "%08d" keys already do — the same order
    mdb_put sees) unless a list/tuple is passed, which is sorted here.
    Finalized pages are written straight to their file offset, so memory
    stays O(one page + one (first_key, pgno) pair per tree node), never
    O(dataset) — an ImageNet-scale conversion streams through.

    Values larger than the in-page node budget go to overflow pages with
    F_BIGDATA nodes, same threshold rule as mdb.c
    (me_nodemax = (psize - PAGEHDRSZ)/2 & -2). Returns the data file path.

    integrity=True (default) also publishes the per-record crc32c
    sidecar (`data.mdb.crc32c`, ISSUE 4) the read paths verify against;
    the 4 bytes/record accumulate in RAM (an ImageNet-scale conversion
    costs a few MB), everything else stays streaming.
    """
    if isinstance(items, (list, tuple)):
        # mdb_put semantics: last write to a key wins
        items = {k: v for k, v in sorted(items, key=lambda kv: kv[0])}.items()
    nodemax = ((psize - PAGEHDRSZ) // 2) & ~1
    maxkey = nodemax - 8 - 8  # node header + overflow pgno must also fit

    if subdir:
        os.makedirs(path, exist_ok=True)
        data_path = os.path.join(path, "data.mdb")
    else:
        data_path = path

    next_pgno = 2  # 0/1 are the metas
    n_leaf = n_branch = n_over = n_entries = 0
    value_crcs: list[int] = [] if integrity else None
    if integrity:
        from .leveldb_io import crc32c as _crc32c

    with open(data_path, "wb") as f:

        def alloc(n=1):
            nonlocal next_pgno
            pg = next_pgno
            next_pgno += n
            return pg

        def put_page(pgno: int, data: bytes):
            f.seek(pgno * psize)
            f.write(data)

        # ---- leaves (and overflow chains), streamed --------------------
        leaves: list[tuple[bytes, int]] = []  # (first_key, pgno)
        cur: _PageBuf | None = None
        prev_key = None

        def flush_leaf():
            nonlocal cur, n_leaf
            if cur is not None and cur.ptrs:
                put_page(cur.pgno, cur.render())
                n_leaf += 1
            cur = None

        for key, value in items:
            if len(key) > maxkey:
                raise LMDBError(f"key too long ({len(key)} > {maxkey})")
            if prev_key is not None and key <= prev_key:
                if key == prev_key:
                    raise LMDBError(
                        f"duplicate key {key!r} in stream (pass a list to "
                        "get mdb_put last-write-wins semantics)")
                raise LMDBError(
                    "streamed items must have strictly ascending keys "
                    f"({key!r} after {prev_key!r}); pass a list to sort")
            prev_key = key
            n_entries += 1
            if integrity:
                value_crcs.append(_crc32c(value))
            big = None
            if 8 + len(key) + len(value) > nodemax:
                npg = (PAGEHDRSZ + len(value) + psize - 1) // psize
                big = alloc(npg)
                n_over += npg
                ov = bytearray(npg * psize)
                _PAGEHDR.pack_into(ov, 0, big, 0, P_OVERFLOW, 0, 0)
                struct.pack_into("<I", ov, 12, npg)  # mp_pages union
                ov[PAGEHDRSZ: PAGEHDRSZ + len(value)] = value
                put_page(big, bytes(ov))
            node = _leaf_node(key, value, big)
            if cur is None or not cur.add(node):
                flush_leaf()
                cur = _PageBuf(alloc(), P_LEAF, psize)
                leaves.append((key, cur.pgno))
                if not cur.add(node):
                    raise LMDBError("node cannot fit an empty leaf page")
        flush_leaf()

        # ---- branches, bottom-up ---------------------------------------
        level = leaves
        depth = 1 if leaves else 0
        while len(level) > 1:
            nxt: list[tuple[bytes, int]] = []
            buf: _PageBuf | None = None
            for first_key, child in level:
                # node 0 of each branch page carries no key (-inf child)
                key = b"" if buf is None else first_key
                node = _branch_node(key, child)
                if buf is not None and not buf.add(node):
                    put_page(buf.pgno, buf.render())
                    n_branch += 1
                    buf = None
                    node = _branch_node(b"", child)
                if buf is None:
                    buf = _PageBuf(alloc(), P_BRANCH, psize)
                    nxt.append((first_key, buf.pgno))
                    if not buf.add(node):
                        raise LMDBError(
                            "branch node cannot fit an empty page")
            if buf is not None and buf.ptrs:
                put_page(buf.pgno, buf.render())
                n_branch += 1
            level = nxt
            depth += 1

        root = level[0][1] if level else P_INVALID

        # ---- metas (written last: root/counters now known) -------------
        last_pg = next_pgno - 1
        mapsize = next_pgno * psize

        def meta_page(pgno: int, txnid: int) -> bytes:
            buf = bytearray(psize)
            _PAGEHDR.pack_into(buf, 0, pgno, 0, P_META, 0, 0)
            _META.pack_into(buf, PAGEHDRSZ, META_MAGIC, META_VERSION, 0,
                            mapsize)
            base = PAGEHDRSZ + _META.size
            # free DB: empty; md_pad carries the page size (mm_psize)
            _DB.pack_into(buf, base, psize, 0, 0, 0, 0, 0, 0, P_INVALID)
            _DB.pack_into(buf, base + _DB.size, 0, 0, depth, n_branch,
                          n_leaf, n_over, n_entries, root)
            struct.pack_into("<QQ", buf, base + 2 * _DB.size, last_pg,
                             txnid)
            return bytes(buf)

        put_page(0, meta_page(0, 0))
        put_page(1, meta_page(1, 1))
    if integrity:
        write_crc_sidecar(data_path, value_crcs)
    return data_path
