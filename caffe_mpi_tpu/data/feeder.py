"""Batch feeder — host-side prefetch pipeline.

Reference machinery being replaced (SURVEY §2.5): DataReader's reader+parser
threads with per-solver round-robin record distribution
(CursorManager, data_reader.hpp:28-53), BasePrefetchingDataLayer's
transformer threads with free/full Batch queues (base_data_layer.hpp:100-159),
and the GPU-side async batch copy.

TPU-native shape: batches are assembled by a thread pool *ahead of* the
training loop (lookahead window = the free/full queue depth), and the jitted
step overlaps host->HBM transfer with compute because feeds for step N+1 are
device_put while step N runs. Record->rank assignment is a pure index
calculation: global record index for (iteration, slot) is
  it * global_batch + rank * batch + slot  (mod dataset size)
which reproduces CursorManager's deterministic striping without cursors.
Epoch shuffling uses a seed-fixed permutation per epoch (DataCache shuffle,
data_reader.hpp:55-101).

Multi-host (ISSUE 11): under `caffe train -hosts N` the CLI passes
rank = jax.process_index() and world = jax.process_count(), so the same
formula IS the per-host record sharding — disjoint, exhaustive, and a
pure function of (iteration, rank, slot), which keeps crc verification
and quarantine substitution replay-identical on every host and across
supervised restarts. Each host journals quarantines to its own
`<prefix>.quarantine.r<k>.json` (resilience.quarantine_journal_path);
rank 0 merges them at snapshot time.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from ..utils.resilience import (FAULTS, QUARANTINE, DataIntegrityError,
                                RecordIntegrityError, retrying)
from .datasets import Dataset, DecodedCacheDataset
from .transformer import DataTransformer

log = logging.getLogger("caffe_mpi_tpu.feeder")

_LOOKAHEAD_HARD_CAP = 16  # queue-depth ceiling even with RAM to spare
# quarantine plane (ISSUE 4): how many successive substitute records to
# probe past a corrupt one before declaring the neighborhood dead, and
# the distinct-record bound past which corruption counts as systematic
# (dataset-level) rather than record-level
_QUARANTINE_PROBES = 16
_QUARANTINE_MAX_FRACTION = 0.05


class FeedError(RuntimeError):
    """A device feed super-batch failed to assemble. Carries the
    originating (it0, k) chunk so the crash names the exact batch —
    the bare Future exception used to surface with no context (or, in
    the abandoned-hint path, not at all) and the solver stalled."""


def _default_mem_budget() -> int:
    """Host-RAM budget for in-flight batches: 25% of physical memory,
    capped at 2 GiB (the reference sizes its queue from free *GPU*
    memory, data_layer.cpp:66-77; here batches live in host RAM until
    device_put)."""
    try:
        phys = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        phys = 8 << 30
    if phys <= 0:  # sysconf can return -1 for "name known, no value"
        phys = 8 << 30
    return min(phys // 4, 2 << 30)


class Feeder:
    def __init__(self, dataset: Dataset, transformer: DataTransformer | None,
                 batch_size: int, *, rank: int = 0, world: int = 1,
                 shuffle: bool = False, seed: int = 0, threads: int = 0,
                 lookahead: int = 3, to_device=None,
                 top_names: tuple[str, str] = ("data", "label"),
                 device_transform: bool = False,
                 mem_budget: int | None = None):
        """to_device: optional callable(feeds_dict) -> feeds_dict placing
        arrays (e.g. MeshPlan.shard_feeds); applied on the consumer side.
        top_names: blob names for the (image, label) tops — from the data
        layer's prototxt `top:` entries.
        device_transform: stage raw uint8 batches + per-record aug
        decisions instead of transforming on the host — must match the
        consuming Net's DataLayer.dev_transform (the CLI binds both from
        the net; see layers/data_layers.py).
        threads=0 (the prototxt default) enables AUTO mode, mirroring the
        reference's iteration-0 prefetch auto-tuning
        (data_layer.cpp:46-113): worker count defaults to the host core
        count and the lookahead window is re-sized at runtime from the
        measured batch-build time vs the consumer's step time, bounded by
        `mem_budget` bytes of in-flight batches. An explicit threads>0
        pins both knobs (reference: explicit threads+parser_threads)."""
        self.top_names = top_names
        self.ds = dataset
        self.tf = transformer
        self.batch = batch_size
        self.rank = rank
        self.world = world
        self.shuffle = shuffle
        self.seed = seed
        self.lookahead = max(lookahead, 1)
        self.to_device = to_device
        self.auto = threads == 0
        if self.auto:
            threads = min(os.cpu_count() or 2, 8)
        self.threads = max(threads, 1)
        self.device_transform = device_transform
        self.mem_budget = (_default_mem_budget() if mem_budget is None
                           else mem_budget)
        # auto-tune telemetry: build durations (producer side), consumer
        # gaps (time spent OUTSIDE __call__ = the training step), and the
        # realized batch footprint
        self._build_times: deque[float] = deque(maxlen=32)
        self._gaps: deque[float] = deque(maxlen=32)
        self._last_exit: float | None = None
        self._calls = 0
        self._batch_bytes = 0
        # native C++ transform path: engaged when built and the transform is
        # expressible there (no force_color/gray); per-batch uniform-shape
        # uint8 checked at run time, python path as fallback
        self._native = False
        if transformer is not None:
            from .. import native
            tp = transformer.tp
            self._native = (native.available() and not tp.force_color
                            and not tp.force_gray)
        # fused native ingestion (ISSUE 10): for JPEG/PNG-encoded
        # datasets, decode -> crop -> mirror -> mean/scale -> f32 (or
        # decode-only, in device-transform staging mode) runs for the
        # whole batch in ONE ctypes call with the GIL released
        # (native/decode.cc), instead of one PIL decode per record under
        # the interpreter lock. None = undecided until the first batch
        # reveals whether the dataset carries encoded records; False =
        # permanently on the classic path (raw/float datums — bitwise
        # today's behavior, decided once so raw datasets never pay a
        # re-probe).
        self._fused_ok: bool | None = None
        if getattr(dataset, "get_datum", None) is None:
            self._fused_ok = False  # no wire-fields API (synthetic, image
            #                         folder, cached) — per-record path
        elif device_transform:
            pass  # fused decode-only staging fill
        elif transformer is None or not self._native:
            self._fused_ok = False  # transform not expressible natively
        elif (transformer.mean is not None
              and transformer.mean.reshape(-1).size not in (1, 3)):
            # full-image mean needs the per-record crop window at the
            # image's own dims, which vary per encoded record; sizes 1/3
            # broadcast over the decoder's fixed 3 BGR channels. Decided
            # HERE so an inexpressible mean never pays the fused fetch
            # just to bail per batch.
            self._fused_ok = False
        self.pool = ThreadPoolExecutor(max_workers=max(threads, 1))
        self._futures: dict[int, Future] = {}
        self._lock = threading.Lock()
        # batch builds currently executing (pool workers + direct
        # callers) — sizes the fused decode's inner thread count so
        # worker-count x per-call threads never oversubscribes the host
        self._inflight = 0
        n = len(dataset)
        if n == 0:
            raise ValueError("empty dataset")
        self._size = n
        self._perm_cache: dict[int, np.ndarray] = {}
        # quarantine plane: distinct corrupt records substituted so far
        # (set membership drives the bounded-ratio hard failure);
        # guarded by _lock — pool workers quarantine concurrently
        self._quarantined: set[int] = set()
        # rec -> substitute memo: substitution is a pure function of
        # the record index, so after the first discovery later epochs
        # read the substitute directly (no re-read + re-checksum of
        # the known-corrupt record, no re-probing)
        self._sub_cache: dict[int, int] = {}
        self._quarantine_limit = max(4, int(n * _QUARANTINE_MAX_FRACTION))

    # ------------------------------------------------------------------
    def _record_index(self, it: int, slot: int) -> int:
        flat = it * self.batch * self.world + self.rank * self.batch + slot
        epoch, within = divmod(flat, self._size)
        if not self.shuffle:
            return within
        perm = self._perm_cache.get(epoch)
        if perm is None:
            perm = np.random.RandomState(self.seed + epoch).permutation(self._size)
            with self._lock:
                self._perm_cache[epoch] = perm
                # bound the cache
                for k in sorted(self._perm_cache):
                    if k < epoch - 2:
                        del self._perm_cache[k]
        return int(perm[within])

    def _decode_threads(self) -> int:
        """Threads for ONE fused native decode call. An explicitly
        pinned feeder keeps its pin (operator's choice, like the classic
        native transform); auto mode divides the host's cores across the
        builds in flight — 8 workers each spawning 8 decode threads is
        the documented oversubscription collapse, not a speedup."""
        if not self.auto:
            return self.threads
        with self._lock:
            inflight = max(self._inflight, 1)
        return max(1, (os.cpu_count() or 1) // inflight)

    def _build_batch(self, it: int) -> dict[str, np.ndarray]:
        t0 = time.perf_counter()
        with self._lock:
            self._inflight += 1
        try:
            out = self._build_batch_inner(it)
        finally:
            with self._lock:
                self._inflight -= 1
        if self.auto:
            # pool worker threads append concurrently with the consumer's
            # retune scan — both sides take the lock
            with self._lock:
                self._build_times.append(time.perf_counter() - t0)
                if not self._batch_bytes:
                    self._batch_bytes = sum(
                        v.nbytes for v in out.values()
                        if isinstance(v, np.ndarray)) or 1
        return out

    def _read_record(self, rec: int):
        """One dataset read with bounded-backoff retry: transient I/O
        errors (NFS blips, DB cursor hiccups — and the injected
        `feeder_read` fault) are absorbed up to the attempt budget; a
        persistent failure surfaces to the consumer with the record
        named, where the supervisor owns the restart. Integrity
        failures (RecordIntegrityError — crc mismatch, structural DB
        rot, undecodable Datum) are DETERMINISTIC: they bypass the
        retry budget and quarantine instead (_read_record_verified)."""
        def get():
            FAULTS.maybe_raise("feeder_read", OSError,
                               f"injected dataset read fault (record {rec})")
            return self.ds.get(rec)
        return retrying(get, attempts=4, base_delay=0.05,
                        desc=f"dataset read (record {rec})")

    def _fetch_datum(self, rec: int):
        """One wire-fields fetch (no image materialization) with the
        same bounded-backoff retry + fault site as _read_record — the
        fused path's fetch stage."""
        def get():
            FAULTS.maybe_raise("feeder_read", OSError,
                               f"injected dataset read fault (record {rec})")
            return self.ds.get_datum(rec)
        return retrying(get, attempts=4, base_delay=0.05,
                        desc=f"dataset read (record {rec})")

    def _read_record_verified(self, rec: int):
        return self._verified(rec, self._read_record)

    def _verified(self, rec: int, read_fn):
        """Read record `rec` via `read_fn`, quarantining it on an
        integrity failure (ISSUE 4): the substitute is the next healthy
        record by index — `(rec + probe) % size`, probe = 1.. — a pure
        function of `rec` (itself a pure function of the iteration
        index), so a resumed or replayed run makes IDENTICAL
        substitution decisions and stays iteration-exact. Each newly
        quarantined record is journaled to `<prefix>.quarantine.json`;
        more than `_quarantine_limit` distinct corrupt records (or a
        fully corrupt probe window) is systematic corruption and raises
        DataIntegrityError — a hard, named failure instead of silently
        training on substitutes.

        `read_fn` is _read_record (full materialization) or
        _fetch_datum (wire fields only, the fused path — decode
        failures there re-enter through _read_record_verified, which
        may add the rotten substitute to the journal one step later
        than the classic path would have; the substitution function
        itself is identical)."""
        sub = self._sub_cache.get(rec)
        if sub is not None:
            # recurse: if the memoized substitute has ITSELF rotted
            # since, it gets quarantined like any primary record
            # (depth bounded by the quarantine limit)
            return self._verified(sub, read_fn)
        try:
            return read_fn(rec)
        except RecordIntegrityError as first:
            src = getattr(self.ds, "path", "") or type(self.ds).__name__
            with self._lock:
                self._quarantined.add(rec)
                n_bad = len(self._quarantined)
            if n_bad > self._quarantine_limit:
                raise DataIntegrityError(
                    f"{n_bad} distinct corrupt records in {src} exceeds "
                    f"the quarantine bound ({self._quarantine_limit} = "
                    f"{_QUARANTINE_MAX_FRACTION:.0%} of {self._size}); "
                    "corruption is systematic — regenerate the dataset "
                    f"(first failure: {first})") from first
            for probe in range(1, _QUARANTINE_PROBES + 1):
                sub = (rec + probe) % self._size
                try:
                    out = read_fn(sub)
                except RecordIntegrityError as e:
                    with self._lock:
                        self._quarantined.add(sub)
                    # probe casualties count toward the systematic
                    # bound, so they must appear in the audit journal
                    # too (substitute -1 = "skipped during probing")
                    QUARANTINE.record(src, sub, -1, e.reason)
                    continue
                QUARANTINE.record(src, rec, sub, first.reason)
                with self._lock:
                    self._sub_cache[rec] = sub
                return out
            raise DataIntegrityError(
                f"records {rec}..{(rec + _QUARANTINE_PROBES) % self._size}"
                f" of {src} are ALL corrupt ({_QUARANTINE_PROBES + 1} "
                "consecutive); corruption is systematic — regenerate "
                f"the dataset (first failure: {first})") from first

    def _assemble(self, raws: list[np.ndarray], labels: list[int],
                  flats: list[int]) -> dict[str, np.ndarray]:
        """Shared batch tail: transform/stage + label top."""
        if self.device_transform:
            out = self._raw_batch(raws, flats)
        else:
            out = {self.top_names[0]: self._transform(raws, flats)}
        if len(self.top_names) > 1:
            out[self.top_names[1]] = np.asarray(labels, np.int32)
        return out

    def _build_batch_inner(self, it: int) -> dict[str, np.ndarray]:
        if self._fused_ok is not False:
            from . import decode as _decode
            if _decode.native_enabled():
                out = self._build_batch_fused(it)
                if out is not None:
                    return out
        raws, labels, flats = [], [], []
        for slot in range(self.batch):
            rec = self._record_index(it, slot)
            img, label = self._read_record_verified(rec)
            raws.append(img)
            labels.append(label)
            flats.append(it * self.batch * self.world
                         + self.rank * self.batch + slot)
        return self._assemble(raws, labels, flats)

    # -- fused native ingestion (ISSUE 10) ------------------------------
    def _build_batch_fused(self, it: int) -> dict[str, np.ndarray] | None:
        """Batch build for encoded datasets: fetch verified wire fields
        per record, then decode JPEG/PNG payloads for the WHOLE batch in
        one GIL-released native call — fused with the transform
        (host-transform mode) or decoding straight into the uniform
        uint8 staging stack (device-transform mode). Cache hits
        (DecodedCacheDataset) skip decode entirely; records the native
        decoder declines fall back one-at-a-time through the classic
        read path, which owns PIL fallback and quarantine. Augmentation
        keys (seed ^ flat-index splitmix64) and the transform arithmetic
        are shared with the classic native path (transform_core.h), so
        engagement changes WHICH decoder ran, never the aug decisions or
        the record->rank striping.

        Returns None exactly once, when the first batch shows the
        dataset has no encoded records — then the Feeder pins itself to
        the classic path (`_fused_ok = False`) and never re-probes."""
        from . import decode as _decode
        from .. import native

        cache = self.ds if isinstance(self.ds, DecodedCacheDataset) else None
        recs, flats = [], []
        for slot in range(self.batch):
            recs.append(self._record_index(it, slot))
            flats.append(it * self.batch * self.world
                         + self.rank * self.batch + slot)
        # per slot: ("enc", jpeg/png bytes, label) | ("arr", CHW, label)
        entries: list[tuple] = []
        for rec in recs:
            hit = cache.lookup(rec) if cache is not None else None
            if hit is not None:
                entries.append(("arr", hit[0], hit[1]))
                continue
            fields = self._verified(rec, self._fetch_datum)
            if fields.encoded:
                entries.append(("enc", fields.data, fields.label))
            else:
                # raw/float datum: materialize in place (identical to
                # what ds.get(rec) would have returned)
                from .datasets import materialize_datum
                try:
                    arr, label = materialize_datum(fields)
                except Exception:
                    arr, label = self._read_record_verified(rec)
                entries.append(("arr", arr, label))
        if self._fused_ok is None:
            self._fused_ok = any(e[0] == "enc" for e in entries)
            if not self._fused_ok:
                # not an encoded dataset: assemble this batch from the
                # already-fetched records (bitwise-identical tail) and
                # stay classic forever
                return self._assemble([e[1] for e in entries],
                                      [e[2] for e in entries], flats)
        enc = [i for i, e in enumerate(entries) if e[0] == "enc"]
        if self.device_transform:
            out = self._fused_staging(entries, enc, recs, flats, cache)
        else:
            out = self._fused_transform(entries, enc, recs, flats, cache)
        if out is not None and enc:
            # fused_records is counted per SUCCESSFUL record inside the
            # helpers (statuses in hand) — a declined record must show
            # up as a PIL fallback, not a native decode, or the
            # --require-native-decode assertion would pass on a run
            # that silently fell back wholesale
            _decode.STATS.count("fused_batches")
        return out

    def _fallback_record(self, slot_rec: int):
        """Per-record fallback for payloads the native decoder declined
        (exotic variant or corrupt bytes): the classic verified read
        decodes via PIL and owns quarantine."""
        from . import decode as _decode
        _decode.STATS.count("fused_fallback_records")
        return self._read_record_verified(slot_rec)

    def _fused_transform(self, entries, enc, recs, flats, cache):
        """Host-transform mode: one native call decodes + transforms all
        encoded slots into their f32 rows (per-record decoded dims may
        vary when cropping — the C side crops each at its own size)."""
        from .. import native
        if not enc:
            # nothing to decode (all cache hits / raw slots): the classic
            # tail IS the fast path — one native transform_batch over the
            # stacked uint8 records, no staging array or scatter
            return self._assemble([e[1] for e in entries],
                                  [e[2] for e in entries], flats)
        tf = self.tf
        crop = tf.tp.crop_size
        n = len(entries)
        labels = [e[2] for e in entries]
        if crop:
            oh = ow = crop
        else:
            # no crop: output dims = decoded dims, which must be uniform
            first = entries[0]
            if first[0] == "arr":
                oh, ow = first[1].shape[-2:]
            else:
                dims = native.decode_probe(first[1])
                if dims is None:
                    arr, labels[0] = self._fallback_record(recs[0])
                    entries[0] = ("arr", arr, labels[0])
                    oh, ow = arr.shape[-2:]
                else:
                    oh, ow = dims
            enc = [i for i in enc if entries[i][0] == "enc"]
        mean = tf.mean
        if mean is not None:
            mean = mean.reshape(-1)  # per-channel (c,1,1)/(c,) -> (c,)
            if mean.size == 1:
                # single mean_value applies to every channel (reference
                # data_transformer.cpp: mean_values_ repeated)
                mean = np.repeat(mean, 3)
        out = np.empty((n, 3, oh, ow), np.float32)
        seed = tf.seed or 0
        train = tf.phase == "TRAIN"
        if enc:
            bufs = [entries[i][1] for i in enc]
            ids = np.asarray([flats[i] for i in enc], np.int64)
            # whole-batch encoded (the common case): the C call writes
            # each record's f32 row straight into `out` — no staging
            # array, no scatter copy. Mixed batches (cache hits / raw
            # slots interleaved) stage the encoded subset and scatter.
            whole = len(enc) == n
            enc_out = out if whole else np.empty((len(enc), 3, oh, ow),
                                                 np.float32)
            decoded = None
            if cache is not None and cache.admitting():
                decoded = []
                for b in bufs:
                    dims = native.decode_probe(b)
                    decoded.append(None if dims is None else
                                   np.empty((3, *dims), np.uint8))
            status = native.decode_transform_batch(
                bufs, ids, crop=crop, mean=mean, scale=tf.tp.scale,
                train=train, mirror=tf.tp.mirror, seed=seed,
                out_h=oh, out_w=ow, out=enc_out, decoded_out=decoded,
                num_threads=self._decode_threads())
            from . import decode as _decode
            for k, i in enumerate(enc):
                if status[k] == native.DECODE_OK:
                    _decode.STATS.count("fused_records")
                    if not whole:
                        out[i] = enc_out[k]
                    if decoded is not None and decoded[k] is not None:
                        cache.insert(recs[i], decoded[k], labels[i])
                else:
                    # failed rows left garbage in `out`; the fallback
                    # re-read below rewrites them via the "arr" pass
                    arr, labels[i] = self._fallback_record(recs[i])
                    entries[i] = ("arr", arr, labels[i])
        # cache hits, raw records, and fallbacks: the classic transform
        # (native batch call per uniform-shape group, python otherwise)
        rest = [i for i in range(len(entries)) if entries[i][0] == "arr"]
        if rest:
            shapes = {entries[i][1].shape for i in rest}
            dtypes = {entries[i][1].dtype for i in rest}
            if len(shapes) == 1 and dtypes == {np.dtype(np.uint8)}:
                rows = self._transform([entries[i][1] for i in rest],
                                       [flats[i] for i in rest])
                for k, i in enumerate(rest):
                    out[i] = rows[k]
            else:
                for i in rest:
                    out[i] = self._transform([entries[i][1]], [flats[i]])[0]
        res = {self.top_names[0]: out}
        if len(self.top_names) > 1:
            res[self.top_names[1]] = np.asarray(labels, np.int32)
        return res

    def _fused_staging(self, entries, enc, recs, flats, cache):
        """Device-transform mode: decode encoded slots straight into the
        uniform uint8 staging stack (the in-graph transform consumes raw
        records + aug decisions; reference use_gpu_transform)."""
        from .. import native
        from .device_transform import aug_key, compute_aug
        n = len(entries)
        labels = [e[2] for e in entries]
        first = entries[0]
        if first[0] == "arr":
            shape = first[1].shape
        else:
            dims = native.decode_probe(first[1])
            if dims is None:
                arr, labels[0] = self._fallback_record(recs[0])
                entries[0] = ("arr", arr, labels[0])
                shape = arr.shape
            else:
                shape = (3, *dims)
            enc = [i for i in enc if entries[i][0] == "enc"]
        if len(shape) != 3 or shape[0] != 3:
            return None  # encoded records decode to 3xHxW; mismatch ->
            #              classic path handles (and errors) as before
        stack = np.empty((n, *shape), np.uint8)
        if enc:
            bufs = [entries[i][1] for i in enc]
            ids = np.asarray([flats[i] for i in enc], np.int64)
            status = native.decode_transform_batch(
                bufs, ids, out_h=shape[1], out_w=shape[2], out=None,
                decoded_out=[stack[i] for i in enc],
                num_threads=self._decode_threads())
            from . import decode as _decode
            for k, i in enumerate(enc):
                if status[k] != native.DECODE_OK:
                    arr, labels[i] = self._fallback_record(recs[i])
                    entries[i] = ("arr", arr, labels[i])
                else:
                    _decode.STATS.count("fused_records")
                    if cache is not None and cache.admitting():
                        cache.insert(recs[i], stack[i].copy(), labels[i])
        for i in range(n):
            kind, payload = entries[i][0], entries[i][1]
            if kind == "arr":
                if payload.shape != shape or payload.dtype != np.uint8:
                    raise ValueError(
                        "device transform requires uniform uint8 records; "
                        "set transform_param { use_gpu_transform: false } "
                        "for this dataset")
                stack[i] = payload
        aug = compute_aug(self.tf, flats, shape[-2:], n)
        res = {self.top_names[0]: stack,
               aug_key(self.top_names[0]): aug}
        if len(self.top_names) > 1:
            res[self.top_names[1]] = np.asarray(labels, np.int32)
        return res

    def _raw_batch(self, raws: list[np.ndarray], flats: list[int]) -> dict:
        """Device-transform staging: uint8 stack + (B,3) aug decisions
        (same per-record Philox streams as the host transform)."""
        from .device_transform import aug_key, compute_aug
        first = raws[0]
        if first.dtype != np.uint8 or any(
                r.shape != first.shape or r.dtype != np.uint8 for r in raws):
            raise ValueError(
                "device transform requires uniform uint8 records; set "
                "transform_param { use_gpu_transform: false } for this "
                "dataset")
        aug = compute_aug(self.tf, flats, first.shape[-2:], len(raws))
        return {self.top_names[0]: np.stack(raws),
                aug_key(self.top_names[0]): aug}

    def _transform(self, raws: list[np.ndarray], flats: list[int]) -> np.ndarray:
        tf = self.tf
        if tf is None:
            # raws are host ndarrays from the dataset reader, never
            # device values; no RTT is paid here
            # host-sync: ok
            return np.stack([np.asarray(r, np.float32) for r in raws])
        if (self._native and raws[0].dtype == np.uint8
                and all(r.shape == raws[0].shape for r in raws)):
            from .. import native
            mean = tf.mean
            if mean is not None and mean.ndim == 3 and mean.shape[1] == 1:
                mean = mean.reshape(-1)  # per-channel (c,1,1) -> (c,)
                if mean.size == 1 and raws[0].shape[0] > 1:
                    # single mean_value broadcasts over channels
                    # (reference data_transformer.cpp); the C kernel
                    # indexes mean[ch], so repeat instead of letting it
                    # read past a 1-float buffer
                    mean = np.repeat(mean, raws[0].shape[0])
            return native.transform_batch(
                np.stack(raws), np.asarray(flats, np.int64),
                crop=tf.tp.crop_size, mean=mean, scale=tf.tp.scale,
                train=(tf.phase == "TRAIN"), mirror=tf.tp.mirror,
                seed=tf.seed or 0, num_threads=self.threads)
        # python reference path: per-record Philox RNG — deterministic
        # augmentation independent of thread scheduling
        return np.stack([tf(r, rng=tf.record_rng(f))
                         for r, f in zip(raws, flats)])

    # ------------------------------------------------------------------
    def _maybe_retune(self) -> None:
        """Reference data_layer.cpp:46-113 sizes parser/transformer thread
        counts once, at iteration 0, from free GPU memory and net cost.
        Here the analogue is the lookahead window (= number of batches
        built concurrently by the pool): need supply rate >= demand rate,
        i.e. lookahead >= build_time / step_time, re-measured at runtime
        and clamped by the host-RAM budget for in-flight batches."""
        with self._lock:
            builds = list(self._build_times)
            bytes_ = self._batch_bytes
        if len(builds) < 5 or len(self._gaps) < 5:
            return
        build = sorted(builds)[len(builds) // 2]
        gap = sorted(self._gaps)[len(self._gaps) // 2]
        want = math.ceil(build / max(gap, 1e-4)) + 1
        cap = _LOOKAHEAD_HARD_CAP
        if bytes_:
            cap = min(cap, max(int(self.mem_budget // bytes_) - 1, 1))
        want = min(max(want, 1), cap)
        if want != self.lookahead:
            log.info("prefetch auto-tune: lookahead %d -> %d "
                     "(build %.1f ms vs step %.1f ms, batch %.1f MiB, "
                     "budget %.0f MiB)", self.lookahead, want, build * 1e3,
                     gap * 1e3, bytes_ / 2**20,
                     self.mem_budget / 2**20)
            self.lookahead = want

    def __call__(self, it: int) -> dict:
        """feed_fn protocol: return the batch for micro-iteration `it`,
        scheduling lookahead batches in the background."""
        if self.auto:
            now = time.perf_counter()
            self._calls += 1
            if self._last_exit is not None and self._calls > 2:
                # skip the first couple of gaps — jit compilation noise
                self._gaps.append(now - self._last_exit)
            # first tune as soon as the warmup window fills, then
            # periodically (datasets and step times can change phase)
            if self._calls >= 8 and (self._calls == 8
                                     or self._calls % 64 == 0):
                self._maybe_retune()
        with self._lock:
            for ahead in range(it, it + self.lookahead + 1):
                if ahead not in self._futures:
                    self._futures[ahead] = self.pool.submit(self._build_batch,
                                                            ahead)
            fut = self._futures.pop(it)
            # drop stale entries (resume/seek) and, when a retune SHRANK
            # the window, best-effort cancel batches scheduled beyond it —
            # otherwise in-flight memory transiently exceeds mem_budget by
            # the old window size. Rebuild-on-demand is safe: batches are
            # pure functions of their index (_record_index + Philox). A
            # future that is already RUNNING can't be cancelled; it is
            # popped anyway (its memory frees when the build finishes) but
            # gets a done-callback so an exception it raises is logged
            # rather than silently swallowed with the dropped handle.
            for k in [k for k in self._futures
                      if k < it or k > it + self.lookahead]:
                dropped = self._futures.pop(k)
                if not dropped.cancel():
                    dropped.add_done_callback(self._log_abandoned)
        feeds = fut.result()
        if self.to_device is not None:
            feeds = self.to_device(feeds)
        if self.auto:
            self._last_exit = time.perf_counter()
        return feeds

    @staticmethod
    def _log_abandoned(fut) -> None:
        exc = None if fut.cancelled() else fut.exception()
        if exc is not None:
            log.warning("abandoned prefetch batch raised: %r", exc)

    def close(self) -> None:
        self.pool.shutdown(wait=False, cancel_futures=True)


class DeviceFeedQueue:
    """Double-buffered DEVICE-resident super-batch queue — the feed side
    of the K-step fused train loop (solver.step_chunk > 1).

    The host-side pipeline above (Feeder) overlaps batch ASSEMBLY with
    the train step but still hands the solver one host batch per
    iteration, costing one dispatch each. This queue extends the
    lookahead to the device: `get(it0, k)` returns a stacked feeds
    pytree with leaves [k, iter_size, ...] already `device_put` (or
    mesh-sharded), and a single worker thread assembles + transfers the
    NEXT super-batch (the `hint`) while the current k-iteration scan
    chunk runs on the chip — so host->HBM transfer hides behind compute,
    the way the reference hides its NCCL allreduce behind backprop
    (parallel.cpp:166-169), but for the input stream.

    Super-batches are pure functions of (it0, k) — the underlying
    feed_fn is indexed (Feeder's deterministic record striping) — so a
    mispredicted hint is dropped and rebuilt with no correctness cost.
    """

    def __init__(self, feed_fn, *, iter_size: int = 1, place=None):
        """place: optional callable(stacked_pytree) -> device pytree
        (e.g. MeshPlan.shard_feeds at batch_axis=2); default is a plain
        jax.device_put."""
        self.feed_fn = feed_fn
        self.iter_size = max(iter_size, 1)
        self.place = place
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="device-feed")
        self._pending: dict[tuple[int, int], Future] = {}

    def _build(self, it0: int, k: int):
        import jax
        import jax.numpy as jnp
        isz = self.iter_size
        micros = [self.feed_fn(m)
                  for m in range(it0 * isz, (it0 + k) * isz)]

        def stack(*leaves):
            if all(isinstance(x, np.ndarray) for x in leaves):
                arr = np.stack(leaves)  # one host copy, then one transfer
            else:
                # device-resident feeds (synthetic benches): stack on
                # device, never pulling them back to host
                arr = jnp.stack([jnp.asarray(x) for x in leaves])
            return arr.reshape((k, isz) + arr.shape[1:])

        tree = jax.tree.map(stack, *micros)
        if self.place is not None:
            return self.place(tree)
        return jax.device_put(tree)

    def prefetch(self, it0: int, k: int) -> None:
        """Schedule (it0, k) on the worker WITHOUT blocking — the
        test-boundary warmup path (solver._prefetch_test_feeds): the
        eval pass's first super-batch assembles and device_puts while
        the train chunk that ends at the boundary is still computing,
        so the boundary itself only pays the dispatch."""
        if (it0, k) not in self._pending:
            self._pending[(it0, k)] = self._pool.submit(self._build, it0, k)

    def ready(self, it0: int, k: int) -> bool:
        """True when (it0, k) is assembled and a get() would not block
        — the solver's opportunistic eval-chunk dispatch asks this
        between train chunks. Schedules the build if it wasn't pending,
        so polling converges."""
        self.prefetch(it0, k)
        return self._pending[(it0, k)].done()

    def get(self, it0: int, k: int, hint: tuple[int, int] | None = None):
        """Super-batch for iterations [it0, it0+k); schedules `hint`
        (the next chunk's (it0, k)) on the worker before blocking."""
        fut = self._pending.pop((it0, k), None)
        if fut is None:
            fut = self._pool.submit(self._build, it0, k)
        if hint is not None and hint != (it0, k) and hint not in self._pending:
            self._pending[hint] = self._pool.submit(self._build, *hint)
        try:
            feeds = fut.result()
        except Exception as e:
            # name the chunk: the worker's traceback alone says nothing
            # about WHICH super-batch died, and a swallowed error here
            # used to leave the solver waiting on a future that would
            # never resolve usefully
            raise FeedError(
                f"feed super-batch for iterations [{it0}, {it0 + k}) "
                f"(it0={it0}, k={k}) failed to assemble: {e!r}") from e
        # drop stale prefetches (resume/seek or a schedule change): they
        # are pure functions of their indices, rebuild-on-demand is safe
        for key in [key for key in self._pending if key != hint]:
            dropped = self._pending.pop(key)
            if not dropped.cancel():
                dropped.add_done_callback(Feeder._log_abandoned)
        return feeds

    def close(self) -> None:
        for fut in self._pending.values():
            fut.cancel()
        self._pending.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)


def feeder_from_layer(lp, phase: str, *, rank: int = 0, world: int = 1,
                      model_dir: str = "",
                      device_transform: bool = False,
                      solver_param=None) -> Feeder:
    """Build a Feeder from a Data/ImageData layer's prototxt config — the
    runner-side binding for DB-backed layers (reference
    DataLayer::LayerSetUp, data_layer.cpp:118-180). device_transform must
    be the consuming net's DataLayer.dev_transform. solver_param (when
    given) supplies run-level ingestion knobs: `decoded_cache_mb` > 0
    wraps the dataset in the bounded decoded-record cache tier
    (ISSUE 10, datasets.DecodedCacheDataset) unless the layer already
    opted into the whole-DB cache."""
    import os

    from .datasets import ImageFolderDataset, open_dataset

    tp = lp.transform_param
    tf = DataTransformer(tp, phase, model_dir=model_dir)
    tops = tuple(lp.top)
    cache_mb = float(getattr(solver_param, "decoded_cache_mb", 0.0) or 0.0)
    if cache_mb < 0:
        # loud, like every sibling knob (reduce_buckets/serve_* reject
        # negatives at init) — a typo'd budget must not silently
        # disable the cache
        raise ValueError(f"decoded_cache_mb must be >= 0, got {cache_mb}")
    if lp.type == "Data":
        p = lp.data_param
        ds = open_dataset(str(p.backend), os.path.join(model_dir, p.source))
        if p.cache:  # whole-DB RAM cache (reference data_param.cache)
            from .datasets import CachedDataset
            ds = CachedDataset(ds)
        elif cache_mb > 0:
            ds = DecodedCacheDataset(ds, cache_mb)
        shuffle = bool(p.shuffle) and phase == "TRAIN"
        # threads=0 (prototxt default) -> auto mode; prefetch seeds the
        # initial lookahead window (reference data_param.prefetch)
        return Feeder(ds, tf, p.batch_size, rank=rank, world=world,
                      shuffle=shuffle, top_names=tops,
                      threads=p.threads, lookahead=max(p.prefetch, 1),
                      device_transform=device_transform)
    if lp.type == "ImageData":
        p = lp.image_data_param
        ds = ImageFolderDataset(os.path.join(model_dir, p.source),
                                root=p.root_folder,
                                new_height=p.new_height, new_width=p.new_width,
                                is_color=p.is_color)
        return Feeder(ds, tf, p.batch_size, rank=rank, world=world,
                      shuffle=bool(p.shuffle) and phase == "TRAIN",
                      top_names=tops)
    raise ValueError(f"not a pipeline data layer: {lp.type}")


class ProbeShape(tuple):
    """Post-transform (C,H,W) that also remembers the raw record shape —
    the device-transform path needs both (the feed is the raw uint8
    record; the top blob is the transformed shape)."""

    raw: tuple | None = None

    def __new__(cls, shape, raw=None):
        self = super().__new__(cls, shape)
        self.raw = raw
        return self


def data_shape_probe(lp, model_dir: str = ""):
    """Open the dataset once to discover record shape, returning the
    post-transform (C,H,W) — the Net-side binding for Data layers
    (reference: DataLayer reads one sample in LayerSetUp). For uniform
    uint8 datasets the result carries `.raw`, enabling the in-graph
    transform path."""
    import os as _os

    from .datasets import open_dataset

    if lp.type == "Data":
        ds = open_dataset(str(lp.data_param.backend),
                          _os.path.join(model_dir, lp.data_param.source))
        img, _ = ds.get(0)
        tf = DataTransformer(lp.transform_param, "TEST", model_dir=model_dir)
        raw = tuple(img.shape) if img.dtype == np.uint8 else None
        if raw is not None:
            # the in-graph transform needs a uniform record shape; sample
            # records spread across the DB (a full scan would read the
            # whole dataset) — mixed-size layouts fall back to the host
            # path, which crops every record to a common shape
            n = len(ds)
            for i in {n // 2, n - 1, *range(1, min(n, 8))}:
                rec, _ = ds.get(int(i))
                if rec.shape != img.shape or rec.dtype != np.uint8:
                    raw = None
                    break
        return ProbeShape(tf.output_shape(img.shape), raw=raw)
    if lp.type == "HDF5Data":
        import h5py
        src = _os.path.join(model_dir, lp.hdf5_data_param.source)
        files = _h5_list_files(src)
        with h5py.File(files[0], "r") as h5:
            return [tuple(h5[top].shape[1:]) for top in lp.top]
    raise ValueError(f"no shape probe for layer type {lp.type}")


def _h5_list_files(source: str) -> list[str]:
    """Resolve an HDF5 source list: each line is a path, absolute or
    relative to the list file's directory (reference hdf5_data_layer.cpp)."""
    import os as _os
    base = _os.path.dirname(source)
    out = []
    with open(source) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(line if _os.path.isabs(line)
                           else _os.path.join(base, line))
    if not out:
        raise ValueError(f"{source}: empty HDF5 source list")
    return out


class HDF5Feeder:
    """Feeds batches from HDF5 files listed in a source file (reference
    hdf5_data_layer.cpp: datasets named by the layer's top blobs).

    STREAMING, file-at-a-time like the reference (LoadHDF5FileData loads
    one file, advances to the next when exhausted): peak RSS is bounded by
    the two largest files (a batch may straddle a boundary), never the
    whole dataset — an ImageNet-scale HDF5 source streams through. With
    shuffle, the file ORDER is re-drawn per epoch and rows are permuted
    per (epoch, file), mirroring the reference's file_permutation_ +
    data_permutation_ pair."""

    _CACHE_FILES = 2  # current + boundary-straddling neighbor

    def __init__(self, lp, *, model_dir: str = "", rank: int = 0,
                 world: int = 1, seed: int = 1701):
        import h5py
        import os as _os
        p = lp.hdf5_data_param
        self.batch = p.batch_size
        self.tops = list(lp.top)
        self.rank, self.world = rank, world
        self.shuffle = bool(p.shuffle)
        self.seed = seed
        self.files = _h5_list_files(_os.path.join(model_dir, p.source))
        # shape/dtype scan only — no data read until a batch needs it, but
        # every file must agree on tops, dtypes, and row shapes NOW (a
        # mismatch discovered mid-epoch would silently change the jitted
        # step's input dtype or KeyError long into training)
        self.lengths = []
        sig: dict[str, tuple] | None = None
        for path in self.files:
            with h5py.File(path, "r") as h5:
                missing = [t for t in self.tops if t not in h5]
                if missing:
                    raise ValueError(f"{path}: missing dataset(s) {missing}")
                this = {t: (h5[t].dtype, tuple(h5[t].shape[1:]))
                        for t in self.tops}
                if sig is None:
                    sig = this
                elif this != sig:
                    raise ValueError(
                        f"{path}: dtype/shape {this} differs from first "
                        f"file's {sig}")
                self.lengths.append(len(h5[self.tops[0]]))
        self.n = sum(self.lengths)
        self.lengths = np.asarray(self.lengths)
        self._sig = sig
        self._cache: dict[int, dict[str, np.ndarray]] = {}  # file -> arrays
        self._cache_order: list[int] = []
        # epoch layout (file order + cumulative bounds + row perms)
        # memoized for the CURRENT epoch only, like the reference's
        # file_permutation_/data_permutation_ pair
        self._layout_epoch = -1
        self._order: np.ndarray | None = None
        self._cum: np.ndarray | None = None
        self._row_perms: dict[int, np.ndarray] = {}

    # -- index plumbing ---------------------------------------------------
    def _epoch_layout(self, epoch: int):
        """(file order, cumulative end positions) for one epoch."""
        if epoch != self._layout_epoch:
            self._layout_epoch = epoch
            self._order = (np.random.RandomState(
                self.seed + epoch).permutation(len(self.files))
                if self.shuffle else np.arange(len(self.files)))
            self._cum = np.cumsum(self.lengths[self._order])
            self._row_perms = {}
        return self._order, self._cum

    def _row_perm(self, epoch: int, fi: int) -> np.ndarray:
        perm = self._row_perms.get(fi)
        if perm is None:
            perm = np.random.RandomState(
                (self.seed * 31 + epoch * 7919 + fi) % (2**32)).permutation(
                    int(self.lengths[fi]))
            self._row_perms[fi] = perm
        return perm

    def _file_arrays(self, fi: int) -> dict[str, np.ndarray]:
        arrays = self._cache.get(fi)
        if arrays is None:
            import h5py
            with h5py.File(self.files[fi], "r") as h5:
                # h5py datasets are host-side; this is the file read
                # itself, not a device materialization
                # host-sync: ok
                arrays = {t: np.asarray(h5[t]) for t in self.tops}
            self._cache[fi] = arrays
            self._cache_order.append(fi)
            while len(self._cache_order) > self._CACHE_FILES:
                self._cache.pop(self._cache_order.pop(0), None)
        return arrays

    def __call__(self, it: int) -> dict[str, np.ndarray]:
        flats = (it * self.batch * self.world + self.rank * self.batch
                 + np.arange(self.batch))
        epochs = flats // self.n
        within = flats % self.n
        # vectorized (epoch, within) -> (file, row): searchsorted over the
        # epoch's cumulative file bounds — O(batch log n_files), no
        # per-sample Python scan
        fis = np.empty(self.batch, np.int64)
        rows = np.empty(self.batch, np.int64)
        for ep in np.unique(epochs):
            m = epochs == ep
            order, cum = self._epoch_layout(int(ep))
            pos = np.searchsorted(cum, within[m], side="right")
            fi = order[pos]
            rows_in = within[m] - (cum[pos] - self.lengths[fi])
            if self.shuffle:
                for f in np.unique(fi):
                    fm = fi == f
                    rows_in[fm] = self._row_perm(int(ep), int(f))[rows_in[fm]]
            fis[m] = fi
            rows[m] = rows_in
        # one fancy-index COPY per spanned file (rows grouped by file):
        # no views pin evicted cache entries, so peak RSS really is
        # bounded by the cached files plus the batch itself
        out = {t: np.empty((self.batch, *self._sig[t][1]), self._sig[t][0])
               for t in self.tops}
        for fi in np.unique(fis):
            m = fis == fi
            arrays = self._file_arrays(int(fi))
            for t in self.tops:
                out[t][m] = arrays[t][rows[m]]
        return out

    def close(self) -> None:
        self._cache.clear()
        self._cache_order.clear()
