"""In-graph (device-side) data augmentation — crop/mean/mirror/scale fused
into the jitted step.

Reference: src/caffe/data_transformer.cu (TransformKernel: one CUDA thread
per output element applying crop window, mean subtraction, mirror and
scale on the GPU) and include/caffe/layers/base_data_layer.hpp:111-116
(`use_gpu_transform`, default-on for fp16 forward types): the reference
moves the transform to the accelerator because the host cannot feed a fast
chip. The TPU-native equivalent stages the *uint8* batch to HBM (4x less
host->device traffic than transformed f32, and the tunnel/PCIe is the
scarce resource) together with a tiny (B,3) int32 tensor of augmentation
decisions, and performs crop + mean + mirror + scale inside the jitted
train step where XLA fuses them into the first conv's input pipeline.

The augmentation DECISIONS stay on the host: they come from the same
per-record Philox streams as the host DataTransformer (transformer.py), so
the device path is bit-compatible with the host path and deterministic
regardless of which path runs — this mirrors how the reference keeps
curand out of it and draws on the CPU (data_transformer.cpp Rand) while
transforming on the GPU.

Operation order matches the host/reference exactly:
  out = mirror(crop(img) - crop(mean)) * scale
(the mean window is the unmirrored crop window; mirroring happens after
subtraction — data_transformer.cpp Transform).
"""

from __future__ import annotations

import numpy as np

AUG_FIELDS = 3  # off_h, off_w, mirror — per-record int32

def aug_key(top: str) -> str:
    """Feed-dict key for a data top's augmentation decisions."""
    return f"{top}__aug"


def compute_aug(tf, flats, in_hw, batch: int) -> np.ndarray:
    """Host-side decision kernel: (B,3) int32 [off_h, off_w, mirror].

    `tf` is the host DataTransformer; draws replay its exact RNG call
    sequence (off_h, off_w, then mirror, from the per-record Philox
    stream), so device and host transforms of the same record agree."""
    tp = tf.tp
    h, w = in_hw
    crop = tp.crop_size
    train = tf.phase == "TRAIN"
    out = np.zeros((batch, AUG_FIELDS), np.int32)
    if crop and not train:
        out[:, 0] = (h - crop) // 2
        out[:, 1] = (w - crop) // 2
    draws_needed = train and (crop or tp.mirror)
    if draws_needed:
        for i, flat in enumerate(flats):
            rng = tf.record_rng(int(flat))
            if crop:
                out[i, 0] = rng.integers(0, h - crop + 1)
                out[i, 1] = rng.integers(0, w - crop + 1)
            if tp.mirror:
                out[i, 2] = rng.integers(2)
    return out


def device_transform(raw, aug, *, crop: int, mean, scale: float):
    """The jittable transform: raw (B,C,H,W) uint8, aug (B,3) int32 ->
    (B,C,crop,crop) float32 (or (B,C,H,W) without crop).

    mean: None, a per-channel (C,1,1) array, or a full-size (C,H,W) array
    (cropped at the same per-record window, like the reference's
    mean_file path). Closed over as a compile-time constant."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    b, c, h, w = raw.shape
    if crop:
        def crop_one(img, oh, ow):
            return lax.dynamic_slice(img, (0, oh, ow), (c, crop, crop))
        x = jax.vmap(crop_one)(raw, aug[:, 0], aug[:, 1])
    else:
        x = raw
    x = x.astype(jnp.float32)

    if mean is not None:
        m = jnp.asarray(mean, jnp.float32)
        if crop and m.ndim == 3 and m.shape[-2:] == (h, w):
            def crop_mean(oh, ow):
                return lax.dynamic_slice(m, (0, oh, ow), (c, crop, crop))
            x = x - jax.vmap(crop_mean)(aug[:, 0], aug[:, 1])
        else:
            x = x - m  # (C,1,1) channel means broadcast; or full, no crop

    mirrored = x[..., ::-1]
    x = jnp.where(aug[:, 2, None, None, None] > 0, mirrored, x)

    if scale != 1.0:
        x = x * scale
    return x


def wants_device_transform(lp) -> bool:
    """Resolve the per-layer device-transform request.

    Mirrors base_data_layer.hpp:111-116: an explicit
    transform_param.use_gpu_transform wins; unset defaults to ON (the
    reference defaults on only for fp16 forward types — on TPU the fused
    path is the right default whenever it is expressible).
    force_color/force_gray change the channel count on the host decode
    side and stay host-only, as in the reference (encoded datums force
    copy_to_cpu, data_layer.cpp:243)."""
    tp = lp.transform_param
    if tp is not None and (tp.force_color or tp.force_gray):
        return False
    if tp is not None and tp.has("use_gpu_transform"):
        return bool(tp.use_gpu_transform)
    return True
