"""DetectNet detection pipeline — augmentation + coverage-grid labels.

Reference: src/caffe/layers/detectnet_transform_layer.{cpp,cu} (753+268 LoC)
+ src/caffe/util/detectnet_coverage_rectangular.cpp. The reference augments
on the GPU mid-graph; here augmentation runs on the host (like every other
transform in this framework — the TPU step stays a pure static-shape
program) and the layer declares the output feed shapes.

Implemented semantics:
- augmentation (DetectNetAugmentationParameter): random crop/shift to the
  network input size, random scale, horizontal flip, hue rotation and
  desaturation — each gated by its *_prob; bboxes transformed alongside.
- ground truth (DetectNetGroundTruthParameter, RECTANGULAR coverage): the
  label tensor has, per class, 5 channels on the stride-decimated grid:
  [coverage, dx1, dy1, dx2, dy2] where the d* channels hold the bbox
  corner offsets (in pixels, relative to each covered grid-cell center) —
  the coverage region is the bbox shrunk by scale_cvg and clamped per
  gridbox_type.
"""

from __future__ import annotations

import numpy as np

from ..proto.config import (
    DetectNetAugmentationParameter,
    DetectNetGroundTruthParameter,
)


# RGB <-> YIQ bases, fixed. The hue-rotation matrix is linear in
# (cos t, sin t): m(t) = _HUE_A + cos(t) * _HUE_B + sin(t) * _HUE_C, with
# all three terms composed ONCE at import time. _hue_rotate may run on an
# XLA host-callback thread (the DetectNetTransformation layer executes
# through jax.pure_callback), where ANY OpenBLAS entry (linalg.inv, 2-D
# `@`) can deadlock against the single-core XLA thread pool — the
# per-call math below is scalar/ufunc arithmetic only.
_T_YIQ = np.array([[0.299, 0.587, 0.114],
                   [0.596, -0.274, -0.322],
                   [0.211, -0.523, 0.312]])
_T_YIQ_INV = np.linalg.inv(_T_YIQ)
_HUE_A = _T_YIQ_INV @ np.diag([1.0, 0.0, 0.0]) @ _T_YIQ
_HUE_B = _T_YIQ_INV @ np.diag([0.0, 1.0, 1.0]) @ _T_YIQ
_HUE_C = _T_YIQ_INV @ np.array([[0, 0, 0], [0, 0, -1.0], [0, 1.0, 0]]) @ _T_YIQ


def _hue_rotate(img: np.ndarray, degrees: float) -> np.ndarray:
    """Rotate hue via a YIQ-space rotation (cheap approximation of the
    reference's HSV hue shift; BGR CHW float input)."""
    theta = np.deg2rad(degrees)
    m = _HUE_A + np.cos(theta) * _HUE_B + np.sin(theta) * _HUE_C
    rgb = img[::-1]  # BGR -> RGB
    out = np.stack([m[i, 0] * rgb[0] + m[i, 1] * rgb[1] + m[i, 2] * rgb[2]
                    for i in range(3)])
    return np.clip(out[::-1], 0, 255)


def _desaturate(img: np.ndarray, amount: float) -> np.ndarray:
    gray = 0.114 * img[0] + 0.587 * img[1] + 0.299 * img[2]
    return img * (1 - amount) + gray[None] * amount


class DetectNetAugmenter:
    """(image CHW float BGR, bboxes (N,5)=[cls,x1,y1,x2,y2]) -> augmented
    pair at the fixed network input size."""

    def __init__(self, aug: DetectNetAugmentationParameter | None,
                 gt: DetectNetGroundTruthParameter, phase: str = "TRAIN"):
        self.aug = aug or DetectNetAugmentationParameter()
        self.gt = gt
        self.phase = phase

    def __call__(self, img: np.ndarray, bboxes: np.ndarray,
                 rng: np.random.Generator, mean: np.ndarray | None = None
                 ) -> tuple[np.ndarray, np.ndarray]:
        """mean: optional per-channel (C,) mean subtracted AFTER the
        photometric augmentations but BEFORE the geometric ones — the
        reference's order (transform_image_cpu: HSV transforms, then
        meanSubtract, then flip/scale/crop), which makes the crop's
        zero-pad equal the mean in pixel space."""
        a = self.aug
        out_w, out_h = self.gt.image_size_x, self.gt.image_size_y
        img = np.asarray(img, np.float32)
        bboxes = np.asarray(bboxes, np.float32).reshape(-1, 5).copy()
        train = self.phase == "TRAIN"

        # photometric first, in [0,255] pixel space (reference does HSV
        # before mean subtraction)
        if train and a.hue_rotation_prob > 0 and rng.random() < a.hue_rotation_prob:
            img = _hue_rotate(img, float(rng.uniform(-a.hue_rotation,
                                                     a.hue_rotation)))
        if train and a.desaturation_prob > 0 and rng.random() < a.desaturation_prob:
            img = _desaturate(img, float(rng.random() * a.desaturation_max))
        if mean is not None:
            img = img - np.asarray(mean, np.float32)[:, None, None]

        if train and a.scale_prob > 0 and rng.random() < a.scale_prob:
            s = a.scale_min + rng.random() * (a.scale_max - a.scale_min)
            c, h, w = img.shape
            nh, nw = max(int(h * s), 1), max(int(w * s), 1)
            from PIL import Image
            # resize in FLOAT (mode 'F', per channel): the image may be
            # mean-subtracted (negative) here — a uint8 round-trip would
            # wrap negatives modulo 256 (the reference resizes the float
            # cv::Mat, transform_image_cpu)
            # lint: ok(host-sync) — PIL resize output, host data end to end
            img = np.stack([
                np.asarray(Image.fromarray(ch, mode="F").resize(
                    (nw, nh), Image.BILINEAR), np.float32)
                for ch in img])
            bboxes[:, 1:] *= s

        c, h, w = img.shape
        # crop/shift to (out_h, out_w)
        if train and rng.random() < a.crop_prob:
            max_x = max(w - out_w, 0) + a.shift_x
            max_y = max(h - out_h, 0) + a.shift_y
            off_x = int(rng.integers(-a.shift_x, max_x + 1)) if max_x else 0
            off_y = int(rng.integers(-a.shift_y, max_y + 1)) if max_y else 0
        else:
            off_x = max((w - out_w) // 2, 0)
            off_y = max((h - out_h) // 2, 0)
        canvas = np.zeros((c, out_h, out_w), np.float32)
        src_x0, src_y0 = max(off_x, 0), max(off_y, 0)
        dst_x0, dst_y0 = max(-off_x, 0), max(-off_y, 0)
        cw = min(w - src_x0, out_w - dst_x0)
        ch = min(h - src_y0, out_h - dst_y0)
        if cw > 0 and ch > 0:
            canvas[:, dst_y0:dst_y0 + ch, dst_x0:dst_x0 + cw] = \
                img[:, src_y0:src_y0 + ch, src_x0:src_x0 + cw]
        img = canvas
        bboxes[:, [1, 3]] -= off_x
        bboxes[:, [2, 4]] -= off_y

        if train and rng.random() < a.flip_prob:
            img = img[:, :, ::-1].copy()
            x1 = out_w - 1 - bboxes[:, 3]
            x2 = out_w - 1 - bboxes[:, 1]
            bboxes[:, 1], bboxes[:, 3] = x1, x2

        # drop bboxes that left the canvas entirely
        keep = (bboxes[:, 3] > 0) & (bboxes[:, 4] > 0) & \
               (bboxes[:, 1] < out_w) & (bboxes[:, 2] < out_h)
        return img, bboxes[keep]


def coverage_label(bboxes: np.ndarray, gt: DetectNetGroundTruthParameter,
                   num_classes: int = 1) -> np.ndarray:
    """bboxes (N,5)=[cls,x1,y1,x2,y2] -> (num_classes*5, gh, gw) label:
    per class [coverage, dx1, dy1, dx2, dy2]
    (detectnet_coverage_rectangular.cpp)."""
    stride = gt.stride
    gw = gt.image_size_x // stride
    gh = gt.image_size_y // stride
    out = np.zeros((num_classes * 5, gh, gw), np.float32)
    for cls, x1, y1, x2, y2 in np.asarray(bboxes, np.float32).reshape(-1, 5):
        ci = int(cls)
        if not 0 <= ci < num_classes:
            continue
        if gt.crop_bboxes:
            x1 = np.clip(x1, 0, gt.image_size_x - 1)
            x2 = np.clip(x2, 0, gt.image_size_x - 1)
            y1 = np.clip(y1, 0, gt.image_size_y - 1)
            y2 = np.clip(y2, 0, gt.image_size_y - 1)
        # coverage region: bbox shrunk around its center by scale_cvg,
        # clamped per gridbox_type
        cx, cy = (x1 + x2) / 2, (y1 + y2) / 2
        cw, ch = (x2 - x1) * gt.scale_cvg, (y2 - y1) * gt.scale_cvg
        if gt.gridbox_type == "GRIDBOX_MAX":
            cw, ch = min(cw, gt.max_cvg_len), min(ch, gt.max_cvg_len)
        else:
            cw, ch = max(cw, gt.min_cvg_len), max(ch, gt.min_cvg_len)
        gx1 = int(np.floor((cx - cw / 2) / stride))
        gx2 = int(np.ceil((cx + cw / 2) / stride))
        gy1 = int(np.floor((cy - ch / 2) / stride))
        gy2 = int(np.ceil((cy + ch / 2) / stride))
        gx1, gy1 = max(gx1, 0), max(gy1, 0)
        gx2, gy2 = min(max(gx2, gx1 + 1), gw), min(max(gy2, gy1 + 1), gh)
        base = ci * 5
        out[base, gy1:gy2, gx1:gx2] = 1.0
        # bbox corner offsets relative to each covered cell center
        ys, xs = np.mgrid[gy1:gy2, gx1:gx2]
        cell_cx = xs * stride + stride / 2
        cell_cy = ys * stride + stride / 2
        out[base + 1, gy1:gy2, gx1:gx2] = x1 - cell_cx
        out[base + 2, gy1:gy2, gx1:gx2] = y1 - cell_cy
        out[base + 3, gy1:gy2, gx1:gx2] = x2 - cell_cx
        out[base + 4, gy1:gy2, gx1:gx2] = y2 - cell_cy
    return out


class DetectNetFeeder:
    """feed_fn producing (data, label) batches from a detection dataset:
    dataset.get(i) -> (CHW uint8 BGR image, bboxes (N,5))."""

    def __init__(self, dataset, lp, phase: str = "TRAIN", *, seed: int = 1701,
                 num_classes: int = 1, rank: int = 0, world: int = 1,
                 top_names=("data", "label")):
        self.ds = dataset
        self.gt = lp.detectnet_groundtruth_param or DetectNetGroundTruthParameter()
        self.augmenter = DetectNetAugmenter(
            lp.detectnet_augmentation_param, self.gt, phase)
        p = lp.data_param
        self.batch = p.batch_size if p else 8
        self.num_classes = num_classes
        self.seed = seed
        self.rank, self.world = rank, world
        self.top_names = top_names

    def __call__(self, it: int) -> dict[str, np.ndarray]:
        gt = self.gt
        imgs, labels = [], []
        n = len(self.ds)
        for slot in range(self.batch):
            flat = it * self.batch * self.world + self.rank * self.batch + slot
            rng = np.random.Generator(np.random.Philox(
                key=(self.seed << 32) ^ flat))
            img, bboxes = self.ds.get(flat % n)
            img, bboxes = self.augmenter(img, bboxes, rng)
            imgs.append(img)
            labels.append(coverage_label(bboxes, gt, self.num_classes))
        return {self.top_names[0]: np.stack(imgs),
                self.top_names[1]: np.stack(labels)}
