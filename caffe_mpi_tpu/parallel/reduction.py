"""Overlapped bucketed gradient reduction — the reference's
ReduceAndUpdate plane (`src/caffe/net.cpp:757-913`) rebuilt as explicit
per-bucket collectives inside the jitted train step.

Reference mechanics being replaced: backward emits param ids in reverse
topological order into a dedicated reduce thread; the thread packs
contiguous gradients from the shared learnable-diff space
(`net.cpp:1350-1374`) into `reduce_buckets` (default 6, caffe.proto:140)
buckets of ~total_count/reduce_buckets elements and ncclAllReduces each
bucket on a high-priority stream WHILE backward still runs
(`Reduce:880`, `ReduceBucket:899`), scaling by 1/solver_count after the
reduce (`net.cpp:891,910`). That overlap of reduction with remaining
backprop is where distributed-SGD scaling lives (arXiv:1810.11112).

TPU-native equivalent: the default mesh path leaves the gradient
all-reduce IMPLICIT — GSPMD inserts per-parameter collectives wherever
dataflow demands, typically combined into one end-of-step reduction.
This module makes the reference's structure explicit so the compiler's
latency-hiding scheduler has independent collectives to hoist
(arXiv:1810.09868: express the communication, let XLA overlap it):

- `plan_buckets`: pack learnable params into contiguous buckets in
  reverse topological layer order — the order backward produces their
  gradients — sized by `reduce_buckets` count or a `grad_bucket_mb`
  byte budget (the diff-space packing, minus the shared allocation).
- `bucketed_value_and_grad`: an opt-in `shard_map` variant of the
  solver's loss/grad computation: each device differentiates its local
  batch shard, then each bucket is flattened into one contiguous
  buffer and `lax.psum`'d over the 'data' axis — one independent
  collective per bucket, issued as soon as its layers' backward
  contributions exist. Dividing by the axis size after the psum
  reproduces the reference's post-reduce 1/solver_count scale, and is
  exact when the axis size is a power of two — accepted steps are then
  BITWISE equal on CPU to the implicit GSPMD path
  (tests/test_reduction.py).
- `unsupported_reason`: the static compatibility gate. The per-device
  backward changes semantics for cross-batch computations, so nets
  with BatchNorm (global-batch statistics), MoE (batch-wide routing
  capacity), host-callback layers, or data-dependent loss
  normalization (SoftmaxWithLoss VALID + ignore_label, normalization
  NONE) fall back to the implicit reduction with a warning. Dropout
  under the bucketed step draws per-device masks (the rng folds in
  `axis_index`) — the reference's per-GPU-mask behavior, statistically
  equivalent but not bitwise vs the global-mask implicit path.
- `collective_stats`: CPU-visible measurement — counts all-reduce ops
  in compiled HLO text and where they sit in program order, so the
  ≥ `reduce_buckets` collectives-per-step claim (and the overlap-span
  proxy) is checkable with the tunnel down.

Multi-host (ISSUE 11): the bucket psums reduce over the mesh 'data'
axis, and under `caffe train -hosts N` that axis spans processes — so
each bucket's collective crosses hosts over DCN with NO change to this
module, exactly the reference's global (multi-node) NCCL communicator
(parallel.cpp:166-169) at bucket granularity.
Solver.reduction_stats() adds the `hosts` /
`cross_host_collectives_per_step` facts (this module stays jax-free).
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass

import numpy as np

log = logging.getLogger("caffe_mpi_tpu.parallel.reduction")


# ---------------------------------------------------------------------------
# Bucket planning
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Bucket:
    """One contiguous reduction unit: entries are (layer, param) keys in
    reverse-topo order, all the same dtype (a psum'd buffer is one
    buffer); nbytes is the packed size."""
    entries: tuple[tuple[str, str], ...]
    sizes: tuple[int, ...]       # element counts, aligned with entries
    dtype: str
    nbytes: int


@dataclass(frozen=True)
class ReductionPlan:
    """The bucket schedule plus the mesh facts the packed psum needs.

    wire_dtype (ISSUE 9): when set (e.g. "bfloat16" under `precision:
    bf16`), each packed bucket is CAST to this dtype before its psum —
    the collective moves half the bytes — and cast back to the gradient
    dtype right after, so the post-psum 1/n scale, clipping, and the
    optimizer update all run in f32. None (default) reduces in the
    gradient's own dtype, bitwise-identical to before the knob."""
    buckets: tuple[Bucket, ...]
    n_data: int
    axis: str = "data"
    wire_dtype: str | None = None

    @property
    def bucket_bytes(self) -> tuple[int, ...]:
        return tuple(b.nbytes for b in self.buckets)

    @property
    def collectives_per_step(self) -> int:
        """Gradient collectives one micro-step issues (the loss psum is
        not counted — it exists on both paths' display plumbing)."""
        return len(self.buckets)

    def stats(self) -> dict:
        out = {
            "mode": "bucketed",
            "reduce_buckets": len(self.buckets),
            "collectives_per_step": self.collectives_per_step,
            "bucket_bytes": list(self.bucket_bytes),
            "n_data": self.n_data,
        }
        if self.wire_dtype:
            out["wire_dtype"] = self.wire_dtype
        return out

    def psum_buckets(self, grads, pred=None):
        """Reduce a congruent grad pytree bucket-by-bucket inside
        shard_map: flatten each bucket into one contiguous buffer
        (the learnable-diff-space packing, net.cpp:1350-1374), one
        `lax.psum` per bucket, then the exact post-reduce 1/n scale
        (net.cpp:891,910).

        `pred` (a traced, always-true scalar) keeps the unpacked grads
        BITWISE equal to the implicit path's: a reduction fused over a
        slice of the flat bucket buffer sums in a different lane order
        than over a standalone array on the CPU backend (measured ~1
        ulp on `sqrt(sum(square(.)))` — exactly the clip_gradients
        global norm), so the unpack runs inside a `lax.cond` branch: a
        separate HLO computation XLA fusion cannot cross, making each
        grad leaf a materialized buffer just like an all-reduce output.
        Same recipe as the solver's train_guard — and as there,
        `lax.optimization_barrier` does NOT survive the CPU pipeline,
        and the two branches are extensionally identical but
        structurally distinct (the else-arm unpacks through flipped
        buffers) so no simplifier can fold the conditional away while
        a mispredicted branch would still return correct values."""
        import jax.numpy as jnp
        from jax import lax

        wire = self.wire_dtype
        reds = []
        for bucket in self.buckets:
            parts = [grads[ln][pn].reshape(-1)
                     for (ln, pn) in bucket.entries]
            flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            if wire and str(flat.dtype) != wire:
                # ISSUE 9: the collective moves bf16 — half the bytes on
                # the wire; everything after the psum is f32 again
                flat = flat.astype(wire)
            red = lax.psum(flat, self.axis)
            if wire:
                red = red.astype(jnp.float32)
            if self.n_data > 1:
                red = red / self.n_data
            reds.append(red)

        def unpack(reds, mirror=False):
            out = {ln: dict(lp) for ln, lp in grads.items()}
            for red, bucket in zip(reds, self.buckets):
                total = sum(bucket.sizes)
                src = jnp.flip(red) if mirror else red
                off = 0
                for (ln, pn), size in zip(bucket.entries, bucket.sizes):
                    if mirror:
                        piece = jnp.flip(src[total - off - size:
                                             total - off])
                    else:
                        piece = src[off:off + size]
                    out[ln][pn] = piece.reshape(grads[ln][pn].shape)
                    off += size
            return out

        if pred is None:
            return unpack(reds)
        return lax.cond(pred, unpack,
                        lambda rs: unpack(rs, mirror=True), reds)


def plan_buckets(entries, *, n_buckets: int = 0,
                 bucket_bytes: int = 0, n_data: int = 1,
                 axis: str = "data",
                 wire_dtype: str | None = None) -> ReductionPlan:
    """Pack `entries` — an iterable of (layer, param, shape, dtype) in
    REVERSE topological layer order, i.e. the order backward produces
    gradients — into contiguous buckets.

    Exactly one sizing mode applies: `bucket_bytes` > 0 packs greedily
    up to the byte budget (a single param larger than the budget gets
    its own bucket, with a warning — it cannot be split without losing
    the one-collective-per-bucket structure); otherwise `n_buckets`
    splits the total bytes into ~equal targets, the reference's
    total_count/reduce_buckets rule (net.cpp:824-863). dtype changes
    always start a new bucket (one psum buffer is one dtype).
    """
    if bucket_bytes <= 0 and n_buckets <= 0:
        raise ValueError("plan_buckets needs n_buckets > 0 or "
                         "bucket_bytes > 0")
    ents = []
    for (lname, pname, shape, dtype) in entries:
        # wire_dtype (ISSUE 9): buckets pack and travel in this dtype —
        # sizing, budgets, and the reported bucket_bytes follow it
        dt = np.dtype(wire_dtype) if wire_dtype else np.dtype(dtype)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        ents.append((lname, pname, size, dt))
    if not ents:
        return ReductionPlan(buckets=(), n_data=n_data, axis=axis,
                             wire_dtype=wire_dtype)

    total = sum(s * dt.itemsize for (_, _, s, dt) in ents)
    buckets: list[Bucket] = []
    cur: list[tuple] = []
    cur_bytes = 0

    def flush():
        nonlocal cur, cur_bytes
        if cur:
            buckets.append(Bucket(
                entries=tuple((l, p) for (l, p, _, _) in cur),
                sizes=tuple(s for (_, _, s, _) in cur),
                dtype=str(cur[0][3]), nbytes=cur_bytes))
            cur, cur_bytes = [], 0

    if bucket_bytes > 0:
        # byte-budget mode: greedy fill; an oversized param cannot be
        # split without losing the one-collective-per-bucket structure
        target = int(bucket_bytes)
        for (lname, pname, size, dt) in ents:
            nbytes = size * dt.itemsize
            if cur and (str(cur[0][3]) != str(dt)
                        or cur_bytes + nbytes > target):
                flush()
            if nbytes > target:
                log.warning(
                    "param %s/%s (%d bytes) exceeds the grad_bucket_mb "
                    "budget (%d bytes); it gets its own bucket",
                    lname, pname, nbytes, target)
            cur.append((lname, pname, size, dt))
            cur_bytes += nbytes
            if cur_bytes >= target:
                flush()
        flush()
    else:
        # count mode: close bucket b when cumulative bytes cross
        # (b+1)/k of the total (the reference's ~total_count/k rule,
        # net.cpp:824-863), also closing early when the remaining
        # entries are only just enough to populate the remaining
        # buckets — so k buckets come out whenever k <= n_params
        k = min(int(n_buckets), len(ents))
        cum = 0
        for i, (lname, pname, size, dt) in enumerate(ents):
            nbytes = size * dt.itemsize
            if cur and str(cur[0][3]) != str(dt):
                flush()
            cur.append((lname, pname, size, dt))
            cur_bytes += nbytes
            cum += nbytes
            remaining = len(ents) - i - 1
            still_needed = k - len(buckets) - 1
            if len(buckets) < k - 1 and (
                    cum >= (len(buckets) + 1) * total / k
                    or remaining <= still_needed):
                flush()
        flush()
    return ReductionPlan(buckets=tuple(buckets), n_data=n_data, axis=axis,
                         wire_dtype=wire_dtype)


def plan_for_net(net, params, *, n_buckets: int = 0,
                 bucket_bytes: int = 0, n_data: int = 1,
                 wire_dtype: str | None = None) -> ReductionPlan:
    """Bucket plan over a Net's param pytree, layers reversed (backward
    order). Every leaf of `params` must land in exactly one bucket —
    clipping consumes the whole grad tree, so an uncovered leaf would
    silently carry an UNREDUCED per-device gradient into the global
    norm."""
    entries = []
    seen = set()
    for layer in reversed(net.layers):
        lparams = params.get(layer.name)
        if not lparams:
            continue
        if layer.name in seen:
            continue
        seen.add(layer.name)
        for pname, arr in lparams.items():
            entries.append((layer.name, pname, np.shape(arr),
                            getattr(arr, "dtype", np.float32)))
    covered = {(l, p) for (l, p, _, _) in entries}
    want = {(ln, pn) for ln, lp in params.items() for pn in lp}
    missing = want - covered
    if missing:
        raise ValueError(
            f"bucket planner lost params {sorted(missing)} — params "
            "exist outside the net's layer list")
    return plan_buckets(entries, n_buckets=n_buckets,
                        bucket_bytes=bucket_bytes, n_data=n_data,
                        wire_dtype=wire_dtype)


# ---------------------------------------------------------------------------
# Compatibility gate
# ---------------------------------------------------------------------------

# losses whose normalizer is a STATIC batch-proportional count, so the
# per-device backward's cotangent is exactly n x the global one (the
# property the post-psum 1/n scale inverts exactly when n is a power of
# two). Everything else falls back to the implicit reduction.
_DP_SAFE_LOSSES = {
    "SoftmaxWithLoss", "EuclideanLoss", "L1Loss",
    "SigmoidCrossEntropyLoss", "HingeLoss", "MultinomialLogisticLoss",
    "InfogainLoss", "ContrastiveLoss",
}
# layer types whose TRAIN computation couples examples ACROSS the batch
# (per-device execution would change semantics, not just schedule)
_CROSS_BATCH_TYPES = {"BatchNorm", "MoE"}


def _walk_layer_params(lp):
    """Yield every LayerParameter reachable from `lp`, descending into
    composite (Pipeline) bodies."""
    yield lp
    pp = getattr(lp, "pipeline_param", None)
    if pp is not None:
        for inner in pp.layer:
            yield from _walk_layer_params(inner)


def unsupported_reason(net) -> str | None:
    """None when the net's TRAIN graph is safe for the bucketed
    per-device backward; else a human-readable reason (the solver logs
    it and falls back to the implicit reduction)."""
    for layer in net.layers:
        if getattr(layer, "host_callback", False):
            return (f"layer {layer.name!r} re-enters the host from "
                    "inside the step (host_callback)")
        for lp in _walk_layer_params(layer.lp):
            if lp.type in _CROSS_BATCH_TYPES:
                return (f"layer {lp.name!r} ({lp.type}) couples examples "
                        "across the batch; per-device backward would "
                        "change its semantics")
        if not (hasattr(layer, "is_loss") and layer.is_loss()):
            continue
        ltype = layer.lp.type
        if ltype not in _DP_SAFE_LOSSES:
            return (f"loss layer {layer.name!r} ({ltype}) is not on the "
                    "static-normalization allowlist")
        p = layer.lp.loss_param
        mode = ""
        if p is not None and p.has("normalization"):
            mode = str(p.normalization).upper()
        if mode == "NONE":
            return (f"loss layer {layer.name!r} uses normalization NONE "
                    "(sum, not batch-mean)")
        ignore = p.ignore_label if p is not None and p.has("ignore_label") \
            else None
        if ignore is not None and ltype == "SoftmaxWithLoss" \
                and mode in ("", "VALID"):
            return (f"loss layer {layer.name!r} normalizes by a "
                    "data-dependent valid count (ignore_label + VALID)")
    return None


# ---------------------------------------------------------------------------
# The overlapped step
# ---------------------------------------------------------------------------

def bucketed_value_and_grad(loss_fn, mesh_plan, plan: ReductionPlan):
    """Drop-in replacement for `jax.value_and_grad(loss_fn,
    has_aux=True)` in the solver's iteration body, for loss_fn of
    signature (params, net_state, feeds, rng) -> (scaled_loss,
    (net_state, loss)).

    The returned function runs the forward/backward per device on the
    local 'data'-axis batch shard under shard_map, reduces the grads
    per bucket (plan.psum_buckets), and psum-averages the loss — the
    reference's reduce-thread consumer loop (net.cpp:757-913) as
    compiler-schedulable dataflow. The rng folds in the device's axis
    index so stochastic layers draw per-device masks (the reference's
    per-GPU behavior)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .mesh import shard_map

    n = plan.n_data
    axis = plan.axis

    def local(params, net_state, feeds, rng):
        idx = lax.axis_index(axis)
        rng = jax.random.fold_in(rng, idx)
        (scaled, (new_state, loss)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, net_state, feeds, rng)
        # idx >= 0 is traced-but-always-true: it gates the bitwise
        # unpack isolation (see psum_buckets), never the values
        grads = plan.psum_buckets(grads, pred=idx >= 0)
        if n > 1:
            scaled = lax.psum(scaled, axis) / n
            loss = lax.psum(loss, axis) / n
        return (scaled, (new_state, loss)), grads

    def vg(params, net_state, feeds, rng):
        fspecs = jax.tree.map(
            lambda x: P(*((axis,) + (None,) * (jnp.ndim(x) - 1))), feeds)
        fn = shard_map(local, mesh=mesh_plan.mesh,
                       in_specs=(P(), P(), fspecs, P()),
                       # everything returned is replicated: grads/loss
                       # are psum'd, net_state is batch-independent by
                       # the unsupported_reason gate
                       out_specs=P(), check_vma=False)
        return fn(params, net_state, feeds, rng)

    return vg


# ---------------------------------------------------------------------------
# Measurement + TPU scheduling knobs
# ---------------------------------------------------------------------------

_AR_RE = re.compile(r"=\s*(?:\S+\s+)?all-reduce(?:-start)?\(")


def collective_stats(hlo_text: str) -> dict:
    """Count all-reduce ops in compiled HLO text and report where they
    sit in program order. `overlap_span` — (last - first all-reduce
    position) / program length — is the CPU-visible overlap proxy: a
    single end-of-step fused reduction scores ~0, collectives spread
    through the backward score high (on TPU the latency-hiding
    scheduler turns that spread into actual compute/comm overlap;
    on CPU it is structure only)."""
    lines = hlo_text.splitlines()
    idx = [i for i, line in enumerate(lines) if _AR_RE.search(line)]
    total = max(len(lines), 1)
    return {
        "all_reduces": len(idx),
        "first_frac": round(idx[0] / total, 4) if idx else None,
        "last_frac": round(idx[-1] / total, 4) if idx else None,
        "overlap_span": round((idx[-1] - idx[0]) / total, 4) if idx
        else 0.0,
    }


def tpu_overlap_flags() -> list[str]:
    """libtpu compiler flags that help the TPU scheduler hide the
    per-bucket collectives behind remaining backward compute. These are
    TPU-compiler flags, NOT XLA_FLAGS entries — this jaxlib's CPU/GPU
    flag parser hard-fails on them (parse_flags_from_env.cc:226), so
    `caffe train -reduce_overlap` appends them to LIBTPU_INIT_ARGS
    before backend init: only libtpu ever reads that env var, making
    the append a no-op on CPU runs and the dryrun.
    CAFFE_TPU_NO_OVERLAP_FLAGS=1 opts out if a libtpu build rejects
    one."""
    return [
        "--xla_tpu_enable_latency_hiding_scheduler=true",
        "--xla_tpu_enable_async_collective_fusion=true",
    ]


def apply_tpu_overlap_flags(environ) -> bool:
    """Append tpu_overlap_flags() to environ['LIBTPU_INIT_ARGS'] (once,
    idempotent). Returns True when anything was added. Call BEFORE the
    first jax computation initializes the backend. A flag the operator
    already spelled in LIBTPU_INIT_ARGS — with ANY value, including an
    explicit `=false` opt-out — is left alone, never contradicted."""
    if environ.get("CAFFE_TPU_NO_OVERLAP_FLAGS") == "1":
        return False
    cur = environ.get("LIBTPU_INIT_ARGS", "")
    add = [f for f in tpu_overlap_flags()
           if f.split("=", 1)[0] not in cur]
    if not add:
        return False
    environ["LIBTPU_INIT_ARGS"] = (cur + " " + " ".join(add)).strip()
    return True
