"""Pipeline parallelism: SPMD shift-register over a 'stage' mesh axis.

The reference has NO pipeline parallelism (SURVEY §2.7: ForwardFromTo is a
sequential loop on one device, net.cpp:669-682); this module is part of the
beyond-reference distributed story (DP: mesh.py; TP: mesh.py sharding
rules; SP: ops/attention.py; EP: ops/moe.py).

TPU-native design — the canonical GPipe-on-SPMD pattern (the
"jax-ml.github.io/scaling-book" pipelining recipe): stages must be
STRUCTURALLY IDENTICAL (a stack of repeated blocks — the transformer /
deep-MLP case where PP pays off). Stage s's params live on mesh position s
of the stage axis: the stacked param pytree has a leading n_stages dim
sharded over that axis, so each device holds exactly ONE stage's weights —
the model memory is truly partitioned, which is the entire point of PP.

Execution is a shift register under shard_map: at tick t every device
applies its stage to the activation it holds, then `ppermute`s the result
to the next device in the ring, while device 0 injects microbatch t and
device S-1 emits a finished microbatch. n_micro + n_stages - 1 ticks
drain the pipe; the (S-1)-tick bubble amortizes as n_micro grows. The
ppermute traffic is neighbor-only, so it rides the ICI ring, and XLA's
latency-hiding scheduler overlaps the transfer of tick t with the compute
of tick t+1 — the overlap the reference builds with threads, done by the
compiler.

Differentiation: plain jax.grad through the scan — AD reverses the
ppermute ring automatically, producing the reverse-direction gradient
pipeline without any hand-written backward schedule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import mark_varying


def stack_stage_params(per_stage_params: list):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim.
    Every stage must have congruent treedef/shapes (structural identity)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def shard_stages(stacked_params, mesh, stage_axis: str = "model"):
    """Place the stacked params with the leading (stage) dim sharded over
    the stage axis — one stage per mesh position, model memory 1/S per
    device."""
    def put(x):
        spec = [stage_axis] + [None] * (x.ndim - 1)
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))
    return jax.tree.map(put, stacked_params)


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, *,
                   stage_axis: str = "model"):
    """Run a homogeneous stage stack as a pipelined SPMD program.

    stage_fn(stage_params, x) -> y        one stage, pure
    stacked_params                        leading dim = n_stages (sharded
                                          or not; sharding constraint is
                                          applied here)
    microbatches: (n_micro, ...)          microbatch-major input
    Returns (n_micro, ...) outputs equal to applying the stages
    sequentially to each microbatch.
    """
    n_stages = mesh.shape[stage_axis]
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(
            f"stacked params have {lead} stages but the '{stage_axis}' "
            f"mesh axis has {n_stages} positions")
    n_micro = microbatches.shape[0]
    if n_micro < 1:
        raise ValueError("need at least one microbatch")

    param_specs = jax.tree.map(
        lambda x: P(*([stage_axis] + [None] * (x.ndim - 1))), stacked_params)

    def spmd(params, mb):
        # params: this device's stage (leading dim 1) — unstack it
        p = jax.tree.map(lambda x: x[0], params)
        idx = lax.axis_index(stage_axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

        mb = mark_varying(mb, stage_axis)
        state0 = jnp.zeros_like(mb[0])
        out0 = mark_varying(jnp.zeros((n_micro, *mb.shape[1:]), mb.dtype),
                            stage_axis)

        def tick(carry, t):
            state, outs = carry
            # device 0 injects microbatch t (zeros once the input drains)
            inject = jnp.where(t < n_micro, mb[jnp.minimum(t, n_micro - 1)],
                               jnp.zeros_like(state))
            x = jnp.where(is_first, inject, state)
            y = stage_fn(p, x)
            # device S-1 finished microbatch t-(S-1) at this tick
            done_t = t - (n_stages - 1)
            outs = jnp.where(
                is_last & (done_t >= 0),
                lax.dynamic_update_index_in_dim(
                    outs, y, jnp.maximum(done_t, 0), 0),
                outs)
            # shift register: everyone hands its activation to stage+1
            state = lax.ppermute(y, stage_axis, perm)
            return (state, outs), None

        n_ticks = n_micro + n_stages - 1
        (_, outs), _ = lax.scan(tick, (state0, out0), jnp.arange(n_ticks))
        # only the last stage holds real outputs; zero the rest and psum
        # to replicate them across the stage axis
        outs = jnp.where(is_last, outs, 0)
        return lax.psum(outs, stage_axis)

    from jax import shard_map
    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(param_specs, P()),      # microbatches replicated in
        out_specs=P(),                    # outputs replicated back
    )
    return fn(stacked_params, microbatches)


