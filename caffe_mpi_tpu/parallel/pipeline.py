"""Pipeline parallelism: SPMD shift-register over a 'stage' mesh axis.

The reference has NO pipeline parallelism (SURVEY §2.7: ForwardFromTo is a
sequential loop on one device, net.cpp:669-682); this module is part of the
beyond-reference distributed story (DP: mesh.py; TP: mesh.py sharding
rules; SP: ops/attention.py; EP: ops/moe.py).

TPU-native design — the canonical GPipe-on-SPMD pattern (the
"jax-ml.github.io/scaling-book" pipelining recipe): stages must be
STRUCTURALLY IDENTICAL (a stack of repeated blocks — the transformer /
deep-MLP case where PP pays off). Stage s's params live on mesh position s
of the stage axis: the stacked param pytree has a leading n_stages dim
sharded over that axis, so each device holds exactly ONE stage's weights —
the model memory is truly partitioned, which is the entire point of PP.

Execution is a shift register under shard_map: at tick t every device
applies its stage to the activation it holds, then `ppermute`s the result
to the next device in the ring, while stage 0 injects microbatch t and
stage S-1 emits a finished microbatch. The ppermute traffic is
neighbor-only, so it rides the ICI ring, and XLA's latency-hiding
scheduler overlaps the transfer of tick t with the compute of tick t+1 —
the overlap the reference builds with threads, done by the compiler.

Microbatch I/O is sharded over the stage axis too (GSPMD-paper style):
device s owns microbatches {t : t mod S == s}, and two auxiliary one-slot
registers ride the same ring — an INPUT register rotating toward stage 0
(so stage 0 receives microbatch t exactly at tick t) and an OUTPUT
register rotating away from stage S-1 (so each finished microbatch lands
back on its owner). Per-device memory is n_micro/S microbatches + O(1)
registers; per-tick traffic is 3 neighbor ppermutes of one microbatch.
Nothing is replicated and there is no final psum.

Differentiation: plain jax.grad through the scan — AD reverses the
ppermute ring automatically, producing the reverse-direction gradient
pipeline without any hand-written backward schedule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import mark_varying


def stack_stage_params(per_stage_params: list):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim.
    Every stage must have congruent treedef/shapes (structural identity)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def shard_stages(stacked_params, mesh, stage_axis: str = "model"):
    """Place the stacked params with the leading (stage) dim sharded over
    the stage axis — one stage per mesh position, model memory 1/S per
    device."""
    def put(x):
        spec = [stage_axis] + [None] * (x.ndim - 1)
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))
    return jax.tree.map(put, stacked_params)


def _arrange(mb, n_stages, n_local):
    """(M, ...) microbatch-major -> (S*L, ...) device-major round-robin:
    row s*L + k holds microbatch k*S + s, so a P(stage) split gives device
    s exactly the microbatches {t : t mod S == s} in slot order."""
    rest = mb.shape[1:]
    return (mb.reshape(n_local, n_stages, *rest)
            .swapaxes(0, 1)
            .reshape(n_stages * n_local, *rest))


def _unarrange(out, n_stages, n_local):
    """Inverse of _arrange on the output side."""
    rest = out.shape[1:]
    return (out.reshape(n_stages, n_local, *rest)
            .swapaxes(0, 1)
            .reshape(n_stages * n_local, *rest))


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, *,
                   stage_axis: str = "model", batch_axis: str | None = None):
    """Run a homogeneous stage stack as a pipelined SPMD program.

    stage_fn(stage_params, x) -> y        one stage, pure, shape-preserving
    stacked_params                        leading dim = n_stages (sharded
                                          or not; sharding constraint is
                                          applied here)
    microbatches: (n_micro, ...)          microbatch-major input
    batch_axis: optional mesh axis the per-microbatch batch dim (dim 1) is
    sharded over — pass 'data' when running inside a DPxPP step so the
    shard_map does not force an all-gather of the data-parallel batch.

    Returns (n_micro, ...) outputs equal to applying the stages
    sequentially to each microbatch.
    """
    n_stages = mesh.shape[stage_axis]
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead != n_stages:
        raise ValueError(
            f"stacked params have {lead} stages but the '{stage_axis}' "
            f"mesh axis has {n_stages} positions")
    n_micro0 = microbatches.shape[0]
    if n_micro0 < 1:
        raise ValueError("need at least one microbatch")

    # pad the microbatch count up to a multiple of S so the round-robin
    # ownership is uniform; pad outputs are sliced off below
    pad = (-n_micro0) % n_stages
    if pad:
        microbatches = jnp.concatenate(
            [microbatches,
         jnp.zeros((pad, *microbatches.shape[1:]), microbatches.dtype)])
    n_micro = n_micro0 + pad
    n_local = n_micro // n_stages

    param_specs = jax.tree.map(
        lambda x: P(*([stage_axis] + [None] * (x.ndim - 1))), stacked_params)
    mb_ndim = microbatches.ndim
    io_spec = P(*([stage_axis, batch_axis] + [None] * (mb_ndim - 2))
                if batch_axis else [stage_axis] + [None] * (mb_ndim - 1))

    def spmd(params, mb_local):
        # params: this device's stage (leading dim 1) — unstack it
        p = jax.tree.map(lambda x: x[0], params)
        idx = lax.axis_index(stage_axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1
        fwd = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        bwd = [(j, (j - 1) % n_stages) for j in range(n_stages)]

        mb_local = mark_varying(mb_local, stage_axis)
        zero = jnp.zeros_like(mb_local[0])
        in_reg0 = mark_varying(zero, stage_axis)
        state0 = mark_varying(zero, stage_axis)
        out_reg0 = mark_varying(zero, stage_axis)
        out_local0 = mark_varying(jnp.zeros_like(mb_local), stage_axis)

        def tick(carry, t):
            in_reg, state, out_reg, out_local = carry
            # 1. register store: a finished microbatch emitted by stage S-1
            #    ((S-1-idx+... ) ticks ago, riding the output register)
            #    reaches its owner this tick
            d_store = t - (n_stages - 1) - ((idx + 1) % n_stages)
            store = ((idx != n_stages - 1) & (d_store >= 0)
                     & (d_store < n_micro) & (d_store % n_stages == idx))
            slot = jnp.clip(d_store // n_stages, 0, n_local - 1)
            out_local = jnp.where(
                store,
                lax.dynamic_update_index_in_dim(out_local, out_reg, slot, 0),
                out_local)
            # 2. load phase: every S ticks each device refills its input
            #    register from its local shard; the register then rotates
            #    toward stage 0, delivering microbatch t at tick t
            k = t // n_stages
            load = (t % n_stages == 0) & (k < n_local)
            in_reg = jnp.where(
                load,
                lax.dynamic_index_in_dim(
                    mb_local, jnp.minimum(k, n_local - 1), 0, keepdims=False),
                in_reg)
            # 3. inject + compute
            x = jnp.where(is_first, in_reg, state)
            y = stage_fn(p, x)
            # 4. emission: stage S-1 finished microbatch t-(S-1); microbatches
            #    it owns itself store directly, the rest board the register
            d_emit = t - (n_stages - 1)
            self_store = (is_last & (d_emit >= 0) & (d_emit < n_micro)
                          & (d_emit % n_stages == n_stages - 1))
            out_local = jnp.where(
                self_store,
                lax.dynamic_update_index_in_dim(
                    out_local, y, jnp.clip(d_emit // n_stages, 0,
                                           n_local - 1), 0),
                out_local)
            out_reg = jnp.where(is_last, y, out_reg)
            # 5. ring rotations (neighbor-only ICI traffic)
            state = lax.ppermute(y, stage_axis, fwd)
            in_reg = lax.ppermute(in_reg, stage_axis, bwd)
            out_reg = lax.ppermute(out_reg, stage_axis, fwd)
            return (in_reg, state, out_reg, out_local), None

        n_ticks = n_micro + 2 * n_stages - 2
        (_, _, _, out_local), _ = lax.scan(
            tick, (in_reg0, state0, out_reg0, out_local0),
            jnp.arange(n_ticks))
        return out_local

    from .mesh import shard_map  # jax-version shim
    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(param_specs, io_spec),  # microbatch I/O sharded over stage
        out_specs=io_spec,
    )
    out = fn(stacked_params, _arrange(microbatches, n_stages, n_local))
    out = _unarrange(out, n_stages, n_local)
    return out[:n_micro0] if pad else out
