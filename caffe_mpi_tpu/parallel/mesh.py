"""Device mesh + data-parallel sharding — the TPU replacement for the
reference's MPI+NCCL distributed backend.

Reference wire protocol (SURVEY §5.8; src/caffe/parallel.cpp, clusters.cpp):
mpirun launches one process per node; rank 0 MPI_Bcasts a ncclUniqueId; a
global NCCL communicator allreduces gradient buckets on a dedicated stream,
overlapped with backward by a reduce thread; weights ncclBcast from rank 0
at start.

TPU-native equivalent implemented here:
- `Clusters` -> `init_distributed()` = jax.distributed.initialize (DCN),
  after which every host sees the global device list.
- ncclUniqueId handshake -> nothing: the TPU runtime already forms the
  ICI/DCN topology.
- per-GPU P2PSync threads -> SPMD: ONE jitted program over a
  jax.sharding.Mesh; XLA partitions it across all chips.
- weight broadcast -> replicated NamedSharding on params (device_put once).
- bucketed ncclAllReduce + reduce thread -> XLA inserts all-reduces for the
  gradient mean when the batch axis is sharded and params are replicated;
  its latency-hiding scheduler overlaps them with remaining backward
  compute, which is exactly the reference's reduce-thread/bucket overlap
  machinery (net.cpp:757-913) done by the compiler.
- divide_batch_size (parallel.cpp:295-348) -> the global batch is sharded
  over the 'data' axis; each chip sees batch/n_data examples.

The mesh also carries a 'model' axis so later tensor/pipeline-parallel
shardings slot in without changing this module's API.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("caffe_mpi_tpu.parallel")


def typeof(x):
    """`jax.typeof` appeared after 0.4.x (this environment pins jax
    0.4.37); fall back to the abstract value, which carries the same
    shape/dtype surface and — matching the pre-vma world — no `.vma`.
    The single version shim every vma-aware call site routes through."""
    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    from jax.core import get_aval
    return get_aval(x)


def vma(x) -> frozenset:
    """The varying-manual-axes set of `x` under shard_map; empty on jax
    versions without vma tracking (0.4.x), where replication checking
    is the coarser whole-value `check_rep`."""
    return frozenset(getattr(typeof(x), "vma", None) or ())


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version shim over the moving shard_map surface: jax 0.4.x ships
    it as `jax.experimental.shard_map.shard_map(check_rep=...)`, newer
    jax as top-level `jax.shard_map(check_vma=...)`. Callers use the
    modern spelling; the shim maps the replication-check kwarg to
    whatever the installed jax accepts."""
    import inspect
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
    params = inspect.signature(_sm).parameters
    kw = {}
    if "check_vma" in params:
        kw["check_vma"] = check_vma
    elif "check_rep" in params:
        kw["check_rep"] = check_vma
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside shard_map.
    `lax.axis_size` postdates jax 0.4.x, where `core.axis_frame(name)`
    returns the size directly (an int)."""
    from jax import lax
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    from jax.core import axis_frame
    fr = axis_frame(axis_name)
    return fr if isinstance(fr, int) else fr.size


def mark_varying(x, axis_name: str | None = None, *, like=None):
    """Mark a value as varying over mesh axes (shard_map per-device type
    tracking). Shim over the in-flux pcast/pvary jax API — the single
    definition used by ring attention and the pipeline schedule.
    Idempotent: axes x already varies over are skipped. On jax versions
    without vma tracking (0.4.x: no pcast/pvary, avals carry no .vma)
    this is a no-op — there is no per-axis type to adjust.

    like: instead of naming an axis, copy the varying-axis set of another
    value — scan carries built from jnp.zeros/full must match the vma of
    the sharded inputs they merge with, whatever axes the enclosing
    shard_map spans (e.g. 'data' x 'model' in a DPxSP step)."""
    from jax import lax
    if like is not None:
        axes = tuple(vma(like))
    else:
        axes = (axis_name,)
    missing = tuple(a for a in axes if a and a not in vma(x))
    if not missing:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, missing, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, missing)
    return x  # pre-vma jax: nothing to mark


def resolve_cluster(sp=None, host_id: int | None = None):
    """Resolve the elastic-cluster shape (ISSUE 11) from the solver
    knobs (`hosts` / `coordinator`) with env fallbacks
    (`CAFFE_TPU_NUM_HOSTS` / `CAFFE_TPU_COORDINATOR` /
    `CAFFE_TPU_HOST_ID`) — the reference reads the same facts from
    mpirun's environment (clusters.cpp:8-45). Returns
    (world, coordinator, rank); world <= 1 means single-host (the
    other two are then unchecked). An incomplete multi-host config
    raises resilience.ClusterError — a bounded, journalable failure
    instead of a later hang."""
    import os

    from ..utils import resilience
    world = int(getattr(sp, "hosts", 0) or 0) if sp is not None else 0
    if world <= 0:
        world = int(os.environ.get("CAFFE_TPU_NUM_HOSTS", "0") or 0)
    coordinator = (str(getattr(sp, "coordinator", "") or "")
                   if sp is not None else "")
    if not coordinator:
        coordinator = os.environ.get("CAFFE_TPU_COORDINATOR", "")
    rank = host_id if host_id is not None and host_id >= 0 else int(
        os.environ.get("CAFFE_TPU_HOST_ID", "-1") or -1)
    if world > 1:
        if not coordinator:
            raise resilience.ClusterError(
                f"hosts={world} but no coordinator: set the solver "
                "`coordinator` knob, -coordinator, or "
                "CAFFE_TPU_COORDINATOR")
        if not 0 <= rank < world:
            raise resilience.ClusterError(
                f"hosts={world} needs a host id in [0, {world}): set "
                "-host_id or CAFFE_TPU_HOST_ID")
    return world, coordinator, rank


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None, *,
                     attempts: int = 3, base_delay: float = 1.0,
                     timeout_s: float | None = None) -> None:
    """Multi-host init (reference Clusters::Init / MPI_Init,
    clusters.cpp:8-12). On single-host this is a no-op; under a
    multi-host launcher either the TPU runtime autodetects or the
    caller passes coordinator/num_processes/process_id explicitly.

    Hardened (ISSUE 11): each attempt is bounded by
    `initialization_timeout` (default from CAFFE_TPU_INIT_TIMEOUT, 60 s
    — the in-library connect loop already retries until then, so one
    attempt absorbs a coordinator that is merely *restarting*), failed
    attempts back off exponentially, and exhaustion raises
    resilience.ClusterError — a missing coordinator is a bounded,
    journaled exit-87 failure, never a hang. The `coordinator_down`
    fault site fails the first `count` attempts for the recovery
    suite."""
    if num_processes is None or num_processes <= 1:
        return
    import inspect
    import os
    import time

    from ..utils import resilience
    from ..utils.resilience import FAULTS
    if timeout_s is None:
        timeout_s = float(os.environ.get("CAFFE_TPU_INIT_TIMEOUT", "60")
                          or 60)
    kw = {}
    if "initialization_timeout" in inspect.signature(
            jax.distributed.initialize).parameters:
        kw["initialization_timeout"] = int(max(timeout_s, 1))
    delay = base_delay
    last: Exception | None = None
    for attempt in range(max(attempts, 1)):
        try:
            FAULTS.maybe_raise(
                "coordinator_down", RuntimeError,
                f"injected coordinator outage (attempt {attempt + 1})")
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=num_processes,
                                       process_id=process_id, **kw)
            log.info("jax.distributed initialized: process %d/%d "
                     "(coordinator %s, attempt %d)", jax.process_index(),
                     jax.process_count(), coordinator, attempt + 1)
            return
        except Exception as e:  # noqa: BLE001 — every failure class
            # (gRPC unavailable, timeout, duplicate registration
            # against a dying coordinator) retries the same way
            last = e
            try:
                jax.distributed.shutdown()
            # lint: ok(typed-failure) — partial-init teardown; the
            # retry loop re-raises the real failure as ClusterError
            except Exception:  # noqa: BLE001 — partial init state
                pass
            if attempt + 1 >= max(attempts, 1):
                break
            log.warning("distributed init attempt %d/%d failed (%s); "
                        "retrying in %.1fs", attempt + 1, attempts, e,
                        delay)
            time.sleep(delay)
            delay = min(delay * 2, 30.0)
    raise resilience.ClusterError(
        f"distributed init failed after {attempts} attempt(s) against "
        f"coordinator {coordinator!r}: {last}") from last


def shutdown_distributed() -> None:
    """Best-effort jax.distributed teardown (after the exit barrier):
    rank 0's coordination service must not die underneath a peer that
    is still mid-KV-call."""
    try:
        jax.distributed.shutdown()
    # lint: ok(typed-failure) — already down IS the goal state; there
    # is nothing left to type or journal after the exit barrier
    except Exception:  # noqa: BLE001 — already down is fine
        pass


def _cluster_client():
    """The live coordination-service client, or None outside a
    jax.distributed run. jax 0.4.x exposes it only via the private
    global_state (the public accessor postdates this pin)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    # lint: ok(typed-failure) — None IS the typed answer here: no
    # distributed runtime; every caller handles the None branch
    except Exception:  # noqa: BLE001 — no distributed runtime
        return None


def cluster_barrier(name: str, timeout_s: float = 600.0) -> bool:
    """All-hosts sync point on the coordination service (snapshot
    commit, end-of-training). True on success; False on timeout or a
    dead service — callers map False to a journaled EXIT_CLUSTER, the
    bounded alternative to waiting forever on a host that died."""
    client = _cluster_client()
    if client is None:
        return True
    try:
        client.wait_at_barrier(name, int(timeout_s * 1000))
        return True
    # lint: ok(typed-failure) — False is the typed result; callers map
    # it to a journaled EXIT_CLUSTER (the docstring contract)
    except Exception as e:  # noqa: BLE001 — timeout and UNAVAILABLE alike
        log.error("cluster barrier %r failed: %s", name, e)
        return False


def cluster_kv_set(key: str, value: str) -> bool:
    """Publish a value on the coordination service's KV store (rank 0's
    resume decision). Best-effort: False when the service is gone."""
    client = _cluster_client()
    if client is None:
        return False
    try:
        client.key_value_set(key, value)
        return True
    # lint: ok(typed-failure) — best-effort publish; False is the
    # typed result the caller branches on
    except Exception as e:  # noqa: BLE001
        log.error("cluster kv set %r failed: %s", key, e)
        return False


def cluster_kv_get(key: str, timeout_s: float = 120.0) -> str | None:
    """Blocking KV read (peers waiting for rank 0's resume decision).
    None on timeout / dead service."""
    client = _cluster_client()
    if client is None:
        return None
    try:
        return client.blocking_key_value_get(key, int(timeout_s * 1000))
    # lint: ok(typed-failure) — None is the typed timeout/dead-service
    # result; callers treat it as "no decision published"
    except Exception as e:  # noqa: BLE001
        log.error("cluster kv get %r failed: %s", key, e)
        return None


class KVBeatTransport:
    """Heartbeat transport over the jax.distributed KV store (the
    channel the cluster already trusts for init — no extra
    infrastructure, works without shared storage). Beats are
    set-once sequence-numbered keys (the coordination service forbids
    overwrite); each publish prunes its own beats a window behind, so
    the store stays bounded. Readers use `latest_seq` (a directory
    listing), NEVER an exact key — a reader that armed late (the
    first-contact grace covers minutes of jit-compile skew) or fell
    behind must catch up from whatever history remains, not wedge on a
    pruned sequence number. A dead coordinator makes every call fail,
    which the HostHeartbeat treats as silence — the whole cluster then
    exits 87 within one deadline, the coordinated-restart property."""

    _PREFIX = "caffe_hb"
    _PRUNE_LAG = 16

    def __init__(self, client=None):
        self._client = client if client is not None else _cluster_client()
        if self._client is None:
            raise _no_cluster_error()

    def _key(self, host: int, seq) -> str:
        return f"{self._PREFIX}/{int(host)}/{seq}"

    def publish(self, host: int, seq: int) -> None:
        self._client.key_value_set(self._key(host, seq), "1")
        if seq >= self._PRUNE_LAG:
            try:
                self._client.key_value_delete(
                    self._key(host, seq - self._PRUNE_LAG))
            # lint: ok(typed-failure) — pruning is best-effort; the
            # store stays bounded either way (readers use latest_seq)
            except Exception:  # noqa: BLE001 — pruning is best-effort
                pass

    def latest_seq(self, host: int) -> int:
        """Newest beat sequence `host` has published, -1 when none
        (missing dirs list as empty)."""
        entries = self._client.key_value_dir_get(
            f"{self._PREFIX}/{int(host)}/")
        latest = -1
        for key, _value in entries:
            tail = key.rsplit("/", 1)[-1]
            if tail.isdigit():
                latest = max(latest, int(tail))
        return latest

    def farewell(self, host: int) -> None:
        self._client.key_value_set(self._key(host, "bye"), "1")

    def is_bye(self, host: int) -> bool:
        try:
            self._client.blocking_key_value_get(self._key(host, "bye"), 1)
            return True
        # lint: ok(typed-failure) — absence of the bye key IS the
        # False answer; the KV get has no non-raising miss spelling
        except Exception:  # noqa: BLE001
            return False


def _no_cluster_error():
    from ..utils import resilience
    return resilience.ClusterError(
        "no jax.distributed runtime: KVBeatTransport needs "
        "init_distributed first (or set CAFFE_TPU_HB_DIR for the "
        "shared-directory transport)")


def heartbeat_transport():
    """The heartbeat channel for this run: the shared-directory
    transport when CAFFE_TPU_HB_DIR is set (tests, suspect
    coordination service), else the coordination-service KV store."""
    import os

    from ..utils import resilience
    hb_dir = os.environ.get("CAFFE_TPU_HB_DIR", "")
    if hb_dir:
        return resilience.DirBeatTransport(hb_dir)
    return KVBeatTransport()


def cluster_generation() -> dict | None:
    """The generation record this worker was launched under (ISSUE 19,
    degraded-mode elasticity — docs/robustness.md): the elastic
    supervisor (resilience.supervise_elastic) exports the current
    generation's shape per child via env. None outside a min_hosts
    run (plain ISSUE 11 clusters and single-host runs), so every
    consumer degrades to today's behavior."""
    import os
    gen = os.environ.get("CAFFE_TPU_CLUSTER_GEN", "")
    hosts = os.environ.get("CAFFE_TPU_CLUSTER_HOSTS", "")
    if not gen or not hosts:
        return None
    try:
        return {
            "generation": int(gen),
            "hosts": [int(h) for h in hosts.split(",") if h != ""],
            "world_full": int(
                os.environ.get("CAFFE_TPU_WORLD_FULL", "0") or 0),
            "self": int(
                os.environ.get("CAFFE_TPU_CLUSTER_SELF", "-1") or -1),
        }
    except ValueError:
        return None


def publish_generation() -> bool:
    """Mirror the live generation record onto the coordination
    service's KV store at `caffe/cluster_gen` (rank 0, right after
    formation): peers and in-band tooling can read the cluster's
    current shape over the channel they already trust. The
    supervisor's shared `<prefix>.cluster/` directory stays the source
    of truth — the KV store dies with the cluster epoch, which is
    exactly when the generation protocol must keep running. False
    when this is not a generation-managed run (or the service is
    gone); best-effort either way."""
    gen = cluster_generation()
    if gen is None:
        return False
    import json
    return cluster_kv_set("caffe/cluster_gen",
                          json.dumps(gen, sort_keys=True))


def to_host_array(a, dtype=None) -> np.ndarray:
    """np.asarray that also works for arrays with REMOTE shards (multi-host
    ZeRO-1 slots / TP weights), used by snapshot weight + history export.

    Replicated arrays read a local replica — no collective, any rank may
    call alone. The allgather branch IS collective: every process must
    reach it, in the same order, with no interleaved training collectives
    (callers serialize against the step loop)."""
    if (isinstance(a, jax.Array) and not a.is_fully_addressable
            and not a.is_fully_replicated):
        from jax.experimental import multihost_utils
        a = multihost_utils.process_allgather(a, tiled=True)
    return np.asarray(a) if dtype is None else np.asarray(a, dtype)


def needs_collective_gather(tree) -> bool:
    """True if host-exporting `tree` involves a cross-process collective —
    i.e. some leaf's shards are neither locally addressable nor replicated."""
    return any(isinstance(a, jax.Array) and not a.is_fully_addressable
               and not a.is_fully_replicated
               for a in jax.tree.leaves(tree))


def node_rank() -> int:
    """Reference Clusters::node_rank."""
    return jax.process_index()


def node_count() -> int:
    """Reference Clusters::node_count."""
    return jax.process_count()


@dataclass
class MeshPlan:
    """A mesh plus the sharding rules the solver uses."""

    mesh: Mesh

    @classmethod
    def data_parallel(cls, devices=None) -> "MeshPlan":
        """All devices on the 'data' axis — the reference's (only) strategy."""
        devs = np.asarray(devices if devices is not None else jax.devices())
        return cls(mesh=Mesh(devs.reshape(-1, 1), ("data", "model")))

    @classmethod
    def from_shape(cls, data: int, model: int = 1, devices=None) -> "MeshPlan":
        devs = np.asarray(devices if devices is not None else jax.devices())
        if devs.size != data * model:
            raise ValueError(
                f"mesh {data}x{model} needs {data * model} devices, "
                f"have {devs.size}")
        return cls(mesh=Mesh(devs.reshape(data, model), ("data", "model")))

    @property
    def n_data(self) -> int:
        return self.mesh.shape["data"]

    # -- shardings ------------------------------------------------------
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharded(self, ndim: int, axis: int = 0) -> NamedSharding:
        spec = [None] * ndim
        spec[axis] = "data"
        return NamedSharding(self.mesh, P(*spec))

    def shard_feeds(self, feeds, batch_axis: int = 0):
        """Place a feed pytree with the batch axis sharded over 'data'.
        Batch dims must divide n_data (the reference rounds up with a
        warning, parallel.cpp:284-293; here sharding requires exactness).

        Single-host: plain device_put. Multi-host: each process passes its
        LOCAL portion of the batch (rank-striped by the Feeder) and the
        global array is assembled from process-local shards — the SPMD
        analogue of the reference's per-node DataReader partitions feeding
        one global allreduce domain."""
        if jax.process_count() > 1:
            def put(x):
                sharding = self.batch_sharded(x.ndim, batch_axis)
                return jax.make_array_from_process_local_data(sharding, x)
        else:
            def put(x):
                return jax.device_put(x, self.batch_sharded(x.ndim, batch_axis))
        return jax.tree.map(put, feeds)

    def replicate(self, tree):
        """Broadcast params/state to every device (the reference's startup
        ncclBcast of all weights, parallel.cpp:208-227)."""
        return jax.device_put(tree, self.replicated())

    def shard_feeds_or_replicate(self, feeds, batch_axis: int = 0):
        """shard_feeds with a replication fallback: returns (placed,
        sharded?) where sharded? is False when ANY leaf's batch dim
        doesn't divide n_data (the reference rounds its divide_batch up
        with a warning, parallel.cpp:284-293; SPMD sharding requires
        exactness, so e.g. an odd-sized test batch evaluates replicated
        instead of crashing). Used by the fused eval pipeline to put
        test super-batches on all chips (ISSUE 2)."""
        if all(getattr(x, "ndim", 0) > batch_axis
               and x.shape[batch_axis] % self.n_data == 0
               for x in jax.tree.leaves(feeds)):
            return self.shard_feeds(feeds, batch_axis=batch_axis), True
        return self.replicate(feeds), False

    # -- ZeRO-1 optimizer-state sharding (beyond the reference) ---------
    def zero_slot_sharding(self, shape) -> NamedSharding | None:
        """Sharding for an optimizer slot under zero_stage 1: dim 0 split
        over 'data' (the gradient-averaging axis doubles as the
        slot-partition axis, à la ZeRO/Deepspeed stage 1). Returns None —
        caller keeps the slot replicated — when dim 0 doesn't divide
        n_data (small biases) or the mesh has no data parallelism."""
        if self.n_data <= 1 or not shape or shape[0] % self.n_data:
            return None
        return NamedSharding(self.mesh,
                             P(*(["data"] + [None] * (len(shape) - 1))))

    # -- tensor parallelism (beyond the reference's DP-only surface) ----
    def param_sharding_rules(self, rules: dict[str, tuple]):
        """Declare per-layer weight shardings over the 'model' axis.

        rules: {layer_name: partition_spec_tuple | "rows" | per-param dict}:
          {"fc6": ("model", None)} (or the "rows" shorthand) shards fc6's
          weight dim 0 (output features) over 'model';
          {"moe1": {"w1": ("model",), "w2": ("model",), "b1": ("model",),
                    "b2": ("model",)}} gives expert parallelism — each
          listed param gets its own spec, unlisted params replicate.
        Returns a placement function for param pytrees.

        With params sharded and activations batch-sharded, XLA's GSPMD
        partitioner inserts the all-gather/reduce-scatter pattern of
        Megatron-style tensor parallelism automatically — the 'model' mesh
        axis becomes an intra-layer parallel domain while 'data' stays the
        gradient-averaging domain."""
        def place(params):
            out = {}
            for lname, lparams in params.items():
                rule = rules.get(lname)
                placed = {}
                for pname, arr in lparams.items():
                    if isinstance(rule, dict):
                        spec = rule.get(pname)
                        if spec is None:
                            placed[pname] = jax.device_put(
                                arr, self.replicated())
                        else:
                            if spec == "rows":
                                spec = ("model",)
                            elif isinstance(spec, str):
                                raise ValueError(
                                    f"per-param rule for {lname}/{pname} "
                                    f"must be a spec tuple or 'rows', got "
                                    f"{spec!r}")
                            spec = list(spec)[:arr.ndim]
                            spec += [None] * (arr.ndim - len(spec))
                            placed[pname] = jax.device_put(
                                arr, NamedSharding(self.mesh, P(*spec)))
                    elif rule is not None and pname == "weight":
                        if rule == "rows":
                            spec = ["model"] + [None] * (arr.ndim - 1)
                        else:
                            spec = list(rule)[:arr.ndim]
                            spec += [None] * (arr.ndim - len(spec))
                        placed[pname] = jax.device_put(
                            arr, NamedSharding(self.mesh, P(*spec)))
                    elif (rule is not None and pname == "bias"
                          and arr.ndim >= 1
                          and (rule == "rows"
                               or (len(rule) > 0 and rule[0] == "model"))):
                        # output-dim-sharded weight => the per-output bias
                        # shards the same way (InnerProduct (out,in) and
                        # Convolution (Cout,Cin/g,kh,kw) both carry the
                        # output dim first)
                        placed[pname] = jax.device_put(
                            arr, NamedSharding(self.mesh,
                                               P(*(["model"]
                                                   + [None] * (arr.ndim - 1)))))
                    else:
                        placed[pname] = jax.device_put(arr, self.replicated())
                out[lname] = placed
            return out
        return place
