"""Heterogeneous pipeline parallelism: MPMD GPipe over explicit devices.

The reference has no pipeline parallelism at all (SURVEY §2.7:
ForwardFromTo is a sequential per-device loop, net.cpp:669-682).
parallel/pipeline.py covers the SPMD shift-register case — stages must be
structurally identical (stacked transformer blocks). This module covers
the OTHER half of the pipeline story: nets whose stages differ in both
computation and activation shape — every CNN in the reference zoo
(GoogLeNet/ResNet change channel count and spatial size per stage), which
no shift register can express because the ppermute wire type is fixed.

TPU-native design — single-controller MPMD instead of SPMD:
- Stage s = a contiguous layer range of a Net, jit-compiled ONCE and
  pinned to its own device (computation follows its committed inputs;
  stage params are device_put to stage s at placement time, so model
  memory is truly partitioned 1/S per device).
- The wire between stages is the set of boundary blobs (computed
  statically from the graph); values cross devices via jax.device_put —
  on hardware this is a direct ICI neighbor copy, and non-adjacent
  crossings (a label feeding the last stage, a long skip) hop straight
  from producer to consumer without relaying through middle stages.
- The GPipe schedule is issued wavefront-order from Python; dispatch is
  asynchronous, so device s computes microbatch m while device s+1
  computes m-1 — the classic 1F-wave/1B-wave overlap without any
  hand-written collective.
- Backward is per-stage rematerialization (the GPipe recipe): only the
  boundary activations are saved; each stage's backward jit recomputes
  its forward inside jax.vjp. Peak memory is n_micro boundary blobs, not
  n_micro full activation sets.

Exactness: stages run Net.apply_range — the same code path as
Net.apply — and RNG folds on absolute layer indices, so the pipelined
loss/grads/state match the sequential microbatch loop bit-for-bit in
exact arithmetic (tests assert to float tolerance).

Semantics: microbatches are processed in order within each stage (layer
state, e.g. BN running stats, updates sequentially exactly as a
sequential loop would); the returned loss and grads are MEANS over
microbatches — the same contract as iter_size gradient accumulation
(reference solver.cpp:277-288).
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from ..utils.flops import layer_macs_per_image


def _stage_cost(layer) -> float:
    """Balance weight for auto-splitting: MXU MACs dominate; fall back to
    activation size for HBM-bound layers so pure-elementwise stretches
    still count a little."""
    macs = layer_macs_per_image(layer)
    act = sum(math.prod(s) for s in layer.out_shapes if s)
    return float(macs) + 0.05 * float(act)


def auto_boundaries(net, n_stages: int) -> list[int]:
    """Choose stage boundaries [0=b0 < b1 < ... < b_S=n_layers] balancing
    cumulative layer cost, preferring cut points with few crossing blobs.

    All InputLayerBase layers must land in stage 0 (they are, in every
    zoo net, the first layers). Candidate cuts are positions where the
    number of crossing float blobs is minimal locally — for ResNet/
    GoogLeNet these are the block seams where exactly one activation
    (plus the integer label) crosses."""
    n = len(net.layers)
    if not 1 <= n_stages <= n:
        raise ValueError(f"n_stages {n_stages} out of range for {n} layers")
    costs = [_stage_cost(l) for l in net.layers]
    total = sum(costs) or 1.0
    # crossing width at each cut position (number of blobs alive across it)
    widths = [len(boundary_blobs(net, cut, n)) for cut in range(n + 1)]
    from ..layers.data_layers import InputLayerBase
    first_cut = max((i + 1 for i, l in enumerate(net.layers)
                     if isinstance(l, InputLayerBase)), default=1)
    bounds = [0]
    for s in range(1, n_stages):
        target = total * s / n_stages
        # best cut near the cost quantile: minimize (width, distance)
        lo = max(bounds[-1] + 1, first_cut)
        best, best_key = None, None
        run = 0.0
        for cut in range(1, n):
            run += costs[cut - 1]
            if cut < lo:
                continue
            if cut > n - (n_stages - s):  # leave room for later stages
                break
            key = (widths[cut], abs(run - target) / total)
            if best_key is None or key < best_key:
                best, best_key = cut, key
        if best is None:
            raise ValueError("could not place stage boundaries")
        bounds.append(best)
    bounds.append(n)
    return bounds


def boundary_blobs(net, lo: int, hi: int) -> list[str]:
    """Blobs that layers [lo, hi) consume but that were last produced by
    an earlier layer (the stage's wire-in set). Sorted for determinism."""
    produced_before = set()
    for l in net.layers[:lo]:
        produced_before.update(l.lp.top)
    produced_in: set[str] = set()
    need: set[str] = set()
    for l in net.layers[lo:hi]:
        for b in l.lp.bottom:
            if b not in produced_in:
                if b not in produced_before:
                    raise ValueError(f"blob {b!r} undefined before layer "
                                     f"range [{lo},{hi})")
                need.add(b)
        produced_in.update(l.lp.top)
    return sorted(need)


class GPipe:
    """Pipelined trainer over a Net partitioned into heterogeneous stages.

    devices: one jax device per stage (defaults: first S of jax.devices()).
    boundaries: explicit [0, ..., n_layers] cut list, or None to
    auto-balance by analytic layer cost.
    """

    def __init__(self, net, n_stages: int | None = None, *,
                 boundaries: Sequence[int] | None = None,
                 devices: Sequence[Any] | None = None):
        self.net = net
        if boundaries is None:
            if n_stages is None:
                raise ValueError("give n_stages or boundaries")
            boundaries = auto_boundaries(net, n_stages)
        boundaries = list(boundaries)
        if (boundaries[0] != 0 or boundaries[-1] != len(net.layers)
                or any(a >= b for a, b in zip(boundaries, boundaries[1:]))):
            raise ValueError(f"bad boundaries {boundaries}")
        self.bounds = boundaries
        self.n_stages = len(boundaries) - 1
        if devices is None:
            devices = jax.devices()[: self.n_stages]
        if len(devices) < self.n_stages:
            raise ValueError(
                f"{self.n_stages} stages need {self.n_stages} devices, "
                f"got {len(devices)}")
        self.devices = list(devices[: self.n_stages])

        from ..layers.data_layers import InputLayerBase
        n = len(net.layers)
        self.in_blobs = [boundary_blobs(net, self.bounds[s],
                                        self.bounds[s + 1])
                         for s in range(self.n_stages)]
        # out wire of stage s: tops (re)produced in s that some later layer
        # still consumes — i.e. the in-wire of the remainder of the net
        self.out_blobs = []
        for s in range(self.n_stages):
            hi = self.bounds[s + 1]
            produced = set()
            for l in net.layers[self.bounds[s]: hi]:
                produced.update(l.lp.top)
            rest_need = (set(boundary_blobs(net, hi, n))
                         if hi < n else set())
            self.out_blobs.append(sorted(produced & rest_need))
        # the value stage s reads for wire blob b comes from the LAST stage
        # BEFORE s that (re)produces b — per-consumer-stage, because
        # in-place tops (conv1 -> bn1 -> relu1 all named "conv1") mean a
        # blob name can be re-produced in a later stage than its origin
        produced_by_stage = []
        for s in range(self.n_stages):
            tops: set[str] = set()
            for l in net.layers[self.bounds[s]: self.bounds[s + 1]]:
                tops.update(l.lp.top)
            produced_by_stage.append(tops)
        self._in_producer: list[dict[str, int]] = []
        for s in range(self.n_stages):
            prod = {}
            for b in self.in_blobs[s]:
                for p in range(s - 1, -1, -1):
                    if b in produced_by_stage[p]:
                        prod[b] = p
                        break
            self._in_producer.append(prod)
        # host-feed keys per stage (InputLayerBase layers in the range)
        self.feed_keys: list[list[str]] = []
        for s in range(self.n_stages):
            keys: list[str] = []
            for l in net.layers[self.bounds[s]: self.bounds[s + 1]]:
                if isinstance(l, InputLayerBase):
                    keys.extend(k for k, _, _ in l.feed_specs())
            self.feed_keys.append(keys)
        # home stage of every layer's params (place_params pins them there)
        self._owner_stage: dict[str, int] = {}
        for s in range(self.n_stages):
            for l in net.layers[self.bounds[s]: self.bounds[s + 1]]:
                self._owner_stage[l.name] = s
        # param layers each stage needs (its own + shared-owner layers
        # that live elsewhere); grads for a shared owner accumulate from
        # every referencing stage
        self.param_layers: list[list[str]] = []
        for s in range(self.n_stages):
            names: set[str] = set()
            for l in net.layers[self.bounds[s]: self.bounds[s + 1]]:
                for pname in l.params:
                    owner = net.param_aliases.get((l.name, pname),
                                                  (l.name, pname))
                    names.add(owner[0])
            self.param_layers.append(sorted(names))
        self.state_layers = [
            [l.name for l in net.layers[self.bounds[s]: self.bounds[s + 1]]]
            for s in range(self.n_stages)]
        self._fwd = [self._make_fwd(s) for s in range(self.n_stages)]
        self._bwd = [self._make_bwd(s) for s in range(self.n_stages)]

    # ------------------------------------------------------------------
    def place_params(self, params):
        """device_put each stage's owned params onto its stage device —
        the memory-partitioning step. Shared params stay with their owner
        stage. Returns the placed params dict (same structure)."""
        out = {}
        for lname, tree in params.items():
            dev = self.devices[self._owner_stage.get(lname, 0)]
            out[lname] = {k: jax.device_put(v, dev) for k, v in tree.items()}
        return out

    def owner_stage(self, lname: str) -> int:
        """Home stage of a layer's params (where place_params pins them and
        where the optimizer update for them runs)."""
        return self._owner_stage.get(lname, 0)

    def owned_param_layers(self, s: int, params) -> list[str]:
        """Layers whose params live on stage s — the partition the
        stage-local optimizer update operates on."""
        return sorted(ln for ln in params
                      if self._owner_stage.get(ln, 0) == s)

    def _stage_params(self, params, s: int):
        """Stage s's param view. A shared owner living on another stage's
        device is copied to dev[s] here — jit refuses inputs committed to
        mixed devices, and the referencing stage genuinely needs a local
        replica (the reference analogue: shared blobs exist once per GPU
        anyway; here once per owning stage + a transient copy).

        Cost note: because params change every optimizer step, this copy
        recurs per referencing stage per train_step — but ONLY for params
        genuinely shared across a stage boundary (owner_stage != s); the
        zoo CNNs share nothing cross-stage and pay zero. Siamese-style
        nets that tie weights across distant layers should pick
        boundaries that colocate the tied layers in one stage."""
        out = {}
        for n in self.param_layers[s]:
            if n not in params:
                continue
            tree = params[n]
            if self._owner_stage.get(n, s) != s:
                tree = {k: jax.device_put(v, self.devices[s])
                        for k, v in tree.items()}
            out[n] = tree
        return out

    def _stage_state(self, state, s: int):
        return {n: state[n] for n in self.state_layers[s] if n in state}

    def _make_fwd(self, s: int):
        lo, hi = self.bounds[s], self.bounds[s + 1]
        outs = self.out_blobs[s]
        snames = self.state_layers[s]

        def fwd(stage_params, stage_state, feeds, env_in, rng):
            env, new_state, loss = self.net.apply_range(
                stage_params, stage_state, feeds, env_in, lo, hi,
                train=True, rng=rng)
            return ({b: env[b] for b in outs}, loss,
                    {k: v for k, v in new_state.items() if k in snames})

        return jax.jit(fwd)

    def _make_bwd(self, s: int):
        lo, hi = self.bounds[s], self.bounds[s + 1]

        def bwd(stage_params, stage_state, feeds, env_in, rng,
                ct_out, ct_loss):
            # ct_out's (static) keys select the differentiable out wires;
            # integer outs (labels) are excluded by the caller
            def f(p, e):
                env, new_state, loss = self.net.apply_range(
                    p, stage_state, feeds, e, lo, hi, train=True, rng=rng)
                return ({b: env[b] for b in ct_out}, loss)

            _, vjp_fn = jax.vjp(f, stage_params, env_in)
            ct_params, ct_env = vjp_fn((ct_out, ct_loss))
            # integer wires (labels) produce float0 cotangents — not a
            # valid jit output type and meaningless upstream: drop here
            ct_env = {b: v for b, v in ct_env.items()
                      if v.dtype != jax.dtypes.float0}
            return ct_params, ct_env

        return jax.jit(bwd)

    # ------------------------------------------------------------------
    def train_step(self, params, state, microbatch_feeds: Sequence[dict],
                   *, rngs: Sequence[jax.Array] | None = None,
                   loss_scale: float = 1.0):
        """One pipelined step over n_micro microbatch feed dicts.

        Returns (loss, grads, new_state): loss and grads are means over
        microbatches (iter_size semantics); grads has the structure of the
        OWNED params referenced by the net; new_state is the post-step
        layer state (microbatches applied in order).

        loss_scale: fp16/bf16 loss scaling (reference global_grad_scale,
        net.cpp:116-119): the backward seed is scaled so low-precision
        cotangents don't underflow inside the per-stage vjp; the returned
        grads are SCALED by loss_scale — the caller unwinds it (the
        reference unwinds in SGDSolver::Normalize, net.cpp:815-818). The
        returned loss is unscaled."""
        n_micro = len(microbatch_feeds)
        if n_micro < 1:
            raise ValueError("need at least one microbatch")
        S = self.n_stages
        if rngs is None:
            rngs = [None] * n_micro
        dev = self.devices

        stage_params = [self._stage_params(params, s) for s in range(S)]
        stage_state = [self._stage_state(state, s) for s in range(S)]
        env: list[dict[str, jax.Array]] = [dict() for _ in range(n_micro)]
        saved = [[None] * n_micro for _ in range(S)]
        losses: list[list[jax.Array]] = [[] for _ in range(n_micro)]

        # forward wavefront: at tick t stage s runs microbatch t-s
        for t in range(S + n_micro - 1):
            for s in range(min(t, S - 1), -1, -1):
                m = t - s
                if not 0 <= m < n_micro:
                    continue
                env_in = {b: jax.device_put(env[m][b], dev[s])
                          for b in self.in_blobs[s]}
                feeds = {k: jax.device_put(microbatch_feeds[m][k], dev[s])
                         for k in self.feed_keys[s]}
                st_in = stage_state[s]
                saved[s][m] = (env_in, feeds, st_in, rngs[m])
                out, loss_s, st_new = self._fwd[s](
                    stage_params[s], st_in, feeds, env_in, rngs[m])
                stage_state[s] = st_new
                env[m].update(out)
                losses[m].append(loss_s)

        # backward wavefront (reverse order; cotangents accumulate on the
        # producing stage's device)
        ct_env: list[dict[str, jax.Array]] = [dict() for _ in range(n_micro)]
        grads: dict[str, dict[str, jax.Array]] = {}
        ct_loss_seed = jnp.float32(loss_scale)
        for t in range(S + n_micro - 2, -1, -1):
            for s in range(min(t, S - 1), -1, -1):
                m = t - s
                if not 0 <= m < n_micro:
                    continue
                env_in, feeds, st_in, rng = saved[s][m]
                saved[s][m] = None  # free the residual as soon as consumed
                ct_out = {}
                for b in self.out_blobs[s]:
                    if not jnp.issubdtype(env[m][b].dtype, jnp.floating):
                        continue  # int wires (labels) carry no gradient
                    ct = ct_env[m].pop(b, None)
                    if ct is None:
                        ct = jnp.zeros(env[m][b].shape, env[m][b].dtype)
                    ct_out[b] = jax.device_put(ct, dev[s])
                ct_params, ct_in = self._bwd[s](
                    stage_params[s], st_in, feeds, env_in, rng,
                    ct_out, jax.device_put(ct_loss_seed, dev[s]))
                for lname, tree in ct_params.items():
                    g = grads.setdefault(lname, {})
                    # accumulate on the owner's device: shared params
                    # receive cotangents from several stages' devices
                    gdev = dev[self._owner_stage.get(lname, s)]
                    for pname, ct in tree.items():
                        ct = jax.device_put(ct, gdev)
                        prev = g.get(pname)
                        g[pname] = ct if prev is None else prev + ct
                for b, ct in ct_in.items():
                    if not jnp.issubdtype(ct.dtype, jnp.floating):
                        continue  # int wire (labels): no gradient
                    p = self._in_producer[s].get(b)
                    if p is None:
                        continue
                    ct = jax.device_put(ct, dev[p])
                    prev = ct_env[m].get(b)
                    ct_env[m][b] = ct if prev is None else prev + ct

        inv = 1.0 / n_micro
        grads = {l: {p: g * inv for p, g in tree.items()}
                 for l, tree in grads.items()}
        loss = sum(jnp.sum(jnp.stack([jax.device_put(x, dev[0])
                                      for x in losses[m]]))
                   for m in range(n_micro)) * inv
        new_state = dict(state)
        for s in range(S):
            new_state.update(stage_state[s])
        return loss, grads, new_state
