from .mesh import MeshPlan, init_distributed, node_count, node_rank
