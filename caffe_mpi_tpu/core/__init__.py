from .types import DtypePolicy, dtype_for
from .fillers import fill
