"""Dtype policy — the TPU mapping of the reference's mixed-precision system.

The reference threads a 5-value `Type` enum (DOUBLE/FLOAT/FLOAT16/INT/UINT,
caffe.proto:6-12) through a per-type `SyncedMemory` projection map inside
`Tensor` (include/caffe/tensor.hpp:18-106), letting each layer pick
forward/backward storage and math precision (caffe.proto:374-382).

On TPU there is no manual memory tiering — `jax.Array` lives in HBM and XLA
manages residency — so the whole Tensor/SyncedMemory machinery collapses to a
*dtype policy*: which jnp dtype each layer computes in, and which dtype
parameters are stored in (master weights). FLOAT16 requests map to bfloat16,
the TPU-native 16-bit format (same exponent range as fp32, so the reference's
loss-scaling support becomes optional rather than required).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

# Caffe Type enum name -> jnp dtype. DOUBLE maps to float32: TPU has no f64
# MXU path, and the reference uses DOUBLE only for debugging precision.
_NAME_TO_DTYPE = {
    "DOUBLE": jnp.float64,
    "FLOAT": jnp.float32,
    "FLOAT16": jnp.bfloat16,
    "INT": jnp.int32,
    "UINT": jnp.uint32,
}


def dtype_for(type_name: str, default: jnp.dtype = jnp.float32):
    if not type_name:
        return default
    try:
        return _NAME_TO_DTYPE[type_name]
    except KeyError:
        raise ValueError(f"unknown Type name {type_name!r}") from None


@dataclass(frozen=True)
class DtypePolicy:
    """Per-layer precision choice, resolved from layer + net defaults the way
    reference net.cpp:100-156 resolves forward_type/backward_type and
    forward_math/backward_math."""

    forward: jnp.dtype = jnp.float32   # activation compute dtype
    backward: jnp.dtype = jnp.float32  # gradient compute dtype
    master: jnp.dtype = jnp.float32    # parameter storage dtype
    # MXU math mode for matmul/conv: "default" lets XLA pick (bf16 multiplies
    # with f32 accumulation — the analogue of the reference's tensor-op math
    # override, cudnn_conv_layer.hpp cudnn_math_override); "highest" forces
    # full-f32 multiplies (FLOAT/DOUBLE *_math request).
    precision: str = "default"

    @property
    def lax_precision(self):
        """Value for lax/jnp `precision=` arguments (None = XLA default)."""
        return None if self.precision == "default" else self.precision

    @classmethod
    def resolve(cls, layer_fwd: str, layer_bwd: str, net_fwd: str, net_bwd: str,
                solver_storage: str = "FLOAT", layer_math: str = "",
                net_math: str = "", layer_bmath: str = "",
                net_bmath: str = "") -> "DtypePolicy":
        fwd = dtype_for(layer_fwd or net_fwd)
        bwd = dtype_for(layer_bwd or net_bwd)
        # XLA derives backward precision from the forward op, so the op runs
        # at the stricter of the forward/backward math requests
        fmath = (layer_math or net_math).upper()
        bmath = (layer_bmath or net_bmath).upper()
        strict = {"FLOAT", "DOUBLE"}
        precision = "highest" if (fmath in strict or bmath in strict) else "default"
        return cls(forward=fwd, backward=bwd,
                   master=dtype_for(solver_storage), precision=precision)

    def cast_in(self, x):
        """Cast an input/param to the forward compute dtype (no-op for ints)."""
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != self.forward:
            return x.astype(self.forward)
        return x
