"""Parameter initializers — functional equivalents of the reference fillers.

The reference's Filler hierarchy (include/caffe/filler.hpp) mutates a Blob in
place from a `FillerParameter`; here each filler is a pure function
`(key, shape, dtype) -> array`, driven by the same FillerParameter schema so
prototxt weight_filler/bias_filler blocks behave identically.

Fan-in/fan-out conventions match filler.hpp: for a weight of shape
(out, in, kh, kw), fan_in = count/out = in*kh*kw and fan_out = count/in.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..proto.config import FillerParameter


def _fans(shape: tuple[int, ...]) -> tuple[float, float]:
    count = math.prod(shape)
    num = shape[0] if shape else 1
    channels = shape[1] if len(shape) > 1 else 1
    fan_in = count / num if num else 1
    fan_out = count / channels if channels else 1
    return fan_in, fan_out


def _scale_n(filler: FillerParameter, shape) -> float:
    fan_in, fan_out = _fans(shape)
    norm = filler.variance_norm.upper()
    if norm == "FAN_OUT":
        return fan_out
    if norm == "AVERAGE":
        return (fan_in + fan_out) / 2.0
    return fan_in


def fill(filler: FillerParameter | None, key: jax.Array, shape: tuple[int, ...],
         dtype=jnp.float32) -> jax.Array:
    """Create an initialized parameter array per the filler spec."""
    if filler is None:
        filler = FillerParameter()
    ftype = filler.type
    if ftype == "constant":
        return jnp.full(shape, filler.value, dtype)
    if ftype == "uniform":
        return jax.random.uniform(key, shape, jnp.float32, filler.min,
                                  filler.max).astype(dtype)
    if ftype == "gaussian":
        out = filler.mean + filler.std * jax.random.normal(key, shape, jnp.float32)
        # sparse option (filler.hpp GaussianFiller): keep each output unit's
        # weights with prob sparse/fan_in, zero the rest
        if filler.sparse > 0:
            fan_in, _ = _fans(shape)
            prob = min(1.0, filler.sparse / max(fan_in, 1))
            mask = jax.random.bernoulli(jax.random.fold_in(key, 1), prob, shape)
            out = jnp.where(mask, out, 0.0)
        return out.astype(dtype)
    if ftype == "xavier":
        scale = math.sqrt(3.0 / _scale_n(filler, shape))
        return jax.random.uniform(key, shape, jnp.float32, -scale,
                                  scale).astype(dtype)
    if ftype == "msra":
        std = math.sqrt(2.0 / _scale_n(filler, shape))
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)
    if ftype == "positive_unitball":
        x = jax.random.uniform(key, shape, jnp.float32)
        flat = x.reshape(shape[0], -1)
        flat = flat / jnp.sum(flat, axis=1, keepdims=True)
        return flat.reshape(shape).astype(dtype)
    if ftype == "bilinear":
        # upsampling kernel for Deconvolution (filler.hpp BilinearFiller)
        if len(shape) != 4 or shape[2] != shape[3]:
            raise ValueError("bilinear filler requires square 4D kernels")
        k = shape[3]
        f = math.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = np.ogrid[:k, :k]
        kern = (1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c))
        return jnp.broadcast_to(jnp.asarray(kern, jnp.float32), shape).astype(dtype)
    raise ValueError(f"unknown filler type {ftype!r}")
