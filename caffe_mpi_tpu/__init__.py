"""caffe_mpi_tpu — a TPU-native training framework with the capabilities of
Caffe-MPI (Inspur's MPI+NCCL multi-node NVCaffe fork), rebuilt idiomatically
on JAX/XLA rather than ported.

Architecture (vs the reference at /root/reference):
- declarative prototxt net/solver configs       -> proto/       (pure-Python parser + schema)
- Blob/Tensor/SyncedMemory + CUB pool           -> core/        (jax.Array substrate, dtype policy)
- 124 CUDA/cuDNN layers                          -> ops/ layers/ (pure jit-compatible functions)
- Net graph runtime (net.cpp)                    -> net.py       (graph -> one compiled train step)
- 6 solvers w/ fused CUDA update kernels         -> solver/      (pure update fns fused by XLA)
- MPI+NCCL allreduce (parallel.cpp)              -> parallel/    (Mesh + psum over ICI)
- DataReader/prefetch threads                    -> data/        (host pipeline, double-buffered feed)
- caffe CLI (tools/caffe.cpp)                    -> tools/       (train/test/time/device_query)
"""

__version__ = "0.1.0"
