"""summarize — tabular net structure listing from a prototxt.

Reference: tools/extra/summarize.py (concise per-layer table to check at a
glance that the specified computation is the expected one). Earlier
versions BUILT the net to report real shapes; since ISSUE 15 the table
comes from the jax-free static shape engine (proto/netshape.py — the
same records netlint and tools/mfu_analysis.py consume, cross-checked
bitwise against the real build for the whole zoo), so summarize works
with the tunnel dead, without jax, and without datasets: dims a Data
layer would learn from its DB print as '?'.

Usage:
    python -m caffe_mpi_tpu.tools.summarize NET.prototxt [-phase TRAIN|TEST]
"""

from __future__ import annotations

import argparse
import sys


def _fmt_bytes(n) -> str:
    return "-" if not n else f"{n / 2**20:.1f}"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="summarize")
    p.add_argument("model")
    p.add_argument("-phase", "--phase", default="TRAIN",
                   choices=["TRAIN", "TEST"])
    args = p.parse_args(argv)

    from ..proto import NetParameter
    from ..proto.netshape import _fmt, analyze_net, layer_footprint

    analysis = analyze_net(NetParameter.from_file(args.model),
                           phase=args.phase)
    total_params = 0
    total_macs = 0
    total_fwd = 0
    total_bwd = 0
    print(f"{'layer':<28}{'type':<18}{'top shape':<22}"
          f"{'params':>12}{'MMACs/img':>12}{'fwd MiB':>10}{'bwd MiB':>10}")
    for info in analysis.layers:
        shape = _fmt(info.out_shapes[0]) if info.out_shapes else "-"
        fp = layer_footprint(info)
        n_params = fp["param_count"] or 0
        macs = fp["macs"]
        total_params += n_params
        total_macs += macs or 0
        total_fwd += fp["fwd_bytes"] or 0
        total_bwd += fp["bwd_bytes"] or 0
        print(f"{info.name:<28}{info.type:<18}{shape:<22}"
              f"{n_params or '-':>12}"
              f"{f'{macs / 1e6:.1f}' if macs else '-':>12}"
              f"{_fmt_bytes(fp['fwd_bytes']):>10}"
              f"{_fmt_bytes(fp['bwd_bytes']):>10}")
    for prob in analysis.problems:
        print(f"!! {prob.layer}: [{prob.kind}] {prob.message}",
              file=sys.stderr)
    print(f"\n{len(analysis.layers)} layers | {total_params:,} params "
          f"({total_params * 4 / 2**20:.1f} MiB f32) | "
          f"{2 * total_macs / 1e9:.2f} GFLOPs/img forward | "
          f"{(total_fwd + total_bwd) / 2**20:.0f} MiB fwd+bwd "
          "traffic/batch")
    return 1 if analysis.problems else 0


if __name__ == "__main__":
    sys.exit(main())
