"""summarize — tabular net structure listing from a prototxt.

Reference: tools/extra/summarize.py (concise per-layer table to check at a
glance that the specified computation is the expected one). This version
additionally BUILDS the net, so it reports real output shapes and
parameter counts (the reference prints only declared fields).

Usage:
    python -m caffe_mpi_tpu.tools.summarize NET.prototxt [-phase TRAIN|TEST]
"""

from __future__ import annotations

import argparse
import math
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="summarize")
    p.add_argument("model")
    p.add_argument("-phase", "--phase", default="TRAIN",
                   choices=["TRAIN", "TEST"])
    args = p.parse_args(argv)

    from ..net import Net
    from ..proto import NetParameter
    from ..utils.flops import layer_macs_per_image

    net = Net(NetParameter.from_file(args.model), phase=args.phase)
    total_params = 0
    total_macs = 0
    print(f"{'layer':<28}{'type':<18}{'top shape':<22}"
          f"{'params':>12}{'MMACs/img':>12}")
    for layer in net.layers:
        shape = ("x".join(str(d) for d in layer.out_shapes[0])
                 if layer.out_shapes else "-")
        n_params = sum(math.prod(d.shape) for d in layer.params.values())
        macs = layer_macs_per_image(layer)
        total_params += n_params
        total_macs += macs
        print(f"{layer.name:<28}{layer.lp.type:<18}{shape:<22}"
              f"{n_params or '-':>12}"
              f"{f'{macs / 1e6:.1f}' if macs else '-':>12}")
    print(f"\n{len(net.layers)} layers | {total_params:,} params "
          f"({total_params * 4 / 2**20:.1f} MiB f32) | "
          f"{2 * total_macs / 1e9:.2f} GFLOPs/img forward")
    return 0


if __name__ == "__main__":
    sys.exit(main())
