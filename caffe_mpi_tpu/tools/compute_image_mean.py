"""compute_image_mean — dataset mean as a BlobProto binaryproto.

Reference: tools/compute_image_mean.cpp (iterates the DB, averages pixels,
writes mean.binaryproto consumed by transform_param.mean_file).

Usage:
    python -m caffe_mpi_tpu.tools.compute_image_mean \
        [-backend lmdb|datumfile] INPUT_DB OUTPUT_FILE
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="compute_image_mean")
    p.add_argument("-backend", "--backend", default="lmdb",
                   choices=["lmdb", "datumfile"])
    p.add_argument("input_db")
    p.add_argument("output_file", nargs="?", default="mean.binaryproto")
    args = p.parse_args(argv)

    from ..data.datasets import open_dataset
    from ..io import save_blob_binaryproto

    ds = open_dataset(args.backend, args.input_db)
    total = None
    n = len(ds)
    for i in range(n):
        img, _ = ds.get(i)
        # lint: ok(host-sync) — DB records decode to host ndarrays
        img = np.asarray(img, np.float64)
        total = img if total is None else total + img
    mean = (total / n).astype(np.float32)
    save_blob_binaryproto(args.output_file, mean[None])  # 4D like reference
    print(f"Wrote mean of {n} images to {args.output_file}; "
          f"channel means: {mean.mean(axis=(1, 2))}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
