"""tpulint — whole-tree static analysis for TPU-hostile code patterns.

Replaces the compile-time safety net the reference stack gets for free
(C++ types + nvcc reject most of its bug classes at build time,
e.g. Makefile + src/caffe/CMakeLists.txt drive a type-checked build;
tools/check_host_syncs.py was this framework's single-pass ancestor).
In the JAX rebuild the costliest defects — a `float()` paying one
tunnel RTT per loop iteration, a Python `if` on a traced value, a
traced `lax.reduce_window` init breaking reverse-mode under the axon
hook — compile fine and only surface on a live TPU, which is exactly
the resource this environment cannot count on. So the checks run on
the AST, before any dispatch, with no jax import: the suite survives a
dead tunnel and costs nothing in tier-1.

Framework shape:

- every check is a `LintPass` subclass registered by `@register`; a
  pass implements `check(ctx)` (per file) and/or `check_tree(ctxs,
  root)` (cross-file, e.g. doc-drift)
- findings are waived per statement with a `lint: ok(<pass>) — reason`
  comment on any line of the statement's span or the line directly
  above; the reason is part of the contract — the author claims, in
  the diff, that the flagged pattern is deliberate
- the legacy `# host-sync: ok` spelling keeps working as a waiver for
  the host-sync pass (compat with pre-framework annotations)
- a waiver naming an unknown pass is itself a finding (bad-waiver):
  a misspelled waiver must fail the run, never silently suppress
- a waiver whose named pass no longer produces any finding on its
  statement is reported as stale (stale-waiver, default-on at the CLI,
  `--no-stale` to silence): the waiver inventory must not rot as
  passes and code evolve. Passes that apply waivers themselves
  (doc-drift, knob-drift — `self_waiving = True`) are exempt.
- CLI: `python -m caffe_mpi_tpu.tools.lint [--select P,...] [--json]
  [--changed REF] [--no-stale] [--profile] [paths...]`; default paths
  are the shipped tree (caffe_mpi_tpu/, tools/, bench.py); `--changed
  REF` lints only files named by `git diff --name-only REF` (plus
  explicit paths) for fast pre-commit runs — a typo'd ref is a usage
  error (exit 2), never a false-clean exit 0; exit 1 on any finding;
  `--profile` reports per-pass wall-ms (and the shared-model build
  count) so the 5 s whole-tree budget stays attributable per pass

See docs/static_analysis.md for the pass catalog and how to add one.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize
from dataclasses import dataclass
from typing import Iterable, Iterator

# ---------------------------------------------------------------------------
# findings + waivers

_WAIVER_RE = re.compile(r"#\s*lint:\s*ok\(([^)]*)\)")
_LEGACY_WAIVER_RE = re.compile(r"#\s*host-sync:\s*ok")


def _waivers_in_comment(text: str) -> set[str]:
    names: set[str] = set()
    for m in _WAIVER_RE.finditer(text):
        names.update(n.strip() for n in m.group(1).split(",")
                     if n.strip())
    if _LEGACY_WAIVER_RE.search(text):
        names.add("host-sync")
    return names


def extract_waivers(src: str,
                    tree: "ast.Module | None" = None) -> dict[int, set[str]]:
    """{line: waived pass names} from the REAL comments of `src`.
    Waiver grammar quoted inside string literals or docstrings must
    NOT register as a waiver — text that merely *mentions* the grammar
    cannot suppress a finding on its statement. With a parsed `tree`
    the string spans come from its Constant/JoinedStr nodes (one cheap
    line scan instead of re-tokenizing the file — the tokenizer
    dominated the whole-tree run); without one (syntax-error files,
    direct callers) the tokenizer remains the arbiter."""
    waivers: dict[int, set[str]] = {}
    if "lint:" not in src and "host-sync:" not in src:
        # fast path: no waiver grammar anywhere
        return waivers
    if tree is None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(src).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = []    # unparseable files surface as 'syntax'
        for ln, text in comments:
            names = _waivers_in_comment(text)
            if names:
                waivers.setdefault(ln, set()).update(names)
        return waivers
    spans: list[tuple[int, int, int, int]] | None = None
    for ln0, line in enumerate(src.splitlines()):
        if "lint:" not in line and "host-sync:" not in line:
            continue
        if spans is None:
            # string-literal spans, collected only when a candidate
            # line exists (JoinedStr covers f-strings whole: pre-3.12
            # their inner Constant locations are unreliable)
            spans = [(n.lineno, n.col_offset, n.end_lineno,
                      n.end_col_offset)
                     for n in ast.walk(tree)
                     if (isinstance(n, ast.Constant)
                         and isinstance(n.value, (str, bytes)))
                     or isinstance(n, ast.JoinedStr)]
        ln = ln0 + 1
        # the comment starts at the first '#' OUTSIDE every string
        # literal; everything after it is comment text
        idx = line.find("#")
        while idx != -1:
            if not any(l0 <= ln <= l1
                       and (ln, idx) >= (l0, c0) and (ln, idx) < (l1, c1)
                       for l0, c0, l1, c1 in spans):
                names = _waivers_in_comment(line[idx:])
                if names:
                    waivers.setdefault(ln, set()).update(names)
                break
            idx = line.find("#", idx + 1)
    return waivers


@dataclass
class Finding:
    """One lint violation. `span` is the (first, last) 1-based line range
    a waiver comment is honored on (None = unwaivable); `detail` is a
    short machine tag (e.g. the flagged call shape) for compat shims."""
    pass_name: str
    path: str
    line: int
    message: str
    span: tuple[int, int] | None = None
    detail: str = ""

    def format(self, root: str | None = None) -> str:
        path = os.path.relpath(self.path, root) if root else self.path
        return f"{path}:{self.line}: [{self.pass_name}] {self.message}"

    def as_dict(self, root: str | None = None) -> dict:
        path = os.path.relpath(self.path, root) if root else self.path
        return {"pass": self.pass_name, "path": path, "line": self.line,
                "message": self.message, "detail": self.detail}


def _build_index(n: ast.AST, stmt: ast.stmt | None, parent: ast.AST | None,
                 order: list, info: dict,
                 _iter=ast.iter_child_nodes, _stmt=ast.stmt) -> None:
    """Recursive DFS filling FileContext._index's (order, info): one
    append + one dict store per node keeps the whole-tree build inside
    the 5 s lint budget (the iterative tuple-stack version cost ~2x).
    Callers bump the recursion limit; AST depth tracks source nesting,
    not file size."""
    start = len(order)
    order.append(n)
    if isinstance(n, _stmt):
        stmt = n
    for c in _iter(n):
        _build_index(c, stmt, n, order, info)
    info[id(n)] = (start, len(order), stmt, parent)


_EMPTY_BUCKET: list[ast.AST] = []


class FileContext:
    """One parsed source file shared by all passes: source text, lines,
    AST (None on syntax error), and the per-line waiver map."""

    def __init__(self, path: str, root: str | None = None):
        self.path = os.path.abspath(path)
        self.root = root
        with open(path, encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree: ast.Module | None = None
        self.syntax_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(self.src, filename=path)
        except SyntaxError as e:
            self.syntax_error = e
        # line -> set of pass names waived on that line (real comments
        # only — quoted grammar in strings does not count)
        self.waivers: dict[int, set[str]] = extract_waivers(self.src,
                                                            self.tree)
        self._idx: tuple | None = None
        self._buckets: dict[type, list[ast.AST]] | None = None

    def _index(self) -> tuple:
        """(preorder, info) — ONE DFS over the file, shared by every
        pass: `info[id(n)] = (start, end, stmt, parent)` where
        `preorder[start:end]` is n's whole subtree (preorder keeps
        subtrees contiguous, unlike ast.walk's BFS), `stmt` is n's
        nearest enclosing statement, and `parent` its AST parent.
        Per-pass ast.walk re-traversals dominated the 5 s whole-tree
        budget; this makes every subtree query a list slice and every
        ancestor query a pointer chase."""
        if self._idx is None:
            order: list[ast.AST] = []
            info: dict[int, tuple] = {}
            if self.tree is not None:
                limit = sys.getrecursionlimit()
                sys.setrecursionlimit(max(limit, 20000))
                try:
                    _build_index(self.tree, None, None, order, info)
                finally:
                    sys.setrecursionlimit(limit)
            self._idx = (order, info)
        return self._idx

    def by_type(self, cls: type) -> list[ast.AST]:
        """All nodes of exact type `cls`, in preorder — built once for
        every node class on first use, so a pass that only cares about
        Call/Try/Attribute nodes scans thousands of nodes, not the
        whole 200k-node tree."""
        if self._buckets is None:
            buckets: dict[type, list[ast.AST]] = {}
            for n in self._index()[0]:
                t = type(n)
                b = buckets.get(t)
                if b is None:
                    buckets[t] = [n]
                else:
                    b.append(n)
            self._buckets = buckets
        return self._buckets.get(cls, _EMPTY_BUCKET)

    def parent_of(self, node: ast.AST) -> ast.AST | None:
        """AST parent of `node`, None for the root or nodes outside
        this file's tree."""
        rec = self._index()[1].get(id(node))
        return rec[3] if rec is not None else None

    def walk(self, node: ast.AST | None = None) -> list[ast.AST]:
        """All nodes of `node`'s subtree (default: the whole file) in
        DFS preorder, from the shared precomputed index. Nodes not in
        this file's tree (synthetic wrappers) fall back to ast.walk."""
        order, info = self._index()
        if node is None or node is self.tree:
            return order
        rec = info.get(id(node))
        if rec is None:
            return list(ast.walk(node))
        return order[rec[0]:rec[1]]

    def stmt_of(self, node: ast.AST) -> ast.stmt | None:
        """Nearest enclosing statement of `node` (itself if a stmt),
        None for nodes outside this file's tree."""
        rec = self._index()[1].get(id(node))
        return rec[2] if rec is not None else None

    @property
    def rel(self) -> str:
        """Path relative to the run root; absolute if outside it."""
        if self.root:
            r = os.path.relpath(self.path, self.root)
            if not r.startswith(".."):
                return r
        return self.path

    def span_of(self, stmt: ast.stmt | ast.expr) -> tuple[int, int]:
        """Waiver-search span for a node: its own line range. `waived`
        additionally honors a comment-ONLY line directly above. For a
        compound statement (if/while/for/with/def) the span is the
        HEADER only — a waiver on some nested body statement must not
        silently suppress a finding anchored to the header."""
        end = getattr(stmt, "end_lineno", None) or stmt.lineno
        body = getattr(stmt, "body", None)
        if isinstance(body, list) and body and isinstance(body[0],
                                                          ast.stmt):
            # header end = end of the test/iter expression (NOT
            # body[0].lineno - 1: a comment line between header and
            # body must not fall inside the header span)
            hdr = stmt.lineno
            for attr in ("test", "iter", "items"):
                v = getattr(stmt, attr, None)
                for n in (v if isinstance(v, list)
                          else [v] if v is not None else []):
                    hdr = max(hdr, getattr(n, "end_lineno", 0) or 0)
            end = min(end, hdr)
        return (stmt.lineno, end)

    def comment_only(self, ln: int) -> bool:
        text = self.lines[ln - 1] if 0 < ln <= len(self.lines) else ""
        return text.lstrip().startswith("#")

    def waiver_lines(self, span: tuple[int, int] | None,
                     pass_name: str) -> list[int]:
        """Lines whose waiver suppresses a finding with this span (see
        `span_waiver_lines` — ONE implementation of the binding
        contract, shared with passes that self-apply waivers). The
        caller records these as HONORED so stale-waiver detection knows
        which waivers still earn their keep."""
        if span is None:
            return []
        return span_waiver_lines(span, pass_name, self.waivers,
                                 self.lines)

    def waived(self, span: tuple[int, int] | None, pass_name: str) -> bool:
        return bool(self.waiver_lines(span, pass_name))


def span_waiver_lines(span: tuple[int, int], pass_name: str,
                      waivers: dict[int, set[str]],
                      lines: list[str]) -> list[int]:
    """THE waiver-binding contract, in one place (FileContext and the
    self-waiving passes both delegate here — two copies of this walk
    drifted once and must not again): a waiver binds anywhere in the
    statement's span (trailing comments included), or anywhere in the
    contiguous COMMENT-ONLY block directly above it (a multi-line
    waiver comment binds to the statement it precedes; a trailing
    waiver on the PREVIOUS statement is not comment-only and so cannot
    leak onto the next one)."""
    lo, hi = span
    out = [ln for ln in range(lo, hi + 1)
           if pass_name in waivers.get(ln, ())]
    above = lo - 1
    while 1 <= above <= len(lines) \
            and lines[above - 1].lstrip().startswith("#"):
        if pass_name in waivers.get(above, ()):
            out.append(above)
        above -= 1
    return out


# ---------------------------------------------------------------------------
# pass registry

class LintPass:
    """Base class. Subclasses set `name` + `description` and override
    `check` (per-file) and/or `check_tree` (whole-run, for cross-file
    invariants). Yield `Finding`s; the framework applies waivers.
    Passes that apply waivers THEMSELVES (whole-tree scans over files
    the caller didn't select, e.g. doc-drift) set `self_waiving = True`
    so stale-waiver detection does not misread their waivers as dead."""

    name: str = ""
    description: str = ""
    self_waiving: bool = False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        return iter(())

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        return iter(())


REGISTRY: dict[str, LintPass] = {}


def register(cls: type[LintPass]) -> type[LintPass]:
    inst = cls()
    assert inst.name and inst.name not in REGISTRY, inst.name
    REGISTRY[inst.name] = inst
    return cls


def _load_passes() -> None:
    # import for side effect: each module registers its pass(es)
    from . import (concrete_init, concurrency, doc_drift,  # noqa: F401
                   failure_path, gated_imports, host_sync, knob_drift,
                   netlint, reference_citation, traced_flow)


# ---------------------------------------------------------------------------
# tree walking + running

def repo_root() -> str:
    """The directory holding the caffe_mpi_tpu package."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.dirname(pkg)


DEFAULT_SCAN = ("caffe_mpi_tpu", "tools", "bench.py")


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for target in paths:
        if os.path.isdir(target):
            for dirpath, dirnames, files in os.walk(target):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)
        elif target.endswith(".py"):
            yield target


def _bad_waiver_findings(ctx: FileContext,
                         known: set[str]) -> Iterator[Finding]:
    for ln, names in sorted(ctx.waivers.items()):
        for name in sorted(names - known):
            yield Finding(
                "bad-waiver", ctx.path, ln,
                f"waiver names unknown pass {name!r} (known: "
                f"{', '.join(sorted(known))}) — a misspelled waiver "
                "suppresses nothing", span=None)


def run_lint(paths: Iterable[str] | None = None,
             select: Iterable[str] | None = None,
             root: str | None = None,
             stale: bool = False,
             profile: dict | None = None) -> list[Finding]:
    """Run the selected passes (default: all) over `paths` (default:
    the shipped tree under `root`). Returns waiver-filtered findings,
    ordered by path then line. `stale=True` (the CLI default; library
    default off for fixture ergonomics) additionally reports every
    waiver in the scanned files whose named pass — when selected and
    not self-waiving — no longer suppresses any finding on its
    statement. `profile`, when a dict, is filled with per-pass wall-ms
    (`passes`), file count (`files`), total ms (`total_ms`), and the
    number of shared concurrency-model builds this run performed
    (`model_builds` — the interprocedural passes must share ONE)."""
    import time
    _load_passes()
    root = root or repo_root()
    t_run0 = time.perf_counter()
    prof_ms: dict[str, float] = {}
    builds0 = 0
    if profile is not None:
        from .concurrency import BUILD_COUNT
        builds0 = BUILD_COUNT[0]
    if paths is None:
        # default-scan entries are filtered by existence (a fixture
        # root need not model bench.py); EXPLICIT paths must exist —
        # a typo'd CI path silently reporting "clean" is the one
        # failure mode a tripwire cannot afford
        paths = [p for p in (os.path.join(root, t) for t in DEFAULT_SCAN)
                 if os.path.exists(p)]
    else:
        paths = list(paths)
        bad = [p for p in paths
               if not os.path.exists(p)
               or (os.path.isfile(p) and not p.endswith(".py"))]
        if bad:
            raise FileNotFoundError(
                f"lint path(s) do not exist or are not .py: {bad}")
    if select is None:
        passes = list(REGISTRY.values())
    else:
        unknown = [s for s in select if s not in REGISTRY]
        if unknown:
            # ValueError, not KeyError: main() maps this to a usage
            # error, and a broad KeyError catch would also swallow
            # genuine pass bugs as exit 2
            raise ValueError(
                f"unknown pass(es) {unknown}; known: {sorted(REGISTRY)}")
        passes = [REGISTRY[s] for s in select]
    selected = {p.name for p in passes}

    ctxs: list[FileContext] = []
    findings: list[Finding] = []
    # (path, line, pass) of every waiver that suppressed a finding —
    # the evidence stale-waiver detection subtracts from the inventory
    honored: set[tuple[str, int, str]] = set()
    for path in iter_py_files(paths):
        ctx = FileContext(path, root=root)
        if ctx.syntax_error is not None:
            e = ctx.syntax_error
            findings.append(Finding(
                "syntax", ctx.path, e.lineno or 0,
                f"SYNTAX ERROR: {e.msg}", span=None,
                detail=f"SYNTAX ERROR: {e.msg}"))
            continue
        ctxs.append(ctx)
        findings.extend(_bad_waiver_findings(ctx, set(REGISTRY)))
        for p in passes:
            t0 = time.perf_counter() if profile is not None else 0.0
            for f in p.check(ctx):
                lines = ctx.waiver_lines(f.span, p.name)
                if lines:
                    honored.update((ctx.path, ln, p.name)
                                   for ln in lines)
                else:
                    findings.append(f)
            if profile is not None:
                prof_ms[p.name] = prof_ms.get(p.name, 0.0) \
                    + (time.perf_counter() - t0) * 1000.0
    for p in passes:
        t0 = time.perf_counter() if profile is not None else 0.0
        findings.extend(p.check_tree(ctxs, root))
        if profile is not None:
            prof_ms[p.name] = prof_ms.get(p.name, 0.0) \
                + (time.perf_counter() - t0) * 1000.0
    # tree findings from files in ctxs honor waivers too
    by_path = {c.path: c for c in ctxs}
    kept = []
    for f in findings:
        if f.pass_name in selected and f.path in by_path:
            lines = by_path[f.path].waiver_lines(f.span, f.pass_name)
            if lines:
                honored.update((f.path, ln, f.pass_name)
                               for ln in lines)
                continue
        kept.append(f)
    findings = kept
    if stale:
        # a waiver for a selected, non-self-waiving pass that matched
        # no finding suppresses nothing — the inventory is rotting
        eligible = {p.name for p in passes if not p.self_waiving}
        for ctx in ctxs:
            for ln in sorted(ctx.waivers):
                for name in sorted(ctx.waivers[ln] & eligible):
                    if (ctx.path, ln, name) not in honored:
                        findings.append(Finding(
                            "stale-waiver", ctx.path, ln,
                            f"stale waiver: pass {name!r} reports no "
                            "finding on this statement any more — "
                            "remove the waiver (or run with "
                            "--no-stale to silence this check)",
                            span=None, detail=name))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name))
    if profile is not None:
        from .concurrency import BUILD_COUNT
        profile["passes"] = {n: round(ms, 3)
                             for n, ms in sorted(prof_ms.items())}
        profile["files"] = len(ctxs)
        profile["total_ms"] = round(
            (time.perf_counter() - t_run0) * 1000.0, 3)
        profile["model_builds"] = BUILD_COUNT[0] - builds0
    return findings


def run_pass_on_file(pass_name: str, path: str,
                     root: str | None = None) -> list[Finding]:
    """One pass over one file (compat-shim entry point). Syntax errors
    come back as a single 'syntax' finding."""
    _load_passes()
    ctx = FileContext(path, root=root or repo_root())
    if ctx.syntax_error is not None:
        e = ctx.syntax_error
        return [Finding("syntax", ctx.path, e.lineno or 0,
                        f"SYNTAX ERROR: {e.msg}", span=None,
                        detail=f"SYNTAX ERROR: {e.msg}")]
    p = REGISTRY[pass_name]
    return [f for f in p.check(ctx) if not ctx.waived(f.span, p.name)]


# ---------------------------------------------------------------------------
# shared AST helpers used by several passes

def attr_root(node: ast.expr) -> str | None:
    """Base name of a dotted chain: `lax.scan` -> 'lax',
    `jax.lax.scan` -> 'jax'. None for anything not Name-rooted."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def dotted_name(node: ast.expr) -> str | None:
    """Full dotted spelling of a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


# ---------------------------------------------------------------------------
# CLI

def main(argv: list[str] | None = None) -> int:
    _load_passes()
    ap = argparse.ArgumentParser(
        prog="python -m caffe_mpi_tpu.tools.lint",
        description="tpulint — static analysis for TPU-hostile patterns")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: shipped tree)")
    ap.add_argument("--select", default=None,
                    help="comma-separated pass names (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array")
    ap.add_argument("--list", action="store_true", dest="list_passes",
                    help="list registered passes and exit")
    ap.add_argument("--changed", metavar="REF", default=None,
                    help="lint only .py files named by `git diff "
                         "--name-only REF` (plus explicit paths) — "
                         "fast pre-commit mode; a bad REF exits 2")
    ap.add_argument("--no-stale", action="store_true", dest="no_stale",
                    help="skip stale-waiver detection (waivers whose "
                         "pass no longer fires on their statement)")
    ap.add_argument("--profile", action="store_true", dest="profile",
                    help="report per-pass wall-ms (text: stderr table; "
                         "--json: a {findings, profile} object) so the "
                         "5 s whole-tree budget stays attributable")
    args = ap.parse_args(argv)
    if args.list_passes:
        for name in sorted(REGISTRY):
            print(f"{name:22s} {REGISTRY[name].description}")
        return 0
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    root = repo_root()
    paths = list(args.paths)
    if args.changed is not None:
        import subprocess
        try:
            proc = subprocess.run(
                ["git", "diff", "--name-only", args.changed, "--"],
                cwd=root, capture_output=True, text=True, timeout=60)
        except subprocess.TimeoutExpired:
            # a wedged git (dead NFS, lock contention) must surface as
            # a usage error, not hang the pre-commit hook forever
            sys.stderr.write(f"git diff --name-only {args.changed} "
                             "timed out after 60s\n")
            return 2
        if proc.returncode != 0:
            # a typo'd ref MUST be a usage error, never a false-clean
            # exit 0 with zero files scanned
            sys.stderr.write(proc.stderr or
                             f"git diff --name-only {args.changed} "
                             "failed\n")
            return 2
        # only files the default scan would cover: tests/ and examples/
        # are deliberately OUTSIDE the lint contract (torch-oracle
        # host syncs etc.), and a pre-commit run must not fail on code
        # the full-tree run deliberately exempts
        dir_roots = tuple(t + "/" for t in DEFAULT_SCAN
                          if not t.endswith(".py"))
        changed = [os.path.join(root, rel)
                   for rel in (line.strip()
                               for line in proc.stdout.splitlines())
                   if rel.endswith(".py")
                   and (rel in DEFAULT_SCAN
                        or rel.startswith(dir_roots))]
        # deleted files appear in the diff but no longer exist; new
        # UNTRACKED files never appear — document, don't guess
        paths.extend(p for p in changed if os.path.exists(p))
        # model edits (ISSUE 15): a changed prototxt under models/ or
        # examples/, or the zoo generator itself, triggers the net-*
        # passes — whole-model-tree (they are whole-tree passes, and
        # the per-run analysis cache keeps that cheap), which covers
        # the affected models a fortiori
        from .netlint import MODEL_SCAN, NET_PASSES
        model_dirs = tuple(d + "/" for d in MODEL_SCAN)
        model_changed = [
            rel for rel in (line.strip()
                            for line in proc.stdout.splitlines())
            if (rel.endswith(".prototxt") and rel.startswith(model_dirs))
            or rel == "models/generate_models.py"]
        if not paths and model_changed:
            # prototxt-only change: run just the net-* family over no
            # .py files at all (unless the user already narrowed with
            # --select) — the passes scan the model tree themselves
            if select is None:
                select = list(NET_PASSES)
            try:
                findings = run_lint([], select=select, root=root)
            except ValueError as e:
                print(e.args[0], file=sys.stderr)
                return 2
            return _emit(findings, root, args.as_json)
        if not paths:
            # the --json contract promises a JSON array on stdout even
            # on this fast path — prose goes to stderr
            if args.as_json:
                print("[]")
            print("lint --changed: no changed python or model files in "
                  "the scanned tree (" + ", ".join(DEFAULT_SCAN)
                  + ", " + ", ".join(MODEL_SCAN) + ")",
                  file=sys.stderr)
            return 0
    profile = {} if args.profile else None
    try:
        findings = run_lint(paths or None, select=select, root=root,
                            stale=not args.no_stale, profile=profile)
    except (ValueError, FileNotFoundError) as e:
        print(e.args[0], file=sys.stderr)
        return 2
    return _emit(findings, root, args.as_json, profile=profile)


def _emit(findings: list[Finding], root: str, as_json: bool,
          profile: dict | None = None) -> int:
    if as_json:
        if profile is not None:
            # --json alone keeps the bare-array contract; --profile
            # opts into the {findings, profile} envelope explicitly
            print(json.dumps({"findings": [f.as_dict(root)
                                           for f in findings],
                              "profile": profile}, indent=1))
        else:
            print(json.dumps([f.as_dict(root) for f in findings],
                             indent=1))
    else:
        for f in findings:
            print(f.format(root))
        if profile is not None:
            print(f"lint --profile: {profile.get('files', 0)} files, "
                  f"{len(profile.get('passes', {}))} passes, "
                  f"{profile.get('model_builds', 0)} shared model "
                  f"build(s), {profile.get('total_ms', 0.0):.0f} ms "
                  "total", file=sys.stderr)
            for name, ms in sorted(profile.get("passes", {}).items(),
                                   key=lambda kv: -kv[1]):
                print(f"  {name:24s} {ms:8.1f} ms", file=sys.stderr)
    if findings:
        print(f"{len(findings)} lint finding(s)", file=sys.stderr)
        return 1
    return 0
