"""concrete-init pass — traced init values in reduce_window / scan.

The axon hook pins `jax_disable_bwd_checks=True`; with it, a
`lax.reduce_window` whose init value is a traced scalar (e.g.
`jnp.zeros(())`) breaks reverse-mode linearization (CLAUDE.md; the
shipped fix is ops/pool.py:73-76 — `np.zeros((), x.dtype)[()]`, a
concrete numpy scalar). The reference has no analogue: its pooling
backward is a hand-written kernel (src/caffe/layers/pooling_layer.cu)
with no AD to break. For `lax.scan`, carried arrays are normal — what
gets flagged is only the same hazard shape: a 0-d `jnp.` constructor
(`jnp.zeros(())`, `jnp.array(0.0)`) in the init slot, which should be
a Python/numpy literal scalar instead (same semantics, no traced
operand, no device transfer at trace time).

Approximate BY DESIGN: a bare name in the init slot is invisible (no
dataflow); the pass flags the constructor-in-slot pattern that caused
the documented breakage.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, FileContext, LintPass, attr_root, dotted_name, register

# jnp calls that return trace-time-concrete Python values, fine as inits
_CONCRETE_JNP = {"issubdtype", "iinfo", "finfo", "result_type",
                 "promote_types"}
_CTORS_0D = {"zeros", "ones", "full", "empty"}


def _jnp_rooted(fn: ast.expr) -> bool:
    if not isinstance(fn, ast.Attribute):
        return False
    root = attr_root(fn)
    full = dotted_name(fn) or ""
    return (root in ("jnp", "lax")
            or full.startswith(("jax.numpy.", "jax.lax.")))


def _traced_call_in(subtree) -> ast.Call | None:
    """Any jnp./lax. call in the subtree (metadata helpers excluded)."""
    for sub in subtree:
        if (isinstance(sub, ast.Call) and _jnp_rooted(sub.func)
                and sub.func.attr not in _CONCRETE_JNP):
            return sub
    return None


def _zero_d_ctor_in(subtree) -> ast.Call | None:
    """A 0-d jnp constructor in the subtree: jnp.zeros(()) /
    jnp.ones([]) / jnp.array(<number>)."""
    for sub in subtree:
        if not (isinstance(sub, ast.Call) and _jnp_rooted(sub.func)):
            continue
        attr = sub.func.attr
        if not sub.args:
            continue
        shape = sub.args[0]
        if attr in _CTORS_0D and isinstance(
                shape, (ast.Tuple, ast.List)) and not shape.elts:
            return sub
        if attr in ("array", "asarray") and isinstance(
                shape, (ast.Constant, ast.UnaryOp)):
            return sub
    return None


@register
class ConcreteInitPass(LintPass):
    name = "concrete-init"
    description = ("lax.reduce_window/lax.scan init values must be "
                   "concrete scalars, not traced jnp constructors")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.by_type(ast.Call):
            if not isinstance(node.func, ast.Attribute):
                continue
            stmt = ctx.stmt_of(node)
            span = ctx.span_of(stmt) if stmt is not None else None
            if node.func.attr == "reduce_window":
                init = (node.args[1] if len(node.args) > 1 else
                        next((kw.value for kw in node.keywords
                              if kw.arg == "init_value"), None))
                if init is None:
                    continue
                hit = _traced_call_in(ctx.walk(init))
                if hit is not None:
                    yield Finding(
                        self.name, ctx.path, init.lineno,
                        "reduce_window init value is a traced "
                        f"`{dotted_name(hit.func)}` expression — under "
                        "the axon hook's jax_disable_bwd_checks this "
                        "breaks reverse-mode linearization; use a "
                        "concrete scalar (literal, or "
                        "`np.zeros((), dtype)[()]` for a typed zero)",
                        span=span)
            elif (node.func.attr == "scan"
                  and attr_root(node.func) in ("lax", "jax")):
                init = (node.args[1] if len(node.args) > 1 else
                        next((kw.value for kw in node.keywords
                              if kw.arg == "init"), None))
                if init is None:
                    continue
                hit = _zero_d_ctor_in(ctx.walk(init))
                if hit is not None:
                    yield Finding(
                        self.name, ctx.path, hit.lineno,
                        "scan init carries a 0-d "
                        f"`{dotted_name(hit.func)}` constructor — "
                        "write the scalar as a Python/numpy literal "
                        "(same semantics, no traced operand; the "
                        "reduce_window variant of this pattern breaks "
                        "reverse-mode under the axon hook)",
                        span=span)
