"""gated-imports pass — third-party imports the image may not have.

The container does not ship lmdb, flask, pybind11 or rust, and torch
(CPU) is reserved for tests as an independent numerical oracle
(CLAUDE.md environment contract). An unguarded `import lmdb` at the
top of a production module turns a missing optional dependency into an
ImportError at package-import time — the reference equivalent is
Makefile.config's USE_LMDB/USE_LEVELDB build gates compiled into
`#ifdef` guards (src/caffe/util/db.cpp); here the gate is a
`try/except ImportError` around the import, with an in-repo fallback
(data/lmdb_io.py implements the on-disk format directly).

Files under a `tests/` directory are exempt: tests may assume their
oracle (torch) and skip via collection machinery instead.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, FileContext, LintPass, register

GATED_MODULES = {"lmdb", "flask", "pybind11", "torch"}


def _handles_import_error(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:           # bare except
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        leaf = n.attr if isinstance(n, ast.Attribute) else getattr(
            n, "id", "")
        if leaf in ("ImportError", "ModuleNotFoundError", "Exception",
                    "BaseException"):
            return True
    return False


@register
class GatedImportsPass(LintPass):
    name = "gated-imports"
    description = ("lmdb/flask/pybind11/torch imports outside tests/ "
                   "must sit under try/except ImportError")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = ctx.path.split("/")
        if "tests" in parts:
            return
        # a finding requires an import naming a gated module, so its
        # name appears literally in the source — skip the tree walk for
        # the vast majority of files that never mention one
        if not any(m in ctx.src for m in GATED_MODULES):
            return

        def visit(node: ast.AST, gated: bool) -> Iterator[Finding]:
            """Check `node` itself, then its children with the gate
            state the runtime would actually see."""
            g = gated
            if isinstance(node, ast.Try):
                # only the try BODY is protected by the handler; an
                # import inside the except/else/finally blocks raises
                # uncaught at runtime
                body_gated = gated or any(_handles_import_error(h)
                                          for h in node.handlers)
                for part in node.body:
                    yield from visit(part, body_gated)
                for part in (*node.handlers, *node.orelse,
                             *node.finalbody):
                    yield from visit(part, gated)
                return
            elif isinstance(node, ast.If):
                t = node.test
                name = (t.attr if isinstance(t, ast.Attribute)
                        else getattr(t, "id", ""))
                if name == "TYPE_CHECKING":
                    # `if TYPE_CHECKING:` never executes at runtime —
                    # but its `else:` branch ALWAYS does, so only the
                    # body inherits the gate
                    for part in node.body:
                        yield from visit(part, True)
                    for part in node.orelse:
                        yield from visit(part, gated)
                    return
            mods: list[str] = []
            if isinstance(node, ast.Import):
                mods = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                mods = [(node.module or "").split(".")[0]]
            for mod in mods:
                if mod in GATED_MODULES and not gated:
                    yield Finding(
                        self.name, ctx.path, node.lineno,
                        f"`import {mod}` is not gated — the image "
                        "may not ship it; wrap in try/except "
                        "ImportError with a fallback or a clear "
                        "named error (CLAUDE.md environment "
                        "contract)",
                        span=ctx.span_of(node))
            for child in ast.iter_child_nodes(node):
                yield from visit(child, g)

        for child in ast.iter_child_nodes(ctx.tree):
            yield from visit(child, False)
