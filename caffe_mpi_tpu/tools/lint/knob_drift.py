"""knob-drift pass — performance knobs must be consumed, flagged, and
documented (the accepted-but-ignored detector).

ISSUE 6's trigger: `reduce_buckets` sat in the config schema for five
PRs as accepted-and-ignored (proto/config.py — the reference consumes
it in net.cpp:757-913, we silently didn't). A knob that parses but
drives nothing is worse than a missing one: recipes carry it, operators
tune it, and nothing changes. This pass holds every registered
performance knob to four legs at once:

  1. declared:  a `SolverParameter` dataclass field in
                caffe_mpi_tpu/proto/config.py (read by AST, no import)
  2. flagged:   spelled in caffe_mpi_tpu/tools/cli.py (the `caffe
                train` surface — a knob users cannot reach from the
                CLI is a solver-internal, not a knob)
  3. documented: named in docs/benchmarks.md (the perf-knob runbook)
  4. consumed:  READ somewhere under caffe_mpi_tpu/ or bench.py
                outside the schema, the CLI plumbing, and this lint
                package itself — a Load-context attribute access
                `.knob` or a `"knob"` string literal passed as a call
                argument (getattr / has checks). Writes (`sp.knob =
                args.knob` is plumbing, not consumption), docstring
                mentions, and this registry's own KNOBS tuple do NOT
                count. This is the leg whose absence means
                accept-and-ignore.

Like doc-drift, this is a whole-tree pass rooted at the run root;
roots without the schema/CLI/docs triple (fixture dirs) produce no
findings. Waive a leg on the knob's registry line below with
`# lint: ok(knob-drift) — reason` (e.g. a knob staged one PR before
its consumer).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from . import FileContext, Finding, LintPass, iter_py_files, register

# the knob registry: solver-level execution-schedule/perf knobs, each
# required to satisfy all four legs. Extend this tuple when adding a
# knob (the docs/benchmarks.md section for it is then enforced too).
KNOBS = (
    "step_chunk",       # ISSUE 1: K-step fused training
    "test_chunk",       # ISSUE 2: fused async evaluation
    "reduce_overlap",   # ISSUE 6: overlapped bucketed reduction
    "reduce_buckets",   # ISSUE 6: bucket count
    "grad_bucket_mb",   # ISSUE 6: bucket byte budget
    "serve_window_ms",  # ISSUE 7: continuous-batching window
    "serve_buckets",    # ISSUE 7: AOT padded-batch bucket ladder
    "serve_hbm_mb",     # ISSUE 7: resident-model HBM budget (LRU spill)
    "precision",        # ISSUE 9: bf16 compute, f32 master weights
    "loss_scale",       # ISSUE 9: static/dynamic bf16 loss scaling
    "loss_scale_window",  # ISSUE 9: clean steps before scale regrowth
    "serve_dtype",      # ISSUE 9: bf16 serving bucket programs
    "decoded_cache_mb",  # ISSUE 10: bounded decoded-record cache tier
    "hosts",            # ISSUE 11: elastic multi-host cluster size
    "coordinator",      # ISSUE 11: coordination-service address
    "host_deadline",    # ISSUE 11: cross-host heartbeat deadline
    "serve_queue_limit",  # ISSUE 12: load-shedding admission control
    "serve_deadline_ms",  # ISSUE 12: per-request dispatch deadline
    "serve_stall_s",    # ISSUE 12: serving dispatch stall breaker
    "serve_decoded_cache_mb",  # ISSUE 14: hot-content request cache
    "serve_program_bank",  # ISSUE 17: persistent AOT program bank
    "serve_replicas",   # ISSUE 18: serving fleet size (replica procs)
    "serve_retry_budget",  # ISSUE 18: router sibling-retry budget
    "replica_deadline",  # ISSUE 18: replica heartbeat deadline
    "min_hosts",        # ISSUE 19: degraded-mode quorum floor
)

CONFIG_FILE = os.path.join("caffe_mpi_tpu", "proto", "config.py")
CLI_FILE = os.path.join("caffe_mpi_tpu", "tools", "cli.py")
DOCS_FILE = os.path.join("docs", "benchmarks.md")
# where a consumer read counts (schema + CLI plumbing excluded: writing
# `sp.knob = args.knob` is not consumption; the lint package excluded:
# its own KNOBS registry naming every knob must not satisfy the leg it
# enforces)
CONSUMER_SCAN = ("caffe_mpi_tpu", "bench.py")
_EXCLUDED_CONSUMERS = (CONFIG_FILE, CLI_FILE)
_EXCLUDED_CONSUMER_DIRS = (os.path.join("caffe_mpi_tpu", "tools", "lint"),)


def _solver_fields(path: str) -> dict[str, int]:
    """{field_name: line} of SolverParameter's dataclass fields (plus
    NetParameter's net-level knobs and ServingParameter's serving-plane
    knobs, which count as declarations too), by AST — the pass must run
    without the package importable."""
    tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
    fields: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in (
                "SolverParameter", "NetParameter", "ServingParameter"):
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    fields.setdefault(stmt.target.id, stmt.lineno)
    return fields


def _mentions(src: str, knob: str) -> bool:
    return knob in src


def _reads(attrs, calls) -> set[str]:
    """Names the AST READS: Load-context `x.attr` attribute accesses,
    plus string literals passed as call arguments (getattr(sp, "knob"),
    sp.has("knob")). Store/Del-context attributes (`sp.knob = args.knob`
    — plumbing) and bare strings outside a call (docstrings, registry
    tuples) are excluded. Takes Attribute and Call node iterables
    (ctx.by_type buckets, or filtered ast.walk); one scan per file
    serves every knob."""
    reads: set[str] = set()
    for node in attrs:
        if isinstance(node.ctx, ast.Load):
            reads.add(node.attr)
    for node in calls:
        for a in node.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                reads.add(a.value)
        for kw in node.keywords:
            a = kw.value
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                reads.add(a.value)
    return reads


@register
class KnobDriftPass(LintPass):
    name = "knob-drift"
    description = ("perf knobs (step_chunk/test_chunk/reduce_*) must be "
                   "declared, CLI-flagged, documented, and CONSUMED — "
                   "no accept-and-ignore")
    self_waiving = True   # applies registry-line waivers itself

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        cfg_path = os.path.join(root, CONFIG_FILE)
        cli_path = os.path.join(root, CLI_FILE)
        docs_path = os.path.join(root, DOCS_FILE)
        if not (os.path.isfile(cfg_path) and os.path.isfile(cli_path)
                and os.path.isfile(docs_path)):
            return
        fields = _solver_fields(cfg_path)
        cli_src = open(cli_path, encoding="utf-8").read()
        docs_src = open(docs_path, encoding="utf-8").read()

        # consumer scan: whole production tree, reusing parsed ctxs
        by_path = {c.path: c for c in ctxs}
        consumed: set[str] = set()
        for target in CONSUMER_SCAN:
            path = os.path.join(root, target)
            if not os.path.exists(path):
                continue
            for fp in iter_py_files([path]):
                rel = os.path.relpath(fp, root)
                if rel in _EXCLUDED_CONSUMERS or any(
                        rel == d or rel.startswith(d + os.sep)
                        for d in _EXCLUDED_CONSUMER_DIRS):
                    continue
                if consumed.issuperset(KNOBS):
                    break
                ctx = by_path.get(os.path.abspath(fp))
                if ctx is not None:
                    if ctx.tree is None:
                        continue
                    reads = _reads(ctx.by_type(ast.Attribute),
                                   ctx.by_type(ast.Call))
                else:
                    try:
                        nodes = list(ast.walk(ast.parse(
                            open(fp, encoding="utf-8").read())))
                    except SyntaxError:
                        continue
                    reads = _reads(
                        (n for n in nodes
                         if isinstance(n, ast.Attribute)),
                        (n for n in nodes if isinstance(n, ast.Call)))
                consumed.update(k for k in KNOBS if k in reads)

        cfg_ctx = by_path.get(os.path.abspath(cfg_path))
        waivers = cfg_ctx.waivers if cfg_ctx is not None else {}
        for knob in KNOBS:
            line = fields.get(knob, 1)

            def waived() -> bool:
                return self.name in waivers.get(line, ()) or \
                    self.name in waivers.get(line - 1, ())

            missing = []
            if knob not in fields:
                missing.append("a Solver/Net/ServingParameter field in "
                               + CONFIG_FILE)
            if not _mentions(cli_src, knob):
                missing.append("a CLI flag in " + CLI_FILE)
            if not _mentions(docs_src, knob):
                missing.append("documentation in " + DOCS_FILE)
            if knob not in consumed:
                missing.append(
                    "a consumer read under caffe_mpi_tpu/ — the knob "
                    "is accepted but IGNORED")
            if missing and not waived():
                yield Finding(
                    self.name, cfg_path, line,
                    f"knob {knob!r} is missing " + "; ".join(missing),
                    span=None)
