"""host-sync pass — device materialization inside a hot loop.

Port of tools/check_host_syncs.py (the framework's single-pass
ancestor; that file is now a deprecation shim delegating here) into
the pass framework, widened from its 7-module allowlist to the whole
tree. The TPU sits behind a tunnel: every device->host
materialization (`float()` / `np.asarray()` / `.item()` /
`jax.device_get`) costs ~tens of ms of round-trip latency, and one of
those inside a loop serializes the async dispatch pipeline (CLAUDE.md;
round 5 found a per-iteration `float()` in the gpipe clip path this
way).

Scope-aware where the ancestor was purely lexical: a function or
lambda *defined* inside a loop opens a new dynamic scope — its body
does not run once per loop iteration at definition time, so loop depth
resets there (the ancestor flagged closure bodies defined in loops;
per-file waiver noise at whole-tree scale would have drowned the
signal).

Static and approximate BY DESIGN: it cannot prove a value is a device
array, so it flags the call pattern and relies on waivers for the
deliberate cases (display-boundary materializations, host-side ndarray
normalization, text parsing). The waiver reason is part of the
contract: the author claims, in the diff, that the sync is intentional
and boundary-rate — or that the operand never lives on device.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, FileContext, LintPass, register

# call shapes that materialize a device value on the host
_NAME_CALLS = {"float"}                      # float(x)
_ATTR_CALLS = {                              # module.attr(x)
    ("np", "asarray"), ("np", "array"), ("numpy", "asarray"),
    ("numpy", "array"), ("jax", "device_get"),
}
_METHOD_CALLS = {"item"}                     # x.item()

# comprehensions/genexprs ARE loops: `[float(l) for l in losses]` pays
# one RTT per element just like the for-statement spelling
_LOOPS = (ast.For, ast.While, ast.AsyncFor,
          ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)

# a def/lambda body is a new dynamic scope: defining it inside a loop
# does not execute it inside the loop
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def call_kind(node: ast.Call) -> str | None:
    fn = node.func
    # a literal operand is never a device value: float("nan"),
    # np.asarray(0.5) and friends are constant folding, not syncs
    if node.args and isinstance(node.args[0], ast.Constant):
        return None
    if isinstance(fn, ast.Name) and fn.id in _NAME_CALLS:
        return fn.id
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and (fn.value.id,
                                               fn.attr) in _ATTR_CALLS:
            return f"{fn.value.id}.{fn.attr}"
        if fn.attr in _METHOD_CALLS and not node.args:
            return f".{fn.attr}()"
    return None


@register
class HostSyncPass(LintPass):
    name = "host-sync"
    description = ("float()/np.asarray()/.item()/device_get inside a "
                   "loop — one tunnel RTT per iteration")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        findings: list[Finding] = []

        def visit(node: ast.AST, depth: int,
                  stmt: ast.stmt | None) -> None:
            """Process `node` at loop depth `depth` (already includes
            this node's own loop contribution), then its children."""
            if isinstance(node, ast.stmt):
                stmt = node
            if depth > 0 and isinstance(node, ast.Call):
                kind = call_kind(node)
                if kind is not None:
                    findings.append(Finding(
                        self.name, ctx.path, node.lineno,
                        f"{kind} inside a loop — a device value here "
                        "costs one tunnel RTT per iteration; keep it "
                        "on device, or waive with "
                        "`# lint: ok(host-sync) — reason` if the sync "
                        "is deliberate and boundary-rate (or the "
                        "operand is host data)",
                        span=(ctx.span_of(stmt) if stmt is not None
                              else None),
                        detail=kind))
            if isinstance(node, (ast.For, ast.AsyncFor)):
                # the iterable is evaluated ONCE, before the first
                # iteration — only target/body/orelse run per pass.
                # descend (not visit): a comprehension AS the iterable
                # still loops over its own elements
                descend(node.iter, depth - 1, stmt)
                for child in [node.target, *node.body, *node.orelse]:
                    descend(child, depth, stmt)
                return
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                # ditto the first generator's source sequence
                gen0 = node.generators[0]
                descend(gen0.iter, depth - 1, stmt)
                rest = [gen0.target, *gen0.ifs, *node.generators[1:]]
                if isinstance(node, ast.DictComp):
                    rest += [node.key, node.value]
                else:
                    rest.append(node.elt)
                for child in rest:
                    descend(child, depth, stmt)
                return
            for child in ast.iter_child_nodes(node):
                descend(child, depth, stmt)

        def descend(child: ast.AST, depth: int,
                    stmt: ast.stmt | None) -> None:
            if isinstance(child, _SCOPES):
                # a def/lambda body is a new dynamic scope — loop
                # depth does not carry into it
                visit(child, 0, stmt)
            else:
                visit(child,
                      depth + (1 if isinstance(child, _LOOPS) else 0),
                      stmt)

        visit(ctx.tree, 0, None)
        yield from findings
