"""host-sync pass — device materialization inside a hot loop.

Port of tools/check_host_syncs.py (the framework's single-pass
ancestor; that file is now a deprecation shim delegating here) into
the pass framework, widened from its 7-module allowlist to the whole
tree. The TPU sits behind a tunnel: every device->host
materialization (`float()` / `np.asarray()` / `.item()` /
`jax.device_get`) costs ~tens of ms of round-trip latency, and one of
those inside a loop serializes the async dispatch pipeline (CLAUDE.md;
round 5 found a per-iteration `float()` in the gpipe clip path this
way).

Scope-aware where the ancestor was purely lexical: a function or
lambda *defined* inside a loop opens a new dynamic scope — its body
does not run once per loop iteration at definition time, so loop depth
resets there (the ancestor flagged closure bodies defined in loops;
per-file waiver noise at whole-tree scale would have drowned the
signal).

Static and approximate BY DESIGN: it cannot prove a value is a device
array, so it flags the call pattern and relies on waivers for the
deliberate cases (display-boundary materializations, host-side ndarray
normalization, text parsing). The waiver reason is part of the
contract: the author claims, in the diff, that the sync is intentional
and boundary-rate — or that the operand never lives on device.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, FileContext, LintPass, register

# call shapes that materialize a device value on the host
_NAME_CALLS = {"float"}                      # float(x)
_ATTR_CALLS = {                              # module.attr(x)
    ("np", "asarray"), ("np", "array"), ("numpy", "asarray"),
    ("numpy", "array"), ("jax", "device_get"),
}
_METHOD_CALLS = {"item"}                     # x.item()

# comprehensions/genexprs ARE loops: `[float(l) for l in losses]` pays
# one RTT per element just like the for-statement spelling
_LOOPS = (ast.For, ast.While, ast.AsyncFor,
          ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)

# a def/lambda body is a new dynamic scope: defining it inside a loop
# does not execute it inside the loop
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def call_kind(node: ast.Call) -> str | None:
    fn = node.func
    # a literal operand is never a device value: float("nan"),
    # np.asarray(0.5) and friends are constant folding, not syncs
    if node.args and isinstance(node.args[0], ast.Constant):
        return None
    if isinstance(fn, ast.Name) and fn.id in _NAME_CALLS:
        return fn.id
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and (fn.value.id,
                                               fn.attr) in _ATTR_CALLS:
            return f"{fn.value.id}.{fn.attr}"
        if fn.attr in _METHOD_CALLS and not node.args:
            return f".{fn.attr}()"
    return None


@register
class HostSyncPass(LintPass):
    name = "host-sync"
    description = ("float()/np.asarray()/.item()/device_get inside a "
                   "loop — one tunnel RTT per iteration")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # candidate-first: scan the shared Call bucket, then climb
        # ancestors only for the handful of matching calls — the old
        # full-tree recursion (2 frames/node) dominated the 5 s
        # whole-tree budget
        for node in ctx.by_type(ast.Call):
            kind = call_kind(node)
            if kind is None:
                continue
            if self._loop_depth(ctx, node) > 0:
                stmt = ctx.stmt_of(node)
                yield Finding(
                    self.name, ctx.path, node.lineno,
                    f"{kind} inside a loop — a device value here "
                    "costs one tunnel RTT per iteration; keep it "
                    "on device, or waive with "
                    "`# lint: ok(host-sync) — reason` if the sync "
                    "is deliberate and boundary-rate (or the "
                    "operand is host data)",
                    span=(ctx.span_of(stmt) if stmt is not None
                          else None),
                    detail=kind)

    @staticmethod
    def _loop_depth(ctx: FileContext, node: ast.Call) -> int:
        """Dynamic loop depth of `node`: loop ancestors below the
        nearest enclosing def/lambda, minus loops whose evaluated-once
        iterable subtree contains `node` (a For's `iter` and a
        comprehension's first-generator source run before the first
        iteration, so they sit one level OUTSIDE their own loop)."""
        depth = 0
        child, parent = node, ctx.parent_of(node)
        while parent is not None:
            if isinstance(parent, _SCOPES):
                break
            if isinstance(parent, ast.While):
                # everything under a while — test included — runs per
                # iteration
                depth += 1
            elif isinstance(parent, (ast.For, ast.AsyncFor)):
                if child is not parent.iter:
                    depth += 1
            elif isinstance(parent, (ast.ListComp, ast.SetComp,
                                     ast.DictComp, ast.GeneratorExp)):
                gen0 = parent.generators[0]
                # `child` here is the comprehension field holding us —
                # the generators are not AST nodes, so the parent chain
                # jumps straight from iter/target/elt to the comp node;
                # containment in gen0.iter decides the evaluated-once
                # exemption
                it = gen0.iter
                rec = ctx._index()[1]
                me, span = rec.get(id(node)), rec.get(id(it))
                inside_iter = (me is not None and span is not None
                               and span[0] <= me[0] < span[1])
                if not inside_iter:
                    depth += 1
            child, parent = parent, ctx.parent_of(parent)
        return depth
