"""doc-drift pass — FAULT_SITES registry vs docs vs call sites.

Folds tests/test_doc_drift.py's fault-injection consistency check into
the lint CLI (the test is now a thin wrapper over this pass — one
enforcement path, two entry points). The site list is load-bearing
operator documentation (docs/robustness.md): a site added at a call
site but missing from the registry silently rots the runbook, a
registry entry whose call site was deleted documents a lever that no
longer exists. Three sources of truth are held equal:

  1. the registry: `FAULT_SITES` in caffe_mpi_tpu/utils/resilience.py
     (read by AST, not import — the pass must run without the package
     importable, e.g. from a checkout with a broken module)
  2. the docs:     the `Sites:` list in docs/robustness.md
  3. the code:     literal site names at FAULTS helper call sites
     under caffe_mpi_tpu/, tools/ and bench.py

Unlike the per-file passes this one always scans the tree rooted at
the run root (`check_tree`), regardless of which paths were selected —
a partial scan must not report half the call sites as dead. Roots
without a registry/docs pair (plain projects, fixture dirs that don't
model them) produce no findings.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from . import (DEFAULT_SCAN, Finding, LintPass, extract_waivers,
               iter_py_files, register, span_waiver_lines)

# every FaultPlane entry point a production call site can name a site
# through (fire/fire_at and the one-line helpers)
_HELPERS = ("fire", "fire_at", "active", "maybe_raise", "maybe_stall",
            "maybe_exit", "corrupt_file", "corrupt_bytes")
_CALL_RE = re.compile(
    r"\.(?:%s)\(\s*[\"']([a-z_]+)[\"']" % "|".join(_HELPERS))

REGISTRY_FILE = os.path.join("caffe_mpi_tpu", "utils", "resilience.py")
DOCS_FILE = os.path.join("docs", "robustness.md")
# source trees whose FAULTS call sites are production injection points
# (tests configure sites by string; they are consumers, not sites) —
# the framework's default scan, so the two roots cannot drift apart
SCAN = DEFAULT_SCAN


def _registry_sites(path: str) -> tuple[dict[str, tuple[int, str]], int]:
    """{site: (line, description)} from the FAULT_SITES dict literal,
    plus the assignment's line (0 when absent)."""
    tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "FAULT_SITES" and \
                    isinstance(value, ast.Dict):
                sites = {}
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        desc = (v.value if isinstance(v, ast.Constant)
                                and isinstance(v.value, str) else "")
                        sites[k.value] = (k.lineno, desc)
                return sites, node.lineno
    return {}, 0


def _stmt_spans(nodes) -> dict[int, tuple[int, int]]:
    """{line: (start, end) of the innermost statement covering it} —
    lets waivers honor the whole statement span for multi-line calls,
    matching FileContext.span_of. Takes a node iterable (ctx.walk() or
    ast.walk(tree)); in both, inner statements come after their parents
    and overwrite. Empty for unparseable files (nodes=())."""
    spans: dict[int, tuple[int, int]] = {}
    for node in nodes:
        if isinstance(node, ast.stmt):
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            for ln in range(node.lineno, end + 1):
                spans[ln] = (node.lineno, end)
    return spans


def _waived_at(pass_name: str, ln: int,
               spans: dict[int, tuple[int, int]],
               waivers: dict[int, set[str]], lines: list[str]) -> bool:
    """Self-applied waiver check — delegates to the framework's ONE
    binding contract (span_waiver_lines), so self-waiving passes can
    never bind differently from everyone else."""
    return bool(span_waiver_lines(spans.get(ln, (ln, ln)), pass_name,
                                  waivers, lines))


def _doc_sites(path: str) -> tuple[set[str], int]:
    text = open(path, encoding="utf-8").read()
    m = re.search(r"Sites:\s*(.*?)\.\s", text, re.DOTALL)
    if not m:
        return set(), 0
    line = text[:m.start()].count("\n") + 1
    return set(re.findall(r"`([a-z_]+)`", m.group(1))), line


# -- exit-code drift (ISSUE 13 satellite) -----------------------------------
# the EXIT_* registry in utils/resilience.py, the exit-code table in
# docs/robustness.md, and the literal sys.exit/os._exit call sites are
# three spellings of one contract: what a dying process MEANS by its
# exit code. The PR 11 "hard-exiting 86" log rot class is exactly this
# table drifting from the code that operators debug against.

_EXIT_NAME_RE = re.compile(r"EXIT_[A-Z_]+")
_EXIT_ROW_RE = re.compile(r"\|\s*\*\*(\d+)\*\*\s*\|([^|]*)\|")
_EXIT_CALL_HINT = ("sys.exit", "os._exit")


def _exit_registry(path: str) -> dict[str, tuple[int, int]]:
    """{EXIT_NAME: (code, line)} from top-level assigns; aliases
    (`EXIT_CLUSTER = EXIT_FAULT`) resolve through the map."""
    try:
        tree = ast.parse(open(path, encoding="utf-8").read(),
                         filename=path)
    except SyntaxError:
        return {}
    out: dict[str, tuple[int, int]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.startswith("EXIT_"):
            name, v = node.targets[0].id, node.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                out[name] = (v.value, node.lineno)
            elif isinstance(v, ast.Name) and v.id in out:
                out[name] = (out[v.id][0], node.lineno)
    return out


def _doc_exit_table(path: str) -> dict[str, tuple[int, int]]:
    """{EXIT_NAME: (code, line)} from docs table rows like
    `| **87** | \\`EXIT_CLUSTER\\` / \\`EXIT_FAULT\\` | ...`."""
    out: dict[str, tuple[int, int]] = {}
    for i, line in enumerate(
            open(path, encoding="utf-8").read().splitlines(), 1):
        m = _EXIT_ROW_RE.match(line.strip())
        if m:
            for name in _EXIT_NAME_RE.findall(m.group(2)):
                out[name] = (int(m.group(1)), i)
    return out


def _exit_call_violations(nodes, exits: dict,
                          codes: set[int]) -> list[tuple[int, str]]:
    """(line, message) for each sys.exit/os._exit call whose argument
    is a bare literal matching a registered code (operators grep for
    the symbol, not the number) or an EXIT_* symbol the registry no
    longer defines (a rename that missed a call site)."""
    out: list[tuple[int, str]] = []
    for node in nodes:
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and (fn.value.id, fn.attr) in (("sys", "exit"),
                                               ("os", "_exit"))):
            continue
        a = node.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, int) \
                and a.value in codes:
            names = sorted(n for n, (c, _l) in exits.items()
                           if c == a.value)
            out.append((node.lineno,
                        f"bare literal exit {a.value} — use the "
                        f"registered symbol ({' / '.join(names)} in "
                        "utils/resilience.py) so the code and the "
                        "operator runbook cannot drift"))
        else:
            name = None
            if isinstance(a, ast.Name):
                name = a.id
            elif isinstance(a, ast.Attribute):
                name = a.attr
            if name and name.startswith("EXIT_") and name not in exits:
                out.append((node.lineno,
                            f"exit call names {name}, which is not in "
                            "the EXIT_* registry in "
                            "utils/resilience.py"))
    return out


@register
class DocDriftPass(LintPass):
    name = "doc-drift"
    description = ("FAULT_SITES registry == docs/robustness.md Sites "
                   "list == FAULTS call sites; EXIT_* registry == "
                   "docs exit-code table == exit call sites")
    self_waiving = True   # scans files outside the selection itself

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        reg_path = os.path.join(root, REGISTRY_FILE)
        docs_path = os.path.join(root, DOCS_FILE)
        if not (os.path.isfile(reg_path) and os.path.isfile(docs_path)):
            return
        yield from self._exit_findings(ctxs, root, reg_path, docs_path)
        registry, reg_line = _registry_sites(reg_path)
        if not reg_line:
            return
        reg_src = open(reg_path, encoding="utf-8").read()
        reg_waivers = extract_waivers(reg_src)
        reg_lines = reg_src.splitlines()

        def reg_waived(ln: int) -> bool:
            """Waiver on the registry entry's line, or on a
            comment-only line directly above — self-applied so both
            entry points (explicit paths and paths=[]) agree."""
            if self.name in reg_waivers.get(ln, ()):
                return True
            return (ln > 1 and reg_lines[ln - 2].lstrip().startswith("#")
                    and self.name in reg_waivers.get(ln - 1, ()))
        doc_sites, doc_line = _doc_sites(docs_path)
        if not doc_line:
            yield Finding(self.name, docs_path, 1,
                          "docs/robustness.md lost its 'Sites:' list",
                          span=None)
            return

        # call sites: always the full production tree under root. This
        # pass scans its files itself (not via ctxs — a partial path
        # selection must not report half the call sites as dead), so it
        # also applies waivers itself: the framework's ctx-based filter
        # only covers files the caller happened to select.
        code_sites: dict[str, tuple[str, int, bool]] = {}
        by_path = {c.path: c for c in ctxs}
        for target in SCAN:
            path = os.path.join(root, target)
            if not os.path.exists(path):
                continue
            for fp in iter_py_files([path]):
                ctx = by_path.get(os.path.abspath(fp))
                if ctx is not None:   # already read+indexed+parsed
                    src, waivers = ctx.src, ctx.waivers
                    nodes = ctx.walk() if ctx.tree is not None else ()
                else:
                    src = open(fp, encoding="utf-8").read()
                    waivers = extract_waivers(src)
                    try:
                        nodes = list(ast.walk(ast.parse(src)))
                    except SyntaxError:
                        nodes = ()
                spans = None    # built on first match — most files
                                # have no FAULTS call site at all
                lines = src.splitlines()
                # whole-text scan: `fire(\n  "site")` wraps across
                # lines and a per-line findall would miss it (the
                # regex's \s* crosses the newline)
                for m in _CALL_RE.finditer(src):
                    site = m.group(1)
                    ln = src.count("\n", 0, m.start()) + 1
                    if spans is None:
                        spans = _stmt_spans(nodes)
                    # waiver honored across the enclosing statement's
                    # span or the comment block directly above (same
                    # contract as FileContext.waiver_lines)
                    waived = _waived_at(self.name, ln, spans, waivers,
                                        lines)
                    prev = code_sites.get(site)
                    # an unwaived call site outranks a waived one
                    if prev is None or (prev[2] and not waived):
                        code_sites[site] = (fp, ln, waived)

        for site in sorted(set(code_sites) - set(registry)):
            fp, ln, waived = code_sites[site]
            if waived:
                continue
            # span=None: this pass applies waivers itself (above, with
            # full statement-span semantics); handing a (ln-1, ln) span
            # to the framework would let a trailing waiver on the
            # previous statement leak onto this finding
            yield Finding(
                self.name, fp, ln,
                f"FAULTS call site {site!r} is not in "
                "resilience.FAULT_SITES — register it and document it "
                "in docs/robustness.md",
                span=None)
        for site in sorted(set(registry) - set(code_sites)):
            ln, _ = registry[site]
            if reg_waived(ln):
                continue
            yield Finding(
                self.name, reg_path, ln,
                f"FAULT_SITES entry {site!r} has no call site — delete "
                "it (and from docs/robustness.md)",
                span=None)
        for site in sorted(set(registry) - doc_sites):
            ln, _ = registry[site]
            if reg_waived(ln):   # one waiver covers the entry's drift
                continue
            yield Finding(
                self.name, reg_path, ln,
                f"FAULT_SITES entry {site!r} is missing from the "
                "docs/robustness.md 'Sites:' list",
                span=None)
        for site in sorted(doc_sites - set(registry)):
            yield Finding(
                self.name, docs_path, doc_line,
                f"docs/robustness.md documents site {site!r} that is "
                "not in resilience.FAULT_SITES",
                span=None)
        for site, (ln, desc) in sorted(registry.items()):
            if not desc:
                yield Finding(
                    self.name, reg_path, ln,
                    f"FAULT_SITES entry {site!r} has no description",
                    span=None)

    def _exit_findings(self, ctxs: list[FileContext], root: str,
                       reg_path: str, docs_path: str) -> Iterator[Finding]:
        """EXIT_* registry vs docs exit-code table vs literal
        sys.exit/os._exit call sites, three-way. Skips entirely for
        roots that model no EXIT_ registry (fixture trees)."""
        exits = _exit_registry(reg_path)
        if not exits:
            return
        codes = {code for code, _ln in exits.values()}
        table = _doc_exit_table(docs_path)
        if not table:
            yield Finding(
                self.name, docs_path, 1,
                "docs/robustness.md lost its exit-code table "
                "(`| **N** | `EXIT_NAME`` rows) while "
                f"{os.path.basename(reg_path)} registers "
                f"{sorted(exits)} — operators debug against this table",
                span=None)
            return
        for name, (code, ln) in sorted(exits.items()):
            doc = table.get(name)
            if doc is None:
                yield Finding(
                    self.name, reg_path, ln,
                    f"exit code {name} ({code}) is not in the "
                    "docs/robustness.md exit-code table", span=None)
            elif doc[0] != code:
                yield Finding(
                    self.name, docs_path, doc[1],
                    f"docs/robustness.md documents {name} as exit "
                    f"{doc[0]} but the registry says {code}", span=None)
        for name, (code, ln) in sorted(table.items()):
            if name not in exits:
                yield Finding(
                    self.name, docs_path, ln,
                    f"docs/robustness.md documents exit code {name} "
                    f"({code}) that is not registered in "
                    f"{os.path.basename(reg_path)}", span=None)
        # call sites: literal exits must use the registered symbols,
        # and exit symbols must exist in the registry. Same self-applied
        # waiver contract as the fault-site scan above.
        by_path = {c.path: c for c in ctxs}
        for target in SCAN:
            path = os.path.join(root, target)
            if not os.path.exists(path):
                continue
            for fp in iter_py_files([path]):
                ctx = by_path.get(os.path.abspath(fp))
                if ctx is not None:
                    src, tree, waivers = ctx.src, ctx.tree, ctx.waivers
                else:
                    src = open(fp, encoding="utf-8").read()
                    if not any(h in src for h in _EXIT_CALL_HINT):
                        continue
                    waivers = extract_waivers(src)
                    try:
                        tree = ast.parse(src)
                    except SyntaxError:
                        continue
                if tree is None or not any(h in src
                                           for h in _EXIT_CALL_HINT):
                    continue
                nodes = (ctx.walk() if ctx is not None
                         else list(ast.walk(tree)))
                viols = _exit_call_violations(nodes, exits, codes)
                if not viols:
                    continue
                spans = _stmt_spans(nodes)
                lines = src.splitlines()
                for viol_line, msg in viols:
                    if not _waived_at(self.name, viol_line, spans,
                                      waivers, lines):
                        yield Finding(self.name, fp, viol_line, msg,
                                      span=None)
