"""failure-path passes — liveness lint for the failure edges (ISSUE 20).

The reference stack's failure paths are process-fatal by construction
(`CHECK`/`LOG(FATAL)` in caffe.cpp + common.cpp abort the rank and MPI
tears the job down), so a swallowed error or a silently-dead worker
thread cannot exist there. This rebuild keeps processes ALIVE through
failure — typed serving errors (serving/errors.py), journaled exits
(utils/resilience.py EXIT_*), supervised restarts — which opens four
leak shapes the review rounds kept re-finding by hand:

  * `future-resolution` — a `concurrent.futures.Future` created on a
    serving/solver path must, on every exit path of its function
    (exception edges included), be resolved (`set_result`/
    `set_exception`/`cancel`) or escape into a registry/queue/return
    value a drain site owns. A raise-after-create with the future
    still local is the PR 7 pending-forever shape: the waiter blocks
    on a future nobody will ever resolve.
  * `typed-failure` — `except Exception:`/bare `except` under
    `serving/`, `solver/`, `parallel/`, and `utils/resilience.py`
    must re-raise, convert to a typed error (ServingError subclass,
    registered EXIT_*, an HTTP 4xx/5xx reply), resolve a future with
    the error, capture the exception object as data, or journal via
    the run-manifest path. Silent `pass`/log-and-continue fails —
    waivable when surviving IS the design, with the reason in the
    diff.
  * `thread-crash` — a `threading.Thread` target (or a pool
    `.submit()` callee whose future is DISCARDED — a kept future
    carries the exception to `.result()`) whose body can raise out
    the top without a catch-all dies silently; the dispatcher/
    harvest/monitor/supervisor entry points must all be wrapped.
  * `deadline-discipline` — `subprocess.run`/`check_output`/
    `.communicate()`/`.wait()` without `timeout=`, and unbounded
    `.join()`/`.result()`/`.get()` on device-adjacent paths
    (`tools/`, `serving/`, the solver dispatch loop) even OUTSIDE
    locks: the CLAUDE.md dead-tunnel contract — a dead tunnel HANGS
    inside C++ jax calls, so any unbounded wait downstream of device
    work is a hang no signal can interrupt — previously enforced
    only under a held lock by `blocking-under-lock`.

All four share the concurrency trio's whole-tree model (one
`tree_model` build per run — concurrency.py collects the thread
targets, deadline events, and Future-bearing class fields in the same
single AST walk per function). Like the trio, they are approximate BY
DESIGN: linear-order escape analysis, not a CFG; structural handler
rules, not dataflow. Deliberate sites are waived in the diff with
written reasons, per the tpulint contract.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from . import FileContext, Finding, LintPass, dotted_name, register
from .concurrency import (_FUTURE_CTORS, _emit, deadline_kind,
                          tree_model)

_RESOLVERS = ("set_result", "set_exception", "cancel")


def _norm_rel(ctx: FileContext, root: str) -> str:
    return os.path.relpath(ctx.path, root).replace(os.sep, "/")


def _broad_handler(handler: ast.ExceptHandler) -> str | None:
    """The spelling of a broad handler ('bare except', 'Exception',
    'BaseException'), or None for a typed one."""
    t = handler.type
    if t is None:
        return "bare except"
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        d = (dotted_name(n) or "").rsplit(".", 1)[-1]
        if d in ("Exception", "BaseException"):
            return d
    return None


def _has_broad_handler(fn_node) -> bool:
    """True when the function body contains a try with a broad handler
    at any depth OUTSIDE nested defs — the catch-all that keeps a
    worker thread from dying silently."""
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Try):
            for h in node.handlers:
                if _broad_handler(h):
                    return True
        stack.extend(ast.iter_child_nodes(node))
    return False


# ---------------------------------------------------------------------------
# future-resolution

_FUTURE_SCOPES = ("caffe_mpi_tpu/serving/", "caffe_mpi_tpu/solver/")


class _FutureFlow:
    """Linear-order escape analysis for one function: track locals
    holding a Future (or an instance of a Future-bearing class) from
    creation until they resolve, escape, or leak. Statements are
    visited in source order through compound bodies (shared pending
    set — an escape in ANY branch clears the name, the optimistic
    reading that keeps false positives out of real code)."""

    def __init__(self, pass_name, fn, future_fields, selected):
        self.pass_name = pass_name
        self.fn = fn
        self.future_fields = future_fields
        self.selected = selected
        self.pending: dict[str, tuple] = {}   # name -> (stmt, detail)
        self.findings: list[Finding] = []

    def run(self) -> list[Finding]:
        for s in self.fn.node.body:
            self._stmt(s)
        for name, (stmt, detail) in self.pending.items():
            self._flag(stmt,
                       f"local {name!r} ({detail}) is created here but "
                       "never resolved, returned, or registered — no "
                       "drain site can ever own it, so any waiter "
                       "blocks forever")
        return self.findings

    # -- statement dispatch ---------------------------------------------
    def _stmt(self, s) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            self._escape_uses(s)    # closure capture = escape
            return
        if isinstance(s, (ast.If, ast.For, ast.AsyncFor, ast.While,
                          ast.With, ast.AsyncWith, ast.Try)):
            for attr in ("test", "iter", "items"):
                v = getattr(s, attr, None)
                for n in (v if isinstance(v, list)
                          else [v] if v is not None else []):
                    self._escape_uses(n)
            for block in ("body", "orelse", "finalbody"):
                for c in getattr(s, block, None) or []:
                    self._stmt(c)
            for h in getattr(s, "handlers", None) or []:
                for c in h.body:
                    self._stmt(c)
            return
        self._simple(s)

    def _simple(self, s) -> None:
        if isinstance(s, ast.Raise):
            for name in list(self.pending):
                stmt0, detail = self.pending.pop(name)
                self._flag(s,
                           f"raise with {name!r} ({detail}, created at "
                           f"line {stmt0.lineno}) still local and "
                           "PENDING — the PR 7 pending-forever shape: "
                           "the waiter blocks on a future nobody will "
                           "resolve; resolve it (set_exception/cancel) "
                           "or create it after the raise paths")
            return
        if isinstance(s, ast.Return):
            if s.value is not None:
                self._escape_uses(s.value)
            for name in list(self.pending):
                stmt0, detail = self.pending.pop(name)
                self._flag(s,
                           f"returning with {name!r} ({detail}, created "
                           f"at line {stmt0.lineno}) still local and "
                           "pending — this exit path strands the "
                           "future")
            return
        created = self._creation(s)
        self._resolutions(s)
        self._escape_uses(s, skip=created)
        if created:
            name, detail = created
            self.pending[name] = (s, detail)

    # -- the events ------------------------------------------------------
    def _creation(self, s) -> tuple[str, str] | None:
        if not (isinstance(s, ast.Assign) and len(s.targets) == 1
                and isinstance(s.targets[0], ast.Name)
                and isinstance(s.value, ast.Call)):
            return None
        d = dotted_name(s.value.func) or ""
        if d in _FUTURE_CTORS:
            return (s.targets[0].id, "a concurrent.futures.Future")
        cls = d.rsplit(".", 1)[-1]
        if cls in self.future_fields:
            return (s.targets[0].id,
                    f"an instance of {cls} holding a Future in "
                    f".{self.future_fields[cls]}")
        return None

    def _resolutions(self, s) -> None:
        for node in self.fn.ctx.walk(s):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _RESOLVERS:
                base = node.func.value
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name):
                    self.pending.pop(base.id, None)

    def _escape_uses(self, node, skip=None) -> None:
        """Any OTHER use of a pending name — call argument, container,
        attribute/subscript store, alias, yield — counts as an escape
        into something a drain site can own. Generous by design."""
        if not self.pending:
            return
        skip_name = skip[0] if skip else None
        for n in self.fn.ctx.walk(node):
            if isinstance(n, ast.Name) and n.id != skip_name \
                    and n.id in self.pending:
                self.pending.pop(n.id, None)

    def _flag(self, stmt, message: str) -> None:
        f = _emit(self.pass_name, self.fn.ctx, stmt, stmt.lineno,
                  message + "; waive with `# lint: ok(future-"
                  "resolution) — reason` only when ownership is "
                  "provably elsewhere", self.selected)
        if f:
            self.findings.append(f)


@register
class FutureResolutionPass(LintPass):
    name = "future-resolution"
    description = ("a Future created on a serving/solver path must be "
                   "resolved or escape to a drain-site owner on every "
                   "exit path (raise-after-create = the PR 7 "
                   "pending-forever shape)")

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        model = tree_model(ctxs, root)
        selected = {c.path: c for c in ctxs}
        for key, fn in model.funcs.items():
            rel = _norm_rel(fn.ctx, root)
            if not rel.startswith(_FUTURE_SCOPES):
                continue
            if "Future" not in fn.ctx.src \
                    and not any(c in fn.ctx.src
                                for c in model.future_fields):
                continue
            flow = _FutureFlow(self.name, fn, model.future_fields,
                               selected)
            yield from flow.run()


# ---------------------------------------------------------------------------
# typed-failure

_TYPED_SCOPES = ("caffe_mpi_tpu/serving/", "caffe_mpi_tpu/solver/",
                 "caffe_mpi_tpu/parallel/")
_TYPED_FILES = ("caffe_mpi_tpu/utils/resilience.py",)

_LOG_ROOTS = {"log", "logging", "logger"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "fatal", "log"}


def _is_log_call(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    base = func.value
    while isinstance(base, ast.Attribute):
        base = base.value
    if isinstance(base, ast.Name) and base.id in _LOG_ROOTS:
        return True
    return func.attr in _LOG_METHODS and isinstance(base, ast.Name) \
        and base.id in _LOG_ROOTS


def _handler_converts(handler: ast.ExceptHandler) -> bool:
    """Structural OK-rules: the handler re-raises, resolves a future
    with the error, journals, exits through the registered EXIT_*
    path, replies with a typed HTTP status, or captures the exception
    OBJECT (not its str()) as data something downstream consumes."""
    caught = handler.name
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            value = getattr(node, "value", None)
            elts = [value] if value is not None else []
            if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
                elts += list(value.elts)
            elif isinstance(value, ast.Dict):
                elts += [v for v in value.values if v is not None]
            if caught and any(isinstance(e, ast.Name) and e.id == caught
                              for e in elts):
                return True     # the exception object stored as data
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        d = dotted_name(func) or ""
        if isinstance(func, ast.Attribute) and func.attr in (
                "set_exception", "cancel"):
            return True
        if any(kw.arg == "exc" for kw in node.keywords):
            return True         # the `_resolve(fut, exc=e)` idiom
        if "journal" in d.lower():
            return True
        if d in ("sys.exit", "os._exit"):
            return True
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, int) \
                and 400 <= node.args[0].value < 600:
            return True         # typed HTTP reply (4xx/5xx + kind)
        if caught and not _is_log_call(node) \
                and any(isinstance(a, ast.Name) and a.id == caught
                        for a in node.args):
            return True         # exception object handed onward
    return False


@register
class TypedFailurePass(LintPass):
    name = "typed-failure"
    description = ("broad `except Exception`/bare except under serving/"
                   "solver/parallel/resilience must re-raise, convert "
                   "to a typed error, resolve a future, or journal — "
                   "silent swallow fails")

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        model = tree_model(ctxs, root)
        selected = {c.path: c for c in ctxs}
        for ctx in model.ctxs:
            rel = _norm_rel(ctx, root)
            if not (rel.startswith(_TYPED_SCOPES)
                    or rel in _TYPED_FILES):
                continue
            for node in ctx.walk():
                if not isinstance(node, ast.Try):
                    continue
                for h in node.handlers:
                    spelled = _broad_handler(h)
                    if spelled is None or _handler_converts(h):
                        continue
                    f = _emit(
                        self.name, ctx, h, h.lineno,
                        f"broad `{spelled}` handler swallows the "
                        "failure UNTYPED (log-and-continue included): "
                        "re-raise, convert to a typed ServingError/"
                        "registered EXIT_*, resolve a future with the "
                        "error, or journal via the run-manifest path; "
                        "waive with `# lint: ok(typed-failure) — "
                        "reason` when surviving is the design",
                        selected)
                    if f:
                        yield f


# ---------------------------------------------------------------------------
# thread-crash

def _has_worker_loop(fn_node) -> bool:
    """A `while` loop outside nested defs — the shape of a long-running
    worker body (dispatcher, harvester, monitor, beat publisher)."""
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.While):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


@register
class ThreadCrashPass(LintPass):
    name = "thread-crash"
    description = ("a Thread target (or discarded pool-submit callee) "
                   "that can raise out the top without a journaling "
                   "catch-all is a silently-dying worker")

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        model = tree_model(ctxs, root)
        selected = {c.path: c for c in ctxs}
        guarded: dict[tuple, bool] = {}

        def _guarded(key) -> bool:
            if key not in guarded:
                fn = model.funcs[key]
                ok = _has_broad_handler(fn.node)
                if not ok:
                    # one-level delegation: a pure wrapper whose every
                    # resolvable callee is itself guarded
                    callees = [c for c in fn.callees if c in model.funcs]
                    ok = bool(callees) and all(
                        _has_broad_handler(model.funcs[c].node)
                        for c in callees)
                guarded[key] = ok
            return guarded[key]

        seen: set[tuple] = set()
        targets = list(model.thread_targets)
        direct = {t["target"] for t in targets}
        # an escaping `self.method` reference whose body runs a worker
        # loop is a thread entry even when the Thread(...) call spells
        # its target through a local (the dispatcher/harvest wiring
        # passes (name, target) tuples) — the PR 11 wedged-dispatcher
        # worker must not escape this pass on spelling
        for key in sorted(model.entries):
            if key in model.funcs and key not in direct \
                    and _has_worker_loop(model.funcs[key].node):
                fn = model.funcs[key]
                targets.append({
                    "target": key, "ctx": fn.ctx, "stmt": fn.node,
                    "line": fn.node.lineno,
                    "via": "escaping worker-loop reference",
                    "discarded": False})
        for t in targets:
            key = t["target"]
            if key not in model.funcs or _guarded(key):
                continue
            if t["via"] == ".submit(...)" and not t["discarded"]:
                continue    # the kept future carries the exception
            fn = model.funcs[key]
            label = f"{key[0]}.{key[1]}" if isinstance(key[0], str) \
                else key[1]
            if t["discarded"]:
                dkey = (t["ctx"].path, t["stmt"].lineno, label)
                if dkey in seen:
                    continue
                seen.add(dkey)
                f = _emit(
                    self.name, t["ctx"], t["stmt"], t["line"],
                    f"pool .submit({label}, ...) discards its future: "
                    "an exception in the callee vanishes with it — "
                    "keep the future (a drain site must .result() it) "
                    "or wrap the callee in a journaling catch-all; "
                    "waive with `# lint: ok(thread-crash) — reason`",
                    selected)
                if f:
                    yield f
                continue
            dkey = (fn.ctx.path, fn.node.lineno)
            if dkey in seen:
                continue
            seen.add(dkey)
            how = ("a worker loop handed out as a thread entry"
                   if t["via"] == "escaping worker-loop reference"
                   else "spawned at "
                   f"{_norm_rel(t['ctx'], root)}:{t['line']}")
            f = _emit(
                self.name, fn.ctx, fn.node, fn.node.lineno,
                f"{label} runs as a thread target ({how}) "
                "with no catch-all: an exception "
                "here kills the worker SILENTLY — wrap the body in a "
                "try/except that journals/resolves/respawns, or waive "
                "with `# lint: ok(thread-crash) — reason` when dying "
                "is the designed failure signal", selected)
            if f:
                yield f


# ---------------------------------------------------------------------------
# deadline-discipline

_DEADLINE_DIRS = ("tools/", "caffe_mpi_tpu/tools/",
                  "caffe_mpi_tpu/serving/", "caffe_mpi_tpu/solver/")
_DEADLINE_FILES = ("bench.py",)


def _deadline_scope(rel: str) -> bool:
    return rel.startswith(_DEADLINE_DIRS) or rel in _DEADLINE_FILES


@register
class DeadlineDisciplinePass(LintPass):
    name = "deadline-discipline"
    description = ("subprocess.run/check_output/.communicate()/.wait() "
                   "without timeout=, and unbounded .join()/.result()/"
                   ".get() on device-adjacent paths (tools/, serving/, "
                   "solver/) — even outside locks")

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        model = tree_model(ctxs, root)
        selected = {c.path: c for c in ctxs}
        seen: set[tuple] = set()
        events = list(model.deadline_events)
        # module-level statements run outside any function walk (smoke
        # scripts calling subprocess at import / __main__ level)
        for ctx in model.ctxs:
            if not _deadline_scope(_norm_rel(ctx, root)):
                continue
            for stmt in ctx.tree.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                for node in ctx.walk(stmt):
                    if isinstance(node, ast.Call):
                        kind = deadline_kind(node)
                        if kind:
                            events.append({"kind": kind, "ctx": ctx,
                                           "stmt": stmt,
                                           "line": node.lineno})
        for ev in events:
            if not _deadline_scope(_norm_rel(ev["ctx"], root)):
                continue
            key = (ev["ctx"].path, ev["line"], ev["kind"])
            if key in seen:
                continue
            seen.add(key)
            f = _emit(
                self.name, ev["ctx"], ev["stmt"], ev["line"],
                f"{ev['kind']} on a device-adjacent path: a dead "
                "tunnel (or wedged child) turns this into a hang no "
                "Python signal can interrupt — bound it with timeout= "
                "and handle the expiry, or waive with `# lint: "
                "ok(deadline-discipline) — reason` (e.g. a sentinel-"
                "woken idle park)", selected)
            if f:
                yield f
