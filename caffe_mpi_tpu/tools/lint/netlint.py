"""netlint — model-level static analysis passes (net-*).

The reference validates a model graph only by BUILDING it: Net::Init
(net.cpp:815-818) runs insert_splits, shape inference, and param checks
at construction, so a broken prototxt surfaces at the first
(tunnel-length) compile. These passes run the same load-bearing checks
ahead of time, over the declarative prototxt alone, through the jax-free
shape/dtype engine (proto/netshape.py — ONE spelling of the Caffe shape
semantics, cross-checked bitwise against the real net.py build for the
whole model zoo by tests/test_netlint.py).

Pass family (all whole-tree: they scan models/ + examples/ under the
run root, like doc-drift scans docs/):

  net-wiring    dangling bottoms, duplicate tops, illegal in-place
                (shape-changing or multi-consumer rewrite), layers
                unreachable in every phase, phase-inconsistent includes,
                top-count mismatches, malformed prototxt
  net-shape     full-graph shape inference must succeed: mismatched
                bottoms, non-positive dims, pad >= kernel, reshape
                count mismatches, swapped loss bottoms
  net-params    param-spec arity (BVLC BatchNorm lr_mult triples bind
                to the wrong blobs under the NVCaffe [mean, var,
                correction, scale?, bias?] layout), shared-param shape
                mismatches
  net-dtype     unknown Type names; FLOAT16 compute requested on a
                bf16-ineligible layer (host-callback/IO layers — the
                `BF16_INELIGIBLE` registry in proto/netshape.py, shared
                with net.py's build-time warning)
  net-serve     deploy nets that silently lose the serving fast paths:
                batch-dim-baking layers that break BucketedForward's
                bucket re-padding, and image inputs ineligible for the
                native request-ingest plan (serving/ingest.py
                build_plan)
  net-footprint a single blob/param whose byte size exceeds the HBM
                budget (CAFFE_NETLINT_HBM_MB, default one v5e chip) —
                the typo'd-dim detector; per-layer bytes/MACs come from
                the same engine records tools/summarize.py renders

Waivers: per layer, a `# lint: ok(net-...) — reason` comment anywhere
inside the layer's `layer { ... }` block (or the comment block directly
above it) suppresses that layer's finding; net-level findings honor a
waiver above the first layer block. Generated prototxts (the
models/generate_models.py zoo) cannot carry hand comments across
regeneration — waive those through `GENERATED_WAIVERS` below instead.
These passes apply their own waivers (self_waiving, like doc-drift), so
stale-waiver detection does not judge them.
"""

from __future__ import annotations

import os
import re
from typing import Iterator

from . import FileContext, Finding, LintPass, register
from ...proto.config import NetParameter, NetState
from ...proto.netshape import (
    BF16_INELIGIBLE,
    LOSS_TYPES as _LOSS_TYPES,
    NetAnalysis,
    analyze_net,
    inplace_hazards,
    layer_footprint,
    _known,
    _fmt,
    _prod,
)
from ...proto.text_format import PrototxtError, parse
from ...proto.upgrade import layer_included

# directories under the run root scanned for model definitions
MODEL_SCAN = ("models", "examples")
PHASES = ("TRAIN", "TEST")
NET_PASSES = ("net-wiring", "net-shape", "net-params", "net-dtype",
              "net-serve", "net-footprint")

# waiver registry for GENERATED prototxts (models/generate_models.py
# output loses hand comments on regeneration): (relpath, pass, layer)
# -> reason. Layer "" = net-level finding.
GENERATED_WAIVERS: dict[tuple[str, str, str], str] = {}

# ONE spelling of the waiver syntax — the framework's regex, so the
# prototxt grammar can never drift from the documented .py grammar
from . import _WAIVER_RE  # noqa: E402

# mini-tokenizer for layer-span discovery: both string quote forms the
# real text-format grammar accepts (text_format._TOKEN_RE), braces,
# words, comments
_TOKEN_RE = re.compile(
    r'"(?:\\.|[^"\\])*"|\'(?:\\.|[^\'\\])*\'|\{|\}|[A-Za-z_][\w./-]*|#')


# cheap net-vs-solver pre-filter: a net file declares layer blocks (the
# text format also accepts the colon message form `layer: { ... }` —
# text_format.py parse_field) or legacy net-level inputs; a solver
# prototxt has neither and skips the full parse entirely
_NETLIKE_RE = re.compile(r"(?m)^\s*(?:layers?\s*:?\s*\{|input\s*:)")


class _NetFile:
    """One parsed+analyzed prototxt net, shared by all net-* passes.
    Layer spans and waiver lines are computed lazily — most files are
    clean and never need them."""

    def __init__(self, path: str):
        self.path = path
        with open(path, encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.parse_error: str | None = None
        self.is_net = False
        self.npar: NetParameter | None = None
        self.analyses: dict[str, NetAnalysis] = {}
        self._spans: list[tuple[str, int, int]] | None = None
        self._waivers: dict[int, set[str]] | None = None
        if not _NETLIKE_RE.search(self.src):
            return  # a solver (or other) prototxt — not a net
        try:
            node = parse(self.src)
        except PrototxtError as e:
            self.parse_error = str(e)
            return
        if not ("layer" in node or "layers" in node or "input" in node):
            return
        self.is_net = True
        try:
            self.npar = NetParameter.from_node(node)
            layers = self.npar.layer or self.npar.layers
            if any(l.include or l.exclude for l in layers):
                for phase in PHASES:
                    self.analyses[phase] = analyze_net(self.npar,
                                                       phase=phase)
            else:
                # no phase rules: TRAIN and TEST filter identically, so
                # one analysis serves both slots (the scan's hot path).
                # The one phase-dependent check (Dropout-in-Pipeline,
                # TRAIN-only) must not fire on a deploy-shaped net that
                # is never trained — pick the phase by whether the net
                # carries a loss at all
                train_like = any(
                    l.type in _LOSS_TYPES or l.loss_weight
                    for l in layers)
                shared = analyze_net(
                    self.npar, phase="TRAIN" if train_like else "TEST")
                self.analyses = {p: shared for p in PHASES}
        except (TypeError, ValueError) as e:
            # schema coercion / normalization error: surfaced as a
            # wiring finding, same as a file that does not parse
            self.parse_error = str(e)
            self.npar = None
            self.analyses = {}

    # -- locating + waiving -------------------------------------------------
    @property
    def spans(self) -> list[tuple[str, int, int]]:
        if self._spans is None:
            self._spans = _layer_spans(self.lines)
        return self._spans

    @property
    def waivers(self) -> dict[int, set[str]]:
        if self._waivers is None:
            self._waivers = _prototxt_waivers(self.lines)
        return self._waivers

    def line_of(self, layer_name: str) -> int:
        for name, start, _end in self.spans:
            if name == layer_name:
                return start
        m = re.search(r'name\s*:\s*"%s"' % re.escape(layer_name), self.src)
        if m:
            return self.src[: m.start()].count("\n") + 1
        return 1

    def waived(self, layer_name: str, pass_name: str, root: str) -> bool:
        rel = os.path.relpath(self.path, root)
        if (rel, pass_name, layer_name) in GENERATED_WAIVERS:
            return True
        spans = [(s, e) for n, s, e in self.spans if n == layer_name]
        if not spans:
            # net-level findings: a waiver anywhere above the first
            # layer block (the file header) binds
            first = min((s for _n, s, _e in self.spans), default=None)
            spans = [(1, (first - 1) if first else len(self.lines))]
        for lo, hi in spans:
            for ln in range(lo, hi + 1):
                if pass_name in self.waivers.get(ln, ()):
                    return True
            above = lo - 1
            while 1 <= above <= len(self.lines) and \
                    self.lines[above - 1].lstrip().startswith("#"):
                if pass_name in self.waivers.get(above, ()):
                    return True
                above -= 1
        return False


def _comment_of(line: str) -> str:
    """The comment portion of one prototxt line — the first `#` NOT
    inside a quoted string (a path like '/data/#shard' must not read
    as a comment, and waiver grammar quoted in a string value must not
    register)."""
    in_q = ""
    i = 0
    while i < len(line):
        c = line[i]
        if in_q:
            if c == "\\":
                i += 2
                continue
            if c == in_q:
                in_q = ""
        elif c in "\"'":
            in_q = c
        elif c == "#":
            return line[i:]
        i += 1
    return ""


def _prototxt_waivers(lines: list[str]) -> dict[int, set[str]]:
    out: dict[int, set[str]] = {}
    for i, line in enumerate(lines, 1):
        # the waiver grammar always spells "lint" — skip the char-wise
        # comment scan for the vast majority of lines that can't match
        if "lint" not in line:
            continue
        comment = _comment_of(line)
        if not comment:
            continue
        names: set[str] = set()
        for m in _WAIVER_RE.finditer(comment):
            names.update(n.strip() for n in m.group(1).split(",")
                         if n.strip())
        if names:
            out.setdefault(i, set()).update(names)
    return out


def _layer_spans(lines: list[str]) -> list[tuple[str, int, int]]:
    """Top-level `layer { ... }` block spans with the block's declared
    name. Brace-counting over a comment/string-aware token scan —
    nested blocks (pipeline_param's inner `layer {`) stay inside the
    outer span."""
    spans = []
    depth = 0
    last_word = ""
    start = None
    for i, raw in enumerate(lines, 1):
        for tok in _TOKEN_RE.finditer(raw):
            t = tok.group(0)
            if t == "#":
                break  # rest of the line is a comment
            if t == "{":
                if depth == 0 and last_word in ("layer", "layers"):
                    start = i
                depth += 1
            elif t == "}":
                depth = max(depth - 1, 0)
                if depth == 0 and start is not None:
                    name = ""
                    text = "\n".join(lines[start - 1: i])
                    m = re.search(r'name\s*:\s*"((?:\\.|[^"\\])*)"', text)
                    if m:
                        name = m.group(1)
                    spans.append((name, start, i))
                    start = None
            elif t[0] not in "\"'":
                last_word = t
    return spans


def _iter_prototxts(root: str) -> Iterator[str]:
    for d in MODEL_SCAN:
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, files in os.walk(base):
            dirnames[:] = sorted(x for x in dirnames if x != "__pycache__")
            for name in sorted(files):
                if name.endswith(".prototxt"):
                    yield os.path.join(dirpath, name)


# run-lifetime cache: every pass in a run re-walks the same files, and
# the engine analysis is the expensive part — key on mtime so edits
# between runs (tests, --changed) invalidate
_CACHE: dict[str, tuple[float, _NetFile]] = {}


def net_files(root: str) -> list[_NetFile]:
    out = []
    for path in _iter_prototxts(root):
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        cached = _CACHE.get(path)
        if cached is None or cached[0] != mtime:
            cached = (mtime, _NetFile(path))
            _CACHE[path] = cached
        out.append(cached[1])
    return out


def _merged_problems(nf: _NetFile, kinds: tuple) -> list:
    """Engine problems of the given kinds across phases, deduped: a
    problem present in every phase reports once, a phase-specific one
    is tagged with its phase (the phase-inconsistent-include signal).
    Unnamed layers are identified by their declaration index so two
    unnamed layers with the same defect never merge into one report."""
    seen: dict[tuple, set] = {}
    for phase, analysis in nf.analyses.items():
        probs = list(analysis.problems)
        if "wiring" in kinds:
            probs += inplace_hazards(analysis)
        for p in probs:
            if p.kind in kinds:
                ident = p.layer or (f"#{p.index}"
                                    if p.index is not None else "")
                seen.setdefault((ident, p.layer, p.message),
                                set()).add(phase)
    out = []
    for (ident, layer, message), phases in seen.items():
        if len(phases) < len(nf.analyses):
            message += f" [phase {'/'.join(sorted(phases))}]"
        out.append((ident, layer, message))
    return out


class _NetPass(LintPass):
    """Base for the net-* family: whole-tree over models/ + examples/,
    self-applied prototxt waivers."""

    self_waiving = True
    kinds: tuple = ()

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        for nf in net_files(root):
            if nf.parse_error is not None:
                # one pass owns the malformed-file finding
                if self.name == "net-wiring":
                    yield Finding(self.name, nf.path, 1,
                                  f"prototxt does not parse/coerce: "
                                  f"{nf.parse_error}", span=None)
                continue
            if not nf.is_net:
                continue
            for ident, layer, message in _merged_problems(nf, self.kinds):
                if nf.waived(ident, self.name, root):
                    continue
                where = (f"layer {layer!r}: " if layer
                         else f"layer {ident} (unnamed): " if ident
                         else "")
                yield Finding(self.name, nf.path, nf.line_of(layer),
                              where + message, span=None)
            for layer, message in self.extra(nf):
                if not nf.waived(layer, self.name, root):
                    where = f"layer {layer!r}: " if layer else ""
                    yield Finding(self.name, nf.path, nf.line_of(layer),
                                  where + message, span=None)

    def extra(self, nf: _NetFile) -> Iterator[tuple[str, str]]:
        return iter(())


@register
class NetWiringPass(_NetPass):
    name = "net-wiring"
    description = ("model graphs: dangling bottoms, duplicate tops, "
                   "illegal in-place, unreachable layers, "
                   "phase-inconsistent includes")
    kinds = ("wiring",)

    def extra(self, nf: _NetFile) -> Iterator[tuple[str, str]]:
        # a waiver naming an unknown pass suppresses nothing — fail it,
        # mirroring the framework's bad-waiver rule for .py files
        from . import REGISTRY
        for ln in sorted(nf.waivers):
            for bad in sorted(nf.waivers[ln] - set(REGISTRY)):
                yield ("", f"line {ln}: waiver names unknown pass "
                           f"{bad!r} — a misspelled waiver suppresses "
                           "nothing")
        # layers unreachable in EVERY standard phase (rules gated on
        # stages/levels are deliberate run-time switches and exempt)
        if nf.npar is None:
            return
        states = {p: NetState(phase=p) for p in PHASES}
        for lp in nf.npar.layer:
            rules = list(lp.include) + list(lp.exclude)
            if any(r.stage or r.not_stage or r.has("min_level")
                   or r.has("max_level") for r in rules):
                continue
            if not any(layer_included(lp, states[p]) for p in PHASES):
                yield (lp.name,
                       "unreachable: include/exclude rules reject the "
                       "layer in both TRAIN and TEST phases")


@register
class NetShapePass(_NetPass):
    name = "net-shape"
    description = ("model graphs: full shape inference must succeed — "
                   "mismatched bottoms, non-positive dims, pad >= kernel")
    kinds = ("shape",)


@register
class NetParamsPass(_NetPass):
    name = "net-params"
    description = ("model graphs: param-spec arity, BatchNorm blob "
                   "layout, shared-param shape agreement")
    kinds = ("params",)


@register
class NetDtypePass(_NetPass):
    name = "net-dtype"
    description = ("model graphs: unknown dtype names; FLOAT16 compute "
                   "requested on bf16-ineligible (host-callback) layers")
    kinds = ("dtype",)

    def extra(self, nf: _NetFile) -> Iterator[tuple[str, str]]:
        seen = set()
        for analysis in nf.analyses.values():
            for info in analysis.layers:
                if info.fwd_type != "FLOAT16" or \
                        info.type not in BF16_INELIGIBLE:
                    continue
                if info.name in seen:
                    continue
                seen.add(info.name)
                how = ("explicit forward_type: FLOAT16"
                       if info.lp.forward_type == "FLOAT16"
                       else "the net-level FLOAT16 default")
                yield (info.name,
                       f"{info.type} computes through a host callback "
                       f"with f32 buffers; {how} requests bf16 it cannot "
                       "honor — pin `forward_type: FLOAT` on this layer "
                       "(registry: proto/netshape.py BF16_INELIGIBLE)")


# layers that bake the batch dimension into their arithmetic — serving's
# BucketedForward re-pads the leading dim across the bucket ladder
# (serving/engine.py), so per-row outputs change with the co-batch
def _bakes_batch(info) -> str | None:
    lp = info.lp
    if info.type == "Reshape":
        p = lp.reshape_param
        spec = list(p.shape.dim) if (p and p.shape) else []
        start = p.axis if p else 0
        if spec and start == 0 and spec[0] not in (0, -1):
            return (f"Reshape pins the batch dimension to {spec[0]} "
                    "(use 0 to copy or -1 to infer)")
    if info.type == "Flatten":
        p = lp.flatten_param
        if p and p.axis == 0:
            return "Flatten with axis 0 folds the batch dimension"
    if info.type == "InnerProduct":
        p = lp.inner_product_param
        if p and p.axis == 0:
            return "InnerProduct with axis 0 contracts over the batch"
    if info.type == "Reduction":
        p = lp.reduction_param
        if p and p.axis == 0:
            return "Reduction with axis 0 sums over the batch"
    return None


@register
class NetServePass(_NetPass):
    name = "net-serve"
    description = ("deploy nets: predicts serving eligibility — "
                   "batch-baking layers break BucketedForward, non-RGB "
                   "image inputs decline the native ingest plan")
    kinds = ()

    def extra(self, nf: _NetFile) -> Iterator[tuple[str, str]]:
        analysis = nf.analyses.get("TEST")
        if analysis is None:
            return
        # deploy-shaped net: pure Input feeds, nothing loss-weighted or
        # metric-bearing in ANY phase (a train_val net whose loss is
        # TRAIN-gated must not read as a deploy under TEST filtering)
        input_layers = [i for i in analysis.layers if i.type == "Input"]
        if not input_layers or any(
                a.loss_blobs or any(
                    i.type == "Accuracy" or i.type in (
                        "Data", "ImageData", "HDF5Data", "WindowData")
                    for i in a.layers)
                for a in nf.analyses.values()):
            return
        for info in analysis.layers:
            why = _bakes_batch(info)
            if why:
                yield (info.name,
                       f"{why} — BucketedForward re-pads the batch "
                       "across the serve_buckets ladder, so this model "
                       "cannot hold row-identical scores when served")
        # native request ingest (serving/ingest.py build_plan): 4-D RGB
        # image input; anything image-LIKE that misses the C==3 gate
        # silently serves through the per-request PIL path
        first = input_layers[0]
        if first.out_shapes and first.out_shapes[0] is not None:
            s = first.out_shapes[0]
            if len(s) == 4 and _known(*s[1:]) and s[2] > 1 and s[3] > 1 \
                    and s[1] != 3:
                yield (first.name,
                       f"image-shaped input {_fmt(s)} has {s[1]} "
                       "channels; ingest.build_plan requires 3 — "
                       "requests will silently take the classic "
                       "per-request PIL path (-require_native_ingest "
                       "would fail)")


@register
class NetFootprintPass(_NetPass):
    name = "net-footprint"
    description = ("model graphs: per-layer bytes/MACs accounting; "
                   "flags any single blob larger than the HBM budget")
    kinds = ()

    def extra(self, nf: _NetFile) -> Iterator[tuple[str, str]]:
        budget_mb = int(os.environ.get("CAFFE_NETLINT_HBM_MB", "16384"))
        budget = budget_mb * 2 ** 20
        seen = set()
        for analysis in nf.analyses.values():
            for info in analysis.layers:
                per_elem = 2 if info.fwd_type == "FLOAT16" else 4
                for t, s in zip(info.lp.top, info.out_shapes):
                    n = _prod(s) if s is not None else None
                    if n is not None and n * per_elem > budget and \
                            (info.name, t) not in seen:
                        seen.add((info.name, t))
                        yield (info.name,
                               f"top {t!r} {_fmt(s)} is "
                               f"{n * per_elem / 2**30:.1f} GiB — larger "
                               f"than the whole {budget_mb} MiB HBM "
                               "budget (CAFFE_NETLINT_HBM_MB); a typo'd "
                               "dim?")
                for pname, p in info.params.items():
                    n = _prod(p.shape)
                    if n is not None and n * 4 > budget and \
                            (info.name, pname) not in seen:
                        seen.add((info.name, pname))
                        yield (info.name,
                               f"param {pname!r} {_fmt(p.shape)} is "
                               f"{n * 4 / 2**30:.1f} GiB — larger than "
                               f"the {budget_mb} MiB HBM budget")
