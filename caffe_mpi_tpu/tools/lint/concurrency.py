"""concurrency passes — static race detection for the threaded planes.

The reference stack's threading bugs (DataReader's blocking queue pairs,
data_reader.hpp:28-53; BasePrefetchingDataLayer's prefetch threads,
base_data_layer.hpp:100-159) were caught by C++ review and crash dumps;
here the same three bug classes were re-found by hand across the
serving/feeder/resilience review rounds (serving/engine.py,
serving/batcher.py, data/feeder.py, utils/resilience.py):

  * a Future resolved under a non-reentrant lock — done-callbacks run
    synchronously in the resolving thread, so a callback that re-enters
    the lock deadlocks (the PR 7 set_result-under-`_rec_lock` shape;
    the harvest loop now resolves OUTSIDE `_rec_lock` by contract);
  * a tunnel-length device call (`jax.device_put`, `.compile()`,
    `np.asarray` of a device value) under a held lock — every other
    thread touching the lock stalls for seconds and the serving stall
    breaker trips on a healthy device (the PR 11
    upload-under-`_upload_lock` shape; `swap_weights` uploads outside
    its locks by contract);
  * undeclared lock-nesting order — the swap-vs-spill race was fixed by
    DECIDING `_upload_lock -> engine._lock` in review, but nothing
    enforced the decision.

Three passes encode the discipline, sharing ONE whole-tree model (lock
aliases, attribute types, a resolvable call graph, one AST walk per
function) built once per run — the 5 s suite budget rules out per-pass
walks:

  * `lock-order` — every observed nesting pair (direct `with` nesting,
    `.acquire()` under a held lock, and lock acquisitions reachable
    through resolvable calls, transitively) must be declared in the
    `LOCK_ORDER` partial order (caffe_mpi_tpu/serving/locks.py);
    inverted pairs and re-acquiring a non-reentrant lock are findings,
    and the registry itself is drift-held (unknown lock ids, cycles,
    dead ATTR_TYPES entries).
  * `blocking-under-lock` — calls that must never run inside a held
    lock span: `Future.set_result`/`set_exception`, `jax.device_put`/
    `device_get`/`.block_until_ready()`/`.compile()`, `np.asarray`/
    `np.array`, `time.sleep`, and unbounded `.join()`/`.get()`/
    `.result()`/`.wait()` (a Condition's own `.wait()` under its lock
    is the sanctioned pattern and is exempt).
  * `thread-shared-mutation` — an attribute mutated both inside a
    thread-entry function (a `threading.Thread(target=...)` body, a
    pool `.submit(...)` callee, a registered monitor callback — any
    escaping `self.method` reference counts) and from a public method,
    where the two sides share no covering lock.

All three are approximate BY DESIGN (they see syntax, not dynamic
ownership): deliberate patterns — caller-holds-lock helpers, uploads
whose serialization is the lock's very purpose — are waived in the
diff with written reasons, per the tpulint contract.

The failure-path passes (ISSUE 20, tools/lint/failure_path.py) ride
the SAME model — `tree_model` additionally collects resolvable
`threading.Thread(target=...)` / pool-`.submit()` targets, unbounded
deadline-family call events (with held-lock context so a Condition's
own `.wait()` stays sanctioned), and Future-bearing class fields —
one build serves all seven interprocedural passes per run
(`BUILD_COUNT` is the witness the `--profile` flag reports).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from . import (DEFAULT_SCAN, FileContext, Finding, LintPass, dotted_name,
               iter_py_files, register)

REGISTRY_FILE = os.path.join("caffe_mpi_tpu", "serving", "locks.py")

_LOCK_CTORS = {"threading.Lock": "Lock", "threading.RLock": "RLock",
               "threading.Condition": "Condition",
               "Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}

# container mutator methods: `self.x.append(...)` mutates self.x
_MUTATORS = {"append", "appendleft", "extend", "insert", "add", "update",
             "remove", "discard", "pop", "popleft", "popitem", "clear",
             "setdefault", "move_to_end"}

_DEVICE_KINDS = {"jax.device_put", "jax.device_get",
                 "jax.block_until_ready", ".block_until_ready()",
                 ".compile()", "np.asarray", "np.array", "numpy.asarray",
                 "numpy.array"}

_FUTURE_CTORS = {"Future", "futures.Future", "concurrent.futures.Future"}

_THREAD_CTORS = {"threading.Thread", "Thread"}

_SUBPROCESS_CALLS = {"subprocess.run", "subprocess.check_output",
                     "subprocess.check_call", "subprocess.call"}


def deadline_kind(node: ast.Call, held: tuple = (),
                  lock_id=None) -> str | None:
    """The deadline-discipline call shapes (failure_path.py), held-lock
    aware so a Condition's own `.wait()` under its lock stays the
    sanctioned pattern. Shared with the module-level walk (held=()) —
    one spelling of what counts as an unbounded block."""
    func = node.func
    dotted = dotted_name(func)
    has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
    if dotted in _SUBPROCESS_CALLS:
        return None if has_timeout else f"{dotted}(...) without timeout="
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr == "communicate":
        return None if has_timeout else ".communicate() without timeout="
    if attr in ("join", "result") and not node.args and not has_timeout:
        return f".{attr}() without timeout"
    if attr == "get" and not node.args and not node.keywords:
        return ".get() without timeout"
    if attr == "wait" and not node.args and not has_timeout:
        if lock_id is not None and lock_id(func.value) in held:
            return None     # Condition.wait under its own lock
        return ".wait() without timeout"
    return None


class _Func:
    """One function/method: AST + file + class, and the facts one walk
    extracts (direct lock acquisitions, resolvable callees)."""

    def __init__(self, ctx, node, cls, stem):
        self.ctx = ctx
        self.node = node
        self.cls = cls          # class name, or None for module funcs
        self.stem = stem        # module stem (basename sans .py)
        self.direct_locks: set[str] = set()
        self.callees: set[tuple] = set()


class _Model:
    """Whole-tree concurrency facts shared by the three passes."""

    def __init__(self):
        self.locks: dict[str, tuple[str, str, int]] = {}
        self.lock_attrs: dict[str, set[str]] = {}
        self.attr_types: dict[tuple[str, str], str] = {}
        self.classes: dict[str, str] = {}
        self.funcs: dict[tuple, _Func] = {}
        self.acquired: dict[tuple, set[str]] = {}
        self.order: list[tuple[str, str, int]] = []
        self.order_path = ""
        self.attr_hints: dict[str, tuple[str, int]] = {}
        self.nestings: list[dict] = []
        self.call_events: list[dict] = []
        self.blocking: list[dict] = []
        self.mutations: list[dict] = []
        self.entries: set[tuple[str, str]] = set()
        self.properties: set[tuple[str, str]] = set()
        self.thread_closure: set[tuple] = set()
        # failure-path facts (ISSUE 20): resolvable Thread targets /
        # pool submit callees, deadline-family call events, and classes
        # whose instances carry a concurrent.futures.Future field
        self.thread_targets: list[dict] = []
        self.deadline_events: list[dict] = []
        self.future_fields: dict[str, str] = {}
        self.ctxs: list[FileContext] = []
        # keys claimed by two different files — dropped before analysis
        # (no resolution beats wrong resolution)
        self._ambiguous: set[tuple] = set()

    # -- phase 1: declarations -----------------------------------------
    def scan_decls(self, ctx: FileContext) -> None:
        stem = os.path.splitext(os.path.basename(ctx.path))[0]
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes.setdefault(node.name, ctx.path)
                for item in ctx.walk(node):
                    if isinstance(item, ast.Assign):
                        self._class_assign(ctx, node.name, item)
                    elif isinstance(item, ast.AnnAssign):
                        self._class_ann(node.name, item)
            elif isinstance(node, ast.Assign):
                kind = self._lock_ctor(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.locks[f"{stem}.{t.id}"] = (
                                kind, ctx.path, node.lineno)

    def _class_assign(self, ctx, cls: str, node: ast.Assign) -> None:
        value = node.value
        for t in node.targets:
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            kind = self._lock_ctor(value)
            if kind:
                self.locks[f"{cls}.{t.attr}"] = (kind, ctx.path,
                                                 node.lineno)
                self.lock_attrs.setdefault(t.attr, set()).add(cls)
            elif isinstance(value, ast.Call) and (
                    dotted_name(value.func) or "") in _FUTURE_CTORS:
                # `self.x = Future()` in __init__: instances of this
                # class carry a future a drain site must own
                self.future_fields.setdefault(cls, t.attr)
            elif isinstance(value, ast.Call) and isinstance(value.func,
                                                            ast.Name):
                # `self.x = ClassName(...)` pins the attribute's type
                self.attr_types.setdefault((cls, t.attr), value.func.id)

    def _class_ann(self, cls: str, node: ast.AnnAssign) -> None:
        """`future: Future = field(default_factory=Future)` (dataclass
        field) or an annotated `self.x: Future = ...` — either makes
        the class future-bearing for the future-resolution pass."""
        is_future = (dotted_name(node.annotation) or "") in _FUTURE_CTORS \
            or self._future_factory(node.value)
        if not is_future:
            return
        t = node.target
        if isinstance(t, ast.Name):
            self.future_fields.setdefault(cls, t.id)
        elif isinstance(t, ast.Attribute) and isinstance(
                t.value, ast.Name) and t.value.id == "self":
            self.future_fields.setdefault(cls, t.attr)

    @staticmethod
    def _future_factory(value) -> bool:
        if not isinstance(value, ast.Call):
            return False
        if (dotted_name(value.func) or "").rsplit(".", 1)[-1] != "field":
            return False
        return any(kw.arg == "default_factory"
                   and (dotted_name(kw.value) or "") in _FUTURE_CTORS
                   for kw in value.keywords)

    @staticmethod
    def _lock_ctor(value) -> str | None:
        if isinstance(value, ast.Call):
            return _LOCK_CTORS.get(dotted_name(value.func) or "")
        return None

    def collect_funcs(self, ctx: FileContext) -> None:
        stem = os.path.splitext(os.path.basename(ctx.path))[0]
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # keys are basename stems (that is what a call site
                # spells) — two files with the same stem (__init__.py
                # packages) could otherwise mis-resolve each other's
                # functions, so a cross-file collision poisons the key:
                # no resolution beats wrong resolution
                key = (("mod", stem), node.name)
                prev = self.funcs.get(key)
                if prev is not None and prev.ctx.path != ctx.path:
                    self._ambiguous.add(key)
                self.funcs[key] = _Func(ctx, node, None, stem)
            elif isinstance(node, ast.ClassDef):
                if self.classes.get(node.name) not in (None, ctx.path):
                    # same class name in two files: method resolution
                    # would conflate them — poison every method key
                    for k in list(self.funcs):
                        if k[0] == node.name:
                            self._ambiguous.add(k)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        key = (node.name, item.name)
                        if self.classes.get(node.name) not in (
                                None, ctx.path):
                            self._ambiguous.add(key)
                        self.funcs[key] = _Func(ctx, item, node.name,
                                                stem)
                        for dec in item.decorator_list:
                            name = dotted_name(dec) or ""
                            if name == "property" or \
                                    name.endswith((".setter", ".getter",
                                                   "cached_property")):
                                # a property READ is a call the AST
                                # shows as an attribute load — it must
                                # not register as an escaping method
                                # reference (thread entry)
                                self.properties.add((node.name,
                                                     item.name))

    # -- phase 2: the declared order -----------------------------------
    def load_registry(self, root: str) -> None:
        path = os.path.join(root, REGISTRY_FILE)
        if not os.path.isfile(path):
            return
        self.order_path = path
        try:
            tree = ast.parse(open(path, encoding="utf-8").read(),
                             filename=path)
        except SyntaxError:
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id == "LOCK_ORDER" and isinstance(
                        value, (ast.Tuple, ast.List)):
                    for pair in value.elts:
                        if isinstance(pair, (ast.Tuple, ast.List)) \
                                and len(pair.elts) == 2 and all(
                                    isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)
                                    for e in pair.elts):
                            self.order.append((pair.elts[0].value,
                                               pair.elts[1].value,
                                               pair.lineno))
                elif t.id == "ATTR_TYPES" and isinstance(value, ast.Dict):
                    for k, v in zip(value.keys, value.values):
                        if isinstance(k, ast.Constant) and isinstance(
                                v, ast.Constant):
                            self.attr_hints[str(k.value)] = (
                                str(v.value), k.lineno)
        for spec, (cls2, _ln) in self.attr_hints.items():
            cls, _, attr = spec.partition(".")
            if attr:
                self.attr_types.setdefault((cls, attr), cls2)

    def reachable(self) -> dict[str, set[str]]:
        """Transitive closure of the declared order: outer -> inners."""
        edges: dict[str, set[str]] = {}
        for a, b, _ln in self.order:
            edges.setdefault(a, set()).add(b)
        closed: dict[str, set[str]] = {}
        for a in edges:
            seen: set[str] = set()
            stack = list(edges[a])
            while stack:
                b = stack.pop()
                if b not in seen:
                    seen.add(b)
                    stack.extend(edges.get(b, ()))
            closed[a] = seen
        return closed

    # -- phase 3: analysis -----------------------------------------------
    def analyze(self) -> None:
        for key in self._ambiguous:
            self.funcs.pop(key, None)
        for key, fn in self.funcs.items():
            _FuncWalk(self, key, fn).run()
        # transitive acquired-locks over the resolvable call graph
        acquired = {k: set(f.direct_locks) for k, f in self.funcs.items()}
        changed = True
        while changed:
            changed = False
            for k, f in self.funcs.items():
                for callee in f.callees:
                    extra = acquired.get(callee)
                    if extra and not acquired[k].issuperset(extra):
                        acquired[k] |= extra
                        changed = True
        self.acquired = acquired
        # nesting pairs through calls: a call made under a held lock
        # acquires (transitively) the callee's locks inside the span
        for ev in self.call_events:
            for lock in sorted(acquired.get(ev["callee"], ())):
                for h in ev["held"]:
                    self.nestings.append({
                        "outer": h, "inner": lock, "ctx": ev["ctx"],
                        "stmt": ev["stmt"], "via": ev["via"],
                        "func": ev["func"]})
        # thread-entry closure over the resolvable call graph
        stack = [e for e in self.entries if e in self.funcs]
        while stack:
            k = stack.pop()
            if k in self.thread_closure:
                continue
            self.thread_closure.add(k)
            stack.extend(c for c in self.funcs[k].callees
                         if c in self.funcs
                         and c not in self.thread_closure)


class _FuncWalk:
    """One function's single walk: lock spans, callees, nesting pairs,
    blocking calls, mutations, thread-entry method references."""

    def __init__(self, model: _Model, key, fn: _Func):
        self.m = model
        self.key = key
        self.fn = fn
        self.local_types: dict[str, str] = {}
        self.local_locks: dict[str, str] = {}

    def run(self) -> None:
        # pre-scan simple local aliases: `x = self.attr` / `x = Cls(..)`
        for node in self.fn.ctx.walk(self.fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                lock = self._lock_id(node.value)
                if lock:
                    self.local_locks.setdefault(name, lock)
                t = self._type_of(node.value)
                if t:
                    self.local_types.setdefault(name, t)
        for child in self.fn.node.body:
            self._walk(child, (), child)

    # -- resolution -----------------------------------------------------
    def _type_of(self, node) -> str | None:
        if isinstance(node, ast.Name):
            if node.id == "self":
                return self.fn.cls
            return self.local_types.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            return self.m.attr_types.get((base, node.attr)) \
                if base is not None else None
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in self.m.classes:
            return node.func.id
        return None

    def _lock_id(self, node) -> str | None:
        if isinstance(node, ast.Name):
            mod_id = f"{self.fn.stem}.{node.id}"
            if mod_id in self.m.locks:
                return mod_id
            return self.local_locks.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            if base is not None and f"{base}.{node.attr}" in self.m.locks:
                return f"{base}.{node.attr}"
            owners = self.m.lock_attrs.get(node.attr)
            if owners and len(owners) == 1:
                return f"{next(iter(owners))}.{node.attr}"
        return None

    def _callee(self, func) -> tuple | None:
        if isinstance(func, ast.Attribute):
            t = self._type_of(func.value)
            if t is not None and (t, func.attr) in self.m.funcs:
                return (t, func.attr)
            if isinstance(func.value, ast.Name):
                key = (("mod", func.value.id), func.attr)
                if key in self.m.funcs:
                    return key
            return None
        if isinstance(func, ast.Name):
            key = (("mod", self.fn.stem), func.id)
            return key if key in self.m.funcs else None
        return None

    def _ref(self, expr) -> tuple | None:
        """Resolve a bare function REFERENCE (a Thread target, a pool
        submit callee) the same way `_callee` resolves a call's func.
        Unresolvable references (locals, closures, foreign objects)
        return None — no resolution beats wrong resolution."""
        return self._callee(expr)

    # -- the walk -------------------------------------------------------
    def _walk(self, node, held: tuple, stmt) -> None:
        if isinstance(node, ast.stmt):
            stmt = node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def body does not run under the lock at def time
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._walk(child, (), stmt)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                lock = self._lock_id(item.context_expr)
                if lock:
                    self.fn.direct_locks.add(lock)
                    for h in inner:
                        self._nesting(h, lock, stmt, "with")
                    inner = inner + (lock,)
                else:
                    self._walk(item.context_expr, held, stmt)
            for child in node.body:
                self._walk(child, inner, stmt)
            return
        if isinstance(node, ast.Call):
            self._call(node, held, stmt)
            func = node.func
            # the func ATTRIBUTE itself is a call, not an escaping
            # method reference — but its base (and any nested calls in
            # a chain like jit(f).lower(...).compile()) still walk
            if isinstance(func, ast.Attribute):
                self._walk(func.value, held, stmt)
            elif not isinstance(func, ast.Name):
                self._walk(func, held, stmt)
            for child in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                self._walk(child, held, stmt)
            return
        if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load) and self.fn.cls is not None \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and (self.fn.cls, node.attr) in self.m.funcs \
                and (self.fn.cls, node.attr) not in self.m.properties:
            # an escaping `self.method` reference (Thread target, pool
            # submit arg, registered callback) marks a thread entry
            self.m.entries.add((self.fn.cls, node.attr))
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            self._mutation(node, held, stmt)
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, stmt)

    def _nesting(self, outer: str, inner: str, stmt, via: str) -> None:
        self.m.nestings.append({"outer": outer, "inner": inner,
                                "ctx": self.fn.ctx, "stmt": stmt,
                                "via": via, "func": self.key})

    def _call(self, node: ast.Call, held: tuple, stmt) -> None:
        func = node.func
        callee = self._callee(func)
        if callee is not None:
            self.fn.callees.add(callee)
            if held:
                label = callee[1] if isinstance(callee[0], tuple) \
                    else f"{callee[0]}.{callee[1]}"
                self.m.call_events.append({
                    "callee": callee, "held": held, "ctx": self.fn.ctx,
                    "stmt": stmt, "via": f"call to {label}",
                    "func": self.key})
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            acq = self._lock_id(func.value)
            if acq:
                self.fn.direct_locks.add(acq)
                for h in held:
                    self._nesting(h, acq, stmt, ".acquire()")
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS \
                and isinstance(func.value, ast.Attribute) \
                and isinstance(func.value.value, ast.Name) \
                and func.value.value.id == "self" \
                and self.fn.cls is not None:
            self.m.mutations.append({
                "cls": self.fn.cls, "attr": func.value.attr,
                "held": held, "ctx": self.fn.ctx, "stmt": stmt,
                "func": self.key})
        if held:
            kind = self._blocking_kind(node, held)
            if kind:
                self.m.blocking.append({
                    "kind": kind, "held": held, "ctx": self.fn.ctx,
                    "stmt": stmt, "line": node.lineno, "func": self.key})
        # failure-path collection (ISSUE 20): Thread targets, pool
        # submit callees, deadline-family events — same single walk
        if dotted_name(func) in _THREAD_CTORS:
            for kw in node.keywords:
                if kw.arg == "target":
                    target = self._ref(kw.value)
                    if target is not None:
                        self.m.thread_targets.append({
                            "target": target, "ctx": self.fn.ctx,
                            "stmt": stmt, "line": node.lineno,
                            "via": "threading.Thread(target=...)",
                            "discarded": False})
        if isinstance(func, ast.Attribute) and func.attr == "submit" \
                and node.args:
            target = self._ref(node.args[0])
            if target is not None:
                # a DISCARDED submit future swallows the callee's
                # exception; a kept future carries it to .result()
                discarded = isinstance(stmt, ast.Expr) \
                    and stmt.value is node
                self.m.thread_targets.append({
                    "target": target, "ctx": self.fn.ctx, "stmt": stmt,
                    "line": node.lineno, "via": ".submit(...)",
                    "discarded": discarded})
        dkind = deadline_kind(node, held, self._lock_id)
        if dkind:
            self.m.deadline_events.append({
                "kind": dkind, "ctx": self.fn.ctx, "stmt": stmt,
                "line": node.lineno, "func": self.key})

    def _blocking_kind(self, node: ast.Call, held: tuple) -> str | None:
        func = node.func
        dotted = dotted_name(func)
        if dotted in ("jax.device_put", "jax.device_get",
                      "jax.block_until_ready", "time.sleep"):
            return dotted
        if dotted in ("np.asarray", "np.array", "numpy.asarray",
                      "numpy.array"):
            if node.args and isinstance(node.args[0], ast.Constant):
                return None     # constant folding, not a device sync
            return dotted
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
        if attr in ("set_result", "set_exception"):
            return f"Future.{attr}"
        if attr == "block_until_ready":
            return ".block_until_ready()"
        if attr == "compile" and not node.args and not node.keywords:
            return ".compile()"
        if attr == "join" and not node.args and not has_timeout:
            return ".join() without timeout"
        if attr == "result" and not node.args and not has_timeout:
            return ".result() without timeout"
        if attr == "get" and not node.args and not node.keywords:
            return ".get() without timeout"
        if attr == "wait":
            if self._lock_id(func.value) in held:
                return None     # Condition.wait under its own lock
            if not node.args and not has_timeout:
                return ".wait() without timeout"
        return None

    def _mutation(self, node, held: tuple, stmt) -> None:
        if self.fn.cls is None:
            return
        targets = [node.target] if isinstance(node, ast.AugAssign) \
            else node.targets
        for t in targets:
            base = t.value if isinstance(t, ast.Subscript) else t
            if isinstance(base, ast.Attribute) and isinstance(
                    base.value, ast.Name) and base.value.id == "self":
                self.m.mutations.append({
                    "cls": self.fn.cls, "attr": base.attr, "held": held,
                    "ctx": self.fn.ctx, "stmt": stmt, "func": self.key})


# ---------------------------------------------------------------------------
# shared model construction (one per run_lint call)

# identity-checked single-entry cache: the framework hands every pass
# the SAME ctxs list within one run; holding the key list strongly
# prevents id-reuse across runs (tests edit files between runs)
_CACHE: list = [None, None]     # [ctxs_list, model]

# how many times the model was actually built (not served from cache)
# since import — `--profile` reports the per-run delta so the
# one-build-for-all-interprocedural-passes claim stays testable
BUILD_COUNT = [0]


def tree_model(ctxs: list[FileContext], root: str) -> _Model:
    if _CACHE[0] is ctxs:
        return _CACHE[1]
    BUILD_COUNT[0] += 1
    model = _Model()
    by_path = {c.path: c for c in ctxs}
    scan_ctxs: list[FileContext] = []
    seen: set[str] = set()
    # always model the full production tree (like doc-drift): a partial
    # selection must not hide half the lock aliases or the call graph
    for target in DEFAULT_SCAN:
        path = os.path.join(root, target)
        if not os.path.exists(path):
            continue
        for fp in iter_py_files([path]):
            fp = os.path.abspath(fp)
            if fp in seen:
                continue
            seen.add(fp)
            ctx = by_path.get(fp)
            if ctx is None:
                try:
                    ctx = FileContext(fp, root=root)
                except OSError:
                    continue
            if ctx.tree is not None:
                scan_ctxs.append(ctx)
    for ctx in ctxs:    # explicitly selected files outside the scan
        if ctx.path not in seen and ctx.tree is not None:
            seen.add(ctx.path)
            scan_ctxs.append(ctx)
    model.ctxs = scan_ctxs
    for ctx in scan_ctxs:
        model.scan_decls(ctx)
        model.collect_funcs(ctx)
    model.load_registry(root)
    model.analyze()
    _CACHE[0], _CACHE[1] = ctxs, model
    return model


def _emit(pass_name: str, ctx: FileContext, stmt, line: int, message: str,
          selected: dict[str, FileContext]) -> Finding | None:
    """Finding with waivers honored: files in the current selection get
    a span (the framework filters them and tracks honored waivers);
    modeled-but-unselected files are self-filtered here, the way the
    doc-drift pass handles its whole-tree call-site scan."""
    span = ctx.span_of(stmt) if stmt is not None else None
    if ctx.path in selected:
        return Finding(pass_name, ctx.path, line, message, span=span)
    if ctx.waived(span, pass_name):
        return None
    return Finding(pass_name, ctx.path, line, message, span=None)


# ---------------------------------------------------------------------------
# the passes

@register
class LockOrderPass(LintPass):
    name = "lock-order"
    description = ("lock nestings must follow the declared LOCK_ORDER "
                   "partial order (serving/locks.py); inverted or "
                   "undeclared pairs are findings")

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        model = tree_model(ctxs, root)
        selected = {c.path: c for c in ctxs}
        closed = model.reachable()
        seen: set[tuple] = set()
        for n in model.nestings:
            a, b = n["outer"], n["inner"]
            key = (n["ctx"].path, n["stmt"].lineno, a, b)
            if key in seen:
                continue
            seen.add(key)
            if a == b:
                kind = model.locks.get(a, ("Lock",))[0]
                if kind == "RLock":
                    continue
                msg = (f"re-acquiring non-reentrant {a} ({kind}) while "
                       f"already holding it ({n['via']}) — "
                       "self-deadlock")
            elif b in closed.get(a, ()):
                continue
            elif a in closed.get(b, ()):
                msg = (f"INVERTED lock nesting: {a} held while "
                       f"acquiring {b} ({n['via']}), but LOCK_ORDER "
                       f"declares {b} -> {a} — this is the deadlock "
                       "shape the declared order exists to prevent")
            else:
                msg = (f"undeclared lock nesting: {a} held while "
                       f"acquiring {b} ({n['via']}) — declare the pair "
                       f"in {REGISTRY_FILE} LOCK_ORDER (with the review "
                       "reason) or restructure; waive with "
                       "`# lint: ok(lock-order) — reason` only if the "
                       "nesting is deliberate and cannot deadlock")
            f = _emit(self.name, n["ctx"], n["stmt"], n["stmt"].lineno,
                      msg, selected)
            if f:
                yield f
        if not model.order_path:
            return
        # registry drift: the declared order must name real locks, stay
        # acyclic, and ATTR_TYPES must name classes that still exist
        for a, b, ln in model.order:
            for lock_id in (a, b):
                if lock_id not in model.locks:
                    yield Finding(
                        self.name, model.order_path, ln,
                        f"LOCK_ORDER names unknown lock {lock_id!r} — "
                        "no matching threading.Lock/RLock/Condition "
                        "alias exists in the tree; sync the registry "
                        "with the code", span=None)
            if a in closed.get(b, set()) and b in closed.get(a, set()):
                yield Finding(
                    self.name, model.order_path, ln,
                    f"LOCK_ORDER contains a cycle through ({a!r}, "
                    f"{b!r}) — a partial order cannot permit both "
                    "directions", span=None)
        for spec, (cls2, ln) in sorted(model.attr_hints.items()):
            cls, _, _attr = spec.partition(".")
            if cls not in model.classes or cls2 not in model.classes:
                yield Finding(
                    self.name, model.order_path, ln,
                    f"ATTR_TYPES entry {spec!r} -> {cls2!r} names a "
                    "class that no longer exists in the tree",
                    span=None)


@register
class BlockingUnderLockPass(LintPass):
    name = "blocking-under-lock"
    description = ("Future.set_result/set_exception, device calls "
                   "(device_put/.compile()/np.asarray), and unbounded "
                   "join/get/result/wait inside a held lock span")

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        model = tree_model(ctxs, root)
        selected = {c.path: c for c in ctxs}
        seen: set[tuple] = set()
        for b in model.blocking:
            key = (b["ctx"].path, b["line"], b["kind"])
            if key in seen:
                continue
            seen.add(key)
            kind, held = b["kind"], ", ".join(b["held"])
            if kind.startswith("Future."):
                why = ("done-callbacks run synchronously in this "
                       "thread, and a callback re-entering the lock "
                       "deadlocks (the PR 7 shape) — resolve futures "
                       "after releasing the lock")
            elif kind in _DEVICE_KINDS:
                why = ("a device call takes tunnel-length seconds and "
                       "stalls every thread touching the lock (the "
                       "swap_weights false-breaker-trip shape) — move "
                       "the device work outside the lock")
            else:
                why = ("an unbounded block while holding a lock turns "
                       "one slow thread into a plane-wide stall — "
                       "bound it or release the lock first")
            f = _emit(self.name, b["ctx"], b["stmt"], b["line"],
                      f"{kind} inside a held lock span ({held}): {why}; "
                      "waive with `# lint: ok(blocking-under-lock) — "
                      "reason` if serializing this call is the lock's "
                      "purpose", selected)
            if f:
                yield f


@register
class ThreadSharedMutationPass(LintPass):
    name = "thread-shared-mutation"
    description = ("attributes mutated both on a thread-entry path and "
                   "from public methods with no shared covering lock")

    def check_tree(self, ctxs: list[FileContext],
                   root: str) -> Iterator[Finding]:
        model = tree_model(ctxs, root)
        selected = {c.path: c for c in ctxs}
        if not model.thread_closure:
            return
        by_attr: dict[tuple[str, str], list[dict]] = {}
        for mut in model.mutations:
            if mut["func"][1] == "__init__":
                continue    # constructors run before any thread exists
            by_attr.setdefault((mut["cls"], mut["attr"]), []).append(mut)
        def _counterpart(m, others):
            return next((o for o in others
                         if not (set(m["held"]) & set(o["held"]))), None)

        def _msg(attr, m, other, side):
            return (f"self.{attr} is mutated here in "
                    f"{m['func'][0]}.{m['func'][1]} (holding "
                    f"[{', '.join(m['held']) or 'no lock'}], "
                    f"{side}) and in {other['func'][1]}() (holding "
                    f"[{', '.join(other['held']) or 'no lock'}]) with "
                    "no shared covering lock — guard both sides with "
                    "one lock, or waive with `# lint: ok(thread-"
                    "shared-mutation) — reason` (e.g. the caller "
                    "holds the lock, or ordering makes the race "
                    "benign)")

        for (cls, attr), muts in sorted(by_attr.items()):
            thread = [m for m in muts
                      if m["func"] in model.thread_closure]
            public = [m for m in muts
                      if m["func"] not in model.thread_closure]
            if not thread or not public:
                continue
            # EVERY unlocked mutation site with a disjoint-lock
            # counterpart on the other side is its own finding — one
            # waived anchor must not silence a race a later edit adds
            # at a different site of the same attribute
            sites: set[tuple] = set()
            emitted = False
            for side_name, side, others in (("thread side", thread,
                                             public),
                                            ("public side", public,
                                             thread)):
                for m in side:
                    if m["held"]:
                        continue
                    other = _counterpart(m, others)
                    if other is None:
                        continue
                    key = (m["ctx"].path, m["stmt"].lineno)
                    if key in sites:
                        continue
                    sites.add(key)
                    emitted = True
                    f = _emit(self.name, m["ctx"], m["stmt"],
                              m["stmt"].lineno,
                              _msg(attr, m, other, side_name),
                              selected)
                    if f:
                        yield f
            if not emitted:
                # both sides locked, but by DISJOINT locks — still a
                # race; anchor the thread side once
                for tm in thread:
                    pm = _counterpart(tm, public)
                    if pm is not None:
                        f = _emit(self.name, tm["ctx"], tm["stmt"],
                                  tm["stmt"].lineno,
                                  _msg(attr, tm, pm, "thread side"),
                                  selected)
                        if f:
                            yield f
                        break
