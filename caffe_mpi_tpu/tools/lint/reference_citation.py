"""reference-citation pass — module docstrings must cite the reference.

The load-bearing repo convention (CLAUDE.md): every module docstring
under caffe_mpi_tpu/ cites the reference files (`file:line`) it
replaces — e.g. solver/solver.py cites src/caffe/solver.cpp:187-351 —
and explains the TPU-native design choice. Until now that was enforced
only by review; this pass makes it mechanical: the docstring must
contain at least one source-file token (path with a known source
extension, brace-groups like `{cpp,cu}` included, `:line` ranges
encouraged). Modules that are genuinely TPU-native with no reference
analogue say so in a waiver: `# lint: ok(reference-citation) — reason`
on the line above (or the line after) the docstring.

Scope: files under the caffe_mpi_tpu package tree (plus anything
scanned from outside the repo, e.g. test fixtures). Trivial modules —
no docstring AND no function/class definitions (re-export __init__
shims) — are exempt.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from . import Finding, FileContext, LintPass, register

_EXT = r"(?:cpp|cc|cu|cuh|hpp|hh|h|py|proto|prototxt|sh|md)"
# a path-ish token ending in a source extension; `{cpp,cu}` brace
# alternation is the repo's multi-file idiom, and no trailing \b — a
# closing brace has no word boundary against the following space
CITATION_RE = re.compile(
    r"[\w/{},\.\-]*\.(?:%s|\{%s(?:,%s)*\})(?::\d[\d\-,]*)?" % (
        _EXT, _EXT, _EXT))


@register
class ReferenceCitationPass(LintPass):
    name = "reference-citation"
    description = ("module docstrings under caffe_mpi_tpu/ must cite "
                   "the reference file(:line) they replace")

    def _in_scope(self, ctx: FileContext) -> bool:
        rel = ctx.rel
        if rel == ctx.path:      # outside the repo root: fixture mode
            return True
        return rel.split("/")[0] == "caffe_mpi_tpu"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        tree = ctx.tree
        doc = ast.get_docstring(tree, clean=False)
        has_defs = any(isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef))
                       for s in tree.body)
        if doc is None:
            if not has_defs:
                return           # trivial re-export shim
            yield Finding(
                self.name, ctx.path, 1,
                "module has no docstring — add one citing the "
                "reference file(:line) it replaces and the TPU-native "
                "design choice (CLAUDE.md convention), or waive with "
                "`# lint: ok(reference-citation) — reason`",
                span=(1, 2))
            return
        if CITATION_RE.search(doc):
            return
        stmt = tree.body[0]
        end = getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno
        # the docstring is the first statement: a leading comment block
        # (anywhere above it) is the natural waiver placement
        yield Finding(
            self.name, ctx.path, stmt.lineno,
            "module docstring cites no reference file — name the "
            "reference source (`file:line`) this module replaces "
            "(CLAUDE.md convention); if it is TPU-native with no "
            "analogue, waive with `# lint: ok(reference-citation) — "
            "reason`",
            span=(1, end + 1))
