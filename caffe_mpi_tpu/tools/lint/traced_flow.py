"""traced-control-flow pass — Python branching on traced values.

Inside a function that gets traced (jit / lax.scan / lax.cond /
grad / vmap bodies), a Python `if`/`while`/`bool()`/`int()` applied to
a `jnp.`/`lax.` expression either raises ConcretizationError or — when
the value happens to be concrete at trace time — silently bakes the
branch into the compiled program and forces a retrace whenever it
flips. The reference never has this failure mode: its graph is the C++
call tree itself (net.cpp Forward/Backward run layer code directly, no
tracing). Here the blueprint is TensorFlow's whole-program validation
(PAPERS.md: OSDI'16) — check the program before dispatch, because
after dispatch is a live-TPU luxury this environment rarely has.

Reachability is a deliberately simple per-module over-approximation:

- roots: functions decorated with / passed to jit-like transforms
  (jit, pjit, grad, value_and_grad, vmap, pmap, checkpoint, remat,
  shard_map) and function-valued arguments of lax control-flow ops
  (scan, cond, while_loop, switch, fori_loop, map, associative_scan)
- edges: bare-name calls to functions defined in the same module
  (methods and cross-module calls are not chased)

Flagged inside reachable functions:

- `if`/`while`/ternary tests containing a `jnp.`/`lax.` call (minus a
  whitelist of trace-time-concrete metadata helpers: issubdtype,
  iinfo, finfo, ...)
- `bool(x)`/`int(x)` where x contains such a call

Both directions are approximate: a traced value held in a bare local
name is invisible (no type inference), and a host-only helper that
shares a name with a traced one is over-flagged — waive the latter
with `# lint: ok(traced-control-flow) — reason`.
"""

from __future__ import annotations

import ast
from typing import Iterator

from . import Finding, FileContext, LintPass, attr_root, dotted_name, register

# transforms whose function-valued arguments are traced
_TRANSFORMS = {"jit", "pjit", "grad", "value_and_grad", "vmap", "pmap",
               "checkpoint", "remat", "shard_map", "custom_vjp",
               "custom_jvp"}
_LAX_FLOW = {"scan", "cond", "while_loop", "switch", "fori_loop", "map",
             "associative_scan"}

# jnp/lax attributes that return trace-time-concrete metadata, not
# traced arrays — branching on them is normal and safe
_CONCRETE_ATTRS = {"issubdtype", "iinfo", "finfo", "result_type",
                   "promote_types", "dtype", "dtypes", "isdtype",
                   "canonicalize_dtype"}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

# substrings at least one of which must appear in a file's source for
# any root to exist there (every _TRANSFORMS/_LAX_FLOW spelling is a
# literal identifier in the call or decorator; `lax.map` needs a
# lax./jax. attribute root, so only the dotted forms are listed —
# `scan` also covers associative_scan)
_PREGATE_TOKENS = tuple(_TRANSFORMS) + (
    "scan", "cond", "while_loop", "switch", "fori_loop",
    "lax.map", "jax.map")


def _is_traced_namespace_call(node: ast.expr) -> ast.Call | None:
    """The first jnp./lax. call in the subtree that produces a traced
    value (metadata helpers excluded), else None."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        if not isinstance(fn, ast.Attribute):
            continue
        root = attr_root(fn)
        full = dotted_name(fn) or ""
        if root in ("jnp", "lax") or full.startswith(("jax.numpy.",
                                                      "jax.lax.")):
            if fn.attr not in _CONCRETE_ATTRS:
                return sub
    return None


@register
class TracedControlFlowPass(LintPass):
    name = "traced-control-flow"
    description = ("Python if/while/bool()/int() on jnp/lax values "
                   "inside traced (jit/scan) functions")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # pregate: a finding needs a jnp./lax./jax.-rooted call (those
        # names appear literally in source) AND a traced root, whose
        # transform name does too — skip the two tree recursions for
        # files that can't possibly fire
        src = ctx.src
        if "jnp" not in src and "lax" not in src and "jax" not in src:
            return
        if not any(t in src for t in _PREGATE_TOKENS):
            return
        # ---- collect function definitions + call edges + roots -------
        funcs: list[dict] = []          # {node, name, calls}
        roots: set[int] = set()         # id(node) of traced roots
        by_name: dict[str, list[dict]] = {}

        def is_jitlike(expr: ast.expr) -> bool:
            """decorator / callee that traces its function argument —
            including the `partial(jax.jit, static_argnums=...)`
            idiom, where the transform hides one Call deeper."""
            target = expr.func if isinstance(expr, ast.Call) else expr
            name = dotted_name(target)
            if name is None:
                return False
            leaf = name.rsplit(".", 1)[-1]
            if leaf == "partial" and isinstance(expr, ast.Call) \
                    and expr.args:
                return is_jitlike(expr.args[0])
            return leaf in _TRANSFORMS

        def collect(node: ast.AST, current: dict | None,
                    stmt: ast.stmt | None = None) -> None:
            for child in ast.iter_child_nodes(node):
                s = child if isinstance(child, ast.stmt) else stmt
                if isinstance(child, _FUNC_DEFS + (ast.Lambda,)):
                    # `stmt` anchors waivers for lambda-body findings:
                    # a lambda has no statements of its own, so its
                    # findings waive on the enclosing statement
                    info = {"node": child, "calls": set(), "idx": len(funcs),
                            "name": getattr(child, "name", "<lambda>"),
                            "stmt": s}
                    funcs.append(info)
                    by_name.setdefault(info["name"], []).append(info)
                    if any(is_jitlike(d) for d in
                           getattr(child, "decorator_list", [])):
                        roots.add(id(child))
                    collect(child, info, s)
                    continue
                if isinstance(child, ast.Call):
                    callee = dotted_name(child.func)
                    if callee:
                        leaf = callee.rsplit(".", 1)[-1]
                        fn_args = ()
                        if leaf in _TRANSFORMS:
                            fn_args = child.args[:1]
                        elif leaf in _LAX_FLOW and attr_root(
                                child.func) in ("lax", "jax"):
                            fn_args = child.args
                        for a in list(fn_args) + [
                                kw.value for kw in child.keywords
                                if kw.arg in ("body", "cond", "f",
                                              "body_fun", "cond_fun",
                                              "fun")]:
                            if isinstance(a, ast.Name):
                                for info in by_name.get(a.id, []):
                                    roots.add(id(info["node"]))
                                if current is not None:
                                    current["calls"].add("__root__" + a.id)
                            elif isinstance(a, ast.Lambda):
                                roots.add(id(a))
                    if current is not None and isinstance(child.func,
                                                          ast.Name):
                        current["calls"].add(child.func.id)
                collect(child, current, s)

        collect(ctx.tree, None)

        # second chance for forward references: a Name passed to a
        # transform before its def was collected
        for info in funcs:
            for c in info["calls"]:
                if c.startswith("__root__"):
                    for target in by_name.get(c[len("__root__"):], []):
                        roots.add(id(target["node"]))

        # ---- propagate reachability over bare-name call edges --------
        reachable = {i for i, f in enumerate(funcs)
                     if id(f["node"]) in roots}
        changed = True
        while changed:
            changed = False
            for i, f in enumerate(funcs):
                if i not in reachable:
                    continue
                for callee in f["calls"]:
                    for target in by_name.get(callee, []):
                        j = target["idx"]
                        if j not in reachable:
                            reachable.add(j)
                            changed = True

        # ---- flag traced-value branching in reachable functions ------
        findings: list[Finding] = []

        def flag(node: ast.expr, what: str, stmt: ast.stmt | None) -> None:
            hit = _is_traced_namespace_call(node)
            if hit is None:
                return
            findings.append(Finding(
                self.name, ctx.path, node.lineno,
                f"{what} on a traced `{dotted_name(hit.func)}` value "
                "inside a jit/scan-reachable function — this forces "
                "concretization (ConcretizationError under jit, or a "
                "silent retrace per flip); use lax.cond/lax.select or "
                "hoist the decision to the host",
                span=ctx.span_of(stmt) if stmt is not None else None))

        consumed: set[int] = set()   # nodes inside an already-judged test

        def check_node(node: ast.AST, s: ast.stmt | None) -> None:
            """The flaggable shapes, applied to one node. A bool()/
            int() nested inside a flagged if/while test is the SAME
            defect — consume the test subtree so it reports once."""
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                what = ("ternary `if`" if isinstance(node, ast.IfExp)
                        else f"Python `{type(node).__name__.lower()}`")
                flag(node.test, what, s)
                consumed.update(id(n) for n in ast.walk(node.test))
            elif (isinstance(node, ast.Call)
                  and id(node) not in consumed
                  and isinstance(node.func, ast.Name)
                  and node.func.id in ("bool", "int")
                  and len(node.args) == 1):
                flag(node.args[0], f"`{node.func.id}()`", s)

        def scan_body(node: ast.AST, stmt: ast.stmt | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_DEFS + (ast.Lambda,)):
                    continue  # nested scopes judged by their own entry
                s = child if isinstance(child, ast.stmt) else stmt
                check_node(child, s)
                scan_body(child, s)

        for i in sorted(reachable):
            node = funcs[i]["node"]
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                # the body node itself (a lambda body can BE the
                # flaggable expression), then everything under it; a
                # lambda body's findings anchor waivers on the
                # statement enclosing the lambda
                s = stmt if isinstance(stmt, ast.stmt) else funcs[i]["stmt"]
                check_node(stmt, s)
                scan_body(stmt, s)

        # dedup (top-level If both flagged directly and via scan? no —
        # scan_body only sees children; direct flag covers the stmt
        # itself). Sort for stable output.
        findings.sort(key=lambda f: f.line)
        yield from findings
