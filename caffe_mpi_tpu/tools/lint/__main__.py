"""CLI entry: `python -m caffe_mpi_tpu.tools.lint` (see package
docstring; ancestor: tools/check_host_syncs.py, now a shim over this.
The reference's analogue is the build system itself — Makefile + nvcc
reject these bug classes at compile time)."""

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
