"""upgrade_net_proto_binary — rewrite a legacy binary NetParameter
(.caffemodel) with modern `layer` (field 100) messages.

Reference: tools/upgrade_net_proto_binary.cpp — reads a binary
NetParameter, runs the V0->V1->V2 upgrade chain, and writes binary back
out. Here the wire-level parser (io.parse_caffemodel) already folds the
V0 (nested V0LayerParameter) and V1 (`layers` field 2) encodings into
the canonical {layer_name: blobs} form, so upgrading is parse +
re-encode. Only the weight-bearing payload matters for a .caffemodel:
the framework never reads architecture from the binary (that comes from
the deploy prototxt), matching how the migrated file is consumed.

Usage:
    python -m caffe_mpi_tpu.tools.upgrade_net_proto_binary IN.caffemodel OUT.caffemodel
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="upgrade_net_proto_binary")
    p.add_argument("input")
    p.add_argument("output")
    args = p.parse_args(argv)

    from ..io import load_caffemodel, save_caffemodel

    weights = load_caffemodel(args.input)
    if not weights:
        print(f"no layers with blobs found in {args.input}",
              file=sys.stderr)
        return 1
    save_caffemodel(args.output, weights)
    n = sum(len(b) for b in weights.values())
    print(f"upgraded {args.input} -> {args.output} "
          f"({len(weights)} layers, {n} blobs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
