"""extract_features — dump named blob activations over N batches.

Reference: tools/extract_features.cpp (writes features to LMDB); here the
output is an HDF5 file with one dataset per blob, which is what downstream
python consumers actually want.

Usage:
    python -m caffe_mpi_tpu.tools.extract_features \
        WEIGHTS_FILE MODEL_PROTOTXT BLOB_NAME1[,BLOB2...] OUTPUT_H5 NUM_BATCHES
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="extract_features")
    p.add_argument("weights")
    p.add_argument("model")
    p.add_argument("blobs")
    p.add_argument("output")
    p.add_argument("num_batches", type=int, nargs="?", default=10)
    args = p.parse_args(argv)

    import h5py
    import jax

    from ..io import load_weights
    from ..net import Net
    from ..proto import NetParameter
    from .cli import _build_feeders, _synthetic_feed

    import os
    net = Net(NetParameter.from_file(args.model), phase="TEST",
              model_dir=os.path.dirname(os.path.abspath(args.model)))
    params, state = net.init(jax.random.PRNGKey(0))
    params, state = net.import_weights(params, state,
                                       load_weights(args.weights))
    blob_names = args.blobs.split(",")
    for b in blob_names:
        if b not in net.blob_shapes:
            print(f"unknown blob {b!r}", file=sys.stderr)
            return 1
    feeder = _build_feeders(net, "TEST")
    fwd = jax.jit(lambda p, s, f: net.apply(p, s, f, train=False)[0])
    chunks: dict[str, list] = {b: [] for b in blob_names}
    import jax.numpy as jnp
    for it in range(args.num_batches):
        feeds = feeder(it) if feeder else _synthetic_feed(net, seed=it)
        feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        env = fwd(params, state, feeds)
        for b in blob_names:
            # feature dump IS the workload: one bounded pull per batch
            # lint: ok(host-sync) — into the HDF5 output
            chunks[b].append(np.asarray(env[b]))
    with h5py.File(args.output, "w") as f:
        for b in blob_names:
            f.create_dataset(b, data=np.concatenate(chunks[b]))
    print(f"Extracted {args.num_batches} batches of {blob_names} "
          f"to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
