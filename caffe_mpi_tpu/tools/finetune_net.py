"""Deprecated shim (reference tools/finetune_net.cpp:3-8 — an equally-thin
LOG(FATAL) redirect): use the caffe CLI subcommand instead."""

import sys


def main(argv=None) -> int:
    print("finetune_net is deprecated. Use: python -m caffe_mpi_tpu.tools.cli "
          "train -weights ...", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
