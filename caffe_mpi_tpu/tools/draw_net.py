"""draw_net — render a net definition to DOT/PNG (reference
python/draw_net.py).

Usage:
    python -m caffe_mpi_tpu.tools.draw_net NET.prototxt OUT.{dot,png,svg}
        [--rankdir LR] [--phase TRAIN|TEST]
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="draw_net")
    p.add_argument("net")
    p.add_argument("output")
    p.add_argument("--rankdir", default="TB")
    p.add_argument("--phase", default=None)
    args = p.parse_args(argv)

    from ..draw import draw_net_to_file
    from ..proto import NetParameter

    draw_net_to_file(NetParameter.from_file(args.net), args.output,
                     rankdir=args.rankdir, phase=args.phase)
    print(f"drew {args.net} -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
