"""upgrade_solver_proto_text — migrate a legacy SolverParameter prototxt
(reference tools/upgrade_solver_proto_text.cpp; thin over the same
machinery as upgrade_net_proto_text -solver, which the reference also
shares via upgrade_proto.cpp).

Usage:
    python -m caffe_mpi_tpu.tools.upgrade_solver_proto_text IN.prototxt OUT.prototxt
"""

from __future__ import annotations

import sys

from .upgrade_net_proto_text import main as _net_main


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    return _net_main(["-solver", *argv])


if __name__ == "__main__":
    sys.exit(main())
