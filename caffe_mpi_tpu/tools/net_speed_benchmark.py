"""Deprecated shim (reference tools/net_speed_benchmark.cpp:3-8 — an equally-thin
LOG(FATAL) redirect): use the caffe CLI subcommand instead."""

import sys


def main(argv=None) -> int:
    print("net_speed_benchmark is deprecated. Use: python -m caffe_mpi_tpu.tools.cli "
          "time ...", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
