"""parse_log — training-log to CSV.

Reference: tools/extra/parse_log.py parses glog training output into
aggregate train/test CSVs for plotting (plot_training_log.py, summarize.py).
This parses this framework's solver log lines:

  I0728 12:00:00 caffe_mpi_tpu.solver] Iteration 120 (9.8 iter/s, 620.0 img/s), loss = 0.034, lr = 0.01
  I0728 12:00:01 caffe_mpi_tpu.solver]     Test net #0: accuracy = 0.99

Usage:
    python -m caffe_mpi_tpu.tools.parse_log LOGFILE [OUTPUT_DIR]
"""

from __future__ import annotations

import argparse
import csv
import os
import re
import sys

TRAIN_RE = re.compile(
    r"Iteration (?P<iter>\d+) \((?P<ips>[\d.e+-]+) iter/s, "
    r"(?P<imgs>[\d.e+-]+) img/s\), loss = (?P<loss>[\d.e+-]+|nan|inf), "
    r"lr = (?P<lr>[\d.e+-]+)")
TEST_RE = re.compile(
    r"Test net #(?P<net>\d+): (?P<blob>\S+) = (?P<value>[\d.e+-]+)")


def parse(path: str):
    train_rows, test_rows = [], []
    last_iter = 0
    with open(path) as f:
        for line in f:
            m = TRAIN_RE.search(line)
            if m:
                last_iter = int(m["iter"])
                train_rows.append({
                    # lint: ok(host-sync) — parsing log text, host strings
                    "NumIters": last_iter,
                    "LearningRate": float(m["lr"]),
                    "loss": float(m["loss"]),
                    "iter_per_s": float(m["ips"]),
                    "img_per_s": float(m["imgs"]),
                })
                continue
            m = TEST_RE.search(line)
            if m:
                test_rows.append({
                    # lint: ok(host-sync) — parsing log text, host strings
                    "NumIters": last_iter,
                    "TestNet": int(m["net"]),
                    m["blob"]: float(m["value"]),
                })
    return train_rows, test_rows


def write_csv(rows, path):
    if not rows:
        return
    keys = sorted({k for r in rows for k in r})
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=keys)
        w.writeheader()
        w.writerows(rows)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="parse_log")
    p.add_argument("logfile")
    p.add_argument("output_dir", nargs="?", default=".")
    args = p.parse_args(argv)
    train, test = parse(args.logfile)
    base = os.path.basename(args.logfile)
    write_csv(train, os.path.join(args.output_dir, base + ".train"))
    write_csv(test, os.path.join(args.output_dir, base + ".test"))
    print(f"{len(train)} train rows, {len(test)} test rows")
    return 0


if __name__ == "__main__":
    sys.exit(main())
