"""caffe CLI — train / test / time / device_query / serve.

Reference: tools/caffe.cpp (499 LoC): command registry, gflags (-solver,
-model, -gpu, -snapshot, -weights, -iterations, -sigint_effect,
-sighup_effect), signal handling (SIGINT->stop, SIGHUP->snapshot), per-layer
timing benchmark (`caffe time`, tools/caffe.cpp:328-445).

Usage (gflags-compatible single-dash long flags accepted):
    python -m caffe_mpi_tpu.tools.cli train -solver solver.prototxt [-weights w.caffemodel | -snapshot s.solverstate] [-gpu all]
    python -m caffe_mpi_tpu.tools.cli test -model net.prototxt -weights w.caffemodel -iterations 50
    python -m caffe_mpi_tpu.tools.cli time -model net.prototxt -iterations 50
    python -m caffe_mpi_tpu.tools.cli device_query
    python -m caffe_mpi_tpu.tools.cli serve -model deploy.prototxt -weights w.caffemodel [-port 5000] [-smoke N] [-serve_queue_limit Q] [-serve_deadline_ms D] [-serve_stall_s S] [-serve_decoded_cache_mb M] [-serve_program_bank DIR [-require_bank_warm]] [-watch SNAPSHOT_PREFIX] [-replicas N [-serve_retry_budget R] [-replica_deadline S] [-fleet_dir D]]
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import time

import numpy as np

log = logging.getLogger("caffe")


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="caffe", description=__doc__)
    p.add_argument("command",
                   choices=["train", "test", "time", "device_query",
                            "serve"])
    for flag, kw in [
        ("solver", dict(default="", help="solver prototxt")),
        ("model", dict(default="", help="net prototxt")),
        ("weights", dict(default="", help=".caffemodel[.h5] to load")),
        ("snapshot", dict(default="", help=".solverstate[.h5|.npz] to resume")),
        ("gpu", dict(default="", help="'all' = full device mesh, or index")),
        ("mesh", dict(default="", help="explicit mesh shape, e.g. "
                      "'data=4,model=2'; layers with param_sharding "
                      "rules go tensor-parallel over 'model'")),
        ("gpipe", dict(type=int, default=0,
                       help="pipeline-train across S stages (heterogeneous "
                       "MPMD GPipe): net auto-cut into S device-pinned "
                       "stages, batch split into micro-batches, stage-local "
                       "optimizer updates; exclusive of -gpu/-mesh")),
        ("gpipe_micro", dict(type=int, default=0,
                             help="micro-batches per iteration under "
                             "-gpipe (default: number of stages)")),
        ("iterations", dict(type=int, default=50)),
        ("sigint_effect", dict(default="stop", choices=["stop", "snapshot", "none"])),
        ("sighup_effect", dict(default="snapshot", choices=["stop", "snapshot", "none"])),
        ("phase", dict(default="TEST", choices=["TRAIN", "TEST"])),
        ("synthetic", dict(action="store_true",
                           help="feed random data into Input layers")),
        ("profile", dict(default="", help="write a JAX/XLA profiler trace "
                                          "(xplane) to this directory")),
        ("max_iter", dict(type=int, default=0,
                          help="override solver max_iter (0 = prototxt)")),
        ("test_iter", dict(type=int, default=0,
                           help="override solver test_iter (0 = prototxt)")),
    ]:
        p.add_argument(f"-{flag}", f"--{flag}", **kw)
    p.add_argument("-step_chunk", "--step_chunk", "--step-chunk",
                   dest="step_chunk", type=int, default=0,
                   help="fuse K iterations into ONE on-device lax.scan "
                   "dispatch (train only; overrides solver step_chunk; "
                   "0 = prototxt value, which defaults to 1). Chunks "
                   "auto-align to display/test_interval/snapshot "
                   "boundaries, so observable behavior is unchanged")
    p.add_argument("-test_chunk", "--test_chunk", "--test-chunk",
                   dest="test_chunk", type=int, default=0,
                   help="fuse T test batches into one evaluation "
                   "dispatch: the test pass runs as a jitted lax.scan "
                   "over a [T, B, ...] super-batch, ceil(test_iter/T) "
                   "dispatches per pass, overlapped with training "
                   "(overrides solver test_chunk; 0 = prototxt value, "
                   "which defaults to auto-sizing T from the eval "
                   "super-batch HBM budget)")
    # overlapped bucketed reduction flags (ISSUE 6, parallel/reduction.py)
    p.add_argument("-reduce_overlap", "--reduce-overlap",
                   dest="reduce_overlap", action="store_true",
                   help="explicit overlapped bucketed gradient "
                   "reduction: the data-parallel step computes grads "
                   "per device (shard_map) and psums them one bucket "
                   "at a time in reverse layer order, so the TPU "
                   "scheduler overlaps each bucket's collective with "
                   "the remaining backward (enables solver "
                   "reduce_overlap; requires -gpu all or -mesh; "
                   "incompatible nets fall back to the implicit "
                   "GSPMD reduction with a warning)")
    p.add_argument("-reduce_buckets", "--reduce-buckets",
                   dest="reduce_buckets", type=int, default=0,
                   help="gradient buckets for -reduce_overlap "
                   "(overrides solver reduce_buckets; 0 = prototxt "
                   "value, which defaults to the net-level "
                   "reduce_buckets, reference default 6); 0/negative "
                   "explicit values are rejected")
    p.add_argument("-grad_bucket_mb", "--grad-bucket-mb",
                   dest="grad_bucket_mb", type=float, default=0.0,
                   help="size -reduce_overlap buckets by a MiB budget "
                   "instead of a count (overrides solver "
                   "grad_bucket_mb; a single param above the budget "
                   "gets its own bucket with a warning; exclusive of "
                   "-reduce_buckets)")
    # mixed-precision flags (ISSUE 9, docs/benchmarks.md
    # "Mixed-precision bf16 training")
    p.add_argument("-precision", "--precision", default="",
                   choices=["", "f32", "bf16"],
                   help="train: compute precision (overrides solver "
                   "precision; '' = prototxt value, default f32 = "
                   "bitwise today). bf16 computes activations/gradients "
                   "in bfloat16 with f32 MASTER params and momentum — "
                   "updates in f32, reduce_overlap buckets psum in bf16 "
                   "(half the collective bytes), loss scaling armed per "
                   "-loss_scale")
    p.add_argument("-loss_scale", "--loss-scale", dest="loss_scale",
                   type=float, default=-1.0,
                   help="bf16 loss scale: 0 = DYNAMIC (scale rides the "
                   "train-scan carry; an overflow step is skipped and "
                   "the scale halves instead of exiting 88, regrowing "
                   "2x after loss_scale_window clean steps); > 0 = that "
                   "static scale (overrides solver loss_scale; -1 = "
                   "prototxt value, which defaults to dynamic). "
                   "Consumed only under -precision bf16")
    p.add_argument("-loss_scale_window", "--loss-scale-window",
                   dest="loss_scale_window", type=int, default=0,
                   help="clean steps before the dynamic loss scale "
                   "grows 2x (overrides solver loss_scale_window; 0 = "
                   "prototxt value, which defaults to 200)")
    # ingestion flags (ISSUE 10, docs/benchmarks.md "Ingestion")
    p.add_argument("-decoded_cache_mb", "--decoded-cache-mb",
                   dest="decoded_cache_mb", type=float, default=0.0,
                   help="train: RAM budget (MiB) for the bounded "
                   "decoded-record cache — post-decode pre-augment "
                   "uint8 records kept across epochs so the cached "
                   "span skips DB read + crc + JPEG/PNG decode after "
                   "epoch 1 (overrides solver decoded_cache_mb; 0 = "
                   "prototxt value, default off). The companion env "
                   "CAFFE_NATIVE_DECODE=0/1 forces the PIL/native "
                   "decoder for A/B runs")
    # survivable-training flags (ISSUE 3, utils/resilience.py)
    p.add_argument("-resume", "--resume", default="",
                   help="'auto' = resume from the newest VERIFIED "
                   "snapshot under the solver's snapshot_prefix (crc32c "
                   "manifest scan + run-manifest journal; corrupt "
                   "snapshots fall back to the newest prior verified "
                   "one; no snapshot = fresh start). A path behaves "
                   "like -snapshot")
    p.add_argument("-max_restarts", "--max-restarts", dest="max_restarts",
                   type=int, default=0,
                   help="supervised training: run the train loop in a "
                   "contained child process and restart it (with "
                   "--resume auto, exponential backoff) up to N times "
                   "on failure — including watchdog hard-exits. 0 "
                   "(default) = unsupervised, today's behavior")
    p.add_argument("-watchdog_deadline", "--watchdog-deadline",
                   dest="watchdog_deadline", type=float, default=0.0,
                   help="arm the dispatch watchdog: journal run state "
                   "and hard-exit (code 86) when any device dispatch/"
                   "harvest blocks longer than this many seconds "
                   "(overrides solver watchdog_deadline; 0 = prototxt "
                   "value, which defaults to off). Must exceed the "
                   "worst jit-compile time")
    p.add_argument("-snapshot_prefix", "--snapshot-prefix",
                   dest="snapshot_prefix", default="",
                   help="override solver snapshot_prefix")
    p.add_argument("-snapshot_every", "--snapshot-every",
                   dest="snapshot_every", type=int, default=0,
                   help="override solver snapshot interval "
                   "(0 = prototxt value)")
    p.add_argument("-snapshot_keep", "--snapshot-keep",
                   dest="snapshot_keep", type=int, default=0,
                   help="keep only the newest N snapshots, GC'ing older "
                   "ones after each write — never the newest verified "
                   "one (overrides solver snapshot_keep; 0 = prototxt "
                   "value, which defaults to keep-everything)")
    # elastic multi-host flags (ISSUE 11, docs/robustness.md
    # "Multi-host elasticity")
    p.add_argument("-hosts", "--hosts", type=int, default=0,
                   help="train: number of host processes in the "
                   "cluster (the reference's mpirun -n). > 1 "
                   "initializes jax.distributed against -coordinator "
                   "(bounded retry/backoff; a missing coordinator "
                   "journals and exits 87, never hangs), spans the "
                   "device mesh across every host, and stripes Feeder "
                   "records per host (overrides solver hosts; 0 = "
                   "prototxt value, default single-process). Env "
                   "fallbacks: CAFFE_TPU_NUM_HOSTS / "
                   "CAFFE_TPU_COORDINATOR / CAFFE_TPU_HOST_ID")
    p.add_argument("-coordinator", "--coordinator", default="",
                   help="train: host:port of host 0's coordination "
                   "service (required with -hosts > 1; overrides "
                   "solver coordinator)")
    p.add_argument("-host_id", "--host-id", dest="host_id", type=int,
                   default=-1,
                   help="train: this process's host index in "
                   "[0, hosts) (-1 = CAFFE_TPU_HOST_ID env)")
    p.add_argument("-host_deadline", "--host-deadline",
                   dest="host_deadline", type=float, default=0.0,
                   help="train: cross-host heartbeat deadline in "
                   "seconds — a peer host silent this long is "
                   "journaled to <prefix>.run.json and this worker "
                   "exits 87 (EXIT_CLUSTER) for the supervisor's "
                   "coordinated restart, instead of hanging inside "
                   "the next collective (overrides solver "
                   "host_deadline; 0 = prototxt value, default off)")
    p.add_argument("-min_hosts", "--min-hosts", dest="min_hosts",
                   type=int, default=0,
                   help="train: degraded-mode quorum floor (ISSUE 19, "
                   "needs -hosts > 1 and -max_restarts). After a "
                   "PERMANENT host loss the surviving supervisors run "
                   "the generation protocol: the lowest survivor "
                   "publishes a remapped generation with world W' >= "
                   "min_hosts and training continues at W' from the "
                   "last verified snapshot; a revived host parks and "
                   "is re-admitted at the next snapshot boundary "
                   "(overrides solver min_hosts; 0 = prototxt value, "
                   "default off = today's restart-all semantics)")
    # self-healing flags (ISSUE 4, docs/robustness.md)
    p.add_argument("-train_guard", "--train-guard", dest="train_guard",
                   action="store_true",
                   help="arm the on-device non-finite guard: a NaN/Inf "
                   "loss or gradient skips the optimizer update for "
                   "that step (params/momentum/BN unchanged) instead "
                   "of poisoning the weights; guard_max_skips "
                   "consecutive skips journals the anomaly and exits "
                   "88 for the supervisor to rewind (enables solver "
                   "train_guard; off by default = bitwise today)")
    p.add_argument("-guard_max_skips", "--guard-max-skips",
                   dest="guard_max_skips", type=int, default=-1,
                   help="consecutive skipped steps before exit 88; "
                   "0 = never exit, skip forever (overrides solver "
                   "guard_max_skips; -1 = prototxt value, which "
                   "defaults to 3)")
    p.add_argument("-anomaly_action", "--anomaly-action",
                   dest="anomaly_action", default="",
                   choices=["", "rewind", "rewind_lr", "abort"],
                   help="supervisor policy on exit 88: rewind to the "
                   "newest verified snapshot (default), rewind_lr = "
                   "rewind with base_lr scaled by anomaly_lr_mult per "
                   "numeric restart, abort = no restart (overrides "
                   "solver anomaly_action)")
    p.add_argument("-lr_scale", "--lr-scale", dest="lr_scale",
                   type=float, default=1.0,
                   help="multiply the solver's base_lr (set by the "
                   "supervisor on rewind_lr restarts; compounded per "
                   "numeric restart)")
    # inference-serving flags (ISSUE 7, caffe_mpi_tpu/serving/)
    p.add_argument("-port", "--port", type=int, default=5000,
                   help="serve: HTTP port (0 picks an ephemeral port)")
    p.add_argument("-labels", "--labels", default="",
                   help="serve: class-label file, one label per line")
    p.add_argument("-image_root", "--image-root", dest="image_root",
                   default="",
                   help="serve: allow GET /classify_path under this "
                   "directory")
    p.add_argument("-serve_window_ms", "--serve-window-ms",
                   dest="serve_window_ms", type=float, default=-1.0,
                   help="serve: continuous-batching window in ms — a "
                   "batch dispatches when this long has passed since "
                   "its first request, or earlier when a full max "
                   "bucket is waiting (overrides ServingParameter "
                   "serve_window_ms; -1 = schema default 5 ms; 0 = "
                   "dispatch immediately)")
    p.add_argument("-serve_buckets", "--serve-buckets",
                   dest="serve_buckets", default="",
                   help="serve: explicit padded-batch bucket ladder, "
                   "comma-separated (e.g. '1,4,16') — every bucket is "
                   "AOT-compiled at model load so arrival-size "
                   "variance never recompiles (overrides "
                   "ServingParameter serve_buckets; default geometric "
                   "1,4,16,... up to the deploy batch)")
    p.add_argument("-serve_hbm_mb", "--serve-hbm-mb",
                   dest="serve_hbm_mb", type=float, default=-1.0,
                   help="serve: HBM budget (MiB) for device-resident "
                   "model weights; the least-recently-used model "
                   "spills to its host master copy when exceeded "
                   "(overrides ServingParameter serve_hbm_mb; -1 = "
                   "schema default 0 = unlimited)")
    p.add_argument("-serve_dtype", "--serve-dtype", dest="serve_dtype",
                   default="", choices=["", "f32", "bf16"],
                   help="serve: bucket-program compute precision "
                   "(overrides ServingParameter serve_dtype; '' = "
                   "schema default f32). bf16 runs every bucket forward "
                   "in bfloat16 and casts scores back to f32 — the "
                   "ladder still AOT-compiles once per bucket, zero "
                   "steady-state compiles either way")
    p.add_argument("-smoke", "--smoke", type=int, default=0,
                   help="serve: self-test — serve N synthetic requests "
                   "of mixed sizes over real HTTP, print the telemetry "
                   "JSON (p50/p99/img_s/compile_count), assert zero "
                   "post-warmup compiles, and exit")
    # serving resilience flags (ISSUE 12, docs/serving.md 'Resilience')
    p.add_argument("-serve_queue_limit", "--serve-queue-limit",
                   dest="serve_queue_limit", type=int, default=-1,
                   help="serve: load-shedding admission control — a "
                   "submit arriving with this many requests already "
                   "backlogged fails fast with HTTP 429 instead of "
                   "queueing unboundedly (overrides ServingParameter "
                   "serve_queue_limit; -1 = schema default 0 = "
                   "unbounded)")
    p.add_argument("-serve_deadline_ms", "--serve-deadline-ms",
                   dest="serve_deadline_ms", type=float, default=-1.0,
                   help="serve: per-request dispatch deadline — a "
                   "request whose batch cannot dispatch this soon "
                   "after arrival fails with HTTP 504 at window close "
                   "(overrides ServingParameter serve_deadline_ms; "
                   "-1 = schema default 0 = no deadline)")
    p.add_argument("-serve_stall_s", "--serve-stall-s",
                   dest="serve_stall_s", type=float, default=-1.0,
                   help="serve: dispatch stall breaker — a device call "
                   "blocked this many seconds (dead tunnel) fails the "
                   "in-flight futures, journals, flips /healthz to 503 "
                   "and sheds new requests until a recovery probe "
                   "succeeds (overrides ServingParameter serve_stall_s; "
                   "-1 = schema default 0 = breaker off)")
    p.add_argument("-require_native_ingest", "--require-native-ingest",
                   dest="require_native_ingest", action="store_true",
                   help="serve -smoke: fail unless the HTTP leg's "
                   "requests actually decoded natively and preprocessed "
                   "through the window-fused plane (tpu_validation's "
                   "serve stage — a silent PIL fallback on hardware "
                   "would invalidate the serving ingest numbers)")
    p.add_argument("-serve_decoded_cache_mb", "--serve-decoded-cache-mb",
                   dest="serve_decoded_cache_mb", type=float, default=-1.0,
                   help="serve: hot-content decoded-request cache budget "
                   "in MiB — decoded uploads are kept in RAM keyed by "
                   "the crc32c of their encoded bytes (LRU), so repeated "
                   "hot images skip JPEG/PNG decode entirely (overrides "
                   "ServingParameter serve_decoded_cache_mb; -1 = schema "
                   "default 0 = cache off)")
    p.add_argument("-serve_program_bank", "--serve-program-bank",
                   dest="serve_program_bank", default="",
                   help="serve: persistent AOT program bank directory "
                   "(ISSUE 17) — each warmed bucket executable is "
                   "serialized there under a verified-atomic crc32c "
                   "manifest, and a bank-warm restart deserializes the "
                   "whole ladder with ZERO compiles (compile_count == "
                   "bank_misses; torn/stale entries recompile and "
                   "repopulate). Overrides ServingParameter "
                   "serve_program_bank; '' = schema default = bank off")
    p.add_argument("-require_bank_warm", "--require-bank-warm",
                   dest="require_bank_warm", action="store_true",
                   help="serve -smoke: fail unless the whole ladder "
                   "loaded from the program bank with zero compiles "
                   "(tpu_validation's serve-bank stage — a silent "
                   "recompile on hardware would invalidate the "
                   "zero-compile cold-start claim)")
    # serving-fleet flags (ISSUE 18, docs/serving.md 'Fleet')
    p.add_argument("-replicas", "--replicas", dest="serve_replicas",
                   type=int, default=-1,
                   help="serve: run N ServingEngine replica PROCESSES "
                   "behind a least-loaded typed-retry router with "
                   "heartbeat replica supervision and rolling -watch "
                   "swaps (sets ServingParameter serve_replicas; -1 = "
                   "schema default 0 = classic single-process serving)")
    p.add_argument("-serve_retry_budget", "--serve-retry-budget",
                   dest="serve_retry_budget", type=int, default=-1,
                   help="serve -replicas: how many sibling replicas a "
                   "typed-retryable failure (429 shed, 503 unhealthy, "
                   "dead-replica connection error) is retried on before "
                   "going typed to the client; 504/400 never retry "
                   "(overrides ServingParameter serve_retry_budget; "
                   "-1 = schema default 1)")
    p.add_argument("-replica_deadline", "--replica-deadline",
                   dest="replica_deadline", type=float, default=-1.0,
                   help="serve -replicas: replica heartbeat deadline in "
                   "seconds — one silent this long is drained from "
                   "rotation, journaled replica_dead, respawned "
                   "bank-warm, and re-admitted after its readyz gate "
                   "(overrides ServingParameter replica_deadline; -1 = "
                   "schema default 5 s)")
    p.add_argument("-fleet_dir", "--fleet-dir", dest="fleet_dir",
                   default="",
                   help="serve -replicas: fleet state directory "
                   "(heartbeats, staged swap weights, shared program "
                   "bank, replica logs, run journal); default "
                   "<model>_fleet. Also marks a spawned replica's own "
                   "process together with -replica_id (internal)")
    p.add_argument("-replica_id", "--replica-id", dest="replica_id",
                   type=int, default=-1,
                   help="internal: this process IS fleet replica K — "
                   "publish heartbeats under -fleet_dir and mount the "
                   "admin POST /swap route (set by FleetSupervisor, "
                   "not by operators)")
    p.add_argument("-watch", "--watch", dest="serve_watch", default="",
                   help="serve: snapshot prefix to tail for verified "
                   "hot-swaps — each newly crc32c-verified snapshot is "
                   "canary-gated and live-reloaded into the serving "
                   "model with zero recompiles; rejects (corrupt bytes, "
                   "non-finite canary) are journaled and the previous "
                   "weights keep serving")
    return p


def _select_mesh(gpu_flag: str, mesh_flag: str = ""):
    """-gpu all => data-parallel mesh over every device (the reference
    spawns one P2PSync per GPU; here one SPMD program).
    -mesh data=N,model=M => explicit 2D mesh: batch sharded over 'data',
    layers with `param_sharding` prototxt rules tensor-parallel over
    'model' — the one-command analogue of the reference's
    `mpirun -n N caffe train` line (README.md:40), generalized beyond DP."""
    from ..parallel import MeshPlan
    if mesh_flag:
        shape = {"data": 1, "model": 1}
        for kv in mesh_flag.split(","):
            k, _, v = kv.partition("=")
            k = k.strip()
            if k not in shape or not v.strip().isdigit():
                raise SystemExit(
                    f"bad -mesh entry {kv!r}: expected data=N[,model=M]")
            shape[k] = int(v)
        return MeshPlan.from_shape(shape["data"], shape["model"])
    if gpu_flag == "all":
        return MeshPlan.data_parallel()
    return None


def _synthetic_feed(net, seed=0):
    """Random feeds shaped from the net's Input layers (the reference's
    `caffe time` uses dummy data the same way). Integer feeds are chosen
    by CONSUMER, not by blob name: a blob eaten by Embed gets token ids in
    [0, input_dim); the target bottom of a classification loss/accuracy
    gets class ids."""
    import jax.numpy as jnp
    from ..utils.model_shapes import _CLASSIFICATION_CONSUMERS
    r = np.random.RandomState(seed)
    int_range: dict[str, int] = {}
    for layer in net.layers:
        lp = layer.lp
        if lp.type == "Embed" and lp.bottom:
            int_range[lp.bottom[0]] = lp.embed_param.input_dim
        elif lp.type in _CLASSIFICATION_CONSUMERS and len(lp.bottom) > 1:
            # one consumer table shared with utils.model_shapes.label_tops
            # so the two integer-feed detectors cannot drift
            int_range.setdefault(lp.bottom[1], 10)
    feeds = {}
    for key, (shape, kind) in net.feed_specs.items():
        if kind == "uint8":
            feeds[key] = jnp.asarray(
                r.randint(0, 256, shape).astype(np.uint8))
        elif kind == "aug":
            # zeros = top-left crop, no mirror — always valid offsets
            feeds[key] = jnp.zeros(shape, jnp.int32)
        elif key in int_range or kind == "int":
            feeds[key] = jnp.asarray(
                r.randint(0, max(int_range.get(key, 10), 1), shape))
        else:
            feeds[key] = jnp.asarray(r.randn(*shape).astype(np.float32))
    return feeds


def _build_feeders(net, phase, rank=0, world=1, model_dir="",
                   solver_param=None):
    """Create a Feeder per DB-backed data layer, or None for Input nets.
    solver_param supplies run-level ingestion knobs (decoded_cache_mb)."""
    from ..data import feeder_from_layer
    from ..data.feeder import HDF5Feeder
    model_dir = model_dir or getattr(net, "model_dir", "")
    for layer in net.layers:
        if layer.lp.type in ("Data", "ImageData"):
            return feeder_from_layer(
                layer.lp, phase, rank=rank, world=world, model_dir=model_dir,
                device_transform=getattr(layer, "dev_transform", False),
                solver_param=solver_param)
        if layer.lp.type == "HDF5Data":
            return HDF5Feeder(layer.lp, rank=rank, world=world,
                              model_dir=model_dir)
        if layer.lp.type == "WindowData":
            from ..data.window import WindowFeeder
            return WindowFeeder(layer.lp, phase, model_dir=model_dir,
                                rank=rank, world=world)
    return None


def _strip_flags(argv: list[str], flags: tuple[str, ...],
                 with_value: bool = True) -> list[str]:
    """Remove `flags` (and their values / `=`-joined spellings) from a
    child argv — the supervisor rewrites these per attempt/generation."""
    out, skip = [], False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok in flags:
            skip = with_value
            continue
        if tok.startswith(tuple(f + "=" for f in flags)):
            continue
        out.append(tok)
    return out


def _supervised_train(args) -> int:
    """Supervisor half of `train --max-restarts N`: run the actual
    training loop in a contained child process (own process group,
    killpg'd on every supervisor exit path) and restart it from the
    newest verified snapshot with exponential backoff when it dies —
    watchdog hard-exits (code 86) included. The crash-loop guard stops
    after N restarts with the per-attempt record in
    `<snapshot_prefix>.failures.log`.

    With `min_hosts` set on a multi-host run (ISSUE 19) the supervisor
    is the ELASTIC one (resilience.supervise_elastic): child failures
    run the generation protocol over the shared `<prefix>.cluster/`
    directory, and each generation's child argv is rewritten to the
    remapped `-hosts W' -host_id k' -coordinator <epoch>`."""
    import os
    from ..proto import SolverParameter
    from ..utils import resilience

    argv = list(getattr(args, "_argv", None) or sys.argv[1:])
    # strip the supervision flag from the child's argv (the env marker
    # below is the belt-and-braces recursion stop)
    child_argv = _strip_flags(
        argv, ("-max_restarts", "--max-restarts", "--max_restarts"))
    sp = SolverParameter.from_file(args.solver)
    prefix = args.snapshot_prefix or sp.snapshot_prefix or "snapshot"
    env = dict(os.environ, CAFFE_SUPERVISED_CHILD="1")
    anomaly_action = (args.anomaly_action or sp.anomaly_action
                      or "rewind")

    # degraded-mode elasticity (ISSUE 19): the generation protocol
    # engages only when the operator set the quorum floor on a real
    # multi-host launch — anything else is the classic supervisor,
    # bitwise
    min_hosts = args.min_hosts or getattr(sp, "min_hosts", 0)
    world = args.hosts or sp.hosts \
        or int(os.environ.get("CAFFE_TPU_NUM_HOSTS", "0") or 0)
    host_id = args.host_id if args.host_id >= 0 \
        else int(os.environ.get("CAFFE_TPU_HOST_ID", "-1") or -1)
    coordinator = args.coordinator or sp.coordinator \
        or os.environ.get("CAFFE_TPU_COORDINATOR", "")
    if min_hosts > 0 and world > 1 and host_id >= 0:
        host_deadline = args.host_deadline or sp.host_deadline or 5.0
        # the address peers reach THIS host at (the publisher of a new
        # generation hosts the next coordination-service epoch):
        # CAFFE_TPU_HOST_ADDR when the operator set it, else the
        # original coordinator's host part (exact for host 0 and for
        # single-machine smokes; multi-machine operators set the env)
        coord_host = os.environ.get("CAFFE_TPU_HOST_ADDR", "") or (
            coordinator.rsplit(":", 1)[0] if ":" in coordinator
            else "127.0.0.1")
        cluster_flags = ("-hosts", "--hosts", "-host_id", "--host-id",
                         "--host_id", "-coordinator", "--coordinator",
                         "-resume", "--resume")
        stable_argv = _strip_flags(child_argv, cluster_flags)

        def build_cmd(gen: dict, rank: int, resume: bool) -> list[str]:
            cmd = [sys.executable, "-m", "caffe_mpi_tpu.tools.cli"] \
                + stable_argv + ["-hosts", str(gen["world"]),
                                 "-host_id", str(rank)]
            if gen["world"] > 1:
                cmd += ["-coordinator", gen["coordinator"]]
            if resume:
                cmd += ["-resume", "auto"]
            return cmd

        journal = prefix if host_id == 0 else f"{prefix}.r{host_id}"
        return resilience.supervise_elastic(
            build_cmd, prefix=prefix, host_id=host_id,
            world_full=world, min_hosts=min_hosts,
            host_deadline=host_deadline, coordinator_host=coord_host,
            coordinator=coordinator, max_restarts=args.max_restarts,
            failure_log=journal + ".failures.log", env=env,
            anomaly_action=anomaly_action,
            anomaly_lr_mult=sp.anomaly_lr_mult)

    base_cmd = [sys.executable, "-m", "caffe_mpi_tpu.tools.cli"] + child_argv
    resume_cmd = base_cmd
    if not any(t in ("-resume", "--resume") or
               t.startswith(("-resume=", "--resume="))
               for t in child_argv):
        resume_cmd = base_cmd + ["-resume", "auto"]
    # fast-fail doomed formation (ISSUE 19): point the supervisor at
    # this host's cluster journal so repeated cluster_init_failed
    # records stop the restart loop early (single-host journals never
    # record that reason, so the param is inert there)
    journal = prefix if host_id <= 0 else f"{prefix}.r{host_id}"
    return resilience.supervise(
        base_cmd, resume_cmd, args.max_restarts,
        failure_log=prefix + ".failures.log", env=env,
        anomaly_action=anomaly_action,
        anomaly_lr_mult=sp.anomaly_lr_mult,
        journal_prefix=journal)


def _cluster_exit(prefix: str, rank: int, reason: str, error: str) -> int:
    """Journal a bounded cluster failure (ISSUE 11) and hand exit 87 to
    the supervisor. Rank 0 owns `<prefix>.run.json`; other ranks write
    their own `.r<k>` journal (same convention as the solver)."""
    from ..utils import resilience
    log.error("%s: %s; exiting %d for the supervisor's coordinated "
              "restart", reason, error, resilience.EXIT_CLUSTER)
    try:
        resilience.write_run_manifest(
            prefix if rank <= 0 else f"{prefix}.r{rank}",
            reason=reason, error=error,
            exit_code=resilience.EXIT_CLUSTER)
    except OSError:
        log.exception("cluster-failure journal failed (continuing)")
    return resilience.EXIT_CLUSTER


def cmd_train(args) -> int:
    from ..proto import SolverParameter
    from ..solver import Solver
    from ..utils import resilience
    if not args.solver:
        log.error("train requires -solver")
        return 1
    import os
    if args.max_restarts > 0 \
            and os.environ.get("CAFFE_SUPERVISED_CHILD") != "1":
        return _supervised_train(args)
    from ..data.feeder import data_shape_probe
    sp = SolverParameter.from_file(args.solver)
    if args.max_iter:
        sp.max_iter = args.max_iter
    if args.test_iter:
        sp.test_iter = [args.test_iter] * max(len(sp.test_iter), 1)
    if args.step_chunk:
        sp.step_chunk = args.step_chunk
    if args.test_chunk:
        sp.test_chunk = args.test_chunk
    if args.snapshot_prefix:
        sp.snapshot_prefix = args.snapshot_prefix
    if args.snapshot_every:
        sp.snapshot = args.snapshot_every
    if args.snapshot_keep:
        sp.snapshot_keep = args.snapshot_keep
    if args.watchdog_deadline:
        sp.watchdog_deadline = args.watchdog_deadline
    if args.reduce_overlap:
        sp.reduce_overlap = True
    # a CLI sizing mode overrides the prototxt's OTHER mode too (a
    # recipe with `reduce_buckets: 4` can be re-run under a CLI byte
    # budget without editing it); both CLI flags at once still reach
    # the solver's "not both" validation and fail loudly
    if args.reduce_buckets:
        sp.reduce_buckets = args.reduce_buckets
        if not args.grad_bucket_mb:
            sp.clear("grad_bucket_mb")
    if args.grad_bucket_mb:
        sp.grad_bucket_mb = args.grad_bucket_mb
        if not args.reduce_buckets:
            sp.clear("reduce_buckets")
    if getattr(sp, "reduce_overlap", False):
        # libtpu scheduling flags for collective/compute overlap —
        # LIBTPU_INIT_ARGS is read only by libtpu, so this is a no-op
        # on CPU runs; must land before the first jax computation
        # initializes the backend (reduction.tpu_overlap_flags)
        from ..parallel import reduction
        if reduction.apply_tpu_overlap_flags(os.environ):
            log.info("TPU overlap flags appended to LIBTPU_INIT_ARGS: %s",
                     " ".join(reduction.tpu_overlap_flags()))
    if args.decoded_cache_mb:
        sp.decoded_cache_mb = args.decoded_cache_mb
    if args.precision:
        sp.precision = args.precision
    if args.loss_scale >= 0:
        # 0 is meaningful (dynamic scaling); -1 = prototxt
        sp.loss_scale = args.loss_scale
    if args.loss_scale_window:
        sp.loss_scale_window = args.loss_scale_window
    if args.train_guard:
        sp.train_guard = True
    if args.guard_max_skips >= 0:
        # 0 is meaningful (never exit — skip forever); -1 = prototxt
        sp.guard_max_skips = args.guard_max_skips
    if args.lr_scale != 1.0:
        # rewind_lr restart: the supervisor scales the recipe's LR so
        # the replay does not step straight back into the divergence
        sp.base_lr = sp.base_lr * args.lr_scale
        log.info("base_lr scaled by %g -> %g (anomaly rewind)",
                 args.lr_scale, sp.base_lr)
    if args.hosts:
        sp.hosts = args.hosts
    if args.coordinator:
        sp.coordinator = args.coordinator
    if args.host_deadline:
        sp.host_deadline = args.host_deadline
    if args.min_hosts:
        sp.min_hosts = args.min_hosts

    # elastic multi-host bootstrap (ISSUE 11): form the jax.distributed
    # cluster BEFORE any jax device use, so the mesh below spans every
    # host. Cluster-formation failure is a bounded, journaled exit 87 —
    # the supervisor's coordinated restart re-forms the cluster.
    from ..parallel import mesh as mesh_mod
    journal_prefix = args.snapshot_prefix or sp.snapshot_prefix \
        or "snapshot"
    world, host_rank = 1, 0
    try:
        world, coordinator, host_rank = mesh_mod.resolve_cluster(
            sp, host_id=args.host_id)
        if world > 1:
            mesh_mod.init_distributed(coordinator, world, host_rank)
            if host_rank == 0:
                # degraded-mode elasticity (ISSUE 19): mirror the
                # generation record the elastic supervisor handed us
                # onto the KV store for in-band observability; no-op
                # outside a min_hosts run
                mesh_mod.publish_generation()
    except resilience.ClusterError as e:
        return _cluster_exit(journal_prefix, max(host_rank, 0),
                             "cluster_init_failed", str(e))
    model_dir = os.path.dirname(os.path.abspath(args.solver)) \
        if not (sp.net and os.path.exists(sp.net)) else ""
    gpipe_cfg = None
    if args.gpipe:
        # pipeline training from the train entrypoint, the way the
        # reference launches ITS parallelism (tools/caffe.cpp:223-225)
        if args.gpu or args.mesh:
            raise SystemExit("-gpipe is exclusive of -gpu/-mesh "
                             "(stages own whole devices)")
        gpipe_cfg = {"stages": args.gpipe, "micro": args.gpipe_micro}
    cluster_rank = 0
    if world > 1:
        import jax as _jax
        cluster_rank = _jax.process_index()
    solver = Solver(sp, mesh=_select_mesh(args.gpu, args.mesh),
                    model_dir=model_dir, gpipe=gpipe_cfg,
                    rank=cluster_rank,
                    data_shape_probe=lambda lp: data_shape_probe(lp, model_dir))
    if args.resume and args.resume != "auto":
        # a concrete path behaves like -snapshot
        args.snapshot = args.snapshot or args.resume
    resumed = None
    if args.resume == "auto":
        # newest verified snapshot (crc32c manifest scan); falls back
        # across corrupt snapshots; None = fresh start. The explicit
        # -snapshot/-weights flags only apply when auto found nothing.
        # Cluster runs must agree on ONE resume point (divergent picks
        # would deadlock the first collective): rank 0 scans and
        # publishes its decision on the coordination service; peers
        # restore exactly that snapshot.
        if world > 1 and cluster_rank > 0:
            # rank 0 crc-verifies (and may fall back across) whole
            # checkpoints before publishing — the wait must scale with
            # checkpoint size, not a fixed constant (env-tunable for
            # huge sharded sets); a dead service still returns fast
            peer = mesh_mod.cluster_kv_get(
                "caffe/resume_state",
                timeout_s=float(os.environ.get(
                    "CAFFE_TPU_RESUME_TIMEOUT", "600") or 600))
            if peer is None:
                return _cluster_exit(
                    journal_prefix, cluster_rank, "cluster_resume_failed",
                    "rank 0 never published its resume decision")
            if peer:
                try:
                    solver.restore(peer)
                except (resilience.SnapshotCorruptError, OSError) as e:
                    # shards not yet visible on this host (NFS lag) or
                    # local bitrot: a journaled 87 lets the supervisor
                    # retry the coordinated resume instead of an
                    # unjournaled crash with a generic exit code
                    return _cluster_exit(
                        journal_prefix, cluster_rank,
                        "cluster_resume_failed",
                        f"rank 0's snapshot {peer} failed to load "
                        f"here: {e}")
                resumed = peer
        else:
            resumed = solver.restore_auto()
            if world > 1 and not mesh_mod.cluster_kv_set(
                    "caffe/resume_state", resumed or ""):
                # peers are blocked waiting for this key; training on
                # alone would end in an unbounded first-collective hang
                # after they give up — the exact hang class ISSUE 11
                # exists to bound
                return _cluster_exit(
                    journal_prefix, cluster_rank, "cluster_resume_failed",
                    "could not publish the resume decision (dead "
                    "coordination service?)")
    if resumed is None:
        if args.snapshot:
            try:
                solver.restore(args.snapshot)
            except resilience.SnapshotCorruptError as e:
                if world > 1:
                    # a PER-HOST fallback scan could land ranks on
                    # divergent iterations and deadlock the first
                    # collective — journal + 87 so the supervisor
                    # retries the coordinated resume instead
                    return _cluster_exit(
                        journal_prefix, cluster_rank,
                        "cluster_resume_failed",
                        f"-snapshot {args.snapshot} corrupt on this "
                        f"host: {e}")
                log.warning("%s", e)
                resumed = solver.restore_auto()
                if resumed is None:
                    raise
                log.warning("resumed from %s instead of the corrupt %s",
                            resumed, args.snapshot)
        elif args.weights:
            for w in args.weights.split(","):
                solver.load_weights(w)

    # signal plumbing (reference SignalHandler, tools/caffe.cpp:209-211):
    # handlers only set flags; actions run at the iteration boundary —
    # snapshotting from inside the handler would race the jitted step's
    # donated buffers
    state = {"stop": False, "snap": False}

    def on_signal(effect):
        def handler(sig, frame):
            if effect == "snapshot":
                state["snap"] = True
            elif effect == "stop":
                state["stop"] = True
                log.info("signal: stopping after this iteration")
        return handler

    signal.signal(signal.SIGINT, on_signal(args.sigint_effect))
    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, on_signal(args.sighup_effect))

    # multi-host: each process reads its stripe of the global batch
    # (reference CursorManager record striping, data_reader.hpp:28-53)
    import jax as _jax
    feeder = _build_feeders(solver.net, "TRAIN",
                            rank=_jax.process_index(),
                            world=_jax.process_count(),
                            solver_param=sp)
    if feeder is None:
        if not args.synthetic:
            log.error("net has no Data layer; pass -synthetic to train on "
                      "random data or use a Data/ImageData net")
            return 1
        feeds = _synthetic_feed(solver.net)
        feed_fn = lambda it: feeds
    else:
        feed_fn = feeder

    test_feed_fns = None
    if solver.test_nets:
        tf = []
        for tnet in solver.test_nets:
            # TEST feeders stripe per host exactly like TRAIN: the
            # eval path assembles each host's batch as a process-local
            # SHARD of the global test batch (shard_feeds), so
            # unstriped feeders would evaluate duplicate copies of
            # stripe 0 and never see the other hosts' records
            f = _build_feeders(tnet, "TEST",
                               rank=_jax.process_index(),
                               world=_jax.process_count(),
                               solver_param=sp)
            if f is None:
                feeds_t = _synthetic_feed(tnet, seed=1)
                tf.append(lambda it, feeds_t=feeds_t: feeds_t)
            else:
                tf.append(f)
        test_feed_fns = tf

    # bind the quarantine journal next to the snapshots: corrupt
    # records the feeder substitutes during this run are audited in
    # <prefix>.quarantine.json (ISSUE 4; appends across supervised
    # restarts). Multi-host runs journal per host (.r<k>, ISSUE 11);
    # rank 0 merges them at snapshot time.
    # Across degraded-mode generations (ISSUE 19) a host's RANK moves
    # (remapped contiguous over the survivors) but its identity does
    # not: key the journal on the stable original host id the elastic
    # supervisor publishes, so quarantine attribution survives remaps.
    _stable_host = os.environ.get("CAFFE_TPU_CLUSTER_SELF")
    resilience.QUARANTINE.configure(resilience.quarantine_journal_path(
        sp.snapshot_prefix or "snapshot", rank=cluster_rank,
        world=world,
        host=int(_stable_host) if _stable_host else None))

    t0 = time.time()
    start_iter = solver.iter
    try:
        while solver.iter < sp.max_iter and not state["stop"]:
            chunk = min(100, sp.max_iter - solver.iter)
            solver.step(chunk, feed_fn, test_feed_fns)
            if state["snap"]:
                state["snap"] = False
                solver.snapshot()
        if not state["stop"] and test_feed_fns and sp.test_interval:
            # final evaluation after the last iteration. Deliberate
            # deviation: the reference only runs its trailing TestAll when
            # iter %% test_interval == 0 (solver.cpp:431); here it runs
            # unconditionally so every completed run reports final scores
            # — the examples parse this line to self-assert accuracy.
            solver.test_all(test_feed_fns)
        if (state["stop"] and args.sigint_effect == "stop") or (
                not state["stop"] and sp.snapshot_prefix
                and solver.should_snapshot_after_train()):
            solver.snapshot()  # reference snapshots at stop/after-train
            # (solver.cpp:402-407)
        if world > 1:
            # end-of-training barrier (ISSUE 11): hosts finish at
            # skewed times; rank 0's coordination service must not die
            # underneath a peer still mid-collective/KV-call. The
            # heartbeat keeps ticking while we wait here, so a peer
            # that CRASHED instead of arriving still becomes a bounded
            # exit-87 within host_deadline.
            if not mesh_mod.cluster_barrier("caffe_train_done"):
                return _cluster_exit(
                    journal_prefix, cluster_rank, "cluster_exit_failed",
                    "end-of-training barrier timed out (peer host "
                    "lost after training?)")
            # only NOW is departure clean — a farewell on a failure
            # path would stop peers monitoring a crashed host
            solver.heartbeat_farewell()
    except resilience.ClusterError as e:
        # a cluster operation inside training (sharded-snapshot write
        # barrier) failed in a bounded way — journal + 87, supervisor
        # restarts the whole cluster. The rejoin trigger (ISSUE 19)
        # rides the same exit with reason "cluster_rejoin" so the
        # elastic supervisor publishes the grow-back generation.
        return _cluster_exit(journal_prefix, cluster_rank,
                             getattr(e, "journal_reason", "cluster_lost"),
                             str(e))
    except resilience.NumericAnomalyError as e:
        # the solver already journaled the anomaly to <prefix>.run.json;
        # exit 88 routes the supervisor through anomaly_action
        # (rewind | rewind_lr | abort) instead of a plain crash restart
        log.error("%s; exiting %d for the supervisor to rewind", e,
                  resilience.EXIT_NUMERIC)
        return resilience.EXIT_NUMERIC
    finally:
        # async interval writes must land even when training raises —
        # a half-written checkpoint is worse than a slow exit — and the
        # fused-mode feed queue's worker thread must not outlive the run
        solver.close()
        # drain any debounced quarantine-journal tail: the audit must
        # be complete on every exit path
        resilience.QUARANTINE.flush()
    if world > 1:
        # past the exit barrier on every host: safe to drop the service
        mesh_mod.shutdown_distributed()
    elapsed = time.time() - t0
    imgs = (solver.iter - start_iter) * solver._batch_images() \
        * max(sp.iter_size, 1) * max(solver._gpipe_micro, 1)
    log.info("Optimization done: %d iters, %.1f s, %.1f img/s overall",
             solver.iter, elapsed, imgs / max(elapsed, 1e-9))
    return 0


def cmd_test(args) -> int:
    import jax
    from ..net import Net
    from ..proto import NetParameter
    from .. import io as caffe_io
    import os
    if not args.model:
        log.error("test requires -model")
        return 1
    net = Net(NetParameter.from_file(args.model), phase="TEST",
              model_dir=os.path.dirname(os.path.abspath(args.model)))
    params, state = net.init(jax.random.PRNGKey(0))
    if args.weights:
        params, state = net.import_weights(params, state,
                                           caffe_io.load_weights(args.weights))
    feeder = _build_feeders(net, "TEST")
    import jax.numpy as jnp
    fwd = jax.jit(lambda p, s, f: net.apply(p, s, f, train=False)[0])
    consumed = {b for l in net.layers for b in l.lp.bottom}
    outputs = [t for l in net.layers for t in l.lp.top if t not in consumed]
    # per-batch score means stay ON DEVICE across the loop (tpulint
    # host-sync: a float() here would pay one tunnel RTT per iteration
    # per blob); the harvest happens after the last batch, and the
    # average itself is summed in float64 on the host exactly like the
    # per-iteration path used to — the perf fix must not change the
    # reported numerics
    totals: dict[str, list] = {b: [] for b in outputs}
    for it in range(args.iterations):
        feeds = feeder(it) if feeder else _synthetic_feed(net, seed=it)
        if feeder:
            feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        blobs = fwd(params, state, feeds)
        for b in outputs:
            totals[b].append(jnp.mean(blobs[b]))  # device scalar, async
    for b in outputs:
        # stack on device first: asarray over a python list of device
        # scalars would pull them one RTT at a time
        # lint: ok(host-sync) — harvest at exit: one bulk pull per blob
        avg = float(np.mean(np.asarray(jnp.stack(totals[b])),
                            dtype=np.float64))
        log.info("%s = %.5g", b, avg)
        print(f"{b} = {avg:.5g}")
    return 0


def cmd_time(args) -> int:
    """Per-layer forward/backward timing (reference tools/caffe.cpp:328-445).
    Per-layer costs come from timing each layer's jitted apply in isolation;
    whole-graph fwd and fwd+bwd are timed as single fused programs — the
    number that actually matters on TPU, where XLA fuses across layers."""
    import jax
    import jax.numpy as jnp
    from ..net import Net
    from ..proto import NetParameter
    if not args.model:
        log.error("time requires -model")
        return 1
    net = Net(NetParameter.from_file(args.model), phase=args.phase)
    params, state = net.init(jax.random.PRNGKey(0))
    feeds = _synthetic_feed(net)

    # materialize every blob once to get per-layer inputs
    blobs, _, _ = net.apply(params, state, feeds, train=False)
    blobs = dict(blobs)
    rows = []
    iters = max(args.iterations, 1)
    def timeit(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    for layer in net.layers:
        from ..layers.data_layers import InputLayerBase
        if isinstance(layer, InputLayerBase):
            continue
        bottoms = [blobs[b] for b in layer.lp.bottom]
        lparams = net._layer_params(layer, params, False)
        lstate = state.get(layer.name, {})
        fn = jax.jit(lambda p, s, bs, layer=layer: layer.apply(
            p, s, bs, train=False, rng=None)[0])
        fwd_ms_l = timeit(fn, lparams, lstate, bottoms)
        # isolated backward: VJP wrt params+float bottoms (reference times
        # each layer's Backward the same way, tools/caffe.cpp:403-423)
        float_idx = [i for i, b in enumerate(bottoms)
                     if jnp.issubdtype(b.dtype, jnp.floating)]
        bwd_ms_l = float("nan")
        if lparams or float_idx:
            def scalar_fn(p, bs, layer=layer, lstate=lstate):
                tops, _ = layer.apply(p, lstate, bs, train=False, rng=None)
                return sum(jnp.sum(t.astype(jnp.float32) ** 2) for t in tops
                           if hasattr(t, "ndim"))
            bwd = jax.jit(jax.grad(scalar_fn, argnums=(0, 1),
                                   allow_int=True))
            try:
                bwd_ms_l = timeit(bwd, lparams, bottoms)
            except Exception:
                pass  # non-differentiable layer: report nan
        rows.append((layer.name, layer.lp.type, fwd_ms_l, bwd_ms_l))

    def whole(train):
        rng_key = jax.random.PRNGKey(0)

        def f(p, s, fd):
            out_blobs, _, loss = net.apply(p, s, fd, train=train,
                                           rng=rng_key if train else None)
            if train:
                return loss
            # eval: force every terminal blob so XLA can't DCE the net
            # when the TEST phase has no loss layer
            return sum(jnp.sum(b.astype(jnp.float32)) for b in
                       out_blobs.values() if hasattr(b, "ndim"))
        if train:
            g = jax.jit(jax.grad(f))
        else:
            g = jax.jit(f)
        # compiled-program memory accounting (replaces the reference's
        # hand-tallied per-net GPU byte report, net.cpp:386-400, with the
        # compiler's actual buffer assignment)
        try:
            mem = g.lower(params, state, feeds).compile().memory_analysis()
            if mem is not None:
                print(f"  [{'train' if train else 'eval'} program] "
                      f"temp {getattr(mem, 'temp_size_in_bytes', 0)/2**20:.1f} MiB, "
                      f"args {getattr(mem, 'argument_size_in_bytes', 0)/2**20:.1f} MiB, "
                      f"output {getattr(mem, 'output_size_in_bytes', 0)/2**20:.1f} MiB")
        except Exception:
            pass
        out = g(params, state, feeds)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(params, state, feeds)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    if args.profile:
        # TPU tracing parity (reference relies on `caffe time`+nvprof; here
        # the xplane trace opens in TensorBoard/XProf)
        with jax.profiler.trace(args.profile):
            fwd_ms = whole(False)
            total_ms = whole(True) if net.loss_blobs else float("nan")
        print(f"profiler trace written to {args.profile}")
    else:
        fwd_ms = whole(False)
        total_ms = whole(True) if net.loss_blobs else float("nan")
    # analytic model FLOPs + MFU (utils/flops.py; the efficiency metric
    # img/s can't express — how busy the MXU actually is)
    from ..utils.flops import (layer_macs_per_image, net_macs_per_image,
                               peak_flops, train_flops_per_image)
    batch = next((net.blob_shapes[b][0] for b in net.feed_blobs), 1)
    layer_gflops = {l.name: 2 * layer_macs_per_image(l) * batch / 1e9
                    for l in net.layers}
    print(f"{'layer':<28}{'type':<20}{'fwd ms':>12}{'bwd ms':>12}"
          f"{'GFLOPs':>10}  (isolated)")
    for name, tname, fms, bms in rows:
        bs = f"{bms:.3f}" if bms == bms else "-"
        gf = layer_gflops.get(name, 0.0)
        gfs = f"{gf:.2f}" if gf else "-"
        print(f"{name:<28}{tname:<20}{fms:>12.3f}{bs:>12}{gfs:>10}")
    print(f"\nwhole-graph forward (fused): {fwd_ms:.3f} ms")
    print(f"whole-graph forward+backward (fused): {total_ms:.3f} ms")
    print(f"sum of isolated per-layer fwd: {sum(r[2] for r in rows):.3f} ms "
          "(>= fused time; the gap is XLA fusion)")
    fwd_gflops = 2 * net_macs_per_image(net) * batch / 1e9
    print(f"model FLOPs: fwd {fwd_gflops:.2f} GFLOPs/batch "
          f"(batch {batch}); fwd+bwd "
          f"{train_flops_per_image(net) * batch / 1e9:.2f}")
    dev = jax.devices()[0]
    peak = peak_flops(dev)
    if fwd_ms == fwd_ms and fwd_ms > 0:
        achieved_f = fwd_gflops / fwd_ms  # GFLOP / ms = TFLOP/s
        line = f"achieved: fwd {achieved_f:.2f} TFLOP/s"
        if total_ms == total_ms and total_ms > 0:
            achieved_t = train_flops_per_image(net) * batch / 1e9 / total_ms
            line += f", fwd+bwd {achieved_t:.2f} TFLOP/s"
            if peak:
                line += (f"; MFU {achieved_t * 1e12 / peak:.1%} "
                         f"({dev.device_kind} peak {peak / 1e12:.0f} TFLOP/s)")
        print(line)
    return 0


def cmd_serve(args) -> int:
    """Production inference serving (ISSUE 7, caffe_mpi_tpu/serving/):
    load the deploy net into a ServingEngine — params device-resident,
    every padded batch bucket AOT-compiled NOW — and mount the stdlib
    HTTP front-end on it. `-smoke N` runs the self-test path instead of
    serving forever."""
    from ..proto.config import ServingParameter
    from ..serving import ServingEngine
    from ..serving.http_front import make_server
    if not args.model:
        log.error("serve requires -model (a deploy prototxt)")
        return 1
    sp = ServingParameter()
    if args.serve_window_ms >= 0:
        sp.serve_window_ms = args.serve_window_ms
    if args.serve_buckets:
        sp.serve_buckets = args.serve_buckets
    if args.serve_hbm_mb >= 0:
        sp.serve_hbm_mb = args.serve_hbm_mb
    if args.serve_dtype:
        sp.serve_dtype = args.serve_dtype
    if args.serve_queue_limit >= 0:
        sp.serve_queue_limit = args.serve_queue_limit
    if args.serve_deadline_ms >= 0:
        sp.serve_deadline_ms = args.serve_deadline_ms
    if args.serve_stall_s >= 0:
        sp.serve_stall_s = args.serve_stall_s
    if args.serve_decoded_cache_mb >= 0:
        sp.serve_decoded_cache_mb = args.serve_decoded_cache_mb
    if args.serve_program_bank:
        sp.serve_program_bank = args.serve_program_bank
    if args.serve_replicas >= 0:
        sp.serve_replicas = args.serve_replicas
    if args.serve_retry_budget >= 0:
        sp.serve_retry_budget = args.serve_retry_budget
    if args.replica_deadline >= 0:
        sp.replica_deadline = args.replica_deadline
    # fleet mode (ISSUE 18): N replica processes behind the typed-retry
    # router — this process becomes the router+supervisor and never
    # builds an engine itself
    if sp.serve_replicas >= 1 and args.replica_id < 0:
        return _serve_fleet(args, sp)
    replica_beat = None
    if args.replica_id >= 0 and args.fleet_dir:
        # this process IS fleet replica K: publish heartbeats so the
        # supervisor can mourn a silent death, and accept admin swaps
        from ..serving.fleet import ReplicaBeat
        replica_beat = ReplicaBeat(args.fleet_dir, args.replica_id,
                                   deadline=sp.replica_deadline)
        replica_beat.start()
    # serving run journal (<model>.serve.run.json): breaker trips, hot
    # swaps + rejections, shutdown — next to the deploy prototxt (fleet
    # replicas journal per-replica so siblings don't clobber each other)
    journal = os.path.splitext(args.model)[0]
    if args.replica_id >= 0:
        journal += f".r{args.replica_id}"
    engine = ServingEngine(sp, journal=journal)
    engine.load_model("default", args.model, args.weights or None)
    watcher = None
    if args.serve_watch:
        from ..serving.watch import SnapshotWatcher
        watcher = SnapshotWatcher(engine, "default", args.serve_watch)
        watcher.start()
    srv = make_server(engine, "default", labels=args.labels or None,
                      image_root=args.image_root or None,
                      port=args.port if not args.smoke else 0,
                      admin=replica_beat is not None)
    host, port = srv.server_address[:2]
    if not args.smoke:
        log.info("serving on http://%s:%s (model %s, buckets %s, "
                 "window %.1f ms)", host, port, args.model,
                 engine.model("default").fwd.ladder, engine.window_ms)
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            if watcher is not None:
                watcher.stop()
            if replica_beat is not None:
                replica_beat.stop()
            srv.shutdown()
            # graceful: stop accepting, flush the window, resolve every
            # in-flight future, then close (docs/serving.md Resilience)
            engine.shutdown()
        return 0
    try:
        return _serve_smoke(args, engine, srv)
    finally:
        if watcher is not None:
            watcher.stop()
        if replica_beat is not None:
            replica_beat.stop()


def _serve_fleet(args, sp) -> int:
    """`caffe serve -replicas N` (ISSUE 18, docs/serving.md "Fleet"):
    spawn N replica processes (each a full `caffe serve` with its own
    engine, bank-warmed from the shared program bank), supervise them
    by heartbeat, and mount the typed-retry router as the public HTTP
    surface. `-watch` tails snapshots ROUTER-side, so each verified
    snapshot canaries on one replica before rolling fleet-wide."""
    from ..serving.fleet import FleetSupervisor, make_router_server
    fleet_dir = args.fleet_dir or os.path.splitext(args.model)[0] + "_fleet"
    sup = FleetSupervisor(args.model, args.weights or "",
                          sp.serve_replicas, fleet_dir, serving_param=sp)
    log.info("fleet: spawning %d replicas under %s (bank %s, heartbeat "
             "deadline %.1fs, retry budget %d)", sp.serve_replicas,
             fleet_dir, sup.bank_dir, sup.deadline,
             sup.router.retry_budget)
    sup.start()
    watcher = None
    if args.serve_watch:
        from ..serving.watch import SnapshotWatcher
        watcher = SnapshotWatcher(sup.router, "default", args.serve_watch)
        watcher.start()
    srv = make_router_server(sup.router,
                             port=args.port if not args.smoke else 0)
    host, port = srv.server_address[:2]
    try:
        if not args.smoke:
            log.info("fleet router serving on http://%s:%s (%d replicas)",
                     host, port, sp.serve_replicas)
            try:
                srv.serve_forever()
            except KeyboardInterrupt:
                pass
            return 0
        return _fleet_smoke(args, sup, srv)
    finally:
        if watcher is not None:
            watcher.stop()
        srv.shutdown()
        sup.stop()


def _fleet_smoke(args, sup, srv) -> int:
    """`serve -replicas N -smoke M`: M synthetic PNG requests through
    the real router HTTP surface, then assert every request resolved
    typed, traffic spread across replicas, and every replica held the
    bank-extended zero-recompile invariant. The full replica-kill /
    rolling-swap proof lives in tools/fleet_smoke.py."""
    import io
    import json
    import threading
    import urllib.error
    import urllib.request
    from PIL import Image

    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    rng = np.random.RandomState(0)
    url = f"http://127.0.0.1:{srv.server_address[1]}/classify"
    ok_n = 0
    for _ in range(args.smoke):
        buf = io.BytesIO()
        Image.fromarray(rng.randint(0, 255, (32, 32, 3), np.uint8)
                        ).save(buf, format="PNG")
        req = urllib.request.Request(
            url, data=buf.getvalue(),
            headers={"Content-Type": "image/png"})
        try:
            json.loads(urllib.request.urlopen(req, timeout=60).read())
            ok_n += 1
        except urllib.error.HTTPError as e:
            # typed failures (429/503/504 with a kind) count as resolved
            doc = json.loads(e.read() or b"{}")
            if not doc.get("kind"):
                log.error("fleet smoke: UNTYPED failure %s: %s",
                          e.code, doc)
                return 1
    stats = sup.router.stats()
    print(json.dumps({"serve_fleet_smoke": stats}))
    spread = sum(1 for doc in stats["replicas"].values()
                 if doc.get("requests", 0) > 0)
    for rid, doc in stats["replicas"].items():
        if "error" in doc:
            log.error("fleet smoke: replica %s unreachable", rid)
            return 1
        bank = doc.get("bank", {})
        if doc.get("compile_count") != bank.get("misses") or \
                doc.get("compile_count", 0) + bank.get("hits", 0) \
                != doc.get("warmed_buckets"):
            log.error("fleet smoke: replica %s broke the zero-recompile "
                      "invariant: %s", rid, doc)
            return 1
    if ok_n == 0 or (args.smoke >= 8 and spread < 2
                     and stats["fleet"]["replicas"] > 1):
        log.error("fleet smoke: no spread (%d ok, %d replicas served)",
                  ok_n, spread)
        return 1
    return 0


def _serve_smoke(args, engine, srv) -> int:
    """`serve -smoke N`: fire N mixed-size synthetic requests — a few
    over real HTTP (the full decode->submit->future path), the rest
    straight into the engine — then print stats and verify the
    zero-recompile claim (tools/tpu_validation.py serve stage)."""
    import json
    import threading
    import urllib.request

    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        model = engine.model("default")
        shape = model.fwd.input_shape()
        rng = np.random.RandomState(0)
        if len(shape) == 4:
            c, h, w = shape[1], shape[2], shape[3]

            def synth():  # HWC with the net's OWN channel count
                return rng.rand(h, w, c).astype(np.float32)
        else:
            def synth():  # non-image input: one row, preprocess reshapes
                return rng.rand(*shape[1:]).astype(np.float32)
        warmed = engine.compile_count
        # the HTTP leg decodes uploads with PIL convert("RGB"), so it
        # only makes sense for 3-channel image nets; others smoke the
        # engine surface alone
        n_http = min(4, args.smoke) \
            if len(shape) == 4 and shape[1] == 3 else 0
        http_err = None
        sent_http = 0
        try:
            from PIL import Image
            import io as _io
            for _ in range(n_http):
                buf = _io.BytesIO()
                Image.fromarray(rng.randint(0, 255, (h, w, 3), np.uint8)
                                ).save(buf, format="PNG")
                req = urllib.request.Request(
                    f"http://127.0.0.1:{srv.server_address[1]}/classify",
                    data=buf.getvalue(),
                    headers={"Content-Type": "image/png"})
                json.loads(urllib.request.urlopen(req, timeout=60).read())
                sent_http += 1
        except ImportError:
            log.warning("PIL missing; smoke skips the HTTP leg")
        except Exception as e:  # noqa: BLE001 — an HTTP-leg failure must
            # still print the telemetry JSON below before failing the smoke
            http_err = e
            log.error("serve smoke: HTTP leg failed: %s", e)
        # the rest straight into the engine, in mixed-size bursts; count
        # from requests actually SENT so a skipped/failed HTTP leg does
        # not shrink the trace the operator asked for
        left = args.smoke - sent_http
        while left > 0:
            burst = int(rng.randint(1, model.fwd.ladder[-1] + 1))
            burst = min(burst, left)
            engine.classify("default", [synth() for _ in range(burst)])
            left -= burst
        engine.drain()
        stats = engine.stats()
        stats["post_warmup_compiles"] = engine.compile_count - warmed
        # decode-path engagement at a glance (ISSUE 14): the HTTP leg is
        # the request-ingest path — which decoder ran and whether the
        # window-fused preprocess engaged (full counters under "ingest")
        ing = stats["ingest"]
        stats["native_ingest_engaged"] = bool(
            ing["decode_plane"]["native_records"] > 0
            and ing["fused_rows"] > 0)
        print(json.dumps({"serve_smoke": stats}))
        if http_err is not None:
            return 1
        if args.require_native_ingest and (
                sent_http == 0 or not stats["native_ingest_engaged"]):
            log.error(
                "serve smoke: native ingest did NOT engage (http leg "
                "%d reqs, native decodes %d, fused rows %d) — build "
                "the native plane with caffe_mpi_tpu/native/build.sh",
                sent_http, ing["decode_plane"]["native_records"],
                ing["fused_rows"])
            return 1
        if args.require_bank_warm and (
                engine.bank is None or engine.compile_count != 0
                or engine.bank_hits != engine.warmed_buckets):
            log.error(
                "serve smoke: program bank was NOT warm (%d compiles, "
                "%d bank hits vs %d warmed buckets, bank %s) — the "
                "zero-compile cold-start claim did not hold",
                engine.compile_count, engine.bank_hits,
                engine.warmed_buckets,
                engine.bank.path if engine.bank else "OFF")
            return 1
        # zero-recompile invariant, extended for the program bank
        # (ISSUE 17): every warmed bucket either compiled (a counted
        # bank miss) or deserialized (a hit) — bank off, hits are 0 and
        # this is the classic compile_count == warmed_buckets
        if stats["post_warmup_compiles"] != 0 or \
                engine.compile_count != engine.bank_misses or \
                engine.compile_count + engine.bank_hits \
                != engine.warmed_buckets:
            log.error("serve smoke: steady-state serving COMPILED "
                      "(%d post-warmup; total %d vs %d warmed buckets, "
                      "bank hits %d misses %d)",
                      stats["post_warmup_compiles"], engine.compile_count,
                      engine.warmed_buckets, engine.bank_hits,
                      engine.bank_misses)
            return 1
        return 0
    finally:
        srv.shutdown()
        engine.close()


def cmd_device_query(args) -> int:
    import jax
    for d in jax.devices():
        print(f"device {d.id}: {d.device_kind} platform={d.platform} "
              f"process={d.process_index}")
        mem = getattr(d, "memory_stats", lambda: None)()
        if mem:
            print(f"  hbm: {mem.get('bytes_limit', 0) / 2**30:.1f} GiB limit, "
                  f"{mem.get('bytes_in_use', 0) / 2**20:.1f} MiB in use")
    return 0


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname).1s%(asctime)s %(name)s] %(message)s",
        datefmt="%m%d %H:%M:%S")
    args = _parser().parse_args(argv)
    # the supervisor rebuilds the child command from the ORIGINAL argv
    # (argparse normalization would drop flag spellings)
    args._argv = list(argv) if argv is not None else sys.argv[1:]
    from ..utils.compile_cache import enable_compile_cache
    enable_compile_cache()
    return {
        "train": cmd_train,
        "test": cmd_test,
        "time": cmd_time,
        "device_query": cmd_device_query,
        "serve": cmd_serve,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
