"""convert_imageset — build a training DB from an image list.

Reference: tools/convert_imageset.cpp: reads `path label` lines, optionally
resizes/encodes, writes Datum records to LMDB/LevelDB with shuffling.

Usage:
    python -m caffe_mpi_tpu.tools.convert_imageset \
        [-resize_height H] [-resize_width W] [-shuffle] [-gray] \
        [-backend lmdb|datumfile] ROOTFOLDER LISTFILE DB_NAME
"""

from __future__ import annotations

import argparse
import os
import random
import sys

import numpy as np


def iter_datums(root: str, items, resize_hw, gray: bool):
    from PIL import Image

    from ..data.datasets import encode_datum

    for path, label in items:
        img = Image.open(os.path.join(root, path))
        img = img.convert("L" if gray else "RGB")
        if resize_hw[0] and resize_hw[1]:
            img = img.resize((resize_hw[1], resize_hw[0]), Image.BILINEAR)
        arr = np.asarray(img)  # lint: ok(host-sync) — PIL image, host data
        if arr.ndim == 2:
            arr = arr[None]
        else:
            arr = arr[:, :, ::-1].transpose(2, 0, 1)  # RGB HWC -> BGR CHW
        yield encode_datum(arr, label)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="convert_imageset")
    p.add_argument("-resize_height", "--resize_height", type=int, default=0)
    p.add_argument("-resize_width", "--resize_width", type=int, default=0)
    p.add_argument("-shuffle", "--shuffle", action="store_true")
    p.add_argument("-gray", "--gray", action="store_true")
    p.add_argument("-backend", "--backend", default="lmdb",
                   choices=["lmdb", "datumfile"])
    p.add_argument("root")
    p.add_argument("listfile")
    p.add_argument("db_name")
    args = p.parse_args(argv)

    items = []
    with open(args.listfile) as f:
        for line in f:
            line = line.strip()
            if line:
                path, _, label = line.rpartition(" ")
                items.append((path, int(label)))
    if args.shuffle:
        random.Random(1701).shuffle(items)  # fixed seed like the reference

    gen = iter_datums(args.root, items,
                      (args.resize_height, args.resize_width), args.gray)
    if args.backend == "lmdb":
        # the reference keys records "%08d_filename" (convert_imageset.cpp);
        # zero-padded index keys preserve insertion order lexicographically
        pairs = ((f"{i:08d}".encode(), buf) for i, buf in enumerate(gen))
        try:
            import lmdb
        except ImportError:
            from ..data.lmdb_io import write_lmdb
            write_lmdb(args.db_name, pairs)
        else:
            env = lmdb.open(args.db_name, map_size=1 << 40)
            with env.begin(write=True) as txn:
                for k, buf in pairs:
                    txn.put(k, buf)
        count = len(items)
    else:
        from ..data.datasets import DatumFileDataset
        count = DatumFileDataset.write(args.db_name, gen)
    print(f"Processed {count} files.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
