"""plot_training_log — render loss / accuracy / lr curves from a training log.

Reference: tools/extra/plot_training_log.py.example + root-level
plot_{loss,top1,top5,train_loss}.py / common_plot.py (multi-log comparison).

Usage:
    python -m caffe_mpi_tpu.tools.plot_training_log OUTPUT.png LOG [LOG2 ...]
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="plot_training_log")
    p.add_argument("output")
    p.add_argument("logs", nargs="+")
    args = p.parse_args(argv)

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from .parse_log import parse

    fig, axes = plt.subplots(1, 2, figsize=(11, 4))
    for logfile in args.logs:
        train, test = parse(logfile)
        label = os.path.basename(logfile)
        if train:
            axes[0].plot([r["NumIters"] for r in train],
                         [r["loss"] for r in train], label=label)
        # one series per (test net, metric) — mixing metrics on one line
        # would zigzag between incomparable scales
        series: dict[tuple, list] = {}
        for r in test:
            for k, v in r.items():
                if k in ("NumIters", "TestNet"):
                    continue
                series.setdefault((r.get("TestNet", 0), k), []).append(
                    (r["NumIters"], v))
        for (net_i, metric), rows in sorted(series.items()):
            axes[1].plot([a for a, _ in rows], [v for _, v in rows],
                         label=f"{label}:#{net_i}:{metric}")
    axes[0].set_xlabel("iteration")
    axes[0].set_ylabel("train loss")
    axes[0].legend(fontsize=7)
    axes[1].set_xlabel("iteration")
    axes[1].set_ylabel("test metric")
    if axes[1].lines:
        axes[1].legend(fontsize=7)
    fig.tight_layout()
    fig.savefig(args.output, dpi=120)
    print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
