"""bench_data — host data-pipeline throughput (img/s per backend) and
the ingestion stage breakdown (ISSUE 10).

The reference's pipeline perf story is DataReader/transformer thread
counts auto-tuned to keep GPUs fed (data_layer.cpp:46-113). Here the
host-side pipeline (dataset read -> decode -> transform -> batch) is the
part that must outrun the TPU step; this tool measures it in isolation,
per backend, with the same Feeder the training path uses.

The `ingest` section (default on; `--ingest-only` for just it) builds a
JPEG-encoded LMDB — the ImageNet-convert layout, where decode dominates
— and reports:
  * per-stage ms/batch: read (DB value fetch), crc (sidecar verify),
    decode (per-record, PIL and native), transform (native batch),
    assemble (stack + labels) — the evidence for WHERE host time goes;
  * end-to-end Feeder img/s for the PIL path (CAFFE_NATIVE_DECODE=0),
    the fused native path, and the decoded-record cache's post-warmup
    epoch — the A/B the acceptance criterion quotes;
All of it is CPU-only (no jax import), so bench.py embeds the JSON
(`--json`) as its `ingest` block on every emit path, tunnel up or down.

Usage:
    python -m caffe_mpi_tpu.tools.bench_data [-n 4096] [-batch 256] \
        [-shape 3x256x256] [-backends lmdb,leveldb,datumfile,hdf5] \
        [--json] [--ingest-only] [--no-ingest] [--ingest-n N]

Prints one line per backend: img/s through Feeder + DataTransformer
(crop+mirror+mean-subtract — the AlexNet training transform), then the
ingest section.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def _make_records(n, shape, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 256, (n, *shape), dtype=np.uint8)
    labels = rng.randint(0, 1000, n)
    return imgs, labels


def _write_db(backend, workdir, imgs, labels):
    """Serialize the synthetic dataset once per backend; returns the path
    (or HDF5 source-list path) the per-sweep feeders open."""
    from ..data.datasets import DatumFileDataset, encode_datum

    n = len(labels)
    recs = ((f"{i:08d}".encode(), encode_datum(imgs[i], int(labels[i])))
            for i in range(n))
    if backend == "lmdb":
        from ..data.lmdb_io import write_lmdb
        path = os.path.join(workdir, "b_lmdb")
        write_lmdb(path, recs)
    elif backend == "leveldb":
        from ..data.leveldb_io import write_leveldb
        path = os.path.join(workdir, "b_leveldb")
        write_leveldb(path, list(recs), compress=True)
    elif backend == "datumfile":
        path = os.path.join(workdir, "b.datumdb")
        DatumFileDataset.write(path, (r for _, r in recs))
    elif backend == "hdf5":
        import h5py
        h5 = os.path.join(workdir, "b.h5")
        with h5py.File(h5, "w") as f:
            f["data"] = imgs
            f["label"] = labels.astype(np.int64)
        path = os.path.join(workdir, "b_src.txt")
        with open(path, "w") as f:
            f.write(h5 + "\n")
    else:
        raise ValueError(backend)
    return path


def _feeder_for(backend, path, batch, crop, threads=0):
    from ..data import DataTransformer, Feeder
    from ..data.datasets import open_dataset
    from ..proto import TransformationParameter

    if backend == "hdf5":
        from ..data.feeder import HDF5Feeder
        from ..proto import NetParameter
        lp = NetParameter.from_text(
            'layer { name: "h" type: "HDF5Data" top: "data" top: "label"\n'
            f'  hdf5_data_param {{ source: "{path}" batch_size: {batch} '
            'shuffle: true } }').layer[0]
        return HDF5Feeder(lp)
    ds = open_dataset(backend.upper(), path)
    tp = TransformationParameter.from_text(
        f"crop_size: {crop} mirror: true mean_value: 104 "
        "mean_value: 117 mean_value: 123")
    return Feeder(ds, DataTransformer(tp, "TRAIN"), batch_size=batch,
                  shuffle=True, threads=threads)


def _ingest_feeder_img_s(path, batch, iters, crop, env_val, *,
                         decoded_cache_mb=0.0, epochs=1, n=0):
    """Per-worker batch-build rate over the encoded LMDB with the decode
    plane pinned to `env_val` ('' = auto/native, '0' = PIL). Batches are
    built DIRECTLY (`_build_batch_inner`), not through the prefetch
    queue — lookahead would build batches off the clock and flatter the
    number; the pool scales this per-worker rate by thread count at
    train time. With a decoded cache, `epochs=2` times only the SECOND
    epoch (the cached steady state). Returns (img/s, stats delta)."""
    from ..data import DataTransformer, Feeder
    from ..data import decode as dmod
    from ..data.datasets import DecodedCacheDataset, open_dataset
    from ..proto import TransformationParameter

    prev = os.environ.get("CAFFE_NATIVE_DECODE")
    if env_val:
        os.environ["CAFFE_NATIVE_DECODE"] = env_val
    else:
        os.environ.pop("CAFFE_NATIVE_DECODE", None)
    try:
        ds = open_dataset("LMDB", path)
        if decoded_cache_mb:
            ds = DecodedCacheDataset(ds, decoded_cache_mb)
        tp = TransformationParameter.from_text(
            f"crop_size: {crop} mirror: true mean_value: 104 "
            "mean_value: 117 mean_value: 123")
        # auto thread sizing: the fused native call threads the batch
        # decode internally (GIL released) with the pool width, which is
        # where it beats the per-record PIL loop — a PIL batch build is
        # sequential inside its worker no matter how many cores exist
        feeder = Feeder(ds, DataTransformer(tp, "TRAIN", seed=3),
                        batch_size=batch, shuffle=True, threads=0)
        it0 = 0
        if epochs > 1:  # warm the cache with a full first epoch
            for it in range(iters):
                feeder._build_batch_inner(it)
            it0 = iters
        feeder._build_batch_inner(it0)  # fused-path decision off-clock
        s0 = dmod.STATS.snapshot()
        t0 = time.perf_counter()
        for it in range(it0 + 1, it0 + iters):
            feeder._build_batch_inner(it)
        dt = time.perf_counter() - t0
        feeder.close()
        s1 = dmod.STATS.snapshot()
        stats = {k: s1[k] - s0[k] for k in s1}
        return batch * (iters - 1) / dt, stats
    finally:
        if prev is None:
            os.environ.pop("CAFFE_NATIVE_DECODE", None)
        else:
            os.environ["CAFFE_NATIVE_DECODE"] = prev


def _ingest_stage_breakdown(path, batch, iters, crop):
    """Direct per-stage instrumentation over the encoded LMDB: the same
    work the Feeder pipelines, timed stage-at-a-time so regressions have
    an address. Decode is timed on BOTH paths (per-record PIL and
    per-record native); transform is the native batch transformer (the
    production path for uniform uint8)."""
    from .. import native
    from ..data import decode as dmod
    from ..data.datasets import materialize_datum, parse_datum_fields
    from ..data.leveldb_io import crc32c
    from ..data.lmdb_io import LMDBReader, read_crc_sidecar

    reader = LMDBReader(path)
    keys = list(reader.keys())
    crcs = read_crc_sidecar(path, expect_count=len(keys))
    mean = np.asarray([104.0, 117.0, 123.0], np.float32)
    stages = {k: 0.0 for k in ("read", "crc", "decode_pil",
                               "decode_native", "transform", "assemble")}
    native_ok = native.available() and native.decode_available()
    for it in range(iters):
        idx = [(it * batch + i) % len(keys) for i in range(batch)]
        t0 = time.perf_counter()
        raws = [reader.get(keys[i]) for i in idx]
        stages["read"] += time.perf_counter() - t0
        if crcs is not None:
            t0 = time.perf_counter()
            for k, i in enumerate(idx):
                assert crc32c(raws[k]) == int(crcs[i])
            stages["crc"] += time.perf_counter() - t0
        fields = [parse_datum_fields(r) for r in raws]
        t0 = time.perf_counter()
        pil = [dmod._pil_decode(f.data) for f in fields]
        stages["decode_pil"] += time.perf_counter() - t0
        if native_ok:
            t0 = time.perf_counter()
            decoded = [native.decode_image_native(f.data) for f in fields]
            stages["decode_native"] += time.perf_counter() - t0
            decoded = [d if d is not None else p
                       for d, p in zip(decoded, pil)]
        else:
            decoded = pil
        # idx/labels are host ints from the DB read, never device values
        # host-sync: ok
        ids = np.asarray(idx, np.int64)
        t0 = time.perf_counter()
        if native_ok:
            out = native.transform_batch(
                np.stack(decoded), ids, crop=crop, mean=mean,
                scale=1.0, train=True, mirror=True, seed=3)
        else:
            out = np.stack([d[:, :crop, :crop].astype(np.float32)
                            for d in decoded]) - mean[:, None, None]
        stages["transform"] += time.perf_counter() - t0
        t0 = time.perf_counter()
        # host-sync: ok
        labels = np.asarray([f.label for f in fields], np.int32)
        batch_out = {"data": np.ascontiguousarray(out), "label": labels}
        stages["assemble"] += time.perf_counter() - t0
        del batch_out
    return {k: round(v * 1e3 / iters, 2) for k, v in stages.items()}


def run_ingest(workdir, n, batch, shape, crop, codec="jpeg",
               cache_mb=512.0) -> dict:
    """Build the JPEG-encoded LMDB and produce the `ingest` block."""
    from .. import native
    from ..data.datasets import encode_datum_image
    from ..data.lmdb_io import write_lmdb

    imgs, labels = _make_records(n, shape, seed=11)
    path = os.path.join(workdir, "ingest_lmdb")
    t0 = time.perf_counter()
    write_lmdb(path, ((f"{i:08d}".encode(),
                       encode_datum_image(imgs[i], int(labels[i]), codec))
                      for i in range(n)))
    build_s = time.perf_counter() - t0
    iters = max(n // batch, 2)
    block = {
        "codec": codec, "n": n, "batch": batch,
        "shape": "x".join(map(str, shape)), "crop": crop,
        "db_build_s": round(build_s, 1),
        "native_available": bool(native.available()
                                 and native.decode_available()),
        "stages_ms_per_batch": _ingest_stage_breakdown(
            path, batch, iters, crop),
    }
    pil_img_s, _ = _ingest_feeder_img_s(path, batch, iters, crop, "0")
    nat_img_s, nat_stats = _ingest_feeder_img_s(path, batch, iters, crop,
                                                "")
    block["pil_img_s"] = round(pil_img_s, 0)
    block["native_img_s"] = round(nat_img_s, 0)
    block["native_speedup"] = round(nat_img_s / max(pil_img_s, 1e-9), 2)
    block["fused_batches"] = nat_stats["fused_batches"]
    block["fused_records"] = nat_stats["fused_records"]
    cached_img_s, cache_stats = _ingest_feeder_img_s(
        path, batch, iters, crop, "", decoded_cache_mb=cache_mb, epochs=2)
    block["cached_img_s"] = round(cached_img_s, 0)
    block["cache_epoch2_decodes"] = cache_stats["decode_calls"]
    return block


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_data")
    p.add_argument("-n", "--n", type=int, default=4096)
    p.add_argument("-batch", "--batch", type=int, default=256)
    p.add_argument("-shape", "--shape", default="3x256x256")
    p.add_argument("-crop", "--crop", type=int, default=227)
    p.add_argument("-backends", "--backends",
                   default="lmdb,leveldb,datumfile,hdf5")
    p.add_argument("-device-transform", "--device-transform",
                   action="store_true",
                   help="stage raw uint8 + aug decisions (the in-graph "
                   "transform feed path) instead of transforming on host")
    p.add_argument("-threads", "--threads", default="0",
                   help="comma list of Feeder thread counts to sweep "
                   "(0 = auto mode, the prototxt default) — shows "
                   "multi-core scaling of the host pipeline")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of text lines "
                   "(bench.py embeds the `ingest` key)")
    p.add_argument("--ingest-only", action="store_true",
                   help="skip the classic backend sweep; just the "
                   "encoded-LMDB ingest section")
    p.add_argument("--no-ingest", action="store_true",
                   help="classic backend sweep only")
    p.add_argument("--ingest-n", type=int, default=0,
                   help="records in the encoded ingest DB (0 = "
                   "min(n, 1024))")
    args = p.parse_args(argv)
    shape = tuple(int(x) for x in args.shape.split("x"))
    sweeps = [int(t) for t in args.threads.split(",")]
    doc: dict = {"backends": []}

    if not args.ingest_only:
        # the classic sweep's dataset (~800 MB at the defaults) — the
        # ingest section builds its own, so skip it under --ingest-only
        # (bench.py runs that mode on every emit path)
        imgs, labels = _make_records(args.n, shape)
    iters = max(args.n // args.batch, 1)
    mode = "raw+aug staging" if args.device_transform else "host transform"
    with tempfile.TemporaryDirectory() as workdir:
        for backend in (args.backends.split(",")
                        if not args.ingest_only else []):
            t_build = time.perf_counter()
            path = _write_db(backend, workdir, imgs, labels)
            build_s = time.perf_counter() - t_build
            # HDF5Feeder has no thread pool — the sweep would print
            # identical single-threaded runs under misleading labels
            backend_sweeps = [None] if backend == "hdf5" else sweeps
            for threads in backend_sweeps:
                feeder = _feeder_for(backend, path, args.batch, args.crop,
                                     threads or 0)
                if args.device_transform:
                    if not hasattr(feeder, "device_transform"):
                        print(f"{backend:>10}: n/a "
                              "(no device-transform path)")
                        close = getattr(feeder, "close", None)
                        if close:
                            close()
                        break
                    feeder.device_transform = True
                feeder(0)  # warm caches / thread pools
                t0 = time.perf_counter()
                for it in range(1, iters + 1):
                    feeder(it)
                dt = time.perf_counter() - t0
                close = getattr(feeder, "close", None)
                if close:
                    close()
                tdesc = ("threads n/a" if threads is None
                         else "auto" if threads == 0 else f"t={threads}")
                img_s = args.batch * iters / dt
                doc["backends"].append(
                    {"backend": backend, "mode": mode, "threads": tdesc,
                     "img_s": round(img_s, 0)})
                if not args.json:
                    print(f"{backend:>10}: {img_s:8.0f} img/s "
                          f"({args.batch}x{args.shape}, crop {args.crop}, "
                          f"{mode}, {tdesc}, build {build_s:.1f}s)")
        if not args.no_ingest:
            # ingestion section (ISSUE 10): JPEG-encoded LMDB, stage
            # breakdown + PIL-vs-native-fused A/B + cached epoch
            n_ing = args.ingest_n or min(args.n, 1024)
            ing = run_ingest(workdir, n_ing, min(args.batch, n_ing),
                             shape, args.crop)
            doc["ingest"] = ing
            if not args.json:
                st = ing["stages_ms_per_batch"]
                print(f"    ingest: JPEG LMDB n={ing['n']} "
                      f"b={ing['batch']} crop={ing['crop']} — "
                      "ms/batch: "
                      + " ".join(f"{k}={v}" for k, v in st.items()))
                print(f"    ingest: PIL {ing['pil_img_s']:.0f} img/s | "
                      f"native fused {ing['native_img_s']:.0f} img/s "
                      f"({ing['native_speedup']}x) | decoded-cache "
                      f"epoch2 {ing['cached_img_s']:.0f} img/s "
                      f"({ing['cache_epoch2_decodes']} decodes)")
    if args.json:
        print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
