"""bench_data — host data-pipeline throughput (img/s per backend).

The reference's pipeline perf story is DataReader/transformer thread
counts auto-tuned to keep GPUs fed (data_layer.cpp:46-113). Here the
host-side pipeline (dataset read -> decode -> transform -> batch) is the
part that must outrun the TPU step; this tool measures it in isolation,
per backend, with the same Feeder the training path uses.

Usage:
    python -m caffe_mpi_tpu.tools.bench_data [-n 4096] [-batch 256] \
        [-shape 3x227x227] [-backends lmdb,leveldb,datumfile,hdf5]

Prints one line per backend: img/s through Feeder + DataTransformer
(crop+mirror+mean-subtract — the AlexNet training transform).
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np


def _make_records(n, shape, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 256, (n, *shape), dtype=np.uint8)
    labels = rng.randint(0, 1000, n)
    return imgs, labels


def _write_db(backend, workdir, imgs, labels):
    """Serialize the synthetic dataset once per backend; returns the path
    (or HDF5 source-list path) the per-sweep feeders open."""
    from ..data.datasets import DatumFileDataset, encode_datum

    n = len(labels)
    recs = ((f"{i:08d}".encode(), encode_datum(imgs[i], int(labels[i])))
            for i in range(n))
    if backend == "lmdb":
        from ..data.lmdb_io import write_lmdb
        path = os.path.join(workdir, "b_lmdb")
        write_lmdb(path, recs)
    elif backend == "leveldb":
        from ..data.leveldb_io import write_leveldb
        path = os.path.join(workdir, "b_leveldb")
        write_leveldb(path, list(recs), compress=True)
    elif backend == "datumfile":
        path = os.path.join(workdir, "b.datumdb")
        DatumFileDataset.write(path, (r for _, r in recs))
    elif backend == "hdf5":
        import h5py
        h5 = os.path.join(workdir, "b.h5")
        with h5py.File(h5, "w") as f:
            f["data"] = imgs
            f["label"] = labels.astype(np.int64)
        path = os.path.join(workdir, "b_src.txt")
        with open(path, "w") as f:
            f.write(h5 + "\n")
    else:
        raise ValueError(backend)
    return path


def _feeder_for(backend, path, batch, crop, threads=0):
    from ..data import DataTransformer, Feeder
    from ..data.datasets import open_dataset
    from ..proto import TransformationParameter

    if backend == "hdf5":
        from ..data.feeder import HDF5Feeder
        from ..proto import NetParameter
        lp = NetParameter.from_text(
            'layer { name: "h" type: "HDF5Data" top: "data" top: "label"\n'
            f'  hdf5_data_param {{ source: "{path}" batch_size: {batch} '
            'shuffle: true } }').layer[0]
        return HDF5Feeder(lp)
    ds = open_dataset(backend.upper(), path)
    tp = TransformationParameter.from_text(
        f"crop_size: {crop} mirror: true mean_value: 104 "
        "mean_value: 117 mean_value: 123")
    return Feeder(ds, DataTransformer(tp, "TRAIN"), batch_size=batch,
                  shuffle=True, threads=threads)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_data")
    p.add_argument("-n", "--n", type=int, default=4096)
    p.add_argument("-batch", "--batch", type=int, default=256)
    p.add_argument("-shape", "--shape", default="3x256x256")
    p.add_argument("-crop", "--crop", type=int, default=227)
    p.add_argument("-backends", "--backends",
                   default="lmdb,leveldb,datumfile,hdf5")
    p.add_argument("-device-transform", "--device-transform",
                   action="store_true",
                   help="stage raw uint8 + aug decisions (the in-graph "
                   "transform feed path) instead of transforming on host")
    p.add_argument("-threads", "--threads", default="0",
                   help="comma list of Feeder thread counts to sweep "
                   "(0 = auto mode, the prototxt default) — shows "
                   "multi-core scaling of the host pipeline")
    args = p.parse_args(argv)
    shape = tuple(int(x) for x in args.shape.split("x"))
    sweeps = [int(t) for t in args.threads.split(",")]

    imgs, labels = _make_records(args.n, shape)
    iters = max(args.n // args.batch, 1)
    mode = "raw+aug staging" if args.device_transform else "host transform"
    with tempfile.TemporaryDirectory() as workdir:
        for backend in args.backends.split(","):
            t_build = time.perf_counter()
            path = _write_db(backend, workdir, imgs, labels)
            build_s = time.perf_counter() - t_build
            # HDF5Feeder has no thread pool — the sweep would print
            # identical single-threaded runs under misleading labels
            backend_sweeps = [None] if backend == "hdf5" else sweeps
            for threads in backend_sweeps:
                feeder = _feeder_for(backend, path, args.batch, args.crop,
                                     threads or 0)
                if args.device_transform:
                    if not hasattr(feeder, "device_transform"):
                        print(f"{backend:>10}: n/a "
                              "(no device-transform path)")
                        close = getattr(feeder, "close", None)
                        if close:
                            close()
                        break
                    feeder.device_transform = True
                feeder(0)  # warm caches / thread pools
                t0 = time.perf_counter()
                for it in range(1, iters + 1):
                    feeder(it)
                dt = time.perf_counter() - t0
                close = getattr(feeder, "close", None)
                if close:
                    close()
                tdesc = ("threads n/a" if threads is None
                         else "auto" if threads == 0 else f"t={threads}")
                print(f"{backend:>10}: {args.batch * iters / dt:8.0f} img/s "
                      f"({args.batch}x{args.shape}, crop {args.crop}, "
                      f"{mode}, {tdesc}, build {build_s:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
