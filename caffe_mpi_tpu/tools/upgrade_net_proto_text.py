"""upgrade_net_proto_text / upgrade_solver_proto_text — explicit legacy
migration (reference tools/upgrade_net_proto_text.cpp and friends; the
framework also migrates automatically on every load).

Usage:
    python -m caffe_mpi_tpu.tools.upgrade_net_proto_text IN.prototxt OUT.prototxt
    python -m caffe_mpi_tpu.tools.upgrade_net_proto_text -solver IN OUT
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="upgrade_net_proto_text")
    p.add_argument("-solver", "--solver", action="store_true",
                   help="treat input as a SolverParameter")
    p.add_argument("input")
    p.add_argument("output")
    args = p.parse_args(argv)

    from ..proto import NetParameter, SolverParameter, normalize_net, solver_type

    if args.solver:
        sp = SolverParameter.from_file(args.input)
        if sp.has("solver_type"):
            sp.type = solver_type(sp)
            sp.solver_type = ""
            sp._node.fields.pop("solver_type", None)  # clear presence
        if sp.net_param is not None:
            normalize_net(sp.net_param)
        out = sp.to_prototxt()
    else:
        net = normalize_net(NetParameter.from_file(args.input))
        out = net.to_prototxt()
    with open(args.output, "w") as f:
        f.write(out + "\n")
    print(f"upgraded {args.input} -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
