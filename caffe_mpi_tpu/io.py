"""Binary protobuf I/O — .caffemodel / .binaryproto interop without protoc.

The reference serializes weights as a binary NetParameter holding per-layer
BlobProtos (net.cpp ToProto/CopyTrainedLayersFrom, blob.cpp ToProto), and
dataset means as a single BlobProto (tools/compute_image_mean.cpp). This
module speaks that wire format directly — a small protobuf-wire
encoder/decoder over the field numbers pinned in the reference schema
(src/caffe/proto/caffe.proto):

  NetParameter: name=1, layer=100 (LayerParameter), layers=2 (V1, read-only)
  LayerParameter: name=1, type=2, blobs=7
  V1LayerParameter: name=4? (read via generic skip; blobs=6)
  BlobProto: shape=7 {dim=1 packed int64}, data=5 (packed float),
             double_data=8, raw_data_type=10, raw_data=12,
             legacy num/channels/height/width = 1..4

Supports reading BVLC & NVCaffe .caffemodel files (incl. fp16 raw_data,
mapped to f32/bf16) and writing files the reference can read back.
"""

from __future__ import annotations

import struct

import numpy as np


# -- wire primitives --------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        if v < 0x80:
            out.append(v)
            return bytes(out)
        out.append((v & 0x7F) | 0x80)
        v >>= 7


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _skip(buf: bytes, pos: int, wire: int) -> int:
    if wire == 0:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire == 1:
        return pos + 8
    if wire == 2:
        size, pos = _read_varint(buf, pos)
        return pos + size
    if wire == 5:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire}")


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value_or_span) over a message."""
    pos, n = 0, len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
            yield field, wire, val
        elif wire == 2:
            size, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos:pos + size]
            pos += size
        elif wire == 5:
            yield field, wire, buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            yield field, wire, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


# -- BlobProto --------------------------------------------------------------

_TYPE_ENUM = {"DOUBLE": 0, "FLOAT": 1, "FLOAT16": 2, "INT": 3, "UINT": 4}
_ENUM_TYPE = {v: k for k, v in _TYPE_ENUM.items()}


def parse_blob(buf: bytes) -> np.ndarray:
    """BlobProto -> float32 ndarray with its declared shape."""
    shape: list[int] = []
    legacy = [0, 0, 0, 0]
    data: np.ndarray | None = None
    raw_type = None
    raw = None
    floats: list[np.ndarray] = []
    doubles: list[np.ndarray] = []
    for field, wire, val in _fields(buf):
        if field == 7 and wire == 2:  # shape
            for f2, w2, v2 in _fields(val):
                if f2 == 1 and w2 == 2:  # packed dims
                    pos = 0
                    while pos < len(v2):
                        d, pos = _read_varint(v2, pos)
                        shape.append(d)
                elif f2 == 1 and w2 == 0:
                    shape.append(v2)
        elif field == 5:
            if wire == 2:
                floats.append(np.frombuffer(val, "<f4"))
            else:
                floats.append(np.frombuffer(bytes(val), "<f4"))
        elif field == 8:
            if wire == 2:
                doubles.append(np.frombuffer(val, "<f8"))
        elif field == 10 and wire == 0:
            raw_type = _ENUM_TYPE.get(val)
        elif field == 12 and wire == 2:
            raw = val
        elif field in (1, 2, 3, 4) and wire == 0:
            legacy[field - 1] = val
    if not shape and any(legacy):
        shape = [d for d in legacy]
    if raw is not None:
        if raw_type == "FLOAT16":
            data = np.frombuffer(raw, "<f2").astype(np.float32)
        elif raw_type == "DOUBLE":
            data = np.frombuffer(raw, "<f8").astype(np.float32)
        else:
            data = np.frombuffer(raw, "<f4").copy()
    elif floats:
        data = np.concatenate(floats)
    elif doubles:
        data = np.concatenate(doubles).astype(np.float32)
    else:
        data = np.zeros(int(np.prod(shape)) if shape else 0, np.float32)
    return data.reshape(shape) if shape else data


def encode_blob(arr: np.ndarray) -> bytes:
    out = bytearray()
    dims = b"".join(_varint(d) for d in arr.shape)
    shape_msg = _tag(1, 2) + _varint(len(dims)) + dims
    out += _tag(7, 2) + _varint(len(shape_msg)) + shape_msg
    raw = np.ascontiguousarray(arr, "<f4").tobytes()
    out += _tag(5, 2) + _varint(len(raw)) + raw
    return bytes(out)


def load_blob_binaryproto(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        return parse_blob(f.read())


def save_blob_binaryproto(path: str, arr: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(encode_blob(arr))


# -- NetParameter weights (.caffemodel) -------------------------------------

def parse_caffemodel(buf: bytes) -> dict[str, list[np.ndarray]]:
    """binary NetParameter -> {layer_name: [blob arrays]} in file order.

    Reads both modern `layer` (field 100) and V1 `layers` (field 2;
    name=4, blobs=6 per V1LayerParameter in the reference schema)."""
    out: dict[str, list[np.ndarray]] = {}
    for field, wire, val in _fields(buf):
        if field == 100 and wire == 2:  # LayerParameter
            name, blobs = "", []
            for f2, w2, v2 in _fields(val):
                if f2 == 1 and w2 == 2:
                    name = v2.decode("utf-8")
                elif f2 == 7 and w2 == 2:
                    blobs.append(parse_blob(v2))
            if blobs:
                out[name] = blobs
        elif field == 2 and wire == 2:  # V1LayerParameter
            name, blobs = "", []
            for f2, w2, v2 in _fields(val):
                if f2 == 4 and w2 == 2:
                    name = v2.decode("utf-8")
                elif f2 == 6 and w2 == 2:
                    blobs.append(parse_blob(v2))
                elif f2 == 1 and w2 == 2:
                    # nested V0LayerParameter (caffe.proto:1473): name=1,
                    # blobs=50 — V0-era .caffemodel files store weights here
                    for f3, w3, v3 in _fields(v2):
                        if f3 == 1 and w3 == 2:
                            name = v3.decode("utf-8")
                        elif f3 == 50 and w3 == 2:
                            blobs.append(parse_blob(v3))
            if blobs:
                out[name] = blobs
    return out


def encode_caffemodel(weights: dict[str, list[np.ndarray]],
                      net_name: str = "", layer_types: dict[str, str] | None = None
                      ) -> bytes:
    out = bytearray()
    if net_name:
        nm = net_name.encode("utf-8")
        out += _tag(1, 2) + _varint(len(nm)) + nm
    for lname, blobs in weights.items():
        msg = bytearray()
        nm = lname.encode("utf-8")
        msg += _tag(1, 2) + _varint(len(nm)) + nm
        if layer_types and lname in layer_types:
            tp = layer_types[lname].encode("utf-8")
            msg += _tag(2, 2) + _varint(len(tp)) + tp
        for blob in blobs:
            b = encode_blob(blob)
            msg += _tag(7, 2) + _varint(len(b)) + b
        out += _tag(100, 2) + _varint(len(msg)) + bytes(msg)
    return bytes(out)


def load_caffemodel(path: str) -> dict[str, list[np.ndarray]]:
    with open(path, "rb") as f:
        return parse_caffemodel(f.read())


def save_caffemodel(path: str, weights: dict[str, list[np.ndarray]],
                    net_name: str = "", layer_types=None) -> None:
    with open(path, "wb") as f:
        f.write(encode_caffemodel(weights, net_name, layer_types))


# -- HDF5 weights (.caffemodel.h5) ------------------------------------------
# Layout (reference Net::ToHDF5, net.cpp:1194-1248): /data/<layer>/<i>
# datasets, one per positional blob.

def save_caffemodel_h5(path: str, weights: dict[str, list[np.ndarray]]) -> None:
    import h5py
    with h5py.File(path, "w") as f:
        data = f.create_group("data")
        for lname, blobs in weights.items():
            g = data.create_group(lname)
            for i, blob in enumerate(blobs):
                # lint: ok(host-sync) — snapshot boundary, one pull per blob
                g.create_dataset(str(i), data=np.asarray(blob, np.float32))


def load_caffemodel_h5(path: str) -> dict[str, list[np.ndarray]]:
    import h5py
    out: dict[str, list[np.ndarray]] = {}
    with h5py.File(path, "r") as f:
        data = f["data"]

        # layer names may contain '/' (GoogLeNet's inception_3a/1x1),
        # which HDF5 stores as NESTED groups — walk to the leaf groups
        # whose children are the positional blob datasets and rebuild the
        # layer name from the path (the reference reads by name, which
        # resolves nesting implicitly; iterating must recurse)
        def walk(group, prefix):
            keys = list(group.keys())
            if keys and all(isinstance(group[k], h5py.Dataset)
                            for k in keys):
                # lint: ok(host-sync) — h5py datasets, host data on load
                out[prefix] = [np.asarray(group[str(i)])
                               for i in range(len(keys))]
                return
            for k in keys:
                child = group[k]
                name = f"{prefix}/{k}" if prefix else k
                if isinstance(child, h5py.Group):
                    walk(child, name)

        walk(data, "")
    return out


def load_weights(path: str) -> dict[str, list[np.ndarray]]:
    """Dispatch on extension (reference CopyTrainedLayersFrom,
    net.cpp:1119-1126)."""
    if path.endswith((".h5", ".hdf5")):
        return load_caffemodel_h5(path)
    return load_caffemodel(path)


# -- SolverState (.solverstate) ---------------------------------------------
# Reference caffe.proto:303-308: iter=1 (varint), learned_net=2 (string),
# history=3 (repeated BlobProto), current_step=4 (varint). History blobs
# are the optimizer slots of the learnable params in net order, slot-major:
# history[i + s*N] = slot s of param i (Adam/AdaDelta append the second
# bank after the first, sgd_solver.cpp PreSolve + adam_solver.cpp:37-39).

def encode_solverstate(it: int, learned_net: str,
                       history: list[np.ndarray],
                       current_step: int = 0) -> bytes:
    out = bytearray()
    out += _tag(1, 0) + _varint(it)
    if learned_net:
        nm = learned_net.encode("utf-8")
        out += _tag(2, 2) + _varint(len(nm)) + nm
    for blob in history:
        # lint: ok(host-sync) — snapshot boundary, one pull per history blob
        b = encode_blob(np.asarray(blob))
        out += _tag(3, 2) + _varint(len(b)) + b
    if current_step:
        out += _tag(4, 0) + _varint(current_step)
    return bytes(out)


def parse_solverstate(buf: bytes) -> tuple[int, str, list[np.ndarray], int]:
    it, learned_net, history, current_step = 0, "", [], 0
    for field, wire, val in _fields(buf):
        if field == 1 and wire == 0:
            it = int(val)
        elif field == 2 and wire == 2:
            learned_net = val.decode("utf-8")
        elif field == 3 and wire == 2:
            history.append(parse_blob(val))
        elif field == 4 and wire == 0:
            current_step = int(val)
    return it, learned_net, history, current_step


def save_solverstate(path: str, it: int, learned_net: str,
                     history: list[np.ndarray], current_step: int = 0) -> None:
    with open(path, "wb") as f:
        f.write(encode_solverstate(it, learned_net, history, current_step))


def load_solverstate(path: str) -> tuple[int, str, list[np.ndarray], int]:
    with open(path, "rb") as f:
        return parse_solverstate(f.read())


def save_solverstate_h5(path: str, it: int, learned_net: str,
                        history: list[np.ndarray],
                        current_step: int = 0) -> None:
    """Reference SnapshotSolverStateToHDF5 layout (sgd_solver.cpp:293-310):
    /iter, /learned_net, /current_step scalars + /history/<i> datasets."""
    import h5py
    with h5py.File(path, "w") as f:
        f.create_dataset("iter", data=np.int32(it))
        f.create_dataset("learned_net", data=learned_net)
        f.create_dataset("current_step", data=np.int32(current_step))
        g = f.create_group("history")
        for i, blob in enumerate(history):
            # lint: ok(host-sync) — snapshot boundary, one pull per blob
            g.create_dataset(str(i), data=np.asarray(blob, np.float32))


def load_solverstate_h5(path: str) -> tuple[int, str, list[np.ndarray], int]:
    import h5py
    with h5py.File(path, "r") as f:
        it = int(np.asarray(f["iter"]))
        ln = f["learned_net"][()]
        learned_net = ln.decode("utf-8") if isinstance(ln, bytes) else str(ln)
        current_step = int(np.asarray(f["current_step"])) \
            if "current_step" in f else 0
        g = f["history"]
        # lint: ok(host-sync) — h5py datasets, host data on load
        history = [np.asarray(g[str(i)]) for i in range(len(g.keys()))]
    return it, learned_net, history, current_step
