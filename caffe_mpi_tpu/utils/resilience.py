"""Fault-tolerant training runtime — the survivability subsystem.

Reference Caffe assumes a reliable local device: its Snapshot() writes
checkpoint files inline with no integrity metadata (solver.cpp:542-604)
and its Solve() loop has no notion of a device that stops answering.
This deployment's device is a remote single-claim TPU behind a tunnel
that can die mid-run and leave the process hung inside uninterruptible
C++ dispatch (CLAUDE.md, docs/crash_hunt_r5.md) — so fault tolerance is
a system property here, not a user script (the TensorFlow design
position, arXiv 1605.08695; availability-dominated multi-node training,
arXiv 1810.11112). Four pieces, composed by solver/cli:

1. **Verified atomic snapshots** — temp-file + `os.replace` publication,
   a crc32c sidecar manifest (`<prefix>_iter_<N>.manifest.json`: per-file
   crc + size, iteration, wall time) written LAST so "manifest exists"
   == "snapshot complete", verification on load, and newest-prior-
   verified fallback on corruption. `gc_snapshots` enforces the
   `snapshot_keep` solver knob while never deleting the newest verified
   snapshot.
2. **Dispatch watchdog** — a monitor thread timestamps every device
   dispatch/harvest section the solver enters; when one exceeds the
   deadline (dead tunnel => C++ hang no Python signal can interrupt) it
   journals the run state to `<prefix>.run.json` and hard-exits with
   EXIT_WATCHDOG, turning an indefinite hang into a bounded, diagnosable
   failure a supervisor can act on.
3. **Supervised auto-resume** — `supervise()` runs the training child
   under utils/subproc.run_contained with exponential backoff and a
   crash-loop guard; restarts resume from the newest verified snapshot
   (`--resume auto` reads the run manifest + verified-manifest scan).
4. **Fault-injection plane** — env-keyed (`CAFFE_TPU_FAULTS`), zero cost
   when off: one falsy-dict check per site. Drives
   tests/test_fault_tolerance.py (feeder read errors, snapshot
   corruption/truncation, kill-mid-write, simulated dispatch stalls).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from contextlib import contextmanager, nullcontext

from glob import escape as glob_escape

log = logging.getLogger("caffe_mpi_tpu.resilience")

# distinct exit codes so the supervisor (and the operator's ps/log
# archaeology) can tell a watchdog trip from an injected fault from an
# ordinary crash — and, since ISSUE 4, from a numeric divergence the
# supervisor should REWIND (not merely restart) from
EXIT_WATCHDOG = 86
EXIT_FAULT = 87
EXIT_NUMERIC = 88
# ISSUE 11: cluster losses (a dead peer host, a severed DCN link, a
# coordinator that never answers) share code 87 with injected faults —
# both are environmental failures the supervisor restarts from (not
# rewinds like 88, not tunnel hangs like 86); the run journal's
# `reason` field carries the specific cluster event.
EXIT_CLUSTER = EXIT_FAULT


class ClusterError(RuntimeError):
    """Multi-host cluster formation or liveness failed in a BOUNDED way:
    `init_distributed` exhausted its retry budget against a missing
    coordinator, or a cluster barrier / KV exchange timed out. The CLI
    journals the event to `<prefix>.run.json` and converts this to exit
    code EXIT_CLUSTER (87) so the supervisor restarts the local worker
    instead of the process hanging inside an uninterruptible
    collective.

    `journal_reason` is the run-manifest `reason` the CLI writes for
    the event; raisers override it per instance when the 87 is not a
    loss — the degraded-mode rejoin trigger (ISSUE 19) sets
    "cluster_rejoin" so the supervisor's membership round grows the
    cluster back instead of merely restarting it."""

    journal_reason = "cluster_lost"


class NumericAnomalyError(RuntimeError):
    """Training declared numeric divergence: `guard_max_skips`
    consecutive steps were skipped by the on-device non-finite /
    loss-spike guard. The solver journals the anomaly to
    `<prefix>.run.json` before raising; the CLI converts this to exit
    code EXIT_NUMERIC (88), which the supervisor maps through the
    `anomaly_action` policy (rewind | rewind_lr | abort)."""

    def __init__(self, it: int, consec: int, skipped: int, last_bad: int):
        self.iter = it
        self.consec = consec
        self.skipped = skipped
        self.last_bad = last_bad
        super().__init__(
            f"numeric divergence at iteration {it}: {consec} consecutive "
            f"skipped step(s) ({skipped} total; last bad iteration "
            f"{last_bad})")


class RecordIntegrityError(RuntimeError):
    """One dataset record failed integrity verification (crc32c
    mismatch, structural DB corruption, or an undecodable Datum).
    Deterministic — NOT retried like transient I/O; the feeder
    quarantines the record instead."""

    def __init__(self, source: str, index: int, reason: str):
        self.source = source
        self.index = index
        self.reason = reason
        super().__init__(
            f"record {index} of {source or 'dataset'} failed integrity "
            f"check: {reason}")


class DataIntegrityError(RuntimeError):
    """The quarantine ratio bound was exceeded: corruption is
    systematic (dataset-level), not record-level — a hard, named
    failure instead of silently training on substitutes."""

_STATE_SUFFIXES = (".solverstate", ".solverstate.h5")
_MANIFEST_SUFFIX = ".manifest.json"
_MANIFEST_SCHEMA = 1


# ---------------------------------------------------------------------------
# Fault-injection plane (test-only; env-keyed; zero cost when off)
# ---------------------------------------------------------------------------

# Every registered injection site, in one place: the docs
# (docs/robustness.md) and the tier-1 doc-drift test
# (tests/test_doc_drift.py) both read this, so a site added at a call
# site without a registry entry — or documented without existing —
# fails fast instead of rotting.
FAULT_SITES = {
    "feeder_read": "transient dataset read error (Feeder retry budget)",
    "snapshot_kill": "hard-exit mid-snapshot-write (torn checkpoint)",
    "snapshot_corrupt": "flip a byte of the model file post-manifest",
    "snapshot_sync": "force interval snapshots to write blocking",
    "dispatch_stall": "sleep inside a train dispatch (watchdog trip)",
    "train_abort": "hard-exit at an iteration boundary (crash sim)",
    "nan_grad": "poison float feeds with NaN for iterations "
                "[arg, arg+count) — non-finite loss/gradients",
    "loss_spike": "scale float feeds 1e3x for iterations "
                  "[arg, arg+count) — finite loss explosion",
    "record_corrupt": "flip a byte of record values [arg, arg+count) "
                      "after fetch (bitrot the crc check must catch)",
    "record_decode": "truncate record values [arg, arg+count) so the "
                     "Datum parse fails",
    "host_loss": "kill the local worker at a heartbeat boundary "
                 "(beat seq >= arg) — a peer host dying mid-run",
    "coordinator_down": "fail distributed init for the first `count` "
                        "attempts (missing/unreachable coordinator)",
    "snapshot_shard_corrupt": "flip a byte in one orbax shard "
                              "post-manifest (sharded-snapshot bitrot)",
    "serve_dispatch_stall": "sleep inside a serving dispatch (stall "
                            "breaker trip — the dead-tunnel shape)",
    "swap_corrupt": "flip a byte of a hot-swap candidate's model file "
                    "post-manifest (verify must reject the swap)",
    "swap_canary_bad": "poison a hot-swap candidate's loaded weights "
                       "with NaN (canary gate must roll back)",
    "bank_corrupt": "flip a byte of a program-bank entry post-manifest "
                    "(verify must reject it into a counted bank miss)",
    "replica_dead": "kill a serving replica at a heartbeat boundary "
                    "(beat seq >= arg) — a fleet replica dying "
                    "mid-traffic",
    "fleet_swap_canary_bad": "flip a byte of the fleet's staged swap "
                             "candidate pre-canary (the rolling swap "
                             "must reject and roll back)",
    "host_perma_loss": "go dark at supervisor level for `arg` seconds "
                       "after the worker dies — the whole host (worker "
                       "AND supervisor) is gone, so the survivors must "
                       "degrade instead of waiting for a restart-all",
}

class FaultPlane:
    """Injects failures at named sites, configured from the
    `CAFFE_TPU_FAULTS` env var: comma-separated `site:count:skip:arg`
    entries (count defaults 1, skip 0, arg empty). A site `fire()`s on
    the (skip+1)-th .. (skip+count)-th eligible calls, then never again.
    count <= 0 is STICKY: the site fires on every eligible call for the
    rest of this process (e.g. "the dataset is gone", not "one read
    blipped").

    `CAFFE_TPU_FAULTS_DIR`, when set, makes firing durable ACROSS
    process restarts: a site that has fired its full count (or, for
    sticky sites, fired at all) writes `<dir>/<site>.done`, and any
    later process (the supervised restart) loads that site disabled —
    so "crash once, then succeed" scenarios terminate instead of
    crash-looping.

    Call-site helpers (`maybe_raise`, `maybe_stall`, `maybe_exit`,
    `corrupt_file`) keep injection one line in production code. When the
    env var is unset `_sites` is empty and `fire()` is a single falsy
    dict check — the zero-cost-when-off contract."""

    def __init__(self):
        self._sites: dict[str, dict] = {}
        self._dir = ""
        self._lock = threading.Lock()
        # bumped on every (re)configure — consumers that cache derived
        # state (the solver's wrapped feed_fn) key on it so a
        # reconfiguration mid-run invalidates their cache
        self.generation = 0

    def load_env(self) -> None:
        self.configure(os.environ.get("CAFFE_TPU_FAULTS", ""),
                       once_dir=os.environ.get("CAFFE_TPU_FAULTS_DIR", ""))

    def configure(self, spec: str, once_dir: str = "") -> None:
        self._dir = once_dir
        self._sites = {}
        self.generation += 1
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            site = parts[0]
            count = int(parts[1]) if len(parts) > 1 and parts[1] else 1
            skip = int(parts[2]) if len(parts) > 2 and parts[2] else 0
            arg = parts[3] if len(parts) > 3 else ""
            if self._done_path(site) and os.path.exists(
                    self._done_path(site)):
                log.info("fault site %r already fired in a previous "
                         "process; disabled", site)
                continue
            self._sites[site] = {"count": count, "skip": skip, "arg": arg}

    def _done_path(self, site: str) -> str:
        return os.path.join(self._dir, f"{site}.done") if self._dir else ""

    def fire(self, site: str, key: float | None = None) -> str | None:
        """Returns the site's arg string when this call should fail,
        else None. `key` (e.g. the current iteration) gates sites whose
        arg is a numeric threshold: they fire only once key >= arg."""
        if not self._sites:
            return None
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                return None
            arg = st["arg"]
            if key is not None and arg:
                try:
                    if key < float(arg):
                        return None
                except ValueError:
                    pass  # non-numeric arg: no threshold gating
            if st["skip"] > 0:
                st["skip"] -= 1
                return None
            if st["count"] <= 0:  # sticky: every call, this process only
                if not st.get("fired"):
                    st["fired"] = True
                    self._mark_done(site)
                return arg
            st["count"] -= 1
            if st["count"] <= 0:
                del self._sites[site]
                self._mark_done(site)
            return arg

    def _mark_done(self, site: str) -> None:
        done = self._done_path(site)
        if done:
            try:
                with open(done, "w") as f:
                    f.write(f"{time.time()}\n")
            except OSError:
                pass

    def active(self, site: str) -> bool:
        """Is `site` configured (without consuming a firing)? The
        zero-cost gate for wrappers that would otherwise add per-call
        work even with faults off."""
        return bool(self._sites) and site in self._sites

    def fire_at(self, site: str, key: float, *,
                durable_done: bool = True) -> str | None:
        """Range-keyed firing: fires iff arg <= key < arg + count,
        WITHOUT consuming the count. Unlike fire(), the decision is a
        pure function of `key` (a record/iteration index), so it is
        deterministic under prefetch-thread call reordering and under
        rebuild-on-demand — the property the feed-poisoning and
        record-corruption sites need for iteration-exact replay.
        durable_done=False skips the cross-process done marker
        (simulated bitrot must PERSIST across a supervised restart,
        while a NaN burst must not re-fire after the rewind)."""
        if not self._sites:
            return None
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                return None
            try:
                lo = float(st["arg"] or 0)
            except ValueError:
                return None
            # count <= 0 keeps the plane-wide STICKY contract: every
            # eligible key from `arg` onward (a finite count bounds the
            # range instead of a consumable budget)
            n = st["count"]
            if key < lo or (n > 0 and key >= lo + n):
                return None
            if durable_done and not st.get("fired"):
                st["fired"] = True
                self._mark_done(site)
            return st["arg"]

    def wrap_feeds(self, feed_fn):
        """Wrap a feed_fn with the `nan_grad` / `loss_spike` poisoning
        sites (ISSUE 4): float leaves of the batch for micro-iterations
        [arg, arg+count) are overwritten with NaN (nan_grad) or scaled
        1e3x (loss_spike). Returns `feed_fn` UNCHANGED when neither
        site is configured — the zero-cost-when-off contract (the
        solver caches the wrapper, so identity matters: a fresh wrapper
        per step() would churn the device feed queue)."""
        if not (self.active("nan_grad") or self.active("loss_spike")):
            return feed_fn
        import numpy as np  # deferred: resilience imports at startup

        def poison(feeds, fn):
            out, hit = {}, False
            for k, v in feeds.items():
                # feeds here are host ndarrays from the batch builder
                # (and this path only exists under fault injection)
                arr = np.asarray(v)  # host-sync: ok
                if np.issubdtype(arr.dtype, np.floating):
                    arr = fn(arr.copy())
                    hit = True
                out[k] = arr
            if not hit:
                # uint8 device-transform staging has no float leaf to
                # poison — silent no-op injection would make a test
                # pass vacuously
                log.warning("fault plane: batch has no float leaves to "
                            "poison (device-transform staging? use "
                            "transform_param { use_gpu_transform: "
                            "false } in the test net)")
            return out

        def wrapped(it):
            feeds = feed_fn(it)
            if self.fire_at("nan_grad", it) is not None:
                log.warning("fault plane: NaN-poisoning feeds for "
                            "micro-iteration %d", it)
                feeds = poison(feeds, lambda a: np.full_like(a, np.nan))
            if self.fire_at("loss_spike", it) is not None:
                log.warning("fault plane: 1e3x-scaling feeds for "
                            "micro-iteration %d", it)
                feeds = poison(feeds, lambda a: a * 1e3)
            return feeds

        return wrapped

    def corrupt_bytes(self, site: str, raw: bytes, key: float) -> bytes:
        """Record-level injection on FETCHED bytes (the mmap itself is
        read-only): `record_corrupt` flips one mid-record byte,
        `record_decode` truncates the record. Keyed by record index and
        durable across restarts (real bitrot does not heal on resume),
        so quarantine decisions replay identically."""
        if not self._sites:
            return raw
        if self.fire_at(site, key, durable_done=False) is not None:
            if site == "record_decode":
                return raw[:max(len(raw) // 2, 1)]
            b = bytearray(raw)
            if b:
                b[len(b) // 2] ^= 0xFF
            return bytes(b)
        return raw

    # -- one-line call-site helpers ------------------------------------
    def maybe_raise(self, site: str, exc_type=OSError, msg: str = "",
                    key: float | None = None) -> None:
        arg = self.fire(site, key=key)
        if arg is not None:
            raise exc_type(msg or f"injected fault at site {site!r}")

    def maybe_stall(self, site: str, key: float | None = None) -> None:
        arg = self.fire(site, key=key)
        if arg is not None:
            secs = float(arg or 30.0)
            log.warning("fault plane: stalling %.1fs at site %r", secs, site)
            time.sleep(secs)

    def maybe_exit(self, site: str, key: float | None = None) -> None:
        arg = self.fire(site, key=key)
        if arg is not None:
            log.warning("fault plane: hard exit at site %r", site)
            sys.stderr.flush()
            os._exit(EXIT_FAULT)

    def corrupt_file(self, site: str, path: str) -> None:
        """Flip one mid-file byte (bitrot/torn-write simulation)."""
        if self.fire(site) is None:
            return
        with open(path, "r+b") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(size // 2, 0))
            b = f.read(1)
            f.seek(max(size // 2, 0))
            f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
        log.warning("fault plane: corrupted %s at site %r", path, site)


FAULTS = FaultPlane()
FAULTS.load_env()


# ---------------------------------------------------------------------------
# Atomic file publication + crc32c integrity
# ---------------------------------------------------------------------------

@contextmanager
def atomic_output(path: str):
    """Yield a temp path for the caller to write; on clean exit fsync it
    and `os.replace` onto `path` (atomic on POSIX), so readers — and the
    resume scan after a mid-write kill — only ever see absent-or-complete
    files. On error the temp file is removed.

    Stale temps from a previous writer killed mid-write (the pid suffix
    differs) are swept first — writers to one path are serialized
    (wait_snapshots), so anything matching is an orphan."""
    import glob as _glob
    for stale in _glob.glob(f"{glob_escape(path)}.tmp*"):
        try:
            os.unlink(stale)
        except OSError:
            pass
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        yield tmp
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def crc32c_file(path: str, chunk: int = 1 << 22) -> int:
    """Streaming crc32c of a file — hardware-accelerated via
    google_crc32c when installed, else the repo's slice-by-8 table path
    (data/leveldb_io.py)."""
    try:
        from google_crc32c import extend as _extend
    except ImportError:
        _extend = None
    if _extend is None:
        from ..data.leveldb_io import crc32c
        with open(path, "rb") as f:
            return crc32c(f.read())
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = _extend(crc, buf)


# ---------------------------------------------------------------------------
# Single-artifact manifests (program-bank entries, ISSUE 17)
# ---------------------------------------------------------------------------

def write_file_manifest(path: str, **meta) -> str:
    """Publish the crc32c commit record for ONE standalone artifact —
    the snapshot-manifest scheme (write_snapshot_manifest) specialised
    to a single file with no iteration counter. Written LAST, after the
    artifact itself landed via atomic_output, so "manifest exists and
    verifies" is the artifact's commit point; extra keyword fields
    (e.g. a program-bank fingerprint) are stored alongside for
    observability."""
    mpath = path + _MANIFEST_SUFFIX
    doc = {"schema": _MANIFEST_SCHEMA, "time": time.time(),
           "files": {"artifact": {
               "file": os.path.basename(path),
               "size": os.path.getsize(path),
               "crc32c": f"{crc32c_file(path):08x}",
           }}}
    doc.update(meta)
    with atomic_output(mpath) as tmp:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
    return mpath


def verify_file_manifest(path: str) -> dict | None:
    """Re-check a single-artifact manifest (write_file_manifest) against
    the file's current size and crc32c. Returns the manifest dict on
    success, None on ANY failure — missing/unreadable/torn manifest,
    missing artifact, size or crc mismatch — so callers treat None as
    'regenerate the artifact', never as an error to raise."""
    mpath = path + _MANIFEST_SUFFIX
    try:
        with open(mpath) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    ent = (doc.get("files") or {}).get("artifact")
    if not isinstance(ent, dict) or ent.get("file") != os.path.basename(path):
        return None
    try:
        if os.path.getsize(path) != ent["size"]:
            return None
        if f"{crc32c_file(path):08x}" != ent["crc32c"]:
            return None
    except (OSError, TypeError):
        return None
    return doc


# ---------------------------------------------------------------------------
# Snapshot manifests: write / verify / scan / GC
# ---------------------------------------------------------------------------

class SnapshotCorruptError(RuntimeError):
    """A snapshot file failed its manifest crc32c check."""


def manifest_for_state(state_path: str) -> str | None:
    """Sidecar manifest path for a .solverstate[.h5] or a sharded
    .orbax checkpoint directory (ISSUE 11); None for formats without a
    manifest scheme (.npz pre-interop). The orbax manifest KEEPS the
    .orbax infix (`s_iter_N.orbax.manifest.json`) — stripping it would
    collide with a flat snapshot's manifest at the same iteration
    under the same prefix and silently orphan one of the two sets."""
    state_path = state_path.rstrip("/")
    if state_path.endswith(".orbax"):
        return state_path + _MANIFEST_SUFFIX
    for suf in _STATE_SUFFIXES:
        if state_path.endswith(suf):
            return state_path[: -len(suf)] + _MANIFEST_SUFFIX
    return None


def write_snapshot_manifest(state_path: str, it: int,
                            files: dict[str, str]) -> str:
    """Publish the integrity manifest for one snapshot — written LAST
    (after every file it covers), atomically, so its existence is the
    commit point of the whole snapshot. `files` maps role (model/state)
    to path; stored as basenames relative to the manifest's directory."""
    mpath = manifest_for_state(state_path)
    if mpath is None:
        raise ValueError(f"no manifest scheme for {state_path!r}")
    entries = {}
    for role, path in files.items():
        entries[role] = {
            "file": os.path.basename(path),
            "size": os.path.getsize(path),
            "crc32c": f"{crc32c_file(path):08x}",
        }
    doc = {"schema": _MANIFEST_SCHEMA, "iteration": int(it),
           "time": time.time(), "files": entries}
    with atomic_output(mpath) as tmp:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
    return mpath


def sharded_snapshot_files(orbax_dir: str) -> list[str]:
    """Every regular file under a sharded (.orbax) checkpoint dir,
    sorted by descending size then path — index 0 is the natural
    victim for the `snapshot_shard_corrupt` injection site (the
    biggest file is a tensorstore data shard, not metadata)."""
    out = []
    for root, _dirs, names in os.walk(orbax_dir):
        for name in names:
            out.append(os.path.join(root, name))
    out.sort(key=lambda p: (-os.path.getsize(p), p))
    return out


def write_sharded_manifest(orbax_dir: str, it: int) -> str:
    """Commit record for a sharded (.orbax) snapshot (ISSUE 11): one
    crc32c + size entry PER SHARD FILE under the checkpoint directory,
    written LAST (after the collective orbax save, after the all-hosts
    write barrier, by rank 0 alone) — so "manifest exists" == "every
    host's shards landed". Entries are paths relative to the dir, so
    verify re-walks exactly the recorded shard set and a torn or
    bit-rotted shard set fails as a unit."""
    orbax_dir = os.path.abspath(orbax_dir.rstrip("/"))
    mpath = manifest_for_state(orbax_dir)
    if mpath is None:
        raise ValueError(f"no manifest scheme for {orbax_dir!r}")
    entries = {}
    for path in sharded_snapshot_files(orbax_dir):
        rel = os.path.relpath(path, orbax_dir)
        entries[rel] = {
            "file": rel,
            "size": os.path.getsize(path),
            "crc32c": f"{crc32c_file(path):08x}",
        }
    if not entries:
        raise ValueError(f"sharded snapshot {orbax_dir!r} is empty")
    doc = {"schema": _MANIFEST_SCHEMA, "kind": "orbax",
           "iteration": int(it), "time": time.time(),
           "dir": os.path.basename(orbax_dir), "files": entries}
    with atomic_output(mpath) as tmp:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
    return mpath


def verify_snapshot(manifest_path: str) -> dict | None:
    """Re-check every file the manifest covers against its recorded size
    and crc32c. Returns the manifest dict (with a resolved 'state' path)
    on success, None on any mismatch / missing file / unreadable
    manifest — callers treat None as 'fall back to an older snapshot'.
    Sharded manifests (kind 'orbax', ISSUE 11) verify every recorded
    shard file relative to the checkpoint dir; 'state' resolves to the
    dir itself."""
    try:
        with open(manifest_path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    base = os.path.dirname(os.path.abspath(manifest_path))
    if doc.get("kind") == "orbax":
        root = os.path.join(base, doc.get("dir") or "")
        if not doc.get("dir") or not os.path.isdir(root) \
                or not doc.get("files"):
            return None
        for ent in doc["files"].values():
            path = os.path.join(root, ent["file"])
            try:
                if os.path.getsize(path) != ent["size"]:
                    return None
                if f"{crc32c_file(path):08x}" != ent["crc32c"]:
                    return None
            except OSError:
                return None
        doc["state"] = root
        doc["manifest"] = os.path.abspath(manifest_path)
        return doc
    state_path = None
    for role, ent in doc.get("files", {}).items():
        path = os.path.join(base, ent["file"])
        try:
            if os.path.getsize(path) != ent["size"]:
                return None
            if f"{crc32c_file(path):08x}" != ent["crc32c"]:
                return None
        except OSError:
            return None
        if role == "state":
            state_path = path
    if state_path is None:
        return None
    doc["state"] = state_path
    doc["manifest"] = os.path.abspath(manifest_path)
    return doc


def iter_snapshot_manifests(prefix: str) -> list[tuple[int, str]]:
    """All `<prefix>_iter_<N>[.orbax].manifest.json` sidecars, newest
    iteration first. Pure directory listing — no file reads, no
    verification."""
    d = os.path.dirname(prefix) or "."
    stem = os.path.basename(prefix) + "_iter_"
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        if not (name.startswith(stem) and name.endswith(_MANIFEST_SUFFIX)):
            continue
        mid = name[len(stem):-len(_MANIFEST_SUFFIX)]
        if mid.endswith(".orbax"):  # sharded sets (ISSUE 11)
            mid = mid[: -len(".orbax")]
        if mid.isdigit():
            out.append((int(mid), os.path.join(d, name)))
    out.sort(key=lambda p: p[0], reverse=True)
    return out


def latest_verified_snapshot(prefix: str,
                             max_iter: int | None = None) -> dict | None:
    """Newest snapshot (optionally strictly below `max_iter`) whose
    manifest verifies; corrupt/incomplete candidates are logged and
    skipped — the corruption-fallback half of the resume contract."""
    for it, mpath in iter_snapshot_manifests(prefix):
        if max_iter is not None and it >= max_iter:
            continue
        doc = verify_snapshot(mpath)
        if doc is not None:
            return doc
        log.warning("snapshot manifest %s failed verification "
                    "(corrupt or incomplete); trying an older snapshot",
                    mpath)
    return None


def gc_snapshots(prefix: str, keep: int,
                 assume_verified: str | None = None) -> list[str]:
    """Delete snapshot file sets beyond the newest `keep` manifests,
    never deleting the newest VERIFIED snapshot (if the newest `keep`
    are all corrupt, the last-known-good survives the sweep so resume
    always has somewhere to land). `assume_verified` names a manifest
    the caller KNOWS is good (the one its own writer just published) so
    the scan skips re-reading hundreds of MB it checksummed moments
    ago. Returns removed paths."""
    if keep <= 0:
        return []
    manifests = iter_snapshot_manifests(prefix)
    if len(manifests) <= keep:
        return []
    assumed = os.path.abspath(assume_verified) if assume_verified else None
    newest_verified = None
    for _it, mpath in manifests:  # newest first; stop at the first good
        if os.path.abspath(mpath) == assumed \
                or verify_snapshot(mpath) is not None:
            newest_verified = mpath
            break
    removed = []
    base = os.path.dirname(prefix) or "."
    for _it, mpath in manifests[keep:]:
        if mpath == newest_verified:
            continue
        victims, dirs = [], []
        try:
            with open(mpath) as f:
                doc = json.load(f)
            if doc.get("kind") == "orbax":
                # sharded snapshot (ISSUE 11): the whole checkpoint
                # DIRECTORY is the file set — per-entry unlinks would
                # leave a half-deleted dir that still looks like a
                # checkpoint to a directory listing
                if doc.get("dir"):
                    dirs = [os.path.join(base, doc["dir"])]
            else:
                victims = [os.path.join(base, ent["file"])
                           for ent in doc.get("files", {}).values()]
        except (OSError, ValueError):
            victims = []
        for d in dirs:  # dir first: a crash here leaves the manifest,
            import shutil  # whose verify then fails (never a dir that
            try:           # a later legacy scan could resurrect)
                shutil.rmtree(d)
                removed.append(d)
            except OSError:
                pass
        for path in victims + [mpath]:
            try:
                os.unlink(path)
                removed.append(path)
            except OSError:
                pass
    if removed:
        log.info("snapshot GC (keep=%d): removed %d file(s)", keep,
                 len(removed))
    return removed


# ---------------------------------------------------------------------------
# Run manifest — the journal the watchdog and supervisor share
# ---------------------------------------------------------------------------

def run_manifest_path(prefix: str) -> str:
    return prefix + ".run.json"


# the run manifest has CONCURRENT same-process writers — the async
# snapshot-writer thread journals "snapshot" while the watchdog monitor
# may journal a trip — and atomic_output's temp path is only pid-unique,
# so unserialized writers would sweep each other's in-progress temp
_RUN_MANIFEST_LOCK = threading.Lock()


def write_run_manifest(prefix: str, **fields) -> str:
    """Journal the run state (iteration, last verified snapshot, RNG
    cursor, reason) next to the snapshots. Atomic: a crash mid-journal
    leaves the previous journal intact. Called at every successful
    snapshot and by the watchdog just before a hard exit (the lock
    serializes those two threads)."""
    path = run_manifest_path(prefix)
    doc = {"schema": _MANIFEST_SCHEMA, "time": time.time(),
           "pid": os.getpid(), **fields}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with _RUN_MANIFEST_LOCK:
        with atomic_output(path) as tmp:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
    return path


def read_run_manifest(prefix: str) -> dict | None:
    try:
        with open(run_manifest_path(prefix)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Quarantine journal — the data-integrity plane's audit artifact
# ---------------------------------------------------------------------------

class QuarantineLog:
    """Journals quarantined dataset records to `<prefix>.quarantine.json`
    (ISSUE 4). The feeder substitutes a corrupt record deterministically
    (a pure function of the record index), so the journal is an AUDIT
    record, not state resume depends on — but the operator reads it to
    learn WHICH records are rotting, and the replay-determinism test
    asserts two runs produce identical entries.

    Writes are quarantine-rate (one atomic rewrite per newly-bad
    record), never per-iteration. Unconfigured (no path), entries
    accumulate in memory and only log — unit tests and library callers
    pay no filesystem cost."""

    def __init__(self):
        self.path: str | None = None
        self.entries: list[dict] = []
        self._seen: set[tuple] = set()       # journal dedup (incl. preload)
        self._warned: set[tuple] = set()     # THIS process's warnings
        self._lock = threading.Lock()
        self._last_flush = 0.0
        self._dirty = False

    def configure(self, path: str | None) -> None:
        """Bind the journal file (the CLI passes
        `<snapshot_prefix>.quarantine.json`). Existing entries from a
        previous attempt are loaded so a supervised restart appends to
        one continuous record instead of clobbering it."""
        with self._lock:
            self.path = path
            self.entries = []
            self._seen = set()
            self._warned = set()
            if not path:
                return
            try:
                with open(path) as f:
                    doc = json.load(f)
                self.entries = list(doc.get("records", []))
                self._seen = {(e.get("source"), e.get("index"))
                              for e in self.entries}
            except (OSError, ValueError):
                pass

    def record(self, source: str, index: int, substitute: int,
               reason: str, key: str = "") -> None:
        with self._lock:
            if (source, index) in self._seen:
                # already journaled (this process or a previous
                # attempt's preload). A probe-casualty placeholder
                # (substitute -1, "skipped during probing") upgrades in
                # place when the record is later substituted as a
                # primary — the audit must reflect the decision
                # actually replayed every epoch.
                upgraded = False
                if substitute >= 0:
                    for ent in self.entries:
                        if (ent.get("source"), ent.get("index")) == \
                                (source, index) \
                                and ent.get("substitute", -1) < 0:
                            ent["substitute"] = int(substitute)
                            ent["reason"] = reason
                            upgraded = True
                            break
                # the OPERATOR of this process must still hear about it
                # once, or corruption that persists across a dataset
                # "fix" goes silent
                if (source, index) not in self._warned:
                    self._warned.add((source, index))
                    log.warning(
                        "quarantined record %d of %s (-> substitute %d; "
                        "already journaled by a previous attempt): %s",
                        index, source or "dataset", substitute, reason)
                if upgraded:
                    self._flush_locked()
                return
            self._seen.add((source, index))
            self._warned.add((source, index))
            self.entries.append({
                "source": source, "index": int(index), "key": key,
                "substitute": int(substitute), "reason": reason,
                "time": time.time()})
            log.warning("quarantined record %d of %s (-> substitute %d): "
                        "%s", index, source or "dataset", substitute,
                        reason)
            self._flush_locked()

    def _flush_locked(self) -> None:
        """Rewrite the journal (caller holds the lock). Debounced past
        64 entries — one atomic rewrite per second instead of per
        record — so mass corruption near the 5% quarantine bound costs
        O(n) I/O, not O(n^2); the journal is a best-effort audit (the
        substitution itself is replay-deterministic), so a crash losing
        the last debounce window is acceptable."""
        if not self.path:
            return
        self._dirty = True
        now = time.monotonic()
        if len(self.entries) > 64 and now - self._last_flush < 1.0:
            return  # debounced; flush() drains the tail at shutdown
        self._last_flush = now
        self._dirty = False
        doc = {"schema": _MANIFEST_SCHEMA, "records": self.entries}
        try:
            # the first quarantine can precede the first snapshot —
            # the prefix directory may not exist yet
            os.makedirs(os.path.dirname(self.path) or ".",
                        exist_ok=True)
            with atomic_output(self.path) as tmp:
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
        except OSError:
            log.exception("quarantine journal write failed "
                          "(continuing)")

    def flush(self) -> None:
        """Drain any debounced tail — call at clean shutdown (the CLI's
        train teardown does) so the audit is complete even when the
        last quarantines landed inside the debounce window."""
        with self._lock:
            if self._dirty:
                self._last_flush = 0.0  # force the write
                self._flush_locked()

    def count(self) -> int:
        with self._lock:
            return len(self.entries)


QUARANTINE = QuarantineLog()


def quarantine_journal_path(prefix: str, rank: int = 0,
                            world: int = 1,
                            host: int | None = None) -> str:
    """Journal file for one host's quarantine decisions. Single-host
    keeps the classic `<prefix>.quarantine.json`; in a multi-host run
    (ISSUE 11) every host journals its OWN stripe's quarantines to
    `<prefix>.quarantine.r<k>.json` (concurrent atomic rewrites of one
    shared file from N hosts would drop entries), and rank 0 merges the
    per-host journals into the classic path at snapshot time.

    `host` (ISSUE 19) is a STABLE host identity for degraded-mode
    runs: generation remaps reassign ranks, so a rank-keyed journal
    would merge one host's quarantines into another host's audit trail
    after a reshape — when the supervisor publishes an original host
    id (CAFFE_TPU_CLUSTER_SELF), the journal keys on it instead
    (`<prefix>.quarantine.h<host>.json`), surviving every generation.
    Rank-keyed runs (min_hosts unset) keep the classic .r<k> path
    byte-identical."""
    if host is not None and world > 1:
        return prefix + f".quarantine.h{int(host)}.json"
    if world <= 1:
        return prefix + ".quarantine.json"
    return prefix + f".quarantine.r{int(rank)}.json"


def merge_quarantine_journals(prefix: str) -> int:
    """Merge every per-host quarantine journal
    (`<prefix>.quarantine.r*.json`, plus the stable-host-keyed
    `.quarantine.h*.json` spelling degraded-mode runs use — ISSUE 19)
    into the classic `<prefix>.quarantine.json`, deduped by
    (source, index) and sorted for a stable audit. Called by rank 0 at
    snapshot time (the same cadence the single-host journal flushes
    at). Returns the merged record count; 0 with no per-host journals
    (single-host runs never pay this)."""
    import glob as _glob
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix) + ".quarantine."
    parts = sorted(
        p for stem in (base + "r", base + "h")
        for p in _glob.glob(
            os.path.join(glob_escape(d), glob_escape(stem) + "*.json")))
    if not parts:
        return 0
    merged: dict[tuple, dict] = {}
    for path in parts:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        for ent in doc.get("records", []):
            merged.setdefault((ent.get("source"), ent.get("index")), ent)
    records = sorted(merged.values(),
                     key=lambda e: (e.get("source") or "",
                                    e.get("index") or 0))
    out = {"schema": _MANIFEST_SCHEMA, "records": records,
           "merged_from": [os.path.basename(p) for p in parts]}
    with atomic_output(prefix + ".quarantine.json") as tmp:
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    return len(records)


# ---------------------------------------------------------------------------
# Dispatch watchdog
# ---------------------------------------------------------------------------

class DispatchWatchdog:
    """Monitor thread that bounds device dispatch/harvest time.

    The solver wraps every device-blocking region in `section(label)`;
    the monitor wakes every `poll` seconds and, when the OLDEST open
    section has been open longer than `deadline`, calls `on_timeout`
    (the solver's run-state journaler) and hard-exits the process with
    EXIT_WATCHDOG. A dead tunnel hangs inside C++ where no Python signal
    can run (CLAUDE.md) — but this thread is already in Python, so
    os._exit still works, converting an indefinite hang into a bounded,
    journaled failure the supervisor restarts from.

    `hard_exit=False` (tests) records the trip in `.tripped` and fires
    `.tripped_event` instead of exiting. The deadline must exceed the
    worst jit-compile a dispatch can trigger — compiles happen inside
    dispatch sections and are legitimate multi-second stalls.

    `pulse` (ISSUE 11): an optional callable invoked once per poll tick
    from the monitor thread — the cross-host heartbeat
    (`HostHeartbeat.tick`) rides here, so one thread owns both liveness
    checks (a dead peer mid-collective and a dead tunnel mid-dispatch
    are the same shape of failure: an uninterruptible C++ wait only a
    Python side-thread can bound). Pulse exceptions are logged, never
    fatal to the monitor; a deadline of `inf` is allowed for
    heartbeat-only arming (sections then never trip)."""

    def __init__(self, deadline: float, on_timeout=None, *,
                 poll: float | None = None, hard_exit: bool = True,
                 pulse=None):
        self.deadline = float(deadline)
        self.on_timeout = on_timeout
        self.pulse = pulse
        self.poll = poll if poll is not None else min(
            max(self.deadline / 4.0, 0.05), 5.0)
        self.hard_exit = hard_exit
        self.tripped: tuple[str, float] | None = None
        self.tripped_event = threading.Event()
        self._lock = threading.Lock()
        self._open: dict[int, tuple[str, float]] = {}
        self._next = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dispatch-watchdog")
        self._thread.start()

    @contextmanager
    def section(self, label: str):
        with self._lock:
            token = self._next
            self._next += 1
            self._open[token] = (label, time.monotonic())
        try:
            yield
        finally:
            with self._lock:
                self._open.pop(token, None)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2 * self.poll + 1.0)

    def open_sections(self) -> list[str]:
        """Labels of the currently-open device sections, oldest first —
        the serving breaker's recovery gate asks this to tell a retired
        stall from a still-wedged call (serving/engine.py)."""
        with self._lock:
            entries = sorted(self._open.values(), key=lambda lt: lt[1])
        return [label for label, _t0 in entries]

    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            if self.pulse is not None:
                try:
                    self.pulse()
                # lint: ok(typed-failure) — the watchdog must survive
                # a bad pulse callback; its deadline check below is
                # the load-bearing path and still runs this tick
                except Exception:
                    log.exception("watchdog: pulse callback failed "
                                  "(continuing)")
            now = time.monotonic()
            with self._lock:
                oldest = min(self._open.values(), key=lambda lt: lt[1],
                             default=None)
            if oldest is None:
                continue
            label, t0 = oldest
            elapsed = now - t0
            if elapsed <= self.deadline:
                continue
            # the consequence differs by mode and the operator reads
            # this line: the training watchdog hard-exits 86, the
            # serving breaker (hard_exit=False, ISSUE 12) keeps the
            # process alive and sheds — claiming "exiting" there sends
            # an operator hunting for a death that never happened
            action = (f"journaling run state and hard-exiting "
                      f"{EXIT_WATCHDOG}" if self.hard_exit else
                      "journaling and tripping the breaker (process "
                      "stays up)")
            log.error("watchdog: device %s exceeded %.1fs deadline "
                      "(%.1fs elapsed) — %s", label, self.deadline,
                      elapsed, action)
            try:
                if self.on_timeout is not None:
                    self.on_timeout(label, elapsed)
            # lint: ok(typed-failure) — the trip proceeds regardless:
            # journaling is best-effort at death, exit 86 is the signal
            except Exception:
                log.exception("watchdog: run-state journal failed")
            self.tripped = (label, elapsed)
            self.tripped_event.set()
            if self.hard_exit:
                logging.shutdown()
                os._exit(EXIT_WATCHDOG)
            return


_NULL_SECTION = nullcontext()


# ---------------------------------------------------------------------------
# Cross-host heartbeat (ISSUE 11) — host-loss detection
# ---------------------------------------------------------------------------

class DirBeatTransport:
    """Heartbeat transport over a shared directory (`CAFFE_TPU_HB_DIR`):
    one atomically-rewritten sequence file per host. The default
    transport is the jax.distributed key-value store
    (parallel/mesh.py:KVBeatTransport); this one exists for unit tests
    and as an operator escape hatch when checkpoint storage is shared
    but the coordination service is suspect. NFS-grade semantics
    suffice: readers only compare monotone sequence numbers.

    The directory OUTLIVES process incarnations (the KV store does
    not — the coordination service is recreated per cluster epoch), so
    every record is stamped with a per-process incarnation token:
    readers fold a token change into a monotone surrogate sequence
    (a restarted publisher's seq-0 still reads as an ADVANCE, never as
    staleness), and a farewell marker only counts for the incarnation
    whose beats are currently being read — a bye left by an earlier
    clean run cannot disable mourning of the next incarnation."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._nonce = f"{os.getpid()}.{int(time.time() * 1e6)}"
        self._token: dict[int, str] = {}  # per-peer current incarnation
        self._base: dict[int, int] = {}   # surrogate offset per token
        self._hi: dict[int, int] = {}     # highest surrogate returned

    def _beat_file(self, host: int) -> str:
        return os.path.join(self.path, f"hb_{int(host)}")

    def publish(self, host: int, seq: int) -> None:
        with atomic_output(self._beat_file(host)) as tmp:
            with open(tmp, "w") as f:
                f.write(f"{self._nonce}:{int(seq)}")

    def _read(self, host: int) -> tuple[str, int] | None:
        try:
            with open(self._beat_file(host)) as f:
                token, _, seq = f.read().strip().rpartition(":")
            return (token, int(seq)) if token else None
        except (OSError, ValueError):
            return None

    def latest_seq(self, host: int) -> int:
        """Newest beat `host` has published as a surrogate sequence
        monotone ACROSS incarnations, -1 when none. Non-blocking (the
        tick cadence is the retry loop). The latest-not-exact contract
        matters: a reader that arms late or stalls must catch up from
        whatever state exists, never wedge on an overwritten beat."""
        rec = self._read(host)
        if rec is None:
            return -1
        token, seq = rec
        if self._token.get(host) != token:
            # a new incarnation restarts at seq 0: offset it past
            # everything the previous one published
            self._base[host] = self._hi.get(host, -1) + 1
            self._token[host] = token
        val = self._base.get(host, 0) + seq
        self._hi[host] = max(self._hi.get(host, -1), val)
        return val

    def farewell(self, host: int) -> None:
        with atomic_output(os.path.join(self.path,
                                        f"bye_{int(host)}")) as tmp:
            with open(tmp, "w") as f:
                f.write(self._nonce)

    def is_bye(self, host: int) -> bool:
        try:
            with open(os.path.join(self.path, f"bye_{int(host)}")) as f:
                bye_token = f.read().strip()
        except OSError:
            return False
        # only the incarnation whose beats we are reading may say
        # goodbye; a stale marker (or one for a peer we never heard)
        # must not suppress mourning
        return bool(bye_token) and bye_token == self._token.get(host)


class HostHeartbeat:
    """Cross-host liveness detection (ISSUE 11) — the multi-host
    spelling of the dead-tunnel problem: a peer host that dies (or a
    severed DCN link) leaves every survivor blocked inside an
    uninterruptible collective, exactly like a dead tunnel hangs a
    dispatch (CLAUDE.md). Detection therefore lives on the watchdog's
    monitor thread (`DispatchWatchdog(pulse=hb.tick)`), not in the
    train loop.

    Protocol: every `interval` seconds each host publishes a
    monotonically sequenced beat; each tick also drains peers' beats.
    A peer silent past `deadline` (measured host-locally — no clock
    sync: receipt time, not payload time) is a LOST HOST: the journal
    callback records it to `<prefix>.run.json` and the process
    hard-exits EXIT_CLUSTER (87) so the supervisor performs the
    coordinated restart. A peer that published its `farewell` marker
    (clean end-of-training, ahead of the exit barrier) is excluded
    instead of mourned. First contact gets `grace` (startup skew:
    peers arm after their own jit compiles).

    `host_loss` fault site: fires at a beat boundary (seq >= arg),
    simulating this host dying mid-run for the recovery suite."""

    def __init__(self, transport, host_id: int, n_hosts: int,
                 deadline: float, *, on_lost=None, interval=None,
                 grace: float | None = None, hard_exit: bool = True):
        self.transport = transport
        self.host = int(host_id)
        self.peers = [p for p in range(int(n_hosts)) if p != self.host]
        self.deadline = float(deadline)
        self.interval = float(interval) if interval else min(
            max(self.deadline / 4.0, 0.1), 5.0)
        self.grace = float(grace) if grace is not None else max(
            3.0 * self.deadline, 30.0)
        self.hard_exit = hard_exit
        self.on_lost = on_lost
        self.lost: tuple[int | str, float] | None = None
        self.lost_event = threading.Event()
        now = time.monotonic()
        self._last_pub = 0.0
        self._seq = 0
        self._first = {p: True for p in self.peers}
        self._last_seen = {p: now for p in self.peers}
        self._last_seq = {p: -1 for p in self.peers}
        self._done: set[int] = set()
        self._pub_warned = False

    def beats_seen(self, peer: int) -> int:
        """Beats observed from `peer` so far (telemetry/tests)."""
        return self._last_seq.get(peer, -1) + 1

    def tick(self) -> None:
        """One liveness round: publish when due, drain peers, mourn the
        stale. Called from the watchdog monitor thread every poll."""
        now = time.monotonic()
        if now - self._last_pub >= self.interval:
            self._last_pub = now
            try:
                self.transport.publish(self.host, self._seq)
            # lint: ok(typed-failure) — publish failure == silence; the
            # peers' deadline clocks decide (the typed outcome is their
            # journaled exit 87, not anything this host could raise)
            except Exception as e:
                if not self._pub_warned:
                    self._pub_warned = True
                    log.warning("heartbeat: publish failed (%s); peers "
                                "will see this host as silent", e)
            # test-only: die AT a beat boundary — the peer hosts must
            # detect the silence and exit 87 within their deadline
            FAULTS.maybe_exit("host_loss", key=self._seq)
            self._seq += 1
        for p in self.peers:
            if p in self._done or self.lost is not None:
                continue
            got = False
            try:
                # latest-not-exact: any ADVANCE counts as a beat, so a
                # reader that armed late or stalled catches up from
                # whatever history the transport still holds — it can
                # never wedge on a pruned sequence number
                seq = self.transport.latest_seq(p)
                if seq > self._last_seq[p]:
                    self._last_seq[p] = seq
                    got = True
            # lint: ok(typed-failure) — KV errors == silence; the
            # deadline clock decides and trips typed below
            except Exception:
                pass  # KV errors == silence; the deadline clock decides
            now = time.monotonic()
            if got:
                self._first[p] = False
                self._last_seen[p] = now
                continue
            try:
                if self.transport.is_bye(p):
                    log.info("heartbeat: host %d finished cleanly", p)
                    self._done.add(p)
                    continue
            # lint: ok(typed-failure) — a failed bye-probe == not a
            # clean departure; the deadline clock trips typed below
            except Exception:
                pass
            allowance = self.deadline + (self.grace if self._first[p]
                                         else 0.0)
            if now - self._last_seen[p] > allowance:
                self._trip(p, now - self._last_seen[p])

    def _trip(self, peer: int, elapsed: float) -> None:
        log.error("heartbeat: host %d silent for %.1fs (deadline %.1fs) "
                  "— peer lost; journaling and exiting %d for the "
                  "supervisor's coordinated restart", peer, elapsed,
                  self.deadline, EXIT_CLUSTER)
        self.lost = (peer, elapsed)
        self.lost_event.set()
        try:
            if self.on_lost is not None:
                self.on_lost(peer, elapsed)
        # lint: ok(typed-failure) — the trip proceeds regardless:
        # journaling is best-effort at death, exit 87 is the signal
        except Exception:
            log.exception("heartbeat: host-lost journal failed")
        if self.hard_exit:
            logging.shutdown()
            os._exit(EXIT_CLUSTER)

    def revive(self, peer: int) -> None:
        """Resume monitoring after `peer` was mourned and supervised
        back up (serving fleet, ISSUE 18). Training mourns once and
        hard-exits for a coordinated restart, so `tick()` latches
        `lost` and stops monitoring EVERY peer; a fleet supervisor
        instead respawns the dead replica in place and needs the
        heartbeat back. Clearing the latch re-arms all peers, and the
        respawned incarnation gets a fresh first-contact grace window
        (it beats from seq 0 under a new transport incarnation token —
        the surrogate-sequence fold reads that as an advance, never as
        staleness)."""
        self.lost = None
        self.lost_event.clear()
        self._first[peer] = True
        self._last_seen[peer] = time.monotonic()
        self._done.discard(peer)

    def farewell(self) -> None:
        """Publish the clean-departure marker (call at solver close,
        after the end-of-training barrier): peers stop expecting beats
        instead of tripping on post-training shutdown skew."""
        try:
            self.transport.farewell(self.host)
        # lint: ok(typed-failure) — best-effort: the exit barrier
        # already synchronized, so a lost farewell costs at worst one
        # spurious peer deadline during shutdown skew
        except Exception:
            pass  # best-effort: the exit barrier already synchronized


# ---------------------------------------------------------------------------
# Bounded retry
# ---------------------------------------------------------------------------

def retrying(fn, *, attempts: int = 4, base_delay: float = 0.05,
             max_delay: float = 2.0, exc_types=(OSError,),
             desc: str = ""):
    """Call `fn()` with bounded exponential backoff on transient errors.
    The LAST failure propagates unchanged (bounded, not infinite — a
    truly dead dataset must surface, and the supervisor owns restarts)."""
    delay = base_delay
    for attempt in range(attempts):
        try:
            return fn()
        except exc_types as e:
            if attempt == attempts - 1:
                raise
            log.warning("transient failure%s (attempt %d/%d): %r; "
                        "retrying in %.2fs",
                        f" in {desc}" if desc else "", attempt + 1,
                        attempts, e, delay)
            time.sleep(delay)
            delay = min(delay * 2, max_delay)


# ---------------------------------------------------------------------------
# Supervisor: contained child + exponential backoff + crash-loop guard
# ---------------------------------------------------------------------------

def supervise(first_cmd: list[str], resume_cmd: list[str],
              max_restarts: int, *, failure_log: str,
              env: dict | None = None, cwd: str | None = None,
              deadline: float | None = None,
              backoff_base: float = 1.0, backoff_cap: float = 60.0,
              anomaly_action: str = "rewind",
              anomaly_lr_mult: float = 0.1,
              journal_prefix: str | None = None) -> int:
    """Run a training child to completion, restarting on failure.

    Attempt 0 runs `first_cmd`; every restart runs `resume_cmd` (which
    carries `--resume auto`, so it lands on the newest verified
    snapshot). Children run under utils/subproc.run_contained — own
    process group, killpg'd on every supervisor exit path, so a
    supervisor kill can't orphan a chip-claiming child. After
    `max_restarts` failed restarts the crash-loop guard gives up with
    the per-attempt record preserved in `failure_log`. Returns the last
    child's exit code (0 on success, None->1 on deadline kill).

    Exit code EXIT_NUMERIC (88, ISSUE 4) — the child's on-device guard
    declared numeric divergence — routes through `anomaly_action`:
    `rewind` restarts from the newest verified snapshot like any
    failure; `rewind_lr` additionally appends `-lr_scale` with
    anomaly_lr_mult compounded per numeric restart, so the replay does
    not step straight back into the divergence; `abort` treats the
    divergence as fatal and returns 88 without restarting.

    Fast-fail doomed formation (ISSUE 19): `journal_prefix` names this
    host's run-manifest journal; when EVERY attempt from the start has
    ended in a fresh `cluster_init_failed` journal, the cluster never
    formed once — the coordinator/peer is unreachable, and burning the
    remaining restarts × CAFFE_TPU_INIT_TIMEOUT would only delay the
    same verdict. Two consecutive such failures give up with one clear
    message naming the unreachable endpoint. A run whose FIRST
    formation succeeded (the journal shows any other reason, or none
    fresh) never fast-fails: a mid-run host loss is exactly what the
    coordinated restart exists for."""
    from .subproc import run_contained
    os.makedirs(os.path.dirname(failure_log) or ".", exist_ok=True)
    rc = 1
    numeric_restarts = 0
    never_formed = True
    for attempt in range(max_restarts + 1):
        cmd = first_cmd if attempt == 0 else list(resume_cmd)
        if attempt > 0 and numeric_restarts and anomaly_action == "rewind_lr":
            cmd = cmd + ["-lr_scale",
                         repr(anomaly_lr_mult ** numeric_restarts)]
        log.info("supervisor: attempt %d/%d: %s", attempt + 1,
                 max_restarts + 1, " ".join(cmd))
        t0 = time.time()
        rc, out, err = run_contained(cmd, deadline, cwd=cwd, env=env,
                                     echo=True)
        dt = time.time() - t0
        if rc == 0:
            if attempt > 0:
                log.info("supervisor: recovered after %d restart(s)",
                         attempt)
            return 0
        reason = ("deadline" if rc is None else
                  "watchdog" if rc == EXIT_WATCHDOG else
                  "numeric divergence" if rc == EXIT_NUMERIC else
                  # 87 = injected fault OR cluster loss (ISSUE 11: a
                  # dead peer / failed distributed init journals the
                  # specific event to <prefix>.run.json); both restart
                  "fault/cluster" if rc == EXIT_FAULT else
                  f"exit {rc}")
        with open(failure_log, "a") as f:
            f.write(f"[{time.ctime()}] attempt {attempt + 1}: {reason} "
                    f"after {dt:.1f}s: {' '.join(cmd)}\n")
            tail = (out or "").strip().splitlines()[-20:] \
                + (err or "").strip().splitlines()[-20:]
            for line in tail:
                f.write(f"    {line}\n")
        if rc == EXIT_NUMERIC:
            if anomaly_action == "abort":
                log.error("supervisor: numeric divergence with "
                          "anomaly_action 'abort'; not restarting "
                          "(log: %s)", failure_log)
                return EXIT_NUMERIC
            numeric_restarts += 1
        # fast-fail doomed formation (ISSUE 19): only a FRESH
        # cluster_init_failed journal (written during this attempt)
        # counts — a stale one from a previous run must not condemn a
        # cluster that is actually forming
        init_fail = None
        if journal_prefix and rc == EXIT_FAULT:
            man = read_run_manifest(journal_prefix)
            if (man and man.get("reason") == "cluster_init_failed"
                    and float(man.get("time", 0) or 0) >= t0):  # lint: ok(host-sync) — journal JSON field, host data
                init_fail = man.get("error", "")
        if init_fail is None:
            never_formed = False
        elif never_formed and attempt >= 1:
            log.error(
                "supervisor: cluster formation failed on every attempt "
                "(%d of them) — %s; the peer is unreachable, so the "
                "remaining %d restart(s) would only replay the same "
                "init timeout. Giving up (log: %s)", attempt + 1,
                init_fail or "distributed init failed",
                max_restarts - attempt, failure_log)
            break
        if attempt >= max_restarts:
            log.error("supervisor: crash-loop guard: %d failure(s); "
                      "giving up (log: %s)", attempt + 1, failure_log)
            break
        delay = min(backoff_base * (2 ** attempt), backoff_cap)
        verb = ("rewinding to" if rc == EXIT_NUMERIC
                else "restarting from")
        log.warning("supervisor: child failed (%s); %s the newest "
                    "verified snapshot in %.1fs", reason, verb, delay)
        time.sleep(delay)
    return 1 if rc is None else rc


# ---------------------------------------------------------------------------
# Degraded-mode elasticity (ISSUE 19) — the generation protocol
# ---------------------------------------------------------------------------
# A PERMANENTLY dead host defeats PR 10's restart-all recovery: every
# survivor re-blocks in init_distributed at the old world size until
# --max-restarts exhausts. The generation protocol reshapes the cluster
# around the survivors instead. It lives at SUPERVISOR level on shared
# storage (the same assumption `--resume auto` already makes for
# snapshots): the coordination-service KV store dies with rank 0's
# worker, so the durable channel is a `<prefix>.cluster/` directory —
# DirBeatTransport supervisor liveness beats (keyed on ORIGINAL host
# ids, which survive every rank remap) plus an atomically-published
# generation record. Workers mirror the live record onto the KV store
# at `caffe/cluster_gen` (mesh.publish_generation) for in-band
# observability; the directory stays the source of truth.

_GEN_FILE = "cluster_gen.json"
_GEN_DONE = "done"


def cluster_dir(prefix: str) -> str:
    """The generation-protocol state directory for a run: beside the
    snapshots (shared storage), one per snapshot prefix."""
    return prefix + ".cluster"


def generation_path(cdir: str) -> str:
    return os.path.join(cdir, _GEN_FILE)


def initial_generation(world: int, coordinator: str) -> dict:
    """Generation 1 — the operator's original launch config. Implicit:
    it is what every supervisor assumes when no generation record
    exists, so a min_hosts run with no failures never writes one."""
    return {"generation": 1, "hosts": list(range(int(world))),
            "world": int(world), "world_full": int(world),
            "coordinator": coordinator, "reason": "cluster_formed"}


def read_generation(cdir: str) -> dict | None:
    """The current generation record, or None (= implicit generation
    1). Torn/invalid records read as None — the publisher's
    atomic_output makes that window a crash artifact, and falling back
    to the previous implicit state is always safe (the next membership
    round republishes)."""
    try:
        with open(generation_path(cdir)) as f:
            doc = json.load(f)
        if int(doc.get("generation", 0)) >= 1 and doc.get("hosts"):
            doc["hosts"] = [int(h) for h in doc["hosts"]]
            return doc
    except (OSError, ValueError, TypeError):
        pass
    return None


def write_generation(cdir: str, gen: dict) -> str:
    """Atomically publish a generation record: the per-generation
    history file `gen_<g>.json` first (the durable audit trail the
    degrade smoke asserts on), then the live `cluster_gen.json` as the
    commit record every parked/restarting supervisor polls."""
    os.makedirs(cdir, exist_ok=True)
    g = int(gen["generation"])
    doc = dict(gen, time=time.time())
    for path in (os.path.join(cdir, f"gen_{g}.json"),
                 generation_path(cdir)):
        with atomic_output(path) as tmp:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
    try:
        # a new generation means the run is live again: a done marker
        # left by an earlier completed run under this prefix must not
        # release the next run's parked rejoiners
        os.unlink(os.path.join(cdir, _GEN_DONE))
    except OSError:
        pass
    return generation_path(cdir)


def observe_live_hosts(cdir: str, world_full: int, self_host: int,
                       window: float, *, min_beats: int = 2) -> list[int]:
    """One membership round: watch the supervisor beat files for
    `window` seconds and return the sorted original host ids seen
    ALIVE. Prime-then-count: a fresh transport reads each host's
    current beat first, then only ADVANCES count — a frozen file left
    by a dead incarnation never reads as liveness, while a revived
    host's new incarnation token folds into a surrogate advance
    (DirBeatTransport). `min_beats` >= 2 rejects a single straggler
    flush from a host that died mid-publish. The observer itself is
    always live."""
    tr = DirBeatTransport(os.path.join(cdir, "hb"))
    hosts = range(int(world_full))
    base = {h: tr.latest_seq(h) for h in hosts}
    advances = {h: 0 for h in hosts}
    t_end = time.monotonic() + max(window, 0.2)
    while time.monotonic() < t_end:
        time.sleep(min(0.1, window / 4))
        for h in hosts:
            seq = tr.latest_seq(h)
            if seq > base[h]:
                advances[h] += seq - base[h]
                base[h] = seq
    live = {h for h in hosts if advances[h] >= min_beats}
    live.add(int(self_host))
    return sorted(live)


class SupervisorBeat:
    """Daemon thread publishing this SUPERVISOR's liveness beats
    (original host id key) to the cluster directory. Distinct from the
    worker's in-band heartbeat (HostHeartbeat): the worker's dies with
    the worker, which is precisely when membership must still be
    observable — a host whose supervisor beats is a rejoin candidate
    even while its worker is down. pause()/resume() exist for the
    `host_perma_loss` fault site (the whole host going dark)."""

    def __init__(self, cdir: str, host_id: int, interval: float):
        self.transport = DirBeatTransport(os.path.join(cdir, "hb"))
        self.host = int(host_id)
        self.interval = max(float(interval), 0.05)
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"sup-beat-{self.host}")

    def start(self) -> None:
        self._thread.start()

    # lint: ok(thread-crash) — a silent supervisor beat IS the loss
    # signal: peers mourn the silence and the membership round decides
    # (a crashed beat thread and a dead supervisor look identical by
    # design, and both resolve through the same degraded-mode path)
    def _run(self) -> None:
        while not self._stop.is_set():
            if not self._paused.is_set():
                try:
                    self.transport.publish(self.host, self._seq)
                    self._seq += 1
                except OSError as e:
                    log.warning("supervisor beat publish failed: %s", e)
            self._stop.wait(self.interval)

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def _wait_generation_advance(cdir: str, beyond: int,
                             timeout: float) -> dict | None:
    """Poll for a generation record newer than `beyond` (the
    non-publisher survivors waiting out the lowest-rank's membership
    round)."""
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        gen = read_generation(cdir)
        if gen and gen["generation"] > beyond:
            return gen
        time.sleep(0.2)
    return None


def _rejoin_wait(cdir: str, host_id: int, beyond: int,
                 park_deadline: float) -> dict | str | None:
    """Park a host excluded from the current generation: keep
    publishing supervisor beats (the SupervisorBeat thread is already
    running) so rank 0's snapshot-boundary rejoin check can see this
    host alive, and poll until a generation re-admits it, the run
    finishes (`done` marker), or the park deadline lapses."""
    log.info("rejoin-wait: generation %d excludes host %d; parking, "
             "publishing beats until rank 0 re-admits this host at a "
             "snapshot boundary", beyond, host_id)
    t_end = time.monotonic() + park_deadline
    while time.monotonic() < t_end:
        if os.path.exists(os.path.join(cdir, _GEN_DONE)):
            return "done"
        gen = read_generation(cdir)
        if gen and gen["generation"] > beyond \
                and int(host_id) in gen["hosts"]:
            return gen
        time.sleep(0.25)
    return None


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def supervise_elastic(build_cmd, *, prefix: str, host_id: int,
                      world_full: int, min_hosts: int,
                      host_deadline: float, coordinator_host: str,
                      coordinator: str, max_restarts: int,
                      failure_log: str, env: dict | None = None,
                      cwd: str | None = None,
                      deadline: float | None = None,
                      backoff_base: float = 1.0,
                      backoff_cap: float = 60.0,
                      anomaly_action: str = "rewind",
                      anomaly_lr_mult: float = 0.1,
                      park_deadline: float = 900.0) -> int:
    """Degraded-mode supervisor (ISSUE 19): `supervise()` plus the
    generation protocol. `build_cmd(gen, rank, resume)` returns the
    worker argv for one generation — remapped `-hosts W' -host_id k'
    -coordinator <epoch>` with `--resume auto` on restarts.

    Per child failure, in order:
    1. `host_perma_loss` fault site — this supervisor goes dark for
       `arg` seconds (beats paused), simulating the whole host dead,
       then revives into step 2.
    2. A NEWER generation exists: a peer already reshaped the cluster.
       Including this host -> switch to it with a FRESH restart budget
       (a generation switch is recovery, not a crash loop); excluding
       it -> rejoin-wait, parked until rank 0 re-admits it at a
       snapshot boundary (or the run finishes).
    3. Exit 87 (cluster event): run a membership round over the
       supervisor beats for ~`host_deadline`. A changed host set with
       >= min_hosts survivors is published as generation g+1 by the
       LOWEST surviving host (who is the new rank 0, so it allocates
       the new coordinator epoch on its own address); the others wait
       for that record. Journal events `cluster_degraded:<g>` /
       `cluster_regrown:<g>` land in the run manifest and in the
       generation history (`gen_<g>.json`).
    4. Same membership (transient loss) or non-cluster failure: the
       plain supervised restart with exponential backoff, bounded by
       `max_restarts` WITHIN the current generation.

    A clean exit in a reshaped run publishes the `done` marker so
    parked hosts return 0 instead of waiting out their park deadline."""
    from .subproc import run_contained
    os.makedirs(os.path.dirname(failure_log) or ".", exist_ok=True)
    cdir = cluster_dir(prefix)
    os.makedirs(cdir, exist_ok=True)
    interval = min(max(float(host_deadline) / 4.0, 0.1), 2.0)
    beat = SupervisorBeat(cdir, host_id, interval)
    beat.start()
    cur = read_generation(cdir) or initial_generation(world_full,
                                                      coordinator)
    attempt = 0
    resume = cur["generation"] > 1
    numeric_restarts = 0
    rc: int | None = 1
    try:
        while True:
            if int(host_id) not in cur["hosts"]:
                got = _rejoin_wait(cdir, host_id, cur["generation"],
                                   park_deadline)
                if got == "done":
                    log.info("rejoin-wait: run finished without this "
                             "host; exiting clean")
                    return 0
                if got is None:
                    log.error("rejoin-wait: no generation re-admitted "
                              "host %d within %.0fs; giving up",
                              host_id, park_deadline)
                    return 1
                cur, attempt, resume = got, 0, True
                continue
            rank = cur["hosts"].index(int(host_id))
            cmd = list(build_cmd(cur, rank, resume))
            if resume and numeric_restarts \
                    and anomaly_action == "rewind_lr":
                cmd += ["-lr_scale",
                        repr(anomaly_lr_mult ** numeric_restarts)]
            child_env = dict(env if env is not None else os.environ)
            child_env.update(
                CAFFE_SUPERVISED_CHILD="1",
                CAFFE_TPU_CLUSTER_DIR=cdir,
                CAFFE_TPU_CLUSTER_GEN=str(cur["generation"]),
                CAFFE_TPU_CLUSTER_HOSTS=",".join(
                    str(h) for h in cur["hosts"]),
                CAFFE_TPU_CLUSTER_SELF=str(int(host_id)),
                CAFFE_TPU_WORLD_FULL=str(
                    cur.get("world_full", world_full)),
                CAFFE_TPU_CLUSTER_DEADLINE=repr(float(host_deadline)))  # lint: ok(host-sync) — host scalar knob
            log.info("supervisor[gen %d]: attempt %d/%d as rank %d/%d: "
                     "%s", cur["generation"], attempt + 1,
                     max_restarts + 1, rank, cur["world"], " ".join(cmd))
            t0 = time.time()
            rc, out, err = run_contained(cmd, deadline, cwd=cwd,
                                         env=child_env, echo=True)
            dt = time.time() - t0
            if rc == 0:
                if cur["generation"] > 1:
                    # release any parked excluded host. NOT
                    # atomic_output: every finishing supervisor writes
                    # this marker CONCURRENTLY and the stale-tmp sweep
                    # assumes serialized writers; only the marker's
                    # existence signals, so a plain racy write is
                    # exactly right
                    try:
                        with open(os.path.join(cdir, _GEN_DONE),
                                  "w") as f:
                            f.write(f"{time.time()}\n")
                    except OSError as e:
                        log.warning("done-marker write failed "
                                    "(a peer's likely landed): %s", e)
                if attempt > 0 or cur["generation"] > 1:
                    log.info("supervisor: recovered (generation %d, %d "
                             "restart(s) in it)", cur["generation"],
                             attempt)
                return 0
            reason = ("deadline" if rc is None else
                      "watchdog" if rc == EXIT_WATCHDOG else
                      "numeric divergence" if rc == EXIT_NUMERIC else
                      "fault/cluster" if rc == EXIT_FAULT else
                      f"exit {rc}")
            with open(failure_log, "a") as f:
                f.write(f"[{time.ctime()}] gen {cur['generation']} "
                        f"attempt {attempt + 1}: {reason} after "
                        f"{dt:.1f}s: {' '.join(cmd)}\n")
                tail = (out or "").strip().splitlines()[-20:] \
                    + (err or "").strip().splitlines()[-20:]
                for line in tail:
                    f.write(f"    {line}\n")
            if rc == EXIT_NUMERIC:
                if anomaly_action == "abort":
                    log.error("supervisor: numeric divergence with "
                              "anomaly_action 'abort'; not restarting "
                              "(log: %s)", failure_log)
                    return EXIT_NUMERIC
                numeric_restarts += 1
            # test-only: the whole host (supervisor included) goes dark
            # for `arg` seconds — the survivors must degrade around it,
            # and its revival must re-enter via rejoin-wait
            dark = FAULTS.fire("host_perma_loss")
            if dark is not None:
                park = float(dark) if dark else 8.0  # lint: ok(host-sync) — fault-spec string arg
                log.warning("fault host_perma_loss: host %d supervisor "
                            "dark for %.1fs", host_id, park)
                beat.pause()
                time.sleep(park)
                beat.resume()
                log.warning("fault host_perma_loss: host %d supervisor "
                            "revived", host_id)
            newer = read_generation(cdir)
            if newer and newer["generation"] > cur["generation"]:
                log.info("supervisor: generation %d -> %d (published "
                         "by a peer while this host was down)",
                         cur["generation"], newer["generation"])
                cur, attempt, resume = newer, 0, True
                continue
            if rc == EXIT_CLUSTER:
                window = max(float(host_deadline), 8 * interval)  # lint: ok(host-sync) — host scalar knob
                live = observe_live_hosts(cdir, world_full, host_id,
                                          window)
                if sorted(live) != sorted(cur["hosts"]) \
                        and len(live) >= max(int(min_hosts), 1):
                    if min(live) == int(host_id):
                        g = cur["generation"] + 1
                        event = ("cluster_degraded"
                                 if len(live) < len(cur["hosts"])
                                 else "cluster_regrown")
                        # the publisher is the LOWEST survivor == the
                        # new rank 0 == the host the new coordination
                        # service must run on: a fresh port on its own
                        # address is always bindable by its own worker
                        newgen = {
                            "generation": g, "hosts": live,
                            "world": len(live),
                            "world_full": int(world_full),
                            "coordinator":
                                f"{coordinator_host}:{_free_port()}",
                            "reason": event,
                            "prev_hosts": cur["hosts"]}
                        write_generation(cdir, newgen)
                        try:
                            write_run_manifest(
                                prefix, reason=f"{event}:{g}",
                                generation=g, hosts=live,
                                world=len(live),
                                world_full=int(world_full))
                        except OSError:
                            log.exception("generation journal failed "
                                          "(continuing)")
                        log.warning(
                            "supervisor: published generation %d "
                            "(%s): hosts %s -> %s, world %d", g,
                            event, cur["hosts"], live, len(live))
                        cur, attempt, resume = newgen, 0, True
                        continue
                    got = _wait_generation_advance(
                        cdir, cur["generation"], window + 15.0)
                    if got is not None:
                        cur, attempt, resume = got, 0, True
                        continue
                    log.warning("supervisor: membership changed (%s -> "
                                "%s) but host %d never published a "
                                "generation; falling back to a plain "
                                "restart", cur["hosts"], live,
                                min(live))
            attempt += 1
            if attempt > max_restarts:
                log.error("supervisor: crash-loop guard: %d failure(s) "
                          "in generation %d; giving up (log: %s)",
                          attempt, cur["generation"], failure_log)
                return 1 if rc is None else rc
            delay = min(backoff_base * (2 ** (attempt - 1)), backoff_cap)
            verb = ("rewinding to" if rc == EXIT_NUMERIC
                    else "restarting from")
            log.warning("supervisor: child failed (%s); %s the newest "
                        "verified snapshot in %.1fs", reason, verb,
                        delay)
            time.sleep(delay)
            resume = True
    finally:
        beat.stop()
