from .timers import Timer
