"""Timers — device-accurate timing (reference util/benchmark.hpp Timer /
CPUTimer, which use CUDA events for GPU-accurate spans).

On TPU, accurate device timing means synchronizing on the arrays a span
produced: `Timer.stop(wait_on=...)` calls block_until_ready before reading
the clock, the JAX analogue of cudaEventSynchronize.
"""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self._start = None
        self._elapsed = 0.0
        self.running = False

    def start(self) -> None:
        self._start = time.perf_counter()
        self.running = True

    def stop(self, wait_on=None) -> float:
        """wait_on: array/pytree to block_until_ready before stopping —
        without it a span around dispatched-but-unfinished device work
        measures only dispatch latency."""
        if wait_on is not None:
            import jax
            jax.block_until_ready(wait_on)
        if self.running:
            self._elapsed += time.perf_counter() - self._start
            self.running = False
        return self._elapsed

    def seconds(self) -> float:
        if self.running:
            return self._elapsed + (time.perf_counter() - self._start)
        return self._elapsed

    def milliseconds(self) -> float:
        return self.seconds() * 1e3

    def reset(self) -> None:
        self._elapsed = 0.0
        self.running = False


CPUTimer = Timer  # host-side spans need no device sync; same interface
