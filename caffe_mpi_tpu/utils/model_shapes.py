"""Input-layer shape + synthetic-feed helpers shared by the bench tools
(bench.py, tools/bench_models.py, tools/mfu_analysis.py) — one definition
of "rewrite the Input batch dim and build matching feeds" instead of three
drifting copies."""

from __future__ import annotations


def input_shapes(npar, batch: int | None = None,
                 train_only: bool = True) -> dict[str, list[int]]:
    """{top: dims} for the net's Input layers. batch, when given, REWRITES
    the leading dim in-place (callers re-use the mutated NetParameter as
    the net definition). train_only skips TEST-phase-gated Input layers so
    the batch override and the feeds track the TRAIN net."""
    shapes: dict[str, list[int]] = {}
    for l in npar.layer:
        if l.type != "Input":
            continue
        if train_only and any(str(getattr(r, "phase", "")) == "TEST"
                              for r in (l.include or [])):
            continue
        decls = list(l.input_param.shape)
        if len(decls) == 1 and len(l.top) > 1:
            # one shape block broadcasts to every top, matching
            # InputLayer.setup (layers/data_layers.py)
            decls = decls * len(l.top)
        for top, shp in zip(l.top, decls):
            if batch:
                shp.dim[0] = batch
            shapes[top] = list(shp.dim)
    return shapes


def synthetic_feeds(shapes: dict[str, list[int]], n_classes: int = 1000,
                    seed: int = 0) -> dict:
    """Random on-device feeds matching input_shapes() output; 'label' tops
    get class ids in [0, n_classes)."""
    import jax.numpy as jnp
    import numpy as np

    r = np.random.RandomState(seed)
    feeds = {}
    for top, dims in shapes.items():
        if top == "label":
            feeds[top] = jnp.asarray(r.randint(0, n_classes, dims[0]))
        else:
            feeds[top] = jnp.asarray(r.randn(*dims).astype(np.float32))
    return feeds
