"""Input-layer shape + synthetic-feed helpers shared by the bench tools
(bench.py, tools/bench_models.py, tools/mfu_analysis.py) — one definition
of "rewrite the Input batch dim and build matching feeds" instead of three
drifting copies."""

from __future__ import annotations

# loss/metric layers whose SECOND bottom is an integer class-id vector
# (reference softmax_loss_layer.cpp etc.: label blob of shape [N])
_CLASSIFICATION_CONSUMERS = frozenset((
    "SoftmaxWithLoss", "Accuracy", "MultinomialLogisticLoss",
    "InfogainLoss", "HingeLoss",
))


def input_shapes(npar, batch: int | None = None,
                 train_only: bool = True) -> dict[str, list[int]]:
    """{top: dims} for the net's Input layers. batch, when given, REWRITES
    the leading dim in-place (callers re-use the mutated NetParameter as
    the net definition). train_only skips TEST-phase-gated Input layers so
    the batch override and the feeds track the TRAIN net."""
    shapes: dict[str, list[int]] = {}
    for l in npar.layer:
        if l.type != "Input":
            continue
        if train_only and any(str(getattr(r, "phase", "")) == "TEST"
                              for r in (l.include or [])):
            continue
        decls = list(l.input_param.shape)
        if len(decls) == 1 and len(l.top) > 1:
            # one shape block broadcasts to every top, matching
            # InputLayer.setup (layers/data_layers.py)
            decls = decls * len(l.top)
        for top, shp in zip(l.top, decls):
            if batch:
                shp.dim[0] = batch
            shapes[top] = list(shp.dim)
    return shapes


def label_tops(npar, shapes: dict[str, list[int]]) -> set[str]:
    """Tops that must be fed INTEGER class ids, detected structurally: a
    1-D blob consumed as the label bottom (bottom[1]) of a classification
    loss/metric layer. Name-independent — a net whose label top is called
    'target' or 'y' gets integer feeds too (ADVICE r5: the old literal
    'label' key match silently fed floats into integer-label losses)."""
    out = set()
    for l in npar.layer:
        if l.type in _CLASSIFICATION_CONSUMERS and len(l.bottom) > 1:
            b = l.bottom[1]
            if b in shapes and len(shapes[b]) == 1:
                out.add(b)
    return out


def synthetic_feeds(shapes: dict[str, list[int]], n_classes: int = 1000,
                    seed: int = 0, npar=None) -> dict:
    """Random on-device feeds matching input_shapes() output. Integer
    class-id feeds are chosen by CONSUMER when `npar` is given
    (label_tops above); without a net to inspect, any 1-D top is treated
    as a label vector — both structural, neither keyed on a blob name."""
    import jax.numpy as jnp
    import numpy as np

    ints = (label_tops(npar, shapes) if npar is not None
            else {t for t, dims in shapes.items() if len(dims) == 1})
    r = np.random.RandomState(seed)
    feeds = {}
    for top, dims in shapes.items():
        if top in ints:
            feeds[top] = jnp.asarray(r.randint(0, n_classes, dims[0]))
        else:
            feeds[top] = jnp.asarray(r.randn(*dims).astype(np.float32))
    return feeds
