"""Watched-subprocess containment for device work.

The TPU chip is single-claim and a dead tunnel hangs inside C++ jax
calls where no Python signal can run (CLAUDE.md). Every tool that
touches the device therefore runs the device work in a child process
with a hard deadline — and the child must be killpg'd AND reaped on
every exit path: an orphan keeps the chip claimed (every later probe
then hangs, indistinguishable from a dead tunnel), and an unreaped
zombie pollutes the `ps` sweep the operator uses to find claim holders.

Shared by tools/tpu_validation.py and tools/bench_models.py (bench.py
keeps subprocess.run: its child is the direct device process with no
grandchildren, and run() reaps on timeout).
"""

from __future__ import annotations

import atexit
import os
import signal
import subprocess

# pgids of live contained children: killed from atexit AND from
# SIGTERM/SIGINT — a `timeout`/`kill` on the PARENT otherwise leaves the
# child alive in its own session, holding the chip (observed live: the
# orphan claimed the TPU for >15 min and every probe looked tunnel-dead)
_ACTIVE: set[int] = set()
_HOOKED = False


def _reap_all(signum=None, frame=None):
    for pgid in list(_ACTIVE):
        try:
            os.killpg(pgid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    if signum is not None:  # re-deliver default behavior
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def _install_hooks():
    global _HOOKED
    if _HOOKED:
        return
    _HOOKED = True
    atexit.register(_reap_all)
    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
        try:
            signal.signal(sig, _reap_all)
        except (ValueError, OSError):  # non-main thread / unsupported
            pass


def run_contained(cmd: list[str], timeout: float | None,
                  cwd: str | None = None, env: dict | None = None,
                  echo: bool = False, tail_lines: int = 400):
    """Run cmd in its own process group with a hard deadline.

    Returns (returncode|None, stdout, stderr) — returncode None means
    the deadline expired. The group is SIGKILLed and the child reaped on
    every exit path, including the parent being SIGTERM'd.

    timeout=None disables the deadline (supervised training children:
    the in-child dispatch watchdog owns hang detection there, and a
    multi-hour run must not be killed by an arbitrary cap). echo=True
    streams the child's output to this process's stdout/stderr as it
    arrives (training logs stay live under supervision) while still
    returning the last `tail_lines` lines of each — memory stays bounded
    on runs that log for hours.
    """
    _install_hooks()
    # Mask the handled signals across Popen -> _ACTIVE.add: a SIGTERM
    # landing in that window would run _reap_all without knowing the new
    # child, leaking a chip-claiming orphan — the exact failure this
    # module exists to prevent. Caveat: pthread_sigmask masks THIS thread
    # only, so the window closes fully only for single-threaded callers
    # (tpu_validation, bench_models — the ones that matter); a
    # process-directed signal may still land on another unblocked thread.
    _sigs = {signal.SIGTERM, signal.SIGINT, signal.SIGHUP}
    try:
        prev_mask = signal.pthread_sigmask(signal.SIG_BLOCK, _sigs)
    except (ValueError, OSError):  # non-main thread restrictions etc.
        prev_mask = None
    try:
        proc = subprocess.Popen(cmd, cwd=cwd, env=env, text=True,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE,
                                start_new_session=True)
        _ACTIVE.add(proc.pid)
    finally:
        if prev_mask is not None:
            signal.pthread_sigmask(signal.SIG_SETMASK, prev_mask)
    try:
        if echo:
            rc, out, err = _pump_echo(proc, timeout, tail_lines)
            return rc, out, err
        out, err = proc.communicate(timeout=timeout)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        # child is now SIGKILLed: drain pipes and reap the zombie
        out, err = proc.communicate()
        return None, out, err
    finally:
        _kill_group(proc)
        proc.wait()
        _ACTIVE.discard(proc.pid)


def _pump_echo(proc: subprocess.Popen, timeout: float | None,
               tail_lines: int):
    """Mirror the child's pipes to this process's streams line by line,
    keeping only a bounded tail of each. Returns (rc|None, out_tail,
    err_tail) — rc None means the deadline expired (group killed, same
    contract as the communicate() path)."""
    import sys
    import threading
    from collections import deque

    tails = {"out": deque(maxlen=tail_lines), "err": deque(maxlen=tail_lines)}

    def pump(pipe, sink, key):
        for line in pipe:
            tails[key].append(line)
            sink.write(line)
            sink.flush()

    threads = [
        threading.Thread(target=pump, args=(proc.stdout, sys.stdout, "out"),
                         daemon=True),
        threading.Thread(target=pump, args=(proc.stderr, sys.stderr, "err"),
                         daemon=True),
    ]
    for t in threads:
        t.start()
    timed_out = False
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        _kill_group(proc)
        proc.wait()
    for t in threads:  # pipes hit EOF once the group is dead
        t.join(timeout=5)
    return (None if timed_out else proc.returncode,
            "".join(tails["out"]), "".join(tails["err"]))


def _kill_group(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
