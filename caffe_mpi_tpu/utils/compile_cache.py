# lint: ok(reference-citation) — TPU-native: the reference compiles AOT
# with nvcc and has no JIT compilation step to cache
"""Persistent XLA compilation cache setup (shared by the CLI and bench).

The AlexNet-class training step costs ~20-40s to compile on TPU; a warm
disk cache turns repeat invocations (and the bench's fresh-process retry)
into a cache hit. JAX_COMPILATION_CACHE_DIR overrides the default dir;
setting it to the empty string disables the cache entirely.
"""

from __future__ import annotations

import os

DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                           "caffe_mpi_tpu_xla")


def runtime_tag() -> str:
    """Version tag binding a serialized XLA executable to the runtime
    that produced it — jax + jaxlib versions plus the backend platform
    and device kind. The program bank (serving/program_bank.py) folds
    this into every entry fingerprint, so a jaxlib upgrade or a
    different accelerator silently misses the bank and recompiles
    instead of deserializing an incompatible program. Touches the
    backend (jax.devices()), so only call when device work is imminent
    — the netshape admission planner stays jax-free."""
    import jax
    import jaxlib
    dev = jax.devices()[0]
    jaxlib_ver = getattr(jaxlib, "__version__", "?")
    return (f"jax-{jax.__version__}/jaxlib-{jaxlib_ver}"
            f"/{dev.platform}/{dev.device_kind}")


def enable_compile_cache(default_dir: str = DEFAULT_DIR) -> str | None:
    """Returns the cache dir in use, or None when disabled/unsupported."""
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", default_dir)
    if not cache_dir:
        return None
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        return None  # older jax: cache flags absent
    return cache_dir
