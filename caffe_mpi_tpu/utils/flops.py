"""Analytic FLOPs / MFU accounting.

Replaces: nothing in the reference — Caffe-MPI reports img/s only
(solver.cpp:619-628). MFU (model FLOPs utilization: achieved FLOP/s over
the chip's peak) is the TPU-native efficiency metric: img/s depends on the
model, MFU says how much of the MXU the program actually keeps busy, which
is what XLA tuning moves.

The count is *model* FLOPs (the textbook cost of the layers, not whatever
the compiler executed): conv and matmul MACs only — elementwise/pool/norm
ops are HBM-bound noise next to the MXU terms. Backward costs 2x forward
(one matmul each for d-input and d-weight per forward matmul).
"""

from __future__ import annotations

import math


def layer_macs_per_image(layer) -> int:
    """Multiply-accumulates per image/sample for one layer (0 for
    non-MXU ops)."""
    t = layer.type_name
    if t == "Convolution":
        # weight (Cout, Cin/g, kh, kw); each output position costs
        # Cin/g*kh*kw MACs for each of Cout channels = weight.size
        _, _, oh, ow = layer.out_shapes[0]
        return math.prod(layer.params["weight"].shape) * oh * ow
    if t == "Deconvolution":
        _, _, ih, iw = layer.in_shapes[0]
        return math.prod(layer.params["weight"].shape) * ih * iw
    if t == "InnerProduct":
        # with axis > 1 the matmul applies per position: (N, *lead, K) ->
        # (N, *lead, out); MACs scale by the positions per sample
        positions = math.prod(layer.out_shapes[0][1:-1]) \
            if len(layer.out_shapes[0]) > 2 else 1
        return math.prod(layer.params["weight"].shape) * positions
    if t == "Attention":
        # per sample: QKV proj S*3C^2 + scores S^2*C + PV S^2*C
        # + out proj S*C^2  =  4*S*C^2 + 2*S^2*C
        _, s, c = layer.in_shapes[0]
        return 4 * s * c * c + 2 * s * s * c
    if t == "MoE":
        # per token: gate C*E + top_k expert FFNs (C*H + H*C)
        shape = layer.in_shapes[0]
        tokens = math.prod(shape[1:-1]) if len(shape) > 2 else 1
        c = shape[-1]
        e, _, h = layer.params["w1"].shape
        k = max(layer.p.top_k, 1)
        return tokens * (c * e + k * 2 * c * h)
    return 0


def net_macs_per_image(net) -> int:
    return sum(layer_macs_per_image(l) for l in net.layers)


def train_flops_per_image(net) -> int:
    """fwd (2 FLOPs/MAC) + bwd (2x fwd: d-input and d-weight matmuls)."""
    return 6 * net_macs_per_image(net)


# Peak dense-matmul FLOP/s per chip at the MXU's native precision
# (bf16 multiply, f32 accumulate) — the denominator for MFU. Sources:
# jax-ml.github.io/scaling-book hardware table / Google Cloud TPU docs.
PEAK_FLOPS_BY_KIND = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v4 lite": 138e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops(device) -> float | None:
    """Peak FLOP/s for a jax device, or None when the kind is unknown."""
    kind = getattr(device, "device_kind", "")
    if kind in PEAK_FLOPS_BY_KIND:
        return PEAK_FLOPS_BY_KIND[kind]
    # longest prefix wins: 'TPU v5 lite pod' must match 'TPU v5 lite',
    # not 'TPU v5'
    for k in sorted(PEAK_FLOPS_BY_KIND, key=len, reverse=True):
        if kind.startswith(k):
            return PEAK_FLOPS_BY_KIND[k]
    return None
