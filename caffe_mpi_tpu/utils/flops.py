"""Analytic FLOPs / MFU accounting.

Replaces: nothing in the reference — Caffe-MPI reports img/s only
(solver.cpp:619-628). MFU (model FLOPs utilization: achieved FLOP/s over
the chip's peak) is the TPU-native efficiency metric: img/s depends on the
model, MFU says how much of the MXU the program actually keeps busy, which
is what XLA tuning moves.

The count is *model* FLOPs (the textbook cost of the layers, not whatever
the compiler executed): conv and matmul MACs only — elementwise/pool/norm
ops are HBM-bound noise next to the MXU terms. Backward costs 2x forward
(one matmul each for d-input and d-weight per forward matmul).

The per-type MAC formulas live in proto/netshape.py (`macs_per_image`) —
ONE spelling shared with the jax-free netlint/summarize path (ISSUE 15);
this module adapts built Layer objects onto it for the bench tools.
"""

from __future__ import annotations


def layer_macs_per_image(layer) -> int:
    """Multiply-accumulates per image/sample for one built layer (0 for
    non-MXU ops). Delegates to the static engine's MAC model so the
    bench/MFU accounting and the prototxt-level analysis cannot drift."""
    from ..proto.netshape import macs_per_image
    macs = macs_per_image(
        layer.type_name, layer.in_shapes, layer.out_shapes,
        {name: tuple(decl.shape) for name, decl in layer.params.items()},
        layer.lp)
    return int(macs or 0)


def net_macs_per_image(net) -> int:
    return sum(layer_macs_per_image(l) for l in net.layers)


def train_flops_per_image(net) -> int:
    """fwd (2 FLOPs/MAC) + bwd (2x fwd: d-input and d-weight matmuls)."""
    return 6 * net_macs_per_image(net)


# Peak dense-matmul FLOP/s per chip at the MXU's native precision
# (bf16 multiply, f32 accumulate) — the denominator for MFU. Sources:
# jax-ml.github.io/scaling-book hardware table / Google Cloud TPU docs.
PEAK_FLOPS_BY_KIND = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v4 lite": 138e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops(device) -> float | None:
    """Peak FLOP/s for a jax device, or None when the kind is unknown."""
    kind = getattr(device, "device_kind", "")
    if kind in PEAK_FLOPS_BY_KIND:
        return PEAK_FLOPS_BY_KIND[kind]
    # longest prefix wins: 'TPU v5 lite pod' must match 'TPU v5 lite',
    # not 'TPU v5'
    for k in sorted(PEAK_FLOPS_BY_KIND, key=len, reverse=True):
        if kind.startswith(k):
            return PEAK_FLOPS_BY_KIND[k]
    return None
