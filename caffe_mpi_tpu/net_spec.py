"""NetSpec — programmatic net authoring (pycaffe net_spec parity).

Reference: python/caffe/net_spec.py (226 LoC): `n = caffe.NetSpec();
n.conv1 = L.Convolution(n.data, kernel_size=5, ...)` builds a NetParameter.
Same API here, emitting prototxt text through this framework's own schema,
so generated models round-trip through the parser used for hand-written
files. Used by the model zoo generators (reference models/modelBuilder/).
"""

from __future__ import annotations

from typing import Any

from .proto.text_format import PbEnum, PbNode

# LayerParameter sub-message field for each layer type (mirrors
# net_spec.py's param_name_dict derived from protobuf introspection).
_PARAM_FIELD = {
    "Accuracy": "accuracy_param", "ArgMax": "argmax_param",
    "BatchNorm": "batch_norm_param", "Bias": "bias_param",
    "Concat": "concat_param", "ContrastiveLoss": "contrastive_loss_param",
    "Convolution": "convolution_param", "Deconvolution": "convolution_param",
    "Crop": "crop_param", "Data": "data_param", "Dropout": "dropout_param",
    "Attention": "attention_param", "LayerNorm": "layer_norm_param",
    "MoE": "moe_param", "Parameter": "parameter_param",
    "DummyData": "dummy_data_param", "Eltwise": "eltwise_param",
    "ELU": "elu_param", "Embed": "embed_param", "Exp": "exp_param",
    "Flatten": "flatten_param", "HDF5Data": "hdf5_data_param",
    "HDF5Output": "hdf5_output_param", "HingeLoss": "hinge_loss_param",
    "ImageData": "image_data_param", "InfogainLoss": "infogain_loss_param",
    "InnerProduct": "inner_product_param", "Input": "input_param",
    "Log": "log_param", "LRN": "lrn_param", "MemoryData": "memory_data_param",
    "MVN": "mvn_param", "Pooling": "pooling_param", "Power": "power_param",
    "PReLU": "prelu_param", "Python": "python_param",
    "Reduction": "reduction_param", "ReLU": "relu_param",
    "Reshape": "reshape_param", "Scale": "scale_param",
    "Sigmoid": "sigmoid_param", "Slice": "slice_param",
    "Softmax": "softmax_param", "SoftmaxWithLoss": "softmax_param",
    "SPP": "spp_param", "TanH": "tanh_param", "Threshold": "threshold_param",
    "Tile": "tile_param", "WindowData": "window_data_param",
}

# kwargs that live directly on LayerParameter, not in the type sub-message
_TOP_LEVEL = {"name", "bottom", "top", "include", "exclude", "loss_weight",
              "param", "propagate_down", "phase", "transform_param",
              "loss_param", "forward_type", "backward_type", "forward_math",
              "backward_math", "ntop", "in_place"}

_ENUM_FIELDS = {"pool", "operation", "norm_region", "backend", "phase",
                "variance_norm", "norm", "round_mode"}


class Top:
    """A named output of a layer function call."""

    __slots__ = ("fn", "index", "_name")

    def __init__(self, fn: "LayerFn", index: int):
        self.fn = fn
        self.index = index
        self._name: str | None = None


def _to_value(v: Any) -> Any:
    if isinstance(v, bool) or isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        return v
    raise TypeError(f"cannot serialize {v!r}")


def _fill_node(node: PbNode, d: dict) -> None:
    for k, v in d.items():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for item in vals:
            if isinstance(item, dict):
                sub = PbNode()
                _fill_node(sub, item)
                node.add(k, sub)
            elif k in _ENUM_FIELDS and isinstance(item, str):
                node.add(k, PbEnum(item))
            else:
                node.add(k, _to_value(item))


import weakref

_ALL_FNS: list = []  # weakrefs to every constructed LayerFn (leak guard)


class LayerFn:
    """One layer invocation; `L.Convolution(bottom, num_output=...)`."""

    def __init__(self, type_name: str, args: tuple, kwargs: dict):
        self.type_name = type_name
        self.bottoms = [a for a in args if isinstance(a, Top)]
        self.kwargs = dict(kwargs)
        self.ntop = self.kwargs.pop("ntop", 1)
        self.in_place = self.kwargs.pop("in_place", False)
        # explicit layer name when it must differ from the top blob's name
        # (e.g. reference vgg16's layer "fc8-5" producing blob "fc8")
        self.layer_name = self.kwargs.pop("layer_name", None)
        self.tops = [Top(self, i) for i in range(self.ntop)]
        # zero-top layers (Silence, HDF5Output) still need a bindable handle
        self.handle = self.tops[0] if self.tops else Top(self, -1)
        _ALL_FNS.append(weakref.ref(self))

    def to_node(self, names: dict[Top, str], autonames: "_AutoNamer") -> PbNode:
        def resolve(top: Top) -> str:
            # in-place layers write into their bottom blob (pycaffe
            # net_spec semantics): references through the in-place top
            # resolve to the underlying blob name
            if top.fn.in_place:
                return resolve(top.fn.bottoms[0])
            return names[top]

        node = PbNode()
        node.add("name", self.layer_name or names.get(self.handle)
                 or autonames.get(self.type_name))
        node.add("type", self.type_name)
        for b in self.bottoms:
            node.add("bottom", resolve(b))
        for t in self.tops:
            node.add("top", resolve(t))
        sub_params: dict[str, Any] = {}
        for k, v in self.kwargs.items():
            if k in _TOP_LEVEL or k.endswith("_param"):
                if isinstance(v, dict):
                    sub = PbNode()
                    _fill_node(sub, v)
                    node.add(k, sub)
                else:
                    vals = v if isinstance(v, (list, tuple)) else [v]
                    for item in vals:
                        if isinstance(item, dict):
                            sub = PbNode()
                            _fill_node(sub, item)
                            node.add(k, sub)
                        elif k == "phase" or (k in _ENUM_FIELDS and isinstance(item, str)):
                            node.add(k, PbEnum(item))
                        else:
                            node.add(k, _to_value(item))
            else:
                sub_params[k] = v
        if sub_params:
            field = _PARAM_FIELD.get(self.type_name)
            if field is None:
                raise ValueError(
                    f"layer type {self.type_name!r} takes no inline params; "
                    "pass explicit *_param dicts")
            sub = PbNode()
            _fill_node(sub, sub_params)
            node.add(field, sub)
        return node


class _Layers:
    """`L.<Type>(*bottoms, **params)` factory namespace."""

    def __getattr__(self, type_name: str):
        def fn(*args, **kwargs):
            lf = LayerFn(type_name, args, kwargs)
            if lf.ntop == 0:
                return lf.handle  # bindable sentinel for zero-top layers
            return lf.tops[0] if lf.ntop == 1 else tuple(lf.tops)
        return fn


class _AutoNamer:
    def __init__(self):
        self.counts: dict[str, int] = {}

    def get(self, type_name: str) -> str:
        n = self.counts.get(type_name, 0) + 1
        self.counts[type_name] = n
        return f"{type_name.lower()}{n}"


L = _Layers()


class NetSpec:
    """Assign tops to attributes to name them; to_proto() emits prototxt."""

    def __init__(self, name: str = ""):
        object.__setattr__(self, "_tops", {})
        object.__setattr__(self, "net_name", name)

    def __setattr__(self, name: str, top: Top):
        if name.startswith("_") or name == "net_name":
            object.__setattr__(self, name, top)
            return
        self._tops[name] = top
        top._name = name

    def __getattr__(self, name: str) -> Top:
        try:
            return self._tops[name]
        except KeyError:
            raise AttributeError(name) from None

    def to_proto(self) -> PbNode:
        # collect all layer fns reachable from named tops, in dependency order
        fns: list[LayerFn] = []
        seen: set[int] = set()

        def visit(fn: LayerFn):
            if id(fn) in seen:
                return
            seen.add(id(fn))
            for b in fn.bottoms:
                visit(b.fn)
            fns.append(fn)

        for top in self._tops.values():
            visit(top.fn)

        # Guard against silently dropped layers: a constructed LayerFn that
        # consumes one of THIS spec's reachable tops but was never bound to
        # an attribute (e.g. a discarded in-place ReLU) would vanish from
        # the emitted net — error instead.
        reachable_tops = {t for fn in fns for t in fn.tops}
        alive = []
        for ref in _ALL_FNS:
            fn = ref()
            if fn is None:
                continue
            alive.append(ref)
            if id(fn) in seen:
                continue
            if any(b in reachable_tops for b in fn.bottoms):
                raise ValueError(
                    f"layer {fn.type_name!r} consumes this net's tops but is "
                    "not reachable from any named top — assign its output to "
                    "a NetSpec attribute (unassigned in-place layers are the "
                    "usual cause)"
                )
        _ALL_FNS[:] = alive  # prune dead weakrefs

        # name every top: named ones by attribute, others from layer name
        names: dict[Top, str] = {}
        autonames = _AutoNamer()
        for attr, top in self._tops.items():
            names[top] = attr
        for fn in fns:
            for t in fn.tops:
                if t not in names:
                    base = names.get(fn.tops[0])
                    names[t] = (f"{base}_{t.index}" if base
                                else autonames.get(fn.type_name))

        root = PbNode()
        if self.net_name:
            root.add("name", self.net_name)
        for fn in fns:
            root.add("layer", fn.to_node(names, autonames))
        return root

    def to_prototxt(self) -> str:
        return self.to_proto().to_text()
