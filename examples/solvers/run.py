#!/usr/bin/env python
"""Solver-zoo comparison (reference examples/solvers/: one recipe dir per
optimizer, trained by shell scripts). Here one command trains the SAME
tiny classification task under each of the six recipe prototxts and
self-asserts every optimizer converges (loss drops by >70%).

Usage: python examples/solvers/run.py
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)

import numpy as np  # noqa: E402

SOLVERS = ["sgd", "nesterov", "adagrad", "adadelta", "adam", "rmsprop"]


def main(argv=None) -> int:
    import jax.numpy as jnp

    from caffe_mpi_tpu.proto import SolverParameter
    from caffe_mpi_tpu.solver import Solver

    # a learnable 4-class problem: class = argmax of 4 fixed projections
    r = np.random.RandomState(0)
    w_true = r.randn(16, 4)
    xs = r.randn(8, 32, 16).astype(np.float32)
    data = [{"x": jnp.asarray(x),
             "t": jnp.asarray(np.argmax(x @ w_true, axis=1))} for x in xs]

    results = {}
    for name in SOLVERS:
        sp = SolverParameter.from_file(
            os.path.join(_HERE, name, "solver.prototxt"))
        solver = Solver(sp, model_dir=_ROOT)
        first = float(solver.step(1, lambda it: data[it % 8]))
        last = float(solver.step(sp.max_iter - 1, lambda it: data[it % 8]))
        results[name] = (first, last)
        status = "ok" if last < 0.3 * first else "NO CONVERGENCE"
        print(f"{name:>9}: loss {first:7.4f} -> {last:7.4f}  {status}")

    bad = [n for n, (f, l) in results.items() if l >= 0.3 * f]
    assert not bad, f"solvers failed to converge: {bad}"
    print("solvers example OK (6/6 converged)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
