// C++ classification example (reference examples/cpp_classification/
// classification.cpp:1 — a standalone C++ program that loads a deploy
// net + weights and prints the top-5 classes for an image).
//
// TPU-native design: the reference links libcaffe and runs the net's
// C++ forward; here the compute path is JAX/XLA, so the C++ program
// EMBEDS CPython and drives the same pycaffe Classifier the Python
// surface uses — the C++ application boundary the reference example
// demonstrates, with the XLA engine underneath.
//
// Build/run: examples/cpp_classification/run.py (compiles via
// python3-config flags, generates a toy deploy+weights+image, executes,
// and checks the output format).

#include <Python.h>

#include <cstdio>
#include <string>

static int fail(const char* msg) {
  if (PyErr_Occurred()) PyErr_Print();
  std::fprintf(stderr, "error: %s\n", msg);
  return 1;
}

int main(int argc, char** argv) {
  if (argc != 5) {
    std::fprintf(stderr,
                 "usage: %s deploy.prototxt weights.caffemodel "
                 "labels.txt img.png\n",
                 argv[0]);
    return 2;
  }
  Py_Initialize();

  // the repo root comes from the caller's PYTHONPATH (run.py sets it);
  // this file only appends the CWD for ad-hoc use
  PyRun_SimpleString("import sys; sys.path.insert(0, '.')");

  // one small driver: Classifier + PIL decode + top-5 print, identical
  // in spirit to the reference's Classifier::Classify + PrintTopN
  const char* driver =
      "import sys\n"
      "import numpy as np\n"
      "from PIL import Image\n"
      "import caffe_mpi_tpu.pycaffe as caffe\n"
      "def classify(model, weights, labels_path, img_path):\n"
      "    clf = caffe.Classifier(model, weights)\n"
      "    labels = [l.strip() for l in open(labels_path)]\n"
      "    img = np.asarray(Image.open(img_path).convert('RGB'),\n"
      "                     np.float32) / 255.0\n"
      "    preds = clf.predict([img], oversample=False)[0]\n"
      "    top = np.argsort(-preds)[:5]\n"
      "    return [(float(preds[i]),\n"
      "             labels[i] if i < len(labels) else str(int(i)))\n"
      "            for i in top]\n";

  PyObject* mod = PyImport_AddModule("__main__");
  PyObject* ns = PyModule_GetDict(mod);
  if (PyRun_String(driver, Py_file_input, ns, ns) == nullptr)
    return fail("driver definition failed");

  PyObject* fn = PyDict_GetItemString(ns, "classify");
  PyObject* out = PyObject_CallFunction(fn, "ssss", argv[1], argv[2],
                                        argv[3], argv[4]);
  if (out == nullptr) return fail("classification failed");

  // ---------- Prediction (reference classification.cpp output shape)
  Py_ssize_t n = PyList_Size(out);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyList_GetItem(out, i);
    double score = PyFloat_AsDouble(PyTuple_GetItem(pair, 0));
    PyObject* label = PyTuple_GetItem(pair, 1);
    std::printf("%.4f - \"%s\"\n", score, PyUnicode_AsUTF8(label));
  }
  Py_DECREF(out);
  Py_Finalize();
  return 0;
}
