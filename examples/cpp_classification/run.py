#!/usr/bin/env python
"""Build + exercise the C++ classification example end to end
(reference examples/cpp_classification/readme.md workflow): compile
classification.cc against the embedded CPython, generate a toy
deploy/weights/labels/image, run the binary, and assert it prints five
"score - "label"" lines with descending scores summing to ~1.

Usage: python examples/cpp_classification/run.py
"""

import os
import subprocess
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)

import numpy as np  # noqa: E402


def build(binary: str) -> None:
    cfg = lambda *a: subprocess.run(
        ["python3-config", *a], capture_output=True, text=True,
        check=True).stdout.split()
    cmd = ["g++", "-O2", os.path.join(_HERE, "classification.cc"),
           "-o", binary, *cfg("--includes"), *cfg("--ldflags", "--embed")]
    subprocess.run(cmd, check=True)


def main(argv=None) -> int:
    import caffe_mpi_tpu.pycaffe as caffe
    from PIL import Image

    with tempfile.TemporaryDirectory() as tmp:
        model = os.path.join(tmp, "deploy.prototxt")
        with open(model, "w") as f:
            f.write("""
name: "toy"
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 1 dim: 3 dim: 8 dim: 8 } } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param { num_output: 5
          weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "score" top: "prob" }
""")
        weights = os.path.join(tmp, "w.caffemodel")
        caffe.Net(model, caffe.TEST).save(weights)
        labels = os.path.join(tmp, "labels.txt")
        with open(labels, "w") as f:
            f.write("\n".join(f"class_{i}" for i in range(5)))
        img = os.path.join(tmp, "cat.png")
        Image.fromarray(np.random.RandomState(0).randint(
            0, 255, (12, 12, 3), np.uint8)).save(img)

        binary = os.path.join(tmp, "classification")
        build(binary)
        env = dict(os.environ,
                   PYTHONPATH=_ROOT + os.pathsep
                   + os.environ.get("PYTHONPATH", ""),
                   # the toy classify runs on the host CPU: the embedded
                   # interpreter must not dial a (possibly dead) remote
                   # TPU tunnel for a 5-class demo net
                   JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        r = subprocess.run([binary, model, weights, labels, img],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        print(r.stdout, end="")
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [l for l in r.stdout.splitlines() if " - " in l]
        assert len(lines) == 5, lines
        scores = [float(l.split(" - ")[0]) for l in lines]
        assert scores == sorted(scores, reverse=True)
        assert abs(sum(scores) - 1.0) < 1e-3
        assert all('"class_' in l for l in lines)
    print("cpp_classification example OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
