#!/usr/bin/env python
"""Net surgery through the pycaffe surface (mirrors the reference's
examples/net_surgery notebook + net_surgery/bvlc_caffenet_full_conv.prototxt):

1. designer filters — overwrite a conv kernel in place via
   net.params[...] and verify the forward reflects it;
2. the fc -> conv cast: transplant InnerProduct weights into convolution
   kernels of a "fully convolutional" variant and verify the conv net
   computes the original net *densely*: its output at grid cell (i, j)
   equals the original net applied to the corresponding input window.

Usage:
    python examples/net_surgery/run.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)

ORIG = """
name: "windownet"
# lint: ok(net-serve) — deliberately grayscale (1-channel) toy net for
# the net-surgery walkthrough; it is never served, so declining the
# RGB-only native ingest plan is expected
layer { name: "in" type: "Input" top: "data"
        input_param { shape { dim: 1 dim: 1 dim: 16 dim: 16 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 8 kernel_size: 5
          weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
        pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "fc" type: "InnerProduct" bottom: "pool1" top: "fc"
        inner_product_param { num_output: 16
          weight_filler { type: "xavier" } } }
layer { name: "relu2" type: "ReLU" bottom: "fc" top: "fc" }
layer { name: "score" type: "InnerProduct" bottom: "fc" top: "score"
        inner_product_param { num_output: 3
          weight_filler { type: "xavier" } } }
"""

# the fully-convolutional cast (reference bvlc_caffenet_full_conv.prototxt:
# fc6 -> fc6-conv kernel 6, fc7/fc8 -> 1x1 convs) on a 24x24 canvas
FULL_CONV = ORIG.replace(
    'dim: 16 dim: 16', 'dim: 24 dim: 24').replace(
    'name: "fc" type: "InnerProduct" bottom: "pool1" top: "fc"\n'
    '        inner_product_param { num_output: 16\n'
    '          weight_filler { type: "xavier" } } }',
    'name: "fc-conv" type: "Convolution" bottom: "pool1" top: "fc"\n'
    '        convolution_param { num_output: 16 kernel_size: 6\n'
    '          weight_filler { type: "xavier" } } }').replace(
    'name: "score" type: "InnerProduct" bottom: "fc" top: "score"\n'
    '        inner_product_param { num_output: 3\n'
    '          weight_filler { type: "xavier" } } }',
    'name: "score-conv" type: "Convolution" bottom: "fc" top: "score"\n'
    '        convolution_param { num_output: 3 kernel_size: 1\n'
    '          weight_filler { type: "xavier" } } }')


def main(argv=None) -> int:
    os.chdir(_ROOT)
    sys.path.insert(0, _ROOT)
    import caffe_mpi_tpu.pycaffe as caffe

    orig_path = os.path.join(_HERE, "windownet.prototxt")
    conv_path = os.path.join(_HERE, "windownet_full_conv.prototxt")
    with open(orig_path, "w") as f:
        f.write(ORIG)
    with open(conv_path, "w") as f:
        f.write(FULL_CONV)

    net = caffe.Net(orig_path, caffe.TEST)

    # -- act 1: designer filters (the notebook edits conv kernels) -------
    w = np.array(net.params["conv1"][0].data)
    w[0] = 0.0
    w[0, 0, 2, 2] = 1.0  # channel 0 becomes an identity tap
    net.params["conv1"][0].data = w
    net.params["conv1"][1].data = np.zeros_like(
        np.array(net.params["conv1"][1].data))
    r = np.random.RandomState(0)
    img = r.randn(1, 1, 16, 16).astype(np.float32)
    net.blobs["data"].data = img
    net.forward()
    got = net.blobs["conv1"].data[0, 0]
    np.testing.assert_allclose(got, np.maximum(img[0, 0, 2:-2, 2:-2], 0),
                               rtol=1e-5, atol=1e-6)
    print("act 1: hand-edited identity kernel verified through forward()")

    # -- act 2: cast the IP layers to convolutions ------------------------
    weights_path = os.path.join(_HERE, "windownet.caffemodel")
    net.save(weights_path)
    # conv1 transfers by name; the renamed fc/score heads stay at their
    # init until transplanted (CopyTrainedLayersFrom semantics)
    net_fc = caffe.Net(conv_path, weights_path, caffe.TEST)
    params = net.params
    fc_params = net_fc.params
    # IP (out, in*kh*kw) rows are Caffe-flattened (c, h, w) — reshape is
    # exactly the fc->conv cast from the notebook
    fc_params["fc-conv"][0].data = np.array(
        params["fc"][0].data).reshape(16, 8, 6, 6)
    fc_params["fc-conv"][1].data = np.array(params["fc"][1].data)
    fc_params["score-conv"][0].data = np.array(
        params["score"][0].data).reshape(3, 16, 1, 1)
    fc_params["score-conv"][1].data = np.array(params["score"][1].data)

    big = r.randn(1, 1, 24, 24).astype(np.float32)
    net_fc.blobs["data"].data = big
    net_fc.forward()
    dense = net_fc.blobs["score"].data  # (1, 3, 5, 5)
    assert dense.shape == (1, 3, 5, 5), dense.shape

    # dense output (i, j) == original net on the input window starting at
    # (2i, 2j) — the pool stride sets the effective window step
    for i, j in [(0, 0), (2, 3), (4, 4)]:
        net.blobs["data"].data = big[:, :, 2 * i:2 * i + 16,
                                     2 * j:2 * j + 16]
        net.forward()
        np.testing.assert_allclose(dense[0, :, i, j],
                                   net.blobs["score"].data[0],
                                   rtol=1e-4, atol=1e-5)
    print("act 2: fully-convolutional cast verified — dense scores match "
          "the original net slid over every window")
    print("PASS: net surgery workflows verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
