#!/usr/bin/env python
"""Train the tiny DetectNet end-to-end (mirrors the reference's
examples/kitti/detectnet_train.sh, which needs the KITTI dataset prepared
by DIGITS). Real KITTI is egress-blocked here, so scenes are synthetic:
bright rectangles ("cars", class 1) on dark noise; labels are
DIGITS-wire-format bbox blobs (layers/detection.py encode_label_blob),
transformed in-net by the DetectNetTransformation layer — crop/shift/
flip/hue augmentation plus the stride-8 coverage grid, exactly the
reference layer's role (detectnet_transform_layer.cpp).

Success criterion printed at the end: the trained coverage head must fire
inside true object cells and stay quiet outside (coverage-label
assertion), and the masked bbox L1 must have dropped.

Usage:
    python examples/kitti/run.py [-max_iter N]
"""

from __future__ import annotations

import argparse
import os
import sys
import getpass
import tempfile

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)

# DetectNetTransformation executes through jax.pure_callback; on a CPU
# backend with ONE device the callback machinery's internal device_put can
# deadlock against the single execution slot (layers/detection.py). Two
# virtual host devices give it a free slot; harmless under a TPU backend.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2"
                               ).strip()

IMG_H, IMG_W = 64, 128
MAX_BOXES = 8


def synthetic_scene(r: np.random.RandomState):
    """One scene: dark noise + 1-3 bright rectangles; returns (CHW float
    image, (n,5) [cls,x1,y1,x2,y2] bboxes)."""
    img = r.randint(0, 60, (3, IMG_H, IMG_W)).astype(np.float32)
    boxes = []
    for _ in range(r.randint(1, 4)):
        w, h = r.randint(20, 48), r.randint(12, 28)
        x1 = r.randint(0, IMG_W - w)
        y1 = r.randint(0, IMG_H - h)
        color = r.randint(170, 256, 3)[:, None, None]
        img[:, y1:y1 + h, x1:x1 + w] = color + r.randint(
            -15, 16, (3, h, w))
        boxes.append([1, x1, y1, x1 + w, y1 + h])
    return np.clip(img, 0, 255), np.asarray(boxes, np.float32)


def make_feed(batch: int, seed_base: int = 0):
    from caffe_mpi_tpu.layers.detection import encode_label_blob

    def feed(it):
        import jax.numpy as jnp
        r = np.random.RandomState(seed_base + it)
        imgs, labels = [], []
        for _ in range(batch):
            img, boxes = synthetic_scene(r)
            imgs.append(img)
            labels.append(encode_label_blob(boxes, MAX_BOXES))
        return {"data": jnp.asarray(np.stack(imgs)),
                "label": jnp.asarray(np.stack(labels))}
    return feed


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-max_iter", "--max_iter", type=int, default=600)
    args = p.parse_args(argv)

    os.chdir(_ROOT)
    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver

    # the reference detectnet_solver.prototxt recipe (Adam, fixed-ish lr),
    # scaled down
    # snapshot under tmp: a default ("snapshot") prefix would litter the
    # repo root with the after-train snapshot + run journal
    snap = os.path.join(tempfile.gettempdir(),
                        f"caffe_tpu_examples-{getpass.getuser()}",
                        "kitti", "snap")
    sp = SolverParameter.from_text(
        'type: "Adam" base_lr: 0.001 momentum: 0.9 momentum2: 0.999\n'
        'lr_policy: "fixed" display: 50\n'
        f'max_iter: {args.max_iter} random_seed: 3\n'
        f'snapshot_prefix: "{snap}"')
    sp.net_param = NetParameter.from_file(
        "examples/kitti/detectnet_tiny.prototxt")
    solver = Solver(sp)
    batch = solver.net.blob_shapes["data"][0]
    solver.solve(make_feed(batch))

    # evaluation on held-out scenes: coverage must localize the objects
    import jax
    eval_feed = make_feed(batch, seed_base=10_000)(0)
    blobs, _, _ = jax.jit(
        lambda p, s, f: solver.net.apply(p, s, f, train=False))(
            solver.params, solver.net_state, eval_feed)
    pred = np.asarray(blobs["coverage"])[:, 0]
    true = np.asarray(blobs["coverage-label"])[:, 0]
    inside = float(pred[true > 0.5].mean())
    outside = float(pred[true <= 0.5].mean())
    bbox_l1 = float(np.abs(np.asarray(blobs["bboxes-masked"])
                           - np.asarray(blobs["bbox-label"])).mean())
    print(f"coverage: mean {inside:.3f} inside objects vs {outside:.3f} "
          f"outside; masked bbox L1 {bbox_l1:.2f} px")
    ok = inside > 0.5 and inside > 4 * max(outside, 1e-3)
    print("PASS" if ok else "FAIL", ": coverage head localizes objects"
          if ok else ": coverage head failed to localize")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
