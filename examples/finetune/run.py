#!/usr/bin/env python
"""Finetuning + feature-extraction workflow, end to end (mirrors the
reference's examples/finetune_flickr_style + tools/extract_features.cpp):

1. pretrain a small CNN on a 10-class synthetic task; snapshot
   `.caffemodel`.
2. finetune on a related 5-class task twice — once initialized from the
   pretrained weights (feature tower transferred by layer-name matching,
   fresh renamed head at lr_mult 10, the flickr_style recipe) and once
   from scratch — and assert the finetuned run converges faster.
3. drive the extract_features tool on the finetuned weights and verify
   the dumped HDF5 activations bit-match a direct forward.

Usage:
    python examples/finetune/run.py [-pretrain_iter N] [-finetune_iter N]
"""

from __future__ import annotations

import argparse
import os
import sys
import getpass
import tempfile

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)


def net_text(head: str, classes: int, head_lr: float) -> str:
    tmpl = open(os.path.join(_HERE, "net.prototxt.tmpl")).read()
    return (tmpl.replace("{HEAD}", head)
            .replace("{CLASSES}", str(classes))
            .replace("{HEAD_LR}", str(head_lr)))


def make_feed(batch, coarse: bool, seed_base=0):
    """10-class cluster task; the finetune task is its 2-to-1 coarsening
    (labels // 2), so the pretrained features transfer."""
    from examples.common import synthetic_clusters
    imgs, labels = synthetic_clusters(4000, (1, 16, 16), seed=seed_base)
    import jax.numpy as jnp

    def feed(it):
        r = np.random.RandomState(seed_base + it)
        idx = r.randint(0, len(labels), batch)
        lab = labels[idx] // 2 if coarse else labels[idx]
        return {"data": jnp.asarray(imgs[idx].astype(np.float32) / 255.0),
                "label": jnp.asarray(lab)}
    return feed


def make_solver(text, max_iter, lr=0.05):
    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver
    # snapshot under tmp: a default ("snapshot") prefix would litter the
    # repo root with the after-train snapshot + run journal
    snap = os.path.join(tempfile.gettempdir(),
                        f"caffe_tpu_examples-{getpass.getuser()}",
                        "finetune", "snap")
    sp = SolverParameter.from_text(
        f'base_lr: {lr} momentum: 0.9 lr_policy: "fixed" '
        f'max_iter: {max_iter} display: 50 random_seed: 5 '
        f'snapshot_prefix: "{snap}"')
    sp.net_param = NetParameter.from_text(text)
    return Solver(sp)


def mean_loss(solver, feed, iters, window=10):
    # one big async run, then only the scored tail steps one-by-one —
    # per-iteration host syncs over the remote-TPU tunnel are the thing
    # CLAUDE.md forbids
    if iters > window:
        solver.step(iters - window, feed)
    losses = [float(solver.step(1, feed)) for _ in range(min(window, iters))]
    return float(np.mean(losses))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-pretrain_iter", type=int, default=300)
    p.add_argument("-finetune_iter", type=int, default=60)
    args = p.parse_args(argv)
    os.chdir(_ROOT)

    from caffe_mpi_tpu import io as caffe_io

    # 1. pretrain on the fine (10-class) task
    pre = make_solver(net_text("fc_pre", 10, 1), args.pretrain_iter)
    pre.solve(make_feed(32, coarse=False))
    weights_path = os.path.join(_HERE, "pretrained.caffemodel")
    caffe_io.save_caffemodel(
        weights_path,
        pre.net.export_weights(pre.params, pre.net_state),
        pre.net.name, {l.name: l.lp.type for l in pre.net.layers})
    print(f"pretrained -> {weights_path}")

    # 2. finetune vs from-scratch on the coarse (5-class) task
    ft_text = net_text("fc_style", 5, 10)
    feed = make_feed(32, coarse=True, seed_base=77)

    finetuned = make_solver(ft_text, args.finetune_iter, lr=0.01)
    fresh = {ln: {pn: np.asarray(a) for pn, a in lp.items()}
             for ln, lp in finetuned.params.items()}
    finetuned.load_weights(weights_path)  # the CLI's -weights path
    # the transfer CONTRACT is deterministic and is what this example
    # exists to demonstrate: every tower layer's weights now bit-match
    # the pretrained caffemodel (name-matched CopyTrainedLayersFrom),
    # while the renamed head kept its fresh initialization
    pre_w = caffe_io.load_weights(weights_path)
    for ln in ("conv1", "conv2", "feat"):
        np.testing.assert_array_equal(
            np.asarray(finetuned.params[ln]["weight"], np.float32),
            np.asarray(pre_w[ln][0], np.float32).reshape(
                np.shape(finetuned.params[ln]["weight"])),
            err_msg=f"tower layer {ln} did not transfer")
    assert np.array_equal(fresh["fc_style"]["weight"],
                          np.asarray(finetuned.params["fc_style"]["weight"])), \
        "renamed head must keep its fresh initialization"
    print("weight transfer verified: tower bit-matches the pretrained "
          "model, head fresh")
    ft_loss = mean_loss(finetuned, feed, args.finetune_iter)

    scratch = make_solver(ft_text, args.finetune_iter, lr=0.01)
    sc_loss = mean_loss(scratch, feed, args.finetune_iter)
    print(f"after {args.finetune_iter} iters: finetuned loss {ft_loss:.4f} "
          f"vs from-scratch {sc_loss:.4f}")

    # 3. extract features with the tool and verify the dump
    ft_weights = os.path.join(_HERE, "finetuned.caffemodel")
    caffe_io.save_caffemodel(
        ft_weights,
        finetuned.net.export_weights(finetuned.params, finetuned.net_state),
        finetuned.net.name,
        {l.name: l.lp.type for l in finetuned.net.layers})
    deploy = os.path.join(_HERE, "deploy_finetune.prototxt")
    with open(deploy, "w") as f:
        f.write(ft_text)
    out_h5 = os.path.join(_HERE, "features.h5")
    from caffe_mpi_tpu.tools.extract_features import main as extract_main
    rc = extract_main([ft_weights, deploy, "feat", out_h5, "3"])
    assert rc == 0, "extract_features failed"

    import h5py
    import jax
    import jax.numpy as jnp
    from caffe_mpi_tpu.net import Net
    from caffe_mpi_tpu.proto import NetParameter
    from caffe_mpi_tpu.tools.cli import _synthetic_feed
    with h5py.File(out_h5) as f:
        feats = np.asarray(f["feat"])
    net = Net(NetParameter.from_file(deploy), phase="TEST", model_dir=_HERE)
    params, state = net.init(jax.random.PRNGKey(0))
    params, state = net.import_weights(params, state,
                                       caffe_io.load_weights(ft_weights))
    want = np.concatenate([
        np.asarray(net.apply(params, state,
                             {k: jnp.asarray(v) for k, v in
                              _synthetic_feed(net, seed=it).items()},
                             train=False)[0]["feat"])
        for it in range(3)])
    # tool path is jitted, this check is not: XLA fusion reorders float
    # ops, so agreement is close-but-not-bitwise
    np.testing.assert_allclose(feats, want, rtol=1e-4, atol=1e-4)
    print(f"extract_features dump verified: {feats.shape} activations "
          "match a direct forward")

    # The finetuned-vs-scratch loss race is REPORTED, not asserted
    # (triaged in ISSUE 9, failing since seed): the synthetic cluster
    # task is linearly separable from raw pixels, so a fresh head on a
    # RANDOM tower converges as fast as on the pretrained one — measured
    # across pretrain {80..300} x finetune {30..60} x data scarcity
    # {64..4000 images} x noise {40..90}, the comparison is a coin flip
    # and at several scales transfer measurably LOSES (a weakly
    # pretrained tower is worse than msra init). The reference's
    # flickr_style claim rides ImageNet-scale features, which no
    # zero-egress synthetic stand-in reproduces; what the workflow
    # guarantees — and what this example now asserts above — is the
    # transfer contract itself plus the extract_features parity.
    faster = ft_loss < sc_loss
    print(f"finetuned {'beat' if faster else 'did not beat'} from-scratch "
          f"at this scale ({ft_loss:.4f} vs {sc_loss:.4f}; reported, "
          "not asserted — see triage note)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
