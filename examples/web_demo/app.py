"""Image-classification web demo (reference examples/web_demo/app.py).

Flask app serving a single endpoint that classifies an uploaded image with
a pycaffe Classifier. Flask is not part of the baked image; the app errors
with instructions if it is missing.

    python examples/web_demo/app.py -model deploy.prototxt -weights w.caffemodel
"""

import argparse
import io as _io
import sys

import numpy as np


def make_app(model: str, weights: str, labels_file: str | None = None):
    try:
        import flask
    except ImportError:
        raise SystemExit(
            "The web demo requires flask, which is not installed in this "
            "environment (pip install flask)."
        )
    import caffe_mpi_tpu.pycaffe as caffe

    clf = caffe.Classifier(model, weights)
    labels = None
    if labels_file:
        with open(labels_file) as f:
            labels = [l.strip() for l in f]

    app = flask.Flask(__name__)

    @app.route("/classify", methods=["POST"])
    def classify():
        from PIL import Image
        file = flask.request.files["image"]
        img = np.asarray(Image.open(_io.BytesIO(file.read())).convert("RGB"),
                         np.float32) / 255.0
        preds = clf.predict([img], oversample=False)[0]
        top = np.argsort(-preds)[:5]
        return flask.jsonify({
            "predictions": [
                {"label": labels[i] if labels else int(i),
                 "score": float(preds[i])} for i in top
            ]
        })

    @app.route("/")
    def index():
        return ("<form method=post action=/classify "
                "enctype=multipart/form-data>"
                "<input type=file name=image>"
                "<input type=submit value=Classify></form>")

    return app


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("-model", required=True)
    p.add_argument("-weights", required=True)
    p.add_argument("-labels", default=None)
    p.add_argument("-port", type=int, default=5000)
    args = p.parse_args()
    make_app(args.model, args.weights, args.labels).run(
        host="127.0.0.1", port=args.port)
