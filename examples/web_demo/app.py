"""Image-classification web demo (reference examples/web_demo/app.py).

The reference serves a Flask+Tornado app with an upload form and a
classify-by-URL endpoint around a pycaffe Classifier. Flask is not in
this image, and since ISSUE 7 the HTTP surface itself lives in the
framework (`caffe_mpi_tpu/serving/http_front.py`, stdlib http.server):
this demo is now a thin client that loads the model into a
ServingEngine — params device-resident, every padded batch bucket
AOT-compiled at load, concurrent uploads continuously batched — and
mounts the stock front-end on it. Same surface as before:

  GET  /                    upload form
  POST /classify            multipart/form-data file field "image", or a
                            raw image body (curl --data-binary)
  GET  /classify_path?path= classify a file under --image-root
  GET  /stats               serving telemetry (p50/p99, img/s, compiles)

Responses are JSON top-5 {label, score} like the reference's result
tuples.

    python examples/web_demo/app.py -model deploy.prototxt \
        -weights w.caffemodel [-labels synset.txt] [-port 5000]

The equivalent production entry point is
    python -m caffe_mpi_tpu.tools.cli serve -model ... -weights ...
"""

from __future__ import annotations

import argparse
from http.server import ThreadingHTTPServer


def make_server(model: str, weights: str, labels_file: str | None = None,
                image_root: str | None = None, port: int = 5000,
                host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Build the demo server (port=0 picks an ephemeral port — tests).

    Signature kept from the pre-engine demo; the engine is parked on the
    returned server as `.engine` so callers can close() it."""
    from caffe_mpi_tpu.serving import ServingEngine
    from caffe_mpi_tpu.serving.http_front import make_server as _front

    engine = ServingEngine()
    engine.load_model("default", model, weights or None)
    srv = _front(engine, "default", labels=labels_file,
                 image_root=image_root, port=port, host=host)
    srv.engine = engine
    return srv


if __name__ == "__main__":
    import os
    import sys
    _HERE = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.abspath(os.path.join(_HERE, "..", "..")))

    p = argparse.ArgumentParser()
    p.add_argument("-model", required=True)
    p.add_argument("-weights", required=True)
    p.add_argument("-labels", default=None)
    p.add_argument("-image-root", default=None,
                   help="allow GET /classify_path under this directory")
    p.add_argument("-port", type=int, default=5000)
    args = p.parse_args()
    srv = make_server(args.model, args.weights, args.labels,
                      args.image_root, args.port)
    print(f"serving on http://127.0.0.1:{srv.server_address[1]}")
    srv.serve_forever()
