#!/usr/bin/env python
"""Create cifar10_{train,test}_lmdb + mean.binaryproto.

Mirrors the reference's examples/cifar10/create_cifar10.sh +
convert_cifar_data.cpp (binary batches -> LMDB) + compute_image_mean.
With --synthetic, generates a separable 10-class 32x32x3 task instead —
same shapes, same wire formats — so the example runs without the dataset.

Usage:
    python examples/cifar10/create_cifar10.py [--dir examples/cifar10] \
        [--cifar-dir DIR_WITH_data_batch_N.bin] [--synthetic] \
        [--train-n 2000] [--test-n 500]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def synthetic_cifar(n: int, seed: int, classes: int = 10):
    from examples.common import synthetic_clusters
    return synthetic_clusters(n, (3, 32, 32), seed, classes)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    here = os.path.dirname(os.path.abspath(__file__))
    p.add_argument("--dir", default=here)
    p.add_argument("--cifar-dir", default=here,
                   help="directory holding data_batch_{1..5}.bin + "
                        "test_batch.bin")
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--train-n", type=int, default=2000)
    p.add_argument("--test-n", type=int, default=500)
    args = p.parse_args(argv)

    from caffe_mpi_tpu.data.datasets import CIFAR10Dataset, encode_datum
    from caffe_mpi_tpu.data.lmdb_io import write_lmdb
    from caffe_mpi_tpu.io import save_blob_binaryproto

    splits = {}
    if args.synthetic:
        splits["train"] = synthetic_cifar(args.train_n, seed=0)
        splits["test"] = synthetic_cifar(args.test_n, seed=1)
    else:
        train_batches = [os.path.join(args.cifar_dir, f"data_batch_{i}.bin")
                         for i in range(1, 6)]
        test_batch = os.path.join(args.cifar_dir, "test_batch.bin")
        missing = [f for f in train_batches + [test_batch]
                   if not os.path.exists(f)]
        if missing:
            print(f"missing {missing[0]} (+{len(missing) - 1} more); get the "
                  "CIFAR-10 binary batches, or pass --synthetic",
                  file=sys.stderr)
            return 1
        for split, paths in (("train", train_batches), ("test", [test_batch])):
            ds = CIFAR10Dataset(*paths)
            pairs = [ds.get(i) for i in range(len(ds))]  # single decode pass
            splits[split] = (np.stack([im for im, _ in pairs]),
                             np.asarray([lab for _, lab in pairs]))

    for split, (imgs, labels) in splits.items():
        db = os.path.join(args.dir, f"cifar10_{split}_lmdb")
        write_lmdb(db, ((f"{i:05d}".encode(), encode_datum(imgs[i],
                                                           int(labels[i])))
                        for i in range(len(labels))))
        print(f"wrote {len(labels)} records to {db}")

    # dataset mean over the TRAIN split (reference compute_image_mean)
    mean = splits["train"][0].astype(np.float64).mean(axis=0)
    mean_path = os.path.join(args.dir, "mean.binaryproto")
    save_blob_binaryproto(mean_path, mean.astype(np.float32)[None])
    print(f"wrote {mean_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
