#!/usr/bin/env python
"""Train CIFAR-10 quick end-to-end: create the DBs if needed, run
`caffe train` (mirrors the reference's examples/cifar10/train_quick.sh).
Falls back to the synthetic separable task when the CIFAR binaries are
absent, so the example always runs.

Usage:
    python examples/cifar10/run.py [-max_iter N] [-gpu all|id]
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.abspath(os.path.join(_HERE, "..", "..")))


def main(argv=None) -> int:
    from examples.common import run_example
    from examples.cifar10.create_cifar10 import main as create_main
    return run_example(
        _HERE,
        artifacts=["cifar10_train_lmdb", "cifar10_test_lmdb",
                   "mean.binaryproto"],
        create_main=create_main,
        real_marker="data_batch_1.bin",
        solver="examples/cifar10/cifar10_quick_solver.prototxt",
        argv=argv,
        # synthetic separable task reaches >=99% with this recipe in 150
        # iters (tests/test_convergence.py::test_cifar10_quick_99pct);
        # reference examples/cifar10 publishes ~75% on real CIFAR-10
        expect_acc=0.99, assert_min_iter=150)


if __name__ == "__main__":
    sys.exit(main())
