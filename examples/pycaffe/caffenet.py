"""Programmatic CaffeNet authoring with NetSpec (reference
examples/pycaffe/caffenet.py — same helper idioms: conv_relu, fc_relu,
max_pool composed into the full topology, then serialized to prototxt).
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, _ROOT)

from caffe_mpi_tpu.net_spec import L, NetSpec  # noqa: E402


def conv_relu(n, name, bottom, ks, nout, stride=1, pad=0, group=1):
    conv = L.Convolution(bottom, kernel_size=ks, stride=stride,
                         num_output=nout, pad=pad, group=group,
                         weight_filler=dict(type="gaussian", std=0.01))
    setattr(n, name, conv)
    setattr(n, "relu_" + name, L.ReLU(conv, in_place=True))
    return conv


def fc_relu(n, name, bottom, nout):
    fc = L.InnerProduct(bottom, num_output=nout,
                        weight_filler=dict(type="gaussian", std=0.005))
    setattr(n, name, fc)
    setattr(n, "relu_" + name, L.ReLU(fc, in_place=True))
    return fc


def max_pool(bottom, ks, stride=1):
    return L.Pooling(bottom, pool="MAX", kernel_size=ks, stride=stride)


def caffenet(batch_size=256, include_acc=False):
    """The CaffeNet topology as a prototxt string (Input-fed variant: the
    zero-egress image has no ImageNet LMDB; swap the Input layer for a
    Data layer to reproduce the reference's LMDB-fed version)."""
    n = NetSpec("CaffeNet")
    n.data, n.label = L.Input(ntop=2, input_param=dict(
        shape=[dict(dim=[batch_size, 3, 227, 227]),
               dict(dim=[batch_size])]))
    conv1 = conv_relu(n, "conv1", n.data, 11, 96, stride=4)
    n.pool1 = max_pool(conv1, 3, stride=2)
    n.norm1 = L.LRN(n.pool1, local_size=5, alpha=1e-4, beta=0.75)
    conv2 = conv_relu(n, "conv2", n.norm1, 5, 256, pad=2, group=2)
    n.pool2 = max_pool(conv2, 3, stride=2)
    n.norm2 = L.LRN(n.pool2, local_size=5, alpha=1e-4, beta=0.75)
    conv3 = conv_relu(n, "conv3", n.norm2, 3, 384, pad=1)
    conv4 = conv_relu(n, "conv4", conv3, 3, 384, pad=1, group=2)
    conv5 = conv_relu(n, "conv5", conv4, 3, 256, pad=1, group=2)
    n.pool5 = max_pool(conv5, 3, stride=2)
    fc6 = fc_relu(n, "fc6", n.pool5, 4096)
    n.drop6 = L.Dropout(fc6, in_place=True, dropout_ratio=0.5)
    fc7 = fc_relu(n, "fc7", n.drop6, 4096)
    n.drop7 = L.Dropout(fc7, in_place=True, dropout_ratio=0.5)
    n.fc8 = L.InnerProduct(n.drop7, num_output=1000,
                           weight_filler=dict(type="gaussian", std=0.01))
    n.loss = L.SoftmaxWithLoss(n.fc8, n.label)
    if include_acc:
        n.acc = L.Accuracy(n.fc8, n.label)
    return n.to_prototxt()


if __name__ == "__main__":
    print(caffenet())
