"""Euclidean loss authored as a Python layer (reference
examples/pycaffe/layers/pyloss.py — same arithmetic: L = sum(diff^2)/2N,
dbottom0 = +diff/N, dbottom1 = -diff/N), against this framework's
functional Python-layer protocol (layers/extension.py: infer_shapes /
forward / backward instead of the reference's setup/reshape mutation)."""

import numpy as np


class EuclideanLossLayer:
    def infer_shapes(self, bottom_shapes):
        if len(bottom_shapes) != 2:
            raise Exception("Need two inputs to compute distance.")
        if tuple(bottom_shapes[0]) != tuple(bottom_shapes[1]):
            raise Exception("Inputs must have the same dimension.")
        return [()]  # scalar loss

    def forward(self, bottoms):
        a, b = bottoms
        diff = a - b
        return [np.sum(diff ** 2) / a.shape[0] / 2.0]

    def backward(self, top_diffs, bottoms):
        a, b = bottoms
        g = np.asarray(top_diffs[0], np.float32)
        diff = (a - b) / a.shape[0]
        return [g * diff, -g * diff]
