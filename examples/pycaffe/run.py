#!/usr/bin/env python
"""pycaffe workflow example (reference examples/pycaffe/): author a net
programmatically with NetSpec and train through a user-defined Python
layer. Self-asserting:

1. caffenet.py's NetSpec output parses, builds, and its learnable layer
   names match the zoo's models/caffenet topology (the parity criterion
   used by tests/test_zoo_parity.py).
2. A regression net whose loss is the Python EuclideanLossLayer
   (layers/pyloss.py) trains to the SAME parameters as the built-in
   EuclideanLoss layer — the Python escape hatch is gradient-exact.

Usage: python examples/pycaffe/run.py
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)
sys.path.insert(0, _HERE)  # `layers.pyloss` importable for python_param

import numpy as np  # noqa: E402


def check_netspec_caffenet() -> None:
    from caffenet import caffenet

    from caffe_mpi_tpu.net import Net
    from caffe_mpi_tpu.proto import NetParameter

    net = Net(NetParameter.from_text(caffenet()), phase="TRAIN",
              data_shape_probe=lambda *a, **k: None)
    zoo = NetParameter.from_file(
        os.path.join(_ROOT, "models/caffenet/train_val.prototxt"))
    want = [l.name for l in zoo.layer
            if l.type in ("Convolution", "InnerProduct")]
    have = [l.name for l in net.layers
            if l.lp.type in ("Convolution", "InnerProduct")]
    assert have == want, f"layer names diverge: {have} vs {want}"
    print(f"NetSpec caffenet: {len(net.layers)} layers, learnable names "
          "match the zoo topology")


def check_python_loss() -> None:
    import jax.numpy as jnp

    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver

    base = """
    name: "lin_%s"
    layer { name: "in" type: "Input" top: "x" top: "t"
            input_param { shape { dim: 8 dim: 5 } shape { dim: 8 dim: 3 } } }
    layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y"
            inner_product_param { num_output: 3
              weight_filler { type: "xavier" } } }
    %s
    """
    builtin = base % ("builtin", 'layer { name: "loss" type: "EuclideanLoss" '
                      'bottom: "y" bottom: "t" top: "l" }')
    # loss_weight must be EXPLICIT for Python layers (same as the
    # reference: only built-in *Loss types imply loss_weight 1)
    pyloss = base % ("py", 'layer { name: "loss" type: "Python" '
                     'bottom: "y" bottom: "t" top: "l" loss_weight: 1 '
                     'python_param { module: "layers.pyloss" '
                     'layer: "EuclideanLossLayer" } }')

    def train(net_text):
        sp = SolverParameter.from_text(
            'base_lr: 0.1 momentum: 0.9 lr_policy: "fixed" max_iter: 20 '
            'type: "SGD" random_seed: 11')
        sp.net_param = NetParameter.from_text(net_text)
        solver = Solver(sp)
        r = np.random.RandomState(0)
        data = [{"x": jnp.asarray(r.randn(8, 5).astype(np.float32)),
                 "t": jnp.asarray(r.randn(8, 3).astype(np.float32))}
                for _ in range(4)]
        loss = solver.step(12, lambda it: data[it % 4])
        return np.asarray(solver.params["ip"]["weight"]), loss

    w_builtin, l_builtin = train(builtin)
    w_py, l_py = train(pyloss)
    np.testing.assert_allclose(w_py, w_builtin, rtol=1e-5, atol=1e-6)
    assert abs(l_py - l_builtin) < 1e-5
    print(f"Python EuclideanLossLayer: trajectory identical to the "
          f"built-in layer (final loss {l_py:.6f})")


def main(argv=None) -> int:
    check_netspec_caffenet()
    check_python_loss()
    print("pycaffe example OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
