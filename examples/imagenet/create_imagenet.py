#!/usr/bin/env python
"""Create ilsvrc12_{train,val}_lmdb + imagenet_mean.binaryproto.

Mirrors the reference's examples/imagenet/create_imagenet.sh +
make_imagenet_mean.sh: JPEG lists -> resized 256x256 Datum LMDBs -> mean
image. With --synthetic, generates a separable 1000-class (well, --classes)
256x256 task instead so the example runs without the dataset.

Usage (real data):
    python examples/imagenet/create_imagenet.py \
        --train-root /path/ilsvrc12/train --train-list train.txt \
        --val-root /path/ilsvrc12/val --val-list val.txt
Usage (no data):
    python examples/imagenet/create_imagenet.py --synthetic
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    here = os.path.dirname(os.path.abspath(__file__))
    p.add_argument("--dir", default=here)
    p.add_argument("--train-root", default=here,
                   help="JPEG root for the train list")
    p.add_argument("--train-list",
                   default=os.path.join(here, "train.txt")
                   if os.path.exists(os.path.join(here, "train.txt"))
                   else "")
    p.add_argument("--val-root", default=here)
    p.add_argument("--val-list",
                   default=os.path.join(here, "val.txt")
                   if os.path.exists(os.path.join(here, "val.txt"))
                   else "")
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--classes", type=int, default=10,
                   help="synthetic-task classes")
    p.add_argument("--train-n", type=int, default=512)
    p.add_argument("--val-n", type=int, default=128)
    args = p.parse_args(argv)

    from caffe_mpi_tpu.data.datasets import encode_datum
    from caffe_mpi_tpu.data.lmdb_io import write_lmdb
    from caffe_mpi_tpu.tools.compute_image_mean import main as mean_main

    if args.synthetic:
        from examples.common import synthetic_clusters
        for split, seed, n in (("train", 0, args.train_n),
                               ("val", 1, args.val_n)):
            # generate in chunks: at 3x256x256 a single 512-sample draw
            # peaks at multiple GB of transient int arrays
            def records():
                chunk = 64
                for base in range(0, n, chunk):
                    m = min(chunk, n - base)
                    imgs, labels = synthetic_clusters(
                        m, (3, 256, 256), seed * 1000 + base, args.classes)
                    for i in range(m):
                        yield (f"{base + i:08d}".encode(),
                               encode_datum(imgs[i], int(labels[i])))
            db = os.path.join(args.dir, f"ilsvrc12_{split}_lmdb")
            write_lmdb(db, records())
            print(f"wrote {n} records to {db}")
        mean_main([os.path.join(args.dir, "ilsvrc12_train_lmdb"),
                   os.path.join(args.dir, "imagenet_mean.binaryproto")])
        return 0
    else:
        from caffe_mpi_tpu.tools.convert_imageset import main as convert
        if not (args.train_list and args.val_list):
            print("need --train-list/--val-list (or --synthetic)",
                  file=sys.stderr)
            return 1
        for split, root, lst in (("train", args.train_root, args.train_list),
                                 ("val", args.val_root, args.val_list)):
            db = os.path.join(args.dir, f"ilsvrc12_{split}_lmdb")
            rc = convert(["-resize_height", "256", "-resize_width", "256",
                          "-shuffle", root, lst, db])
            if rc:
                return rc
        # dataset mean over the train split (make_imagenet_mean.sh ->
        # the in-repo compute_image_mean tool)
        mean_main([os.path.join(args.dir, "ilsvrc12_train_lmdb"),
                   os.path.join(args.dir, "imagenet_mean.binaryproto")])
    return 0


if __name__ == "__main__":
    sys.exit(main())
