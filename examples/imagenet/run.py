#!/usr/bin/env python
"""Train CaffeNet on ImageNet end-to-end: create the DBs if needed, run
`caffe train` (mirrors the reference's examples/imagenet/train_caffenet.sh).
Falls back to a synthetic 256x256 task when the JPEG lists are absent.

Usage:
    python examples/imagenet/run.py [-max_iter N] [-gpu all|id]
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.abspath(os.path.join(_HERE, "..", "..")))


def main(argv=None) -> int:
    from examples.common import run_example
    from examples.imagenet.create_imagenet import main as create_main
    return run_example(
        _HERE,
        artifacts=["ilsvrc12_train_lmdb", "ilsvrc12_val_lmdb",
                   "imagenet_mean.binaryproto"],
        create_main=create_main,
        real_marker="train.txt",
        solver="examples/imagenet/caffenet_solver.prototxt",
        argv=argv, synthetic_test_iter=3)


if __name__ == "__main__":
    sys.exit(main())
