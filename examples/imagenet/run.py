#!/usr/bin/env python
"""Train CaffeNet on ImageNet end-to-end: create the DBs if needed, run
`caffe train` (mirrors the reference's examples/imagenet/train_caffenet.sh).
Falls back to a synthetic 256x256 task when the JPEG lists are absent.

Usage:
    python examples/imagenet/run.py [-max_iter N] [-gpu all|id]
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.abspath(os.path.join(_HERE, "..", "..")))


def main(argv=None) -> int:
    from examples.common import run_example
    from examples.imagenet.create_imagenet import main as create_main
    return run_example(
        _HERE,
        artifacts=["ilsvrc12_train_lmdb", "ilsvrc12_val_lmdb",
                   "imagenet_mean.binaryproto"],
        create_main=create_main,
        real_marker="train.txt",
        solver="examples/imagenet/caffenet_solver.prototxt",
        argv=argv, synthetic_test_iter=3,
        # CaffeNet sits at chance through the early plateau (measured:
        # accuracy 0.1, loss ln(10) at iter 100 on the synthetic task —
        # round-5 CPU run); no run of assert_min_iter length has been
        # affordable on this 1-core host (~30 s/iter), so the bar is
        # deliberately a conservative "learning happened at all" check
        # (3x chance on 10 classes), not a convergence claim: 5000 iters
        # is ~850 epochs of the synthetic DB, and a net still at 0.1
        # there is defective. Tighten after a measured TPU-length run.
        expect_acc=0.3, assert_min_iter=5000)


if __name__ == "__main__":
    sys.exit(main())
