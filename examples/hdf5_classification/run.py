#!/usr/bin/env python
"""HDF5 classification, end to end (mirrors the reference's
examples/hdf5_classification notebook: generate a nonlinear 2-class
vector dataset, write train/test HDF5 files + list files, train the
2-layer MLP whose data comes from HDF5Data layers, report test
accuracy).

Usage:
    python examples/hdf5_classification/run.py [-max_iter N]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)


def make_data():
    """Nonlinear, not linearly separable 2-class task in 4-D (the
    reference notebook uses sklearn make_classification + a squared
    feature; here: label = sign of a quadratic form, zero egress)."""
    r = np.random.RandomState(0)
    X = r.randn(10_000, 4).astype(np.float32)
    y = ((X[:, 0] * X[:, 1] + X[:, 2] ** 2 - X[:, 3]) > 0).astype(np.int64)
    return (X[:8000], y[:8000]), (X[8000:], y[8000:])


def write_h5(split, X, y):
    import h5py
    d = os.path.join(_HERE, "data")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{split}.h5")
    with h5py.File(path, "w") as f:
        f.create_dataset("data", data=X)
        f.create_dataset("label", data=y.astype(np.float32))
    with open(os.path.join(d, f"{split}.txt"), "w") as f:
        # list file with a path relative to the list (hdf5_data_layer.cpp)
        f.write(f"{split}.h5\n")
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-max_iter", type=int, default=1000)
    args = p.parse_args(argv)
    os.chdir(_ROOT)

    (Xtr, ytr), (Xte, yte) = make_data()
    write_h5("train", Xtr, ytr)
    write_h5("test", Xte, yte)

    from caffe_mpi_tpu.proto import SolverParameter
    from caffe_mpi_tpu.solver import Solver
    from caffe_mpi_tpu.tools.cli import _build_feeders

    # the reference's hdf5_classification solver recipe
    sp = SolverParameter.from_text(
        'net: "examples/hdf5_classification/nonlinear_train_val.prototxt"\n'
        'test_iter: 250 test_interval: 1000\n'
        'base_lr: 0.01 momentum: 0.9 weight_decay: 0.0005\n'
        'lr_policy: "step" gamma: 0.1 stepsize: 5000\n'
        f'display: 500 max_iter: {args.max_iter} type: "SGD"')
    solver = Solver(sp)
    feed = _build_feeders(solver.net, "TRAIN")
    test_feed = _build_feeders(solver.test_nets[0], "TEST")
    solver.step(args.max_iter, feed)
    scores = solver.test_all([test_feed])[0]
    acc = scores["accuracy"]
    print(f"test accuracy after {args.max_iter} iters: {acc:.3f}")
    ok = acc > 0.75
    print("PASS" if ok else "FAIL",
          ": nonlinear HDF5 classification" + (" learned" if ok else
                                               " failed to learn"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
