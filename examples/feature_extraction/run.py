#!/usr/bin/env python
"""Feature extraction workflow, end to end (reference
examples/feature_extraction/readme.md + tools/extract_features.cpp):

1. build an image folder + "path label" file list (the readme's
   find/sed step), with synthetic images;
2. define an ImageData-fed extraction net (the readme patches CaffeNet's
   data layer into an ImageDataLayer the same way);
3. run the extract_features tool on an inner blob over N batches;
4. verify the dump: re-run the same forward directly and assert the
   stored activations match batch for batch.

Usage: python examples/feature_extraction/run.py [-batches N]
"""

import argparse
import os
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)

import numpy as np  # noqa: E402

NET = """
name: "feat_net"
layer { name: "data" type: "ImageData" top: "data" top: "label"
        transform_param { scale: 0.00390625 }
        image_data_param { source: "%s" batch_size: 4
                           new_height: 16 new_width: 16 } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 6 kernel_size: 3 stride: 2
          weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "fc6" type: "InnerProduct" bottom: "conv1" top: "fc6"
        inner_product_param { num_output: 10
          weight_filler { type: "xavier" } } }
"""


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-batches", type=int, default=3)
    args = p.parse_args(argv)

    import h5py
    import jax

    import caffe_mpi_tpu.pycaffe as caffe
    from caffe_mpi_tpu.data.feeder import feeder_from_layer
    from caffe_mpi_tpu.net import Net
    from caffe_mpi_tpu.proto import NetParameter
    from caffe_mpi_tpu.tools.extract_features import main as extract_main

    with tempfile.TemporaryDirectory() as tmp:
        # 1. images + file list (readme: find ... > temp.txt; sed 's/$/ 0/')
        from PIL import Image
        img_dir = os.path.join(tmp, "images")
        os.makedirs(img_dir)
        r = np.random.RandomState(0)
        listing = []
        for i in range(args.batches * 4):
            path = os.path.join(img_dir, f"img_{i:03d}.png")
            Image.fromarray(r.randint(0, 255, (20, 20, 3), np.uint8)
                            ).save(path)
            listing.append(f"{path} {i % 3}")
        file_list = os.path.join(tmp, "file_list.txt")
        with open(file_list, "w") as f:
            f.write("\n".join(listing) + "\n")

        # 2. the extraction net + randomly-initialized weights
        model = os.path.join(tmp, "extract.prototxt")
        with open(model, "w") as f:
            f.write(NET % file_list)
        weights = os.path.join(tmp, "w.caffemodel")
        caffe.Net(model, caffe.TEST).save(weights)

        # 3. the tool (reference: extract_features net proto blob db N)
        out_h5 = os.path.join(tmp, "features.h5")
        rc = extract_main([weights, model, "fc6", out_h5,
                           str(args.batches)])
        assert rc == 0

        # 4. verify against a direct forward over the same feeder order
        npar = NetParameter.from_file(model)
        net = Net(npar, phase="TEST", model_dir=tmp)
        params, state = net.init(jax.random.PRNGKey(0))
        from caffe_mpi_tpu.io import load_weights
        params, state = net.import_weights(params, state,
                                           load_weights(weights))
        feeder = feeder_from_layer(npar.layer[0], "TEST", model_dir=tmp)
        with h5py.File(out_h5, "r") as h5:
            dumped = np.asarray(h5["fc6"])
        got = []
        for it in range(args.batches):
            feeds = feeder(it)
            blobs, _, _ = net.apply(params, state, feeds, train=False,
                                    rng=None)
            got.append(np.asarray(blobs["fc6"]))
        feeder.close()
        direct = np.concatenate(got)
        assert dumped.shape == direct.shape, (dumped.shape, direct.shape)
        np.testing.assert_allclose(dumped, direct, rtol=1e-5, atol=1e-6)
        print(f"feature_extraction example OK: fc6 dump "
              f"{dumped.shape} matches direct forward")
    return 0


if __name__ == "__main__":
    sys.exit(main())
