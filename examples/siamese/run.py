#!/usr/bin/env python
"""Train the MNIST siamese network end-to-end (mirrors the reference's
examples/siamese/train_mnist_siamese.sh): paired inputs through two
weight-shared towers + ContrastiveLoss. Pairs come from the real MNIST
idx files when present in examples/mnist/, else from the synthetic
separable task — either way run.py always runs.

Usage:
    python examples/siamese/run.py [-max_iter N]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.abspath(os.path.join(_HERE, "..", ".."))
sys.path.insert(0, _ROOT)


def load_images():
    mnist_dir = os.path.join(_ROOT, "examples", "mnist")
    img_f = os.path.join(mnist_dir, "train-images-idx3-ubyte")
    lab_f = os.path.join(mnist_dir, "train-labels-idx1-ubyte")
    if os.path.exists(img_f) and os.path.exists(lab_f):
        from caffe_mpi_tpu.data import MNISTDataset
        ds = MNISTDataset(img_f, lab_f)
        pairs = [ds.get(i) for i in range(min(len(ds), 10000))]
        return (np.stack([im for im, _ in pairs]),
                np.asarray([lab for _, lab in pairs]))
    from examples.common import synthetic_clusters
    return synthetic_clusters(2000, (1, 28, 28), seed=0)


def pair_feed(imgs, labels, batch, seed_base=0):
    """The reference interleaves pair channels in one Datum
    (convert_mnist_siamese_data.cpp); here pairs are drawn on the fly:
    half same-class (sim=1), half different (sim=0)."""
    import jax.numpy as jnp
    n = len(labels)
    by_class = {c: np.where(labels == c)[0] for c in np.unique(labels)}
    classes = list(by_class)

    def feed(it):
        r = np.random.RandomState(seed_base + it)
        a_idx, b_idx, sim = [], [], []
        for k in range(batch):
            if k % 2 == 0:  # similar pair
                c = classes[r.randint(len(classes))]
                i, j = r.choice(by_class[c], 2)
                sim.append(1)
            else:           # dissimilar pair
                c1, c2 = r.choice(len(classes), 2, replace=False)
                i = r.choice(by_class[classes[c1]])
                j = r.choice(by_class[classes[c2]])
                sim.append(0)
            a_idx.append(i)
            b_idx.append(j)
        scale = 1.0 / 256.0
        return {"data": jnp.asarray(imgs[a_idx].astype(np.float32) * scale),
                "data_p": jnp.asarray(imgs[b_idx].astype(np.float32) * scale),
                "sim": jnp.asarray(np.asarray(sim, np.float32))}
    return feed


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-max_iter", "--max_iter", type=int, default=3000)
    args = p.parse_args(argv)

    os.chdir(_ROOT)
    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver

    # the reference's mnist_siamese_solver.prototxt recipe
    sp = SolverParameter.from_text(
        'base_lr: 0.01 momentum: 0.9 weight_decay: 0.0000\n'
        'lr_policy: "inv" gamma: 0.0001 power: 0.75\n'
        f'display: 100 max_iter: {args.max_iter} snapshot: {args.max_iter}\n'
        'snapshot_prefix: "examples/siamese/mnist_siamese" type: "SGD"')
    sp.net_param = NetParameter.from_file(
        "examples/siamese/mnist_siamese.prototxt")
    solver = Solver(sp)

    imgs, labels = load_images()
    batch = solver.net.blob_shapes["data"][0]
    solver.solve(pair_feed(imgs, labels, batch))

    # report embedding quality: mean same-class vs cross-class distance
    import jax.numpy as jnp
    feed = pair_feed(imgs, labels, batch, seed_base=10_000)
    blobs, _, _ = solver.net.apply(solver.params, solver.net_state,
                                   feed(0), train=False)
    d = np.linalg.norm(np.asarray(blobs["feat"])
                       - np.asarray(blobs["feat_p"]), axis=1)
    sim = np.asarray(feed(0)["sim"])
    print(f"mean embedding distance: similar pairs {d[sim == 1].mean():.3f}, "
          f"dissimilar pairs {d[sim == 0].mean():.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
