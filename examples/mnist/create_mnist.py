#!/usr/bin/env python
"""Create mnist_train_lmdb / mnist_test_lmdb.

Mirrors the reference's examples/mnist/create_mnist.sh +
convert_mnist_data.cpp (idx files -> LMDB of Datum records), using the
dependency-free LMDB writer. With --synthetic (or when the idx files are
absent and --synthetic is passed), generates a separable 10-class
28x28 task instead — same shapes, same wire format — so the example runs
in a zero-egress environment.

Usage:
    python examples/mnist/create_mnist.py [--dir examples/mnist] \
        [--synthetic] [--train-n 2000] [--test-n 500]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

IDX_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def synthetic_mnist(n: int, seed: int, classes: int = 10):
    from examples.common import synthetic_clusters
    return synthetic_clusters(n, (1, 28, 28), seed, classes)


def write_split(db_path: str, imgs, labels):
    from caffe_mpi_tpu.data.datasets import encode_datum
    from caffe_mpi_tpu.data.lmdb_io import write_lmdb

    write_lmdb(db_path, ((f"{i:08d}".encode(), encode_datum(imgs[i],
                                                            int(labels[i])))
                         for i in range(len(labels))))
    print(f"wrote {len(labels)} records to {db_path}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--dir", default=os.path.dirname(os.path.abspath(__file__)))
    p.add_argument("--synthetic", action="store_true",
                   help="generate a separable synthetic task instead of "
                        "reading idx files")
    p.add_argument("--train-n", type=int, default=2000)
    p.add_argument("--test-n", type=int, default=500)
    args = p.parse_args(argv)

    for split, seed, n in (("train", 0, args.train_n),
                           ("test", 1, args.test_n)):
        db = os.path.join(args.dir, f"mnist_{split}_lmdb")
        if args.synthetic:
            imgs, labels = synthetic_mnist(n, seed)
        else:
            from caffe_mpi_tpu.data import MNISTDataset
            img_f, lab_f = (os.path.join(args.dir, f)
                            for f in IDX_FILES[split])
            if not (os.path.exists(img_f) and os.path.exists(lab_f)):
                print(f"missing {img_f} / {lab_f}; download MNIST idx files "
                      "here, or pass --synthetic", file=sys.stderr)
                return 1
            ds = MNISTDataset(img_f, lab_f)
            pairs = [ds.get(i) for i in range(len(ds))]  # single decode pass
            imgs = np.stack([im for im, _ in pairs])
            labels = np.asarray([lab for _, lab in pairs])
        write_split(db, imgs, labels)
    return 0


if __name__ == "__main__":
    sys.exit(main())
