#!/usr/bin/env python
"""Train LeNet end-to-end: create the DBs if needed, run `caffe train`.

Mirrors the reference's examples/mnist/train_lenet.sh (which invokes
`caffe train -solver lenet_solver.prototxt` after create_mnist.sh). With
no MNIST idx files present, falls back to the synthetic separable task so
the example always runs.

Usage:
    python examples/mnist/run.py [-max_iter N] [-gpu all|id]
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.abspath(os.path.join(_HERE, "..", "..")))


def main(argv=None) -> int:
    from examples.common import run_example
    from examples.mnist.create_mnist import main as create_main
    return run_example(
        _HERE,
        artifacts=["mnist_train_lmdb", "mnist_test_lmdb"],
        create_main=create_main,
        real_marker="train-images-idx3-ubyte",
        solver="examples/mnist/lenet_solver.prototxt",
        argv=argv,
        # reference examples/mnist/readme.md publishes ~99.1%; the
        # synthetic stand-in task must hit the same bar (proven at 250
        # iters by tests/test_convergence.py::test_lenet_99pct)
        expect_acc=0.99, assert_min_iter=250)


if __name__ == "__main__":
    sys.exit(main())
