"""Shared driver for the runnable examples (mirrors the reference's
train_*.sh scripts: ensure the DBs exist, then exec `caffe train`)."""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                     ".."))


def synthetic_clusters(n: int, shape: tuple, seed: int, classes: int = 10,
                       template_seed: int = 42, noise: int = 40):
    """Separable cluster task: one fixed random uint8 template per class
    (shared across splits via template_seed), samples = template + pixel
    noise. The zero-egress stand-in for MNIST/CIFAR in the examples AND
    the convergence tests — one definition so the tests prove the task the
    examples actually run."""
    import numpy as np
    templates = np.random.RandomState(template_seed).randint(
        0, 256, (classes, *shape))
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    delta = rng.randint(-noise, noise + 1, (n, *shape))
    imgs = np.clip(templates[labels] + delta, 0, 255).astype("uint8")
    return imgs, labels


def run_example(here: str, artifacts: list[str], create_main,
                real_marker: str, solver: str, argv=None,
                synthetic_test_iter: int = 0, expect_acc: float = 0.0,
                assert_min_iter: int = 0) -> int:
    """Create missing dataset artifacts, then run `caffe train -solver ...`.

    artifacts: every file/dir the net prototxt needs (train+test DBs, mean
    file, ...) — creation re-runs unless ALL exist, so a partially-created
    dataset is repaired. real_marker: a file whose presence means the real
    dataset is available (else --synthetic). synthetic_test_iter: when the
    synthetic fallback is active, shrink the recipe's eval length to this
    (a 1000-iter eval over a few hundred synthetic records just cycles the
    tiny DB for no information).

    expect_acc: the example's success criterion on the SYNTHETIC task —
    the final test accuracy (the trailing TestAll `caffe train` now runs,
    like the reference's Solve) must reach this, the way the reference's
    example readmes publish expected accuracies (examples/mnist/readme.md:
    ~99.1%). Enforced only when the run is at least assert_min_iter
    iterations (the documented convergence length for the synthetic task);
    shorter smoke runs report the accuracy without failing.
    """
    sys.path.insert(0, _ROOT)
    p = argparse.ArgumentParser()
    p.add_argument("-max_iter", "--max_iter", type=int, default=0,
                   help="override solver max_iter (0 = use the prototxt)")
    p.add_argument("-gpu", "--gpu", default="",
                   help="forwarded to caffe train (e.g. 'all')")
    args = p.parse_args(argv)

    have_real = os.path.exists(os.path.join(here, real_marker))
    if not all(os.path.exists(os.path.join(here, a)) for a in artifacts):
        rc = create_main([] if have_real else ["--synthetic"])
        if rc:
            return rc

    from caffe_mpi_tpu.tools.cli import main as caffe_main
    cli = ["train", "-solver", solver]
    if args.max_iter:
        cli += ["-max_iter", str(args.max_iter)]
    if not have_real and synthetic_test_iter:
        cli += ["-test_iter", str(synthetic_test_iter)]
    if args.gpu:
        cli += ["-gpu", args.gpu]
    os.chdir(_ROOT)  # solver paths are repo-relative, like the reference's

    import logging
    accs: list[float] = []
    handler = None
    solver_log = logging.getLogger("caffe_mpi_tpu.solver")
    prev_level = solver_log.level
    if expect_acc and not have_real:
        class _CaptureScores(logging.Handler):
            def emit(self, rec):
                # Solver.test_all: log.info("    Test net #%d: %s = %.5g",
                # ti, blob, value)
                a = rec.args
                if a and len(a) == 3 and a[1] == "accuracy":
                    accs.append(float(a[2]))
        handler = _CaptureScores()
        solver_log.addHandler(handler)
        # pin the logger's own level: cli.main's basicConfig is a NO-OP
        # when a host process (pytest) already configured the root
        # logger, leaving the effective level at WARNING — the INFO
        # score lines were then filtered before this handler ever ran,
        # and the self-assert reported "no test evaluation ran" even
        # though evaluation DID run (the standing mnist/finetune
        # failure since seed)
        solver_log.setLevel(logging.INFO)
    try:
        rc = caffe_main(cli)
    finally:
        if handler is not None:
            solver_log.removeHandler(handler)
            solver_log.setLevel(prev_level)
    if rc == 0 and handler is not None:
        from caffe_mpi_tpu.proto import SolverParameter
        ran = args.max_iter or SolverParameter.from_file(
            os.path.join(_ROOT, solver)).max_iter
        if accs and ran >= assert_min_iter:
            if accs[-1] < expect_acc:
                print(f"FAILED self-assert: final synthetic accuracy "
                      f"{accs[-1]:.4f} < {expect_acc} after {ran} iters")
                return 1
            print(f"self-assert OK: final synthetic accuracy "
                  f"{accs[-1]:.4f} >= {expect_acc}")
        elif accs:
            print(f"(short run: {ran} < {assert_min_iter} iters — final "
                  f"synthetic accuracy {accs[-1]:.4f}, threshold "
                  f"{expect_acc} not enforced)")
        elif ran >= assert_min_iter:
            # a run long enough that the threshold WOULD be enforced
            # produced zero accuracy records: the evaluation never ran
            # (test_interval/test net misconfigured, or the score-capture
            # hook broke). Passing silently here would turn the example's
            # convergence guarantee into a no-op — fail instead.
            print(f"FAILED self-assert: no test evaluation ran in {ran} "
                  f"iters (expected a final accuracy >= {expect_acc}); "
                  "check test_interval / test nets")
            return 1
        else:
            print(f"self-assert: no test evaluation ran in {ran} iters "
                  "(short run; accuracy threshold not checked)")
    return rc
