#!/usr/bin/env python
"""Benchmark — prints ONE JSON line with the headline metric.

Metric: AlexNet training throughput (img/s) at batch 256 on one chip —
f32 parameter storage and accumulation, MXU multiplies at XLA default
precision (the TPU analogue of NVCaffe's tensor-op math override; forcing
full-f32 multiplies via `default_forward_math: FLOAT` measures ~half).
Baseline: the reference's only published absolute throughput — CaffeNet,
20 iterations x 256 images in 19.2 s with cuDNN on a Tesla K40
(docs/performance_hardware.md:17-24) = 266.7 img/s; the 16-GPU results are
speedups over this class of single-GPU run (BASELINE.md).
vs_baseline = ours / 266.7.

The full training step — forward, backward, SGD+momentum update — runs as
one jit-compiled XLA program, the same path `caffe train` uses.
"""

import json
import os
import sys
import time

import numpy as np

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _ROOT)

BASELINE_IMG_S = 256 * 20 / 19.2  # K40 + cuDNN, reference docs


def main():
    import jax
    import jax.numpy as jnp

    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver

    batch = 256
    sp = SolverParameter.from_file(
        os.path.join(_ROOT, "models/alexnet/solver.prototxt"))
    sp.max_iter = 10**9
    sp.display = 0
    sp.snapshot = 0
    sp.test_interval = 0
    solver = Solver(sp, model_dir=_ROOT)

    r = np.random.RandomState(0)
    feeds = {
        "data": jnp.asarray(r.randn(batch, 3, 227, 227).astype(np.float32)),
        "label": jnp.asarray(r.randint(0, 1000, batch)),
    }
    feed_fn = lambda it: feeds

    # warmup (compile + first steps)
    solver.step(3, feed_fn)
    jax.block_until_ready(solver.params)

    iters = 20
    t0 = time.perf_counter()
    solver.step(iters, feed_fn)
    jax.block_until_ready(solver.params)
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    # f32 storage/accumulation; MXU multiplies at XLA default precision —
    # the TPU analogue of NVCaffe's tensor-op math override. Forcing
    # full-f32 multiplies (default_forward_math: FLOAT) measures ~half this.
    print(json.dumps({
        "metric": "alexnet_b256_train_img_per_s_1chip",
        "value": round(img_s, 1),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 2),
    }))


if __name__ == "__main__":
    # one retry IN A FRESH PROCESS: the TPU tunnel in this environment
    # occasionally drops a claim, and jax caches the dead PJRT client, so
    # an in-process retry would reuse the broken connection
    try:
        main()
    except Exception:
        import subprocess
        import traceback
        traceback.print_exc()
        if os.environ.get("CAFFE_TPU_BENCH_RETRY") == "1":
            sys.exit(1)
        print("bench attempt 1 failed; retrying in a fresh process",
              file=sys.stderr)
        time.sleep(30)
        env = dict(os.environ, CAFFE_TPU_BENCH_RETRY="1")
        sys.exit(subprocess.run([sys.executable, __file__], env=env).returncode)
