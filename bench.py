#!/usr/bin/env python
"""Benchmark — prints ONE JSON line with the headline metric.

Metric: AlexNet training throughput (img/s) at batch 256 on one chip —
f32 parameter storage and accumulation, MXU multiplies at XLA default
precision (the TPU analogue of NVCaffe's tensor-op math override; forcing
full-f32 multiplies via `default_forward_math: FLOAT` measures ~half).
Baseline: the reference's only published absolute throughput — CaffeNet,
20 iterations x 256 images in 19.2 s with cuDNN on a Tesla K40
(docs/performance_hardware.md:17-24) = 266.7 img/s; the 16-GPU results are
speedups over this class of single-GPU run (BASELINE.md).
vs_baseline = ours / 266.7. Also reports MFU: analytic fwd+bwd model FLOPs
(caffe_mpi_tpu/utils/flops.py) over measured step time and chip peak.

The full training step — forward, backward, SGD+momentum update — runs as
one jit-compiled XLA program, the same path `caffe train` uses.

Failure containment (the TPU here sits behind a flaky tunnel, and a dead
tunnel HANGS inside C++ device calls, where no Python signal handler can
run): ALL device work happens in watched subprocesses — a cheap probe
first, then the bench body — each with a hard subprocess timeout. The
parent never touches the device, so it always emits the JSON line
(value: null + error on failure) within the total budget.
"""

import json
import math
import os
import subprocess
import sys
import time

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _ROOT)

BASELINE_IMG_S = 256 * 20 / 19.2  # K40 + cuDNN, reference docs
PROBE_DEADLINE_S = 90       # tiny device op, incl. client init + tunnel RTT
TOTAL_BUDGET_S = 600        # hard cap: probe + compile (~40s) + 23 steps
                            # x2 phases (f32 + the ISSUE 9 bf16 variant)
_IS_CHILD = os.environ.get("CAFFE_TPU_BENCH_CHILD") == "1"

# debug/staged knobs (the headline metric is always AlexNet f32 batch 256,
# 20 iters, step_chunk 10; overriding any knob renames the metric so an
# alternate line can't be mistaken for it). Staged configs for a hardware
# window (docs/mfu_analysis.md): CAFFE_BENCH_DTYPE=bf16 switches to the
# fp16 prototxt variant (FLOAT16 -> bf16 storage, f32 master weights);
# CAFFE_BENCH_MODEL=resnet50 benches the north-star topology.
# CAFFE_BENCH_STEP_CHUNK: iterations fused into one lax.scan dispatch
# (solver step_chunk; 20 timed iters at K=10 = 2 host dispatches instead
# of 20 — over the tunnel, 2 RTTs instead of 20). Set 1 for the classic
# per-iteration dispatch mode.
BATCH = int(os.environ.get("CAFFE_BENCH_BATCH", 256))
WARMUP = int(os.environ.get("CAFFE_BENCH_WARMUP", 3))
ITERS = int(os.environ.get("CAFFE_BENCH_ITERS", 20))
MODEL = os.environ.get("CAFFE_BENCH_MODEL", "alexnet")
DTYPE = os.environ.get("CAFFE_BENCH_DTYPE", "f32")
STEP_CHUNK = max(int(os.environ.get("CAFFE_BENCH_STEP_CHUNK", 10)), 1)
# fused-eval telemetry phase (untimed; CAFFE_BENCH_EVAL=0 skips): 2 test
# boundaries overlapped with training, test_iter batches per pass fused
# at test_chunk batches per eval dispatch
EVAL_TEST_ITER = int(os.environ.get("CAFFE_BENCH_TEST_ITER", 8))
EVAL_TEST_CHUNK = int(os.environ.get("CAFFE_BENCH_TEST_CHUNK", 4))
# CAFFE_BENCH_GUARD: the on-device non-finite guard (ISSUE 4,
# solver train_guard). Default ON for the headline so the "guard is
# ~free on device" claim is what the committed number actually
# measures — the same program with per-step finiteness selects in the
# scan. skipped_steps / guard_syncs in the JSON are the CPU-visible
# proxies (0 skips expected on synthetic data; guard_syncs = chunk
# boundaries, each a 5-scalar transfer). Set 0 for the unguarded
# program (renames the metric like every other knob).
GUARD = os.environ.get("CAFFE_BENCH_GUARD", "1") != "0"
# CAFFE_BENCH_MESH=all: run the headline config data-parallel over every
# visible device with the overlapped bucketed reduction engaged (ISSUE 6,
# solver reduce_overlap — parallel/reduction.py). The JSON line then
# carries a "reduction" block: collectives_per_step + bucket_bytes from
# the active plan and the HLO overlap-span proxy
# (reduction.collective_stats over the compiled step). Default "" keeps
# the 1-chip headline program unchanged; setting it renames the metric
# like every other knob.
MESH = os.environ.get("CAFFE_BENCH_MESH", "")
# CAFFE_BENCH_BF16: the mixed-precision headline variant (ISSUE 9,
# solver `precision` knob — docs/benchmarks.md "Mixed-precision bf16
# training"). Default ON: after the f32 headline region is banked
# (bitwise-untouched — the bf16 phase builds its OWN solver from a
# fresh parse of the same recipe), the child re-runs the same
# model/batch/step_chunk with `precision: bf16` + dynamic loss scaling
# and attaches a "bf16" block: img/s, MFU, speedup vs the f32 number,
# loss-scale/overflow counters, and (under CAFFE_BENCH_MESH=all) its
# own "reduction" block whose bucket_bytes are HALF the f32 ones (bf16
# wire). Set 0 to skip the phase; the headline metric is unaffected
# either way.
BF16 = os.environ.get("CAFFE_BENCH_BF16", "1") != "0"
# CAFFE_BENCH_SERVING: the inference-serving telemetry block (ISSUE 7,
# caffe_mpi_tpu/serving/ — docs/serving.md). Default ON: the parent
# runs tools/bench_serving.py in its own watched subprocess (CPU-forced
# inside that script, so a dead tunnel cannot hang it) and attaches its
# JSON — p50/p99 latency, sustained img/s, and the zero-recompile proof
# (compile_count == warmed buckets across a mixed-size trace on two
# resident models) — to the emitted line, headline success or not. The
# headline metric itself is untouched (separate process, untimed).
SERVING = os.environ.get("CAFFE_BENCH_SERVING", "1") != "0"
SERVING_DEADLINE_S = 180
# CAFFE_BENCH_INGEST: the host-ingestion telemetry block (ISSUE 10,
# native/decode.cc + data/decode.py — docs/benchmarks.md "Ingestion").
# Default ON: the parent runs `bench_data --ingest-only --json` in its
# own watched subprocess (CPU-only, no jax import, so a dead tunnel
# cannot touch it) and attaches the `ingest` JSON — per-stage ms/batch
# (read/crc/decode/transform/assemble over a JPEG-encoded LMDB), the
# PIL-vs-native-fused img/s A/B, and the decoded-cache epoch-2 rate —
# to the emitted line on every path, headline success or not. The
# headline metric itself is untouched (separate process, untimed).
INGEST = os.environ.get("CAFFE_BENCH_INGEST", "1") != "0"
INGEST_DEADLINE_S = 240
_SOLVERS = {
    ("alexnet", "f32"): "models/alexnet/solver.prototxt",
    ("alexnet", "bf16"): "models/alexnet/solver_fp16.prototxt",
    ("resnet50", "f32"): "models/resnet50/solver.prototxt",
    ("resnet50", "bf16"): "models/resnet50/solver_fp16.prototxt",
}
# BF16 deliberately absent from the debug-rename tuple: the bf16 phase
# runs after the f32 region and cannot perturb the headline number
_IS_DEBUG = (BATCH, ITERS, WARMUP, MODEL, DTYPE, STEP_CHUNK,
             EVAL_TEST_ITER, EVAL_TEST_CHUNK, GUARD, MESH) != (
                 256, 20, 3, "alexnet", "f32", 10, 8, 4, True, "")
METRIC = ("alexnet_b256_train_img_per_s_1chip" if not _IS_DEBUG
          else f"debug_{MODEL}_{DTYPE}_b{BATCH}_i{ITERS}_k{STEP_CHUNK}"
               f"{'' if GUARD else '_noguard'}"
               f"{f'_mesh_{MESH}' if MESH else ''}_train_img_per_s_1chip")


def emit(value=None, vs_baseline=None, extra=None, error=None):
    line = {"metric": METRIC, "value": value, "unit": "img/s",
            "vs_baseline": vs_baseline}
    if extra:
        line.update(extra)
    if error:
        line["error"] = error
    print(json.dumps(line))
    sys.stdout.flush()


def probe():
    """Touch the device from a THROWAWAY process with a deadline. A dead
    tunnel makes the first jax call hang forever; only a separate process
    can be abandoned safely (jax would cache the dead PJRT client)."""
    code = ("import jax, jax.numpy as jnp; d = jax.devices()[0]; "
            "x = float(jnp.sum(jnp.ones(16))); "
            "print(d.platform, d.device_kind, sep='|')")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=PROBE_DEADLINE_S)
    except subprocess.TimeoutExpired:
        return (f"device probe timed out after {PROBE_DEADLINE_S}s "
                "(TPU tunnel down?)")
    if r.returncode != 0:
        return "device probe failed: " + r.stderr.strip()[-300:]
    return None


def run_bench():
    import jax

    # warm-cacheable compiles: the retry child + later runs skip the
    # ~20-40s AlexNet-step compile
    from caffe_mpi_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache(os.path.join(_ROOT, ".jax_cache"))

    from caffe_mpi_tpu.proto import NetParameter, SolverParameter
    from caffe_mpi_tpu.solver import Solver
    from caffe_mpi_tpu.utils.flops import peak_flops, train_flops_per_image

    try:
        solver_path = _SOLVERS[(MODEL, DTYPE)]
    except KeyError:
        raise SystemExit(f"unknown bench config model={MODEL} dtype={DTYPE}; "
                         f"known: {sorted(_SOLVERS)}")
    sp = SolverParameter.from_file(os.path.join(_ROOT, solver_path))
    sp.max_iter = 10**9
    sp.display = 0
    sp.snapshot = 0
    sp.test_interval = 0
    sp.step_chunk = STEP_CHUNK
    sp.train_guard = GUARD
    from caffe_mpi_tpu.utils.model_shapes import input_shapes, synthetic_feeds
    npar = NetParameter.from_file(os.path.join(_ROOT, sp.net))
    shapes = input_shapes(npar, batch=BATCH)
    sp.net = ""
    sp.net_param = npar
    mesh_plan = None
    if MESH:
        if MESH != "all":
            raise SystemExit(f"unknown CAFFE_BENCH_MESH={MESH!r}; "
                             "supported: 'all'")
        from caffe_mpi_tpu.parallel import MeshPlan, reduction
        mesh_plan = MeshPlan.data_parallel()
        sp.reduce_overlap = True
        # same libtpu scheduler flags `caffe train -reduce_overlap`
        # sets (no-op on CPU; nothing above has touched the device, so
        # this lands before backend init) — the bench must measure the
        # bucketed program WITH the latency-hiding scheduler, not the
        # collectives serialized
        reduction.apply_tpu_overlap_flags(os.environ)
    solver = Solver(sp, model_dir=_ROOT, mesh=mesh_plan)

    feeds = synthetic_feeds(shapes, npar=npar)
    feed_fn = lambda it: feeds

    # warmup (compile + first steps). With K-step fusion active, warm at
    # least one FULL chunk so the timed region reuses the compiled scan
    # program instead of compiling it on the clock.
    warmup = max(WARMUP, sp.step_chunk if sp.step_chunk > 1 else 0)
    solver.step(warmup, feed_fn)
    jax.block_until_ready(solver.params)

    d0, s0 = solver.dispatch_count, solver.host_sync_count
    g0 = solver.guard_sync_count
    t0 = time.perf_counter()
    solver.step(ITERS, feed_fn)
    jax.block_until_ready(solver.params)
    dt = time.perf_counter() - t0
    dispatches = solver.dispatch_count - d0
    host_syncs = solver.host_sync_count - s0
    guard_syncs = solver.guard_sync_count - g0

    img_s = BATCH * ITERS / dt
    flops_img = train_flops_per_image(solver.net)
    achieved = flops_img * img_s

    # fused-eval telemetry (ISSUE 2), measured OUTSIDE the timed region:
    # drive test boundaries overlapped with training and report the
    # dispatch accounting — test_dispatches_per_pass should be
    # ceil(test_iter/T) + 1 (the +1 is the shared-param copy), and
    # eval_stall_ms is the host time the TRAIN loop lost per pass
    # (boundary dispatch + harvest wait), NOT the full pass. Counted
    # host-side like dispatches_per_100_iters, so the reduction is
    # CPU-visible when the tunnel is down. The headline img/s above is
    # untouched (its region ran with test_interval 0).
    eval_extra = {}
    if solver.test_nets and os.environ.get("CAFFE_BENCH_EVAL", "1") != "0":
        sp.test_iter = [EVAL_TEST_ITER]
        sp.test_interval = 3
        sp.test_chunk = EVAL_TEST_CHUNK
        tfeed = [lambda k: feeds]
        # warmup: compile the eval scan + param-copy programs OFF the
        # stall clock (same reason the train region warms a full chunk)
        solver.test_all(tfeed)
        d0, p0, s0 = (solver.test_dispatch_count, solver.test_pass_count,
                      solver.eval_stall_ms)
        solver.step(6, feed_fn, test_feed_fns=tfeed)
        jax.block_until_ready(solver.params)
        passes = solver.test_pass_count - p0
        if passes:
            eval_extra = {
                "test_iter": EVAL_TEST_ITER,
                "test_chunk": EVAL_TEST_CHUNK,
                "test_dispatches_per_pass": round(
                    (solver.test_dispatch_count - d0) / passes, 1),
                "eval_stall_ms": round(
                    (solver.eval_stall_ms - s0) / passes, 1),
            }

    device = jax.devices()[0]
    peak = peak_flops(device)
    extra = {
        "device": device.device_kind,
        "model_tflops_per_s": round(achieved / 1e12, 2),
        "mfu": round(achieved / peak, 4) if peak else None,
        # host dispatches per 100 training iterations: ~100 in classic
        # mode, ~100/K + host-event syncs with K-step fusion. Platform-
        # independent, so the dispatch-reduction win is visible from the
        # CPU fallback even when the tunnel is down.
        "step_chunk": sp.step_chunk,
        "dispatches_per_100_iters": round(dispatches * 100 / ITERS, 1),
        # 0 in the headline config (display off): the timed region never
        # blocks on the device between chunks
        "host_syncs": host_syncs,
        # self-healing guard telemetry (ISSUE 4): skipped_steps must be
        # 0 on synthetic data (any other value is itself a finding);
        # guard_syncs counts the per-chunk 5-scalar counter reads — the
        # guard's ONLY host traffic, so "~free on device" is measured
        # by comparing this line against CAFFE_BENCH_GUARD=0
        "train_guard": sp.train_guard,
        "skipped_steps": solver.skipped_steps,
        "guard_syncs": guard_syncs,
    }
    extra.update(eval_extra)
    if mesh_plan is not None:
        # ISSUE 6 telemetry, computed OUTSIDE the timed region: the
        # active bucket plan (collectives_per_step, bucket_bytes — or
        # mode "implicit" + fallback_reason when the net couldn't
        # engage) plus the HLO overlap-span proxy from a one-iteration
        # compile (reduction.collective_stats; one extra XLA compile,
        # after the headline number is already banked)
        rstats = solver.reduction_stats() or {}
        try:
            rstats.update(reduction.collective_stats(
                solver.step_hlo_text(feeds)))
        except Exception as e:  # telemetry must not kill the headline
            rstats["hlo_error"] = str(e)[-200:]
        extra["reduction"] = rstats

    # ISSUE 9: the bf16 headline variant, measured AFTER the f32 number
    # is banked. A fresh parse of the same recipe + `precision: bf16`
    # (dynamic loss scaling by default) — same model, batch, step_chunk,
    # guard — so the pair of numbers is the one-knob A/B the precision
    # section of docs/benchmarks.md quotes. The f32 metric above is
    # bitwise-untouched: nothing here runs before it.
    if BF16 and DTYPE == "f32":
        try:
            sp2 = SolverParameter.from_file(os.path.join(_ROOT, solver_path))
            sp2.max_iter = 10**9
            sp2.display = 0
            sp2.snapshot = 0
            sp2.test_interval = 0
            sp2.step_chunk = STEP_CHUNK
            sp2.train_guard = GUARD
            sp2.precision = "bf16"
            if mesh_plan is not None:
                sp2.reduce_overlap = True  # fresh parse: re-opt-in
            sp2.net = ""
            sp2.net_param = npar
            solver2 = Solver(sp2, model_dir=_ROOT, mesh=mesh_plan)
            warm2 = max(WARMUP, STEP_CHUNK if STEP_CHUNK > 1 else 0)
            solver2.step(warm2, feed_fn)
            jax.block_until_ready(solver2.params)
            t0 = time.perf_counter()
            solver2.step(ITERS, feed_fn)
            jax.block_until_ready(solver2.params)
            dt2 = time.perf_counter() - t0
            img_s2 = BATCH * ITERS / dt2
            bf16 = {
                "img_per_s": round(img_s2, 1),
                "mfu": round(flops_img * img_s2 / peak, 4) if peak
                else None,
                "speedup_vs_f32": round(img_s2 / img_s, 2),
                # dynamic loss-scale telemetry: 0 overflows expected on
                # synthetic data, scale at its 2^15 start
                "loss_scale": solver2.loss_scale_value,
                "overflow_steps": solver2.overflow_steps,
                "skipped_steps": solver2.skipped_steps,
            }
            if mesh_plan is not None:
                # bucket_bytes here are HALF the f32 reduction block's:
                # the buckets pack and psum in bf16 (wire_dtype)
                bf16["reduction"] = solver2.reduction_stats() or {}
            solver2.close()
            extra["bf16"] = bf16
        except Exception as e:  # the variant must not kill the headline
            extra["bf16"] = {"error": str(e)[-300:]}
    return round(img_s, 1), round(img_s / BASELINE_IMG_S, 2), extra


def serving_block():
    """Run the serving bench in a watched child; returns the `serving`
    dict (or {"error": ...}). CPU work only — safe with the tunnel down."""
    script = os.path.join(_ROOT, "tools", "bench_serving.py")
    try:
        r = subprocess.run([sys.executable, script], text=True,
                           capture_output=True, timeout=SERVING_DEADLINE_S)
    except subprocess.TimeoutExpired:
        return {"error": f"serving bench exceeded {SERVING_DEADLINE_S}s"}
    for line in reversed(r.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                block = json.loads(line)["serving"]
            except (ValueError, KeyError):
                break
            if r.returncode != 0:
                block["error"] = "zero-recompile assertion FAILED"
            return block
    tail = [l for l in r.stderr.strip().splitlines() if l.strip()]
    return {"error": (tail[-1][-300:] if tail
                      else f"serving bench exited rc={r.returncode}")}


def ingest_block():
    """Run the ingestion bench in a watched child; returns the `ingest`
    dict (or {"error": ...}). CPU work only — safe with the tunnel
    down; this is exactly the host-side evidence the tunnel-dead rounds
    were missing."""
    cmd = [sys.executable, "-m", "caffe_mpi_tpu.tools.bench_data",
           "--ingest-only", "--json", "--ingest-n", "768",
           "-batch", "128"]
    try:
        r = subprocess.run(cmd, text=True, capture_output=True, cwd=_ROOT,
                           timeout=INGEST_DEADLINE_S)
    except subprocess.TimeoutExpired:
        return {"error": f"ingest bench exceeded {INGEST_DEADLINE_S}s"}
    for line in reversed(r.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)["ingest"]
            except (ValueError, KeyError):
                break
    tail = [l for l in r.stderr.strip().splitlines() if l.strip()]
    return {"error": (tail[-1][-300:] if tail
                      else f"ingest bench exited rc={r.returncode}")}


def _attempt(deadline_s):
    """Run the bench body in a watched child; return (json_line|None, err)."""
    env = dict(os.environ, CAFFE_TPU_BENCH_CHILD="1")
    try:
        r = subprocess.run([sys.executable, __file__], env=env, text=True,
                           capture_output=True, timeout=deadline_s)
    except subprocess.TimeoutExpired:
        return None, f"bench attempt exceeded its {deadline_s:.0f}s deadline"
    sys.stderr.write(r.stderr)
    if r.returncode == 0 and r.stdout.strip():
        return r.stdout.strip().splitlines()[-1], None
    tail = [l for l in r.stderr.strip().splitlines() if l.strip()]
    return None, (tail[-1][-300:] if tail
                  else f"bench child exited rc={r.returncode}")


if __name__ == "__main__":
    if _IS_CHILD:
        # child: device work only; crash loudly on failure (parent reports)
        value, vs, extra = run_bench()
        emit(value, vs, extra)
        sys.exit(0)

    # the budget clock starts BEFORE the serving/ingest benches: their
    # subprocess deadlines spend the same total wall budget the
    # docstring promises, instead of extending it
    start = time.monotonic()
    # CPU-only telemetry first (own subprocesses): it must ride the
    # emitted line on every path, device success, failure, or dead
    # tunnel — the zero-recompile and ingestion claims are CPU-visible
    # by design
    telemetry = {}
    if SERVING:
        telemetry["serving"] = serving_block()
    if INGEST:
        telemetry["ingest"] = ingest_block()
    telemetry = telemetry or None

    err = probe()
    if err:
        emit(error=err, extra=telemetry)
        sys.exit(0)

    last_err = "unknown"
    for attempt in (1, 2):
        remaining = TOTAL_BUDGET_S - (time.monotonic() - start) - 10
        if attempt == 2:
            # a dropped tunnel claim takes a moment to release; give it a
            # bounded backoff without blowing the budget
            backoff = min(30, remaining - 70)
            if backoff > 0:
                print(f"bench attempt 1 failed ({last_err}); retrying in "
                      f"{backoff:.0f}s", file=sys.stderr)
                time.sleep(backoff)
                remaining -= backoff
        if remaining < 60:
            break
        line, last_err = _attempt(remaining)
        if line is not None:
            if telemetry is not None:
                try:
                    obj = json.loads(line)
                    obj.update(telemetry)
                    line = json.dumps(obj)
                except ValueError:
                    pass  # never let telemetry mangle the headline line
            print(line)
            sys.exit(0)
    emit(error=last_err, extra=telemetry)
    sys.exit(0)
