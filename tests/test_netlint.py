"""netlint (ISSUE 15): the jax-free model-graph analysis engine and the
net-* pass family.

Three contracts hold here:
1. Engine-vs-built-net cross-check: proto/netshape.py's inferred blob
   shapes and param declarations are BITWISE equal to what net.py
   actually builds, for every prototxt in the model zoo, both phases —
   the engine can never drift from what really compiles.
2. Zoo-wide clean gate: every zoo model, every example prototxt runs
   netlint-clean.
3. Seeded mutations: each classic prototxt defect produces exactly its
   expected net-* finding.
"""

import glob
import os
import subprocess
import sys
import textwrap

import pytest

from caffe_mpi_tpu.proto import NetParameter
from caffe_mpi_tpu.proto.netshape import (
    BF16_ELIGIBLE,
    BF16_INELIGIBLE,
    RULES,
    analyze_net,
    layer_footprint,
    macs_per_image,
)
from caffe_mpi_tpu.tools import lint
from caffe_mpi_tpu.tools.lint.netlint import NET_PASSES

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ZOO_NETS = sorted(
    f for f in glob.glob(os.path.join(_ROOT, "models", "*", "*.prototxt"))
    if "solver" not in os.path.basename(f))


def _run_net_passes(root, select=NET_PASSES):
    return lint.run_lint(paths=[], select=list(select), root=str(root))


def _write_net(tmp_path, body, name="models/fixture/net.prototxt"):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))
    return p


# ---------------------------------------------------------------------------
# engine <-> built net cross-check (acceptance criterion)

def test_zoo_has_the_expected_models():
    dirs = {os.path.basename(os.path.dirname(f)) for f in ZOO_NETS}
    # every zoo model dir is cross-checked (16 dirs, 40 net files incl.
    # fp16 / pipeline / sequence-parallel variants)
    assert len(dirs) >= 16, sorted(dirs)
    assert "transformer_lm" in dirs and "inception_v3" in dirs


@pytest.mark.parametrize("path", ZOO_NETS,
                         ids=[os.path.relpath(f, _ROOT) for f in ZOO_NETS])
@pytest.mark.parametrize("phase", ["TRAIN", "TEST"])
def test_engine_matches_built_net(path, phase):
    """Inferred shapes bitwise-equal to the real Net build (net.py) —
    out shapes, blob table, and param declarations, layer for layer."""
    from caffe_mpi_tpu.net import Net

    net = Net(NetParameter.from_file(path), phase=phase, model_dir=_ROOT)
    analysis = analyze_net(NetParameter.from_file(path), phase=phase)
    assert [l.name for l in net.layers] == [l.name for l in analysis.layers]
    assert not analysis.problems, analysis.problems
    for built, inferred in zip(net.layers, analysis.layers):
        assert [tuple(s) for s in built.out_shapes] == \
            [tuple(s) for s in inferred.out_shapes], built.name
        assert {n: tuple(d.shape) for n, d in built.params.items()} == \
            {n: p.shape for n, p in inferred.params.items()}, built.name
        # param multipliers resolve positionally the same way
        for n, d in built.params.items():
            assert (d.lr_mult, d.decay_mult) == (
                inferred.params[n].lr_mult,
                inferred.params[n].decay_mult), (built.name, n)
    assert {k: tuple(v) for k, v in net.blob_shapes.items()} == \
        {k: tuple(v) for k, v in analysis.blob_shapes.items()}
    # the MAC model agrees between the built-layer adapter
    # (utils/flops.py) and the static records
    from caffe_mpi_tpu.utils.flops import layer_macs_per_image
    for built, inferred in zip(net.layers, analysis.layers):
        static = macs_per_image(
            inferred.type, inferred.in_shapes, inferred.out_shapes,
            {n: p.shape for n, p in inferred.params.items()}, inferred.lp)
        assert layer_macs_per_image(built) == int(static or 0), built.name


def test_rules_cover_layer_registry():
    """Every registered layer type has a shape rule and vice versa — a
    new layer cannot ship without static inference."""
    from caffe_mpi_tpu.layers import LAYER_REGISTRY
    assert set(RULES) == set(LAYER_REGISTRY), \
        set(RULES) ^ set(LAYER_REGISTRY)


def test_bf16_registry_is_exhaustive_and_disjoint():
    """The bf16-eligibility registry (shared by net.py's build warning
    and the net-dtype pass) classifies every layer type exactly once."""
    from caffe_mpi_tpu.layers import LAYER_REGISTRY
    assert BF16_ELIGIBLE | BF16_INELIGIBLE == set(LAYER_REGISTRY), \
        (BF16_ELIGIBLE | BF16_INELIGIBLE) ^ set(LAYER_REGISTRY)
    assert not (BF16_ELIGIBLE & BF16_INELIGIBLE)


# ---------------------------------------------------------------------------
# zoo-wide clean gate (acceptance criterion)

def test_zoo_and_examples_are_netlint_clean():
    findings = _run_net_passes(_ROOT)
    assert findings == [], "\n".join(f.format(_ROOT) for f in findings)


def test_netlint_registered_and_listed():
    lint._load_passes()
    for name in NET_PASSES:
        assert name in lint.REGISTRY, name
        assert lint.REGISTRY[name].description


# ---------------------------------------------------------------------------
# seeded mutations: each produces exactly its expected finding

_INPUT_2 = """
    layer { name: "in" type: "Input" top: "data" top: "label"
            input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 }
                          shape { dim: 8 } } }
"""

MUTATIONS = [
    ("swapped_bottoms", "net-shape", _INPUT_2 + """
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    layer { name: "loss" type: "SoftmaxWithLoss"
            bottom: "label" bottom: "fc" top: "loss" }
    """),
    ("bn_blob_count_off_by_one", "net-params", _INPUT_2 + """
    layer { name: "bn" type: "BatchNorm" bottom: "data" top: "bn"
            param { lr_mult: 0 } param { lr_mult: 0 } param { lr_mult: 0 }
            batch_norm_param { eps: 1e-4 } }
    """),
    ("pad_ge_kernel", "net-shape", _INPUT_2 + """
    layer { name: "pool" type: "Pooling" bottom: "data" top: "pool"
            pooling_param { pool: MAX kernel_size: 2 stride: 2 pad: 2 } }
    """),
    ("bf16_on_ineligible_layer", "net-dtype", """
    default_forward_type: FLOAT16
    default_backward_type: FLOAT16
    """ + _INPUT_2 + """
    layer { name: "py" type: "Python" bottom: "data" top: "py"
            python_param { module: "mymod" layer: "MyLayer" } }
    """),
    ("dangling_bottom", "net-wiring", _INPUT_2 + """
    layer { name: "fc" type: "InnerProduct" bottom: "dta" top: "fc"
            inner_product_param { num_output: 4 } }
    """),
    ("duplicate_top", "net-wiring", _INPUT_2 + """
    layer { name: "fc1" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    layer { name: "fc2" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    """),
    ("inplace_on_multi_consumer_blob", "net-wiring", _INPUT_2 + """
    layer { name: "branch" type: "InnerProduct" bottom: "data" top: "b"
            inner_product_param { num_output: 4 } }
    layer { name: "relu" type: "ReLU" bottom: "data" top: "data" }
    """),
    ("inplace_shape_change", "net-wiring", _INPUT_2 + """
    layer { name: "conv" type: "Convolution" bottom: "data" top: "data"
            convolution_param { num_output: 4 kernel_size: 3 } }
    """),
    ("unreachable_layer", "net-wiring", _INPUT_2 + """
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 }
            exclude { phase: TRAIN } exclude { phase: TEST } }
    """),
    ("eltwise_shape_mismatch", "net-shape", _INPUT_2 + """
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    layer { name: "sum" type: "Eltwise" bottom: "data" bottom: "fc"
            top: "sum" }
    """),
    ("reshape_count_mismatch", "net-shape", _INPUT_2 + """
    layer { name: "rs" type: "Reshape" bottom: "data" top: "rs"
            reshape_param { shape { dim: 0 dim: 5 dim: 8 dim: 8 } } }
    """),
    ("phase_inconsistent_include", "net-wiring", """
    layer { name: "in" type: "Input" top: "data"
            include { phase: TEST }
            input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 } } }
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    """),
    ("batch_baked_reshape_in_deploy", "net-serve", """
    layer { name: "in" type: "Input" top: "data"
            input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 } } }
    layer { name: "rs" type: "Reshape" bottom: "data" top: "rs"
            reshape_param { shape { dim: 8 dim: 192 } } }
    """),
    ("non_rgb_image_deploy", "net-serve", """
    layer { name: "in" type: "Input" top: "data"
            input_param { shape { dim: 8 dim: 4 dim: 16 dim: 16 } } }
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    """),
    ("hbm_blowout_blob", "net-footprint", """
    layer { name: "in" type: "Input" top: "data"
            input_param { shape { dim: 4096 dim: 3 dim: 22700 dim: 22700 } } }
    """),
]


@pytest.mark.parametrize("name,expected,body", MUTATIONS,
                         ids=[m[0] for m in MUTATIONS])
def test_seeded_mutation_caught(tmp_path, name, expected, body):
    _write_net(tmp_path, 'name: "fixture"\n' + body)
    findings = _run_net_passes(tmp_path)
    assert findings, f"{name}: no findings"
    got = {f.pass_name for f in findings}
    assert got == {expected}, \
        f"{name}: expected only {expected}, got " + \
        "\n".join(f.format(str(tmp_path)) for f in findings)


def test_clean_fixture_is_clean(tmp_path):
    _write_net(tmp_path, 'name: "ok"\n' + _INPUT_2 + """
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    layer { name: "loss" type: "SoftmaxWithLoss"
            bottom: "fc" bottom: "label" top: "loss" }
    """)
    assert _run_net_passes(tmp_path) == []


def test_malformed_prototxt_is_a_wiring_finding(tmp_path):
    _write_net(tmp_path, 'layer { name: "x" type: ??? }')
    findings = _run_net_passes(tmp_path)
    assert [f.pass_name for f in findings] == ["net-wiring"]
    assert "parse" in findings[0].message


def test_missing_bottom_is_a_finding_not_a_crash(tmp_path):
    """A layer omitting a required bottom must produce a net-wiring
    finding — not an IndexError that aborts the whole-tree lint."""
    _write_net(tmp_path, 'name: "f"\n' + _INPUT_2 + """
    layer { name: "r" type: "ReLU" top: "x" }
    """)
    findings = _run_net_passes(tmp_path)
    assert findings and {f.pass_name for f in findings} == {"net-wiring"}


def test_zero_stride_is_a_finding_not_a_crash(tmp_path):
    _write_net(tmp_path, 'name: "f"\n' + _INPUT_2 + """
    layer { name: "c" type: "Convolution" bottom: "data" top: "c"
            convolution_param { num_output: 4 kernel_size: 3 stride: 0 } }
    layer { name: "p" type: "Pooling" bottom: "data" top: "p"
            pooling_param { pool: MAX kernel_size: 2 stride: 0 } }
    layer { name: "d" type: "Convolution" bottom: "data" top: "d"
            convolution_param { num_output: 4 kernel_size: 3
                                dilation: 1 dilation: 1 dilation: 1 } }
    """)
    findings = _run_net_passes(tmp_path)
    assert findings and {f.pass_name for f in findings} == {"net-shape"}
    assert sum("stride" in f.message for f in findings) == 2
    assert any("dilation" in f.message for f in findings)


def test_colon_message_form_net_is_scanned(tmp_path):
    """The text format accepts `layer: { ... }`; the solver prefilter
    must not misread that spelling as a solver file."""
    _write_net(tmp_path, 'name: "f"\n' + """
    layer: { name: "in" type: "Input" top: "data"
             input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 } } }
    layer: { name: "fc" type: "InnerProduct" bottom: "nosuch" top: "fc"
             inner_product_param { num_output: 4 } }
    """)
    findings = _run_net_passes(tmp_path)
    assert findings and any(
        f.pass_name == "net-wiring" and "nosuch" in f.message
        for f in findings)


def test_deploy_pipeline_with_dropout_is_not_flagged(tmp_path):
    """The Dropout-in-Pipeline rule is TRAIN-only; a deploy-shaped net
    (no phase rules) must not inherit it from the shared-analysis fast
    path."""
    _write_net(tmp_path, 'name: "pp"\n' + """
    layer { name: "in" type: "Input" top: "x"
            input_param { shape { dim: 4 dim: 8 dim: 16 } } }
    layer { name: "trunk" type: "Pipeline" bottom: "x" top: "y"
            pipeline_param { num_stages: 2 micro_batches: 2
              layer { name: "ln" type: "LayerNorm" bottom: "x" top: "n" }
              layer { name: "do" type: "Dropout" bottom: "n" top: "n2" }
              layer { name: "res" type: "Eltwise" bottom: "x"
                      bottom: "n2" top: "out" } } }
    """)
    findings = _run_net_passes(tmp_path)
    assert not any("Dropout" in f.message for f in findings), \
        "\n".join(f.format(str(tmp_path)) for f in findings)
    # ...while a TRAIN net (phase-ruled, so analyzed per phase) with the
    # same block is flagged, tagged to TRAIN
    _write_net(tmp_path, 'name: "pp2"\n' + """
    layer { name: "in" type: "Input" top: "x"
            include { phase: TRAIN }
            input_param { shape { dim: 4 dim: 8 dim: 16 } } }
    layer { name: "in" type: "Input" top: "x"
            include { phase: TEST }
            input_param { shape { dim: 4 dim: 8 dim: 16 } } }
    layer { name: "trunk" type: "Pipeline" bottom: "x" top: "y"
            pipeline_param { num_stages: 2 micro_batches: 2
              layer { name: "ln" type: "LayerNorm" bottom: "x" top: "n" }
              layer { name: "do" type: "Dropout" bottom: "n" top: "n2" }
              layer { name: "res" type: "Eltwise" bottom: "x"
                      bottom: "n2" top: "out" } } }
    """, name="models/fixture2/net.prototxt")
    findings = [f for f in _run_net_passes(tmp_path)
                if "fixture2" in f.path]
    assert any("Dropout" in f.message and "[phase TRAIN]" in f.message
               for f in findings), \
        "\n".join(f.format(str(tmp_path)) for f in findings)


def test_distinct_unnamed_layers_report_distinctly(tmp_path):
    _write_net(tmp_path, 'name: "anon"\n' + """
    layer { type: "Input" top: "a" }
    layer { type: "Input" top: "b" }
    """)
    findings = [f for f in _run_net_passes(tmp_path)
                if "input_param.shape required" in f.message]
    assert len(findings) == 2, \
        "\n".join(f.format(str(tmp_path)) for f in findings)
    assert {f.message.split(":")[0] for f in findings} == \
        {"layer #0 (unnamed)", "layer #1 (unnamed)"}


def test_single_quoted_hash_does_not_corrupt_spans_or_waivers(tmp_path):
    """text_format accepts single-quoted strings; a '#' inside one must
    not read as a comment (span corruption / waiver leakage)."""
    body = """
    layer { name: "in" type: "Input" top: "data"
            input_param { shape { dim: 8 dim: 4 dim: 16 dim: 16 } } }
    layer { name: "h5" type: "HDF5Output" bottom: "data"
            hdf5_output_param { file_name: '/data/#shard1.h5' } }
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    """
    from caffe_mpi_tpu.tools.lint.netlint import _layer_spans
    spans = _layer_spans(('name: "q"\n' + body).splitlines())
    assert len(spans) == 3 and [n for n, _s, _e in spans] == \
        ["in", "h5", "fc"]
    _write_net(tmp_path, 'name: "q"\n' + body)
    findings = _run_net_passes(tmp_path)
    # the only finding is the net-serve C=4 one, anchored to 'in' —
    # not suppressed or displaced by the quoted '#'
    assert [f.pass_name for f in findings] == ["net-serve"]


def test_legacy_v1_net_analyzes_clean_for_both_phases(tmp_path):
    """normalize_net must be idempotent: netlint analyzes ONE parse for
    TRAIN and TEST, and the V1 blobs_lr migration used to misread its
    own output as 'mixes legacy and modern specs' on the second pass."""
    _write_net(tmp_path, 'name: "legacy"\n' + """
    layer { name: "in" type: "Input" top: "data" top: "label"
            include { phase: TRAIN }
            input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 }
                          shape { dim: 8 } } }
    layer { name: "in" type: "Input" top: "data" top: "label"
            include { phase: TEST }
            input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 }
                          shape { dim: 8 } } }
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            blobs_lr: 1 blobs_lr: 2
            inner_product_param { num_output: 4 } }
    layer { name: "loss" type: "SoftmaxWithLoss"
            bottom: "fc" bottom: "label" top: "loss" }
    """)
    assert _run_net_passes(tmp_path) == []


def test_solver_prototxts_are_skipped(tmp_path):
    _write_net(tmp_path, 'net: "train.prototxt"\nbase_lr: 0.01\n',
               name="models/fixture/solver.prototxt")
    assert _run_net_passes(tmp_path) == []


# ---------------------------------------------------------------------------
# prototxt waiver grammar (satellite: per-layer waiver or generated
# registry)

_NON_RGB = """
    layer { name: "in" type: "Input" top: "data"
            input_param { shape { dim: 8 dim: 4 dim: 16 dim: 16 } } }
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
"""


def test_prototxt_waiver_inside_layer_block(tmp_path):
    body = _NON_RGB.replace(
        'top: "data"',
        'top: "data"  # lint: ok(net-serve) — grayscale+alpha by design')
    _write_net(tmp_path, 'name: "w"\n' + body)
    assert _run_net_passes(tmp_path) == []


def test_prototxt_waiver_in_comment_block_above(tmp_path):
    body = _NON_RGB.replace(
        'layer { name: "in"',
        '# lint: ok(net-serve) — grayscale+alpha by design\n'
        '    layer { name: "in"')
    _write_net(tmp_path, 'name: "w"\n' + body)
    assert _run_net_passes(tmp_path) == []


def test_prototxt_waiver_on_other_layer_does_not_suppress(tmp_path):
    body = _NON_RGB.replace(
        'top: "fc"',
        'top: "fc"  # lint: ok(net-serve) — wrong layer')
    _write_net(tmp_path, 'name: "w"\n' + body)
    findings = _run_net_passes(tmp_path)
    assert [f.pass_name for f in findings] == ["net-serve"]


def test_generated_waiver_registry(tmp_path, monkeypatch):
    from caffe_mpi_tpu.tools.lint import netlint
    _write_net(tmp_path, 'name: "w"\n' + _NON_RGB)
    monkeypatch.setitem(
        netlint.GENERATED_WAIVERS,
        (os.path.join("models", "fixture", "net.prototxt"),
         "net-serve", "in"),
        "generated model, grayscale+alpha by design")
    assert _run_net_passes(tmp_path) == []


def test_misspelled_prototxt_waiver_is_a_finding(tmp_path):
    _write_net(tmp_path, 'name: "w"\n' + _INPUT_2 + """
    # lint: ok(net-sreve) — typo'd pass name must fail, not suppress
    layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
            inner_product_param { num_output: 4 } }
    """)
    findings = _run_net_passes(tmp_path)
    assert [f.pass_name for f in findings] == ["net-wiring"]
    assert "unknown pass" in findings[0].message


# ---------------------------------------------------------------------------
# --changed learns about model files (satellite)

def test_changed_mode_prototxt_triggers_net_passes(tmp_path, monkeypatch):
    """A diff containing only a prototxt used to exit 0 without looking
    at models at all; now it runs the net-* family."""
    import subprocess as sp
    real_run = sp.run

    def fake_run(cmd, **kw):
        if cmd[:3] == ["git", "diff", "--name-only"]:
            class R:
                returncode = 0
                stdout = "models/alexnet/train_val.prototxt\n"
                stderr = ""
            return R()
        return real_run(cmd, **kw)

    monkeypatch.setattr(sp, "run", fake_run)
    # the real tree is clean -> exit 0, but via the net-pass path (a
    # seeded broken zoo would exit 1; proven by the fixture variant
    # below through run_lint)
    assert lint.main(["--changed", "HEAD", "--no-stale"]) == 0


def test_changed_mode_generator_edit_triggers_net_passes(monkeypatch):
    import subprocess as sp
    real_run = sp.run

    def fake_run(cmd, **kw):
        if cmd[:3] == ["git", "diff", "--name-only"]:
            class R:
                returncode = 0
                stdout = "models/generate_models.py\n"
                stderr = ""
            return R()
        return real_run(cmd, **kw)

    monkeypatch.setattr(sp, "run", fake_run)
    assert lint.main(["--changed", "HEAD", "--no-stale"]) == 0


# ---------------------------------------------------------------------------
# summarize rides the same engine, jax-free

def test_summarize_is_jax_free_and_reports_totals():
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "for m in ('jax', 'jaxlib'):\n"
         "    sys.modules[m] = None\n"
         "from caffe_mpi_tpu.tools.summarize import main\n"
         "raise SystemExit(main(['models/alexnet/train_val.prototxt']))"],
        cwd=_ROOT, capture_output=True, text=True, timeout=120,
        env=dict(os.environ, PYTHONPATH=_ROOT))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "60,965,224 params" in r.stdout
    assert "MMACs/img" in r.stdout and "bwd MiB" in r.stdout


def test_summarize_surfaces_problems_and_exits_nonzero(tmp_path):
    p = _write_net(tmp_path, 'name: "bad"\n' + _INPUT_2 + """
    layer { name: "pool" type: "Pooling" bottom: "data" top: "pool"
            pooling_param { pool: MAX kernel_size: 2 stride: 2 pad: 2 } }
    """)
    from caffe_mpi_tpu.tools.summarize import main
    assert main([str(p)]) == 1


def test_footprint_handles_unknown_dims():
    analysis = analyze_net(NetParameter.from_file(
        os.path.join(_ROOT, "examples/mnist/lenet_train_test.prototxt")),
        phase="TRAIN")
    assert not analysis.problems
    conv1 = next(l for l in analysis.layers if l.name == "conv1")
    fp = layer_footprint(conv1)
    assert fp["macs"] is None and fp["param_count"] is None
    # channels propagate once known: conv2's weight is fully shaped
    conv2 = next(l for l in analysis.layers if l.name == "conv2")
    assert layer_footprint(conv2)["param_count"] == 25050
