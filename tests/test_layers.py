"""Op-level tests: forward numerics (torch CPU as independent oracle where
available, naive numpy otherwise) + finite-difference gradient checks.

Mirrors the reference's per-layer test files (src/caffe/test/test_*_layer.cpp)
and their GradientChecker usage.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch
import torch.nn.functional as F

from gradcheck import check_gradients, make_layer


def rand(shape, rng, scale=1.0):
    return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)


class TestConvolution:
    def test_forward_matches_torch(self, rng):
        layer, params, state = make_layer(
            'name: "c" type: "Convolution" bottom: "x" top: "y"\n'
            'convolution_param { num_output: 6 kernel_size: 3 stride: 2 pad: 1\n'
            '  weight_filler { type: "gaussian" std: 0.1 } }',
            [(2, 4, 9, 9)],
        )
        x = rand((2, 4, 9, 9), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        ref = F.conv2d(torch.tensor(np.array(x)),
                       torch.tensor(np.array(params["weight"])),
                       torch.tensor(np.array(params["bias"])),
                       stride=2, padding=1)
        np.testing.assert_allclose(np.array(y), ref.numpy(), rtol=1e-4, atol=1e-5)
        assert y.shape == (2, 6, 5, 5)

    def test_grouped_dilated(self, rng):
        layer, params, state = make_layer(
            'name: "c" type: "Convolution"  top: "y" bottom: "x"\n'
            'convolution_param { num_output: 4 kernel_size: 3 group: 2\n'
            '  dilation: 2 weight_filler { type: "xavier" } }',
            [(1, 4, 10, 10)],
        )
        x = rand((1, 4, 10, 10), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        ref = F.conv2d(torch.tensor(np.array(x)),
                       torch.tensor(np.array(params["weight"])),
                       torch.tensor(np.array(params["bias"])),
                       dilation=2, groups=2)
        np.testing.assert_allclose(np.array(y), ref.numpy(), rtol=1e-4, atol=1e-5)

    def test_nhwc_experiment_path_matches_nchw(self, rng, monkeypatch):
        """The CAFFE_CONV_LAYOUT=NHWC hardware-A/B branch must stay
        numerically identical to the default path — a silent divergence
        would invalidate the layout experiment it exists for."""
        from caffe_mpi_tpu.ops import conv as conv_ops
        x = rand((2, 4, 9, 9), rng)
        w = rand((6, 2, 3, 3), rng)
        ref = conv_ops.conv2d(x, w, (2, 1), (1, 2), dilation=(2, 1),
                              groups=2)
        monkeypatch.setattr(conv_ops, "_NHWC", True)
        out = conv_ops.conv2d(x, w, (2, 1), (1, 2), dilation=(2, 1),
                              groups=2)
        np.testing.assert_allclose(np.array(out), np.array(ref), rtol=1e-5,
                                   atol=1e-6)

    def test_asymmetric_kernel_matches_torch(self, rng):
        # 1x7 kernel with asymmetric padding (inception_v3's factorized conv)
        layer, params, state = make_layer(
            'name: "c" type: "Convolution" bottom: "x" top: "y"\n'
            'convolution_param { num_output: 4 kernel_h: 1 kernel_w: 7\n'
            '  pad_h: 0 pad_w: 3 weight_filler { type: "gaussian" std: 0.1 } }',
            [(2, 3, 9, 9)],
        )
        x = rand((2, 3, 9, 9), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        ref = F.conv2d(torch.tensor(np.array(x)),
                       torch.tensor(np.array(params["weight"])),
                       torch.tensor(np.array(params["bias"])),
                       padding=(0, 3))
        np.testing.assert_allclose(np.array(y), ref.numpy(), rtol=1e-4,
                                   atol=1e-5)
        assert y.shape == (2, 4, 9, 9)

    def test_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "c" type: "Convolution" bottom: "x" top: "y"\n'
            'convolution_param { num_output: 3 kernel_size: 3 pad: 1\n'
            '  weight_filler { type: "gaussian" std: 0.3 } }',
            [(2, 2, 5, 5)],
        )
        check_gradients(layer, params, state, [rand((2, 2, 5, 5), rng)])


class TestDeconvolution:
    def test_forward_matches_torch(self, rng):
        layer, params, state = make_layer(
            'name: "d" type: "Deconvolution" bottom: "x" top: "y"\n'
            'convolution_param { num_output: 3 kernel_size: 4 stride: 2 pad: 1\n'
            '  weight_filler { type: "gaussian" std: 0.1 } }',
            [(2, 5, 6, 6)],
        )
        x = rand((2, 5, 6, 6), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        ref = F.conv_transpose2d(torch.tensor(np.array(x)),
                                 torch.tensor(np.array(params["weight"])),
                                 torch.tensor(np.array(params["bias"])),
                                 stride=2, padding=1)
        np.testing.assert_allclose(np.array(y), ref.numpy(), rtol=1e-4, atol=1e-5)
        assert y.shape == (2, 3, 12, 12)

    def test_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "d" type: "Deconvolution" bottom: "x" top: "y"\n'
            'convolution_param { num_output: 2 kernel_size: 3 stride: 2\n'
            '  weight_filler { type: "gaussian" std: 0.3 } }',
            [(1, 2, 4, 4)],
        )
        check_gradients(layer, params, state, [rand((1, 2, 4, 4), rng)])


def naive_caffe_avg_pool(x, k, s, p):
    """Direct transcription of the reference AVE arithmetic
    (pooling_layer.cpp:196-215) as an oracle."""
    import math
    n, c, h, w = x.shape
    oh = int(math.ceil((h + 2 * p - k) / s)) + 1
    ow = int(math.ceil((w + 2 * p - k) / s)) + 1
    if p > 0:
        if (oh - 1) * s >= h + p:
            oh -= 1
        if (ow - 1) * s >= w + p:
            ow -= 1
    out = np.zeros((n, c, oh, ow), np.float32)
    for ph in range(oh):
        for pw in range(ow):
            hs, ws = ph * s - p, pw * s - p
            he, we = min(hs + k, h + p), min(ws + k, w + p)
            pool_size = (he - hs) * (we - ws)
            hs_, ws_ = max(hs, 0), max(ws, 0)
            he_, we_ = min(he, h), min(we, w)
            region = x[:, :, hs_:he_, ws_:we_]
            out[:, :, ph, pw] = region.sum(axis=(2, 3)) / pool_size
    return out


class TestPooling:
    def test_max_ceil_mode_matches_torch(self, rng):
        # 6x6 input, k=3 s=2: ceil -> 3x3 output (floor would give 2x2)
        layer, params, state = make_layer(
            'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
            'pooling_param { pool: MAX kernel_size: 3 stride: 2 }',
            [(2, 3, 6, 6)],
        )
        x = rand((2, 3, 6, 6), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        assert y.shape == (2, 3, 3, 3)
        ref = F.max_pool2d(torch.tensor(np.array(x)), 3, 2, 0, ceil_mode=True)
        np.testing.assert_allclose(np.array(y), ref.numpy(), rtol=1e-6)

    def test_max_with_pad(self, rng):
        layer, params, state = make_layer(
            'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
            'pooling_param { pool: MAX kernel_size: 3 stride: 2 pad: 1 }',
            [(1, 2, 6, 6)],
        )
        x = rand((1, 2, 6, 6), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        ref = F.max_pool2d(torch.tensor(np.array(x)), 3, 2, 1, ceil_mode=True)
        assert y.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.array(y), ref.numpy(), rtol=1e-6)

    def test_avg_caffe_divisor(self, rng):
        layer, params, state = make_layer(
            'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
            'pooling_param { pool: AVE kernel_size: 3 stride: 2 pad: 1 }',
            [(2, 2, 5, 5)],
        )
        x = rand((2, 2, 5, 5), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        ref = naive_caffe_avg_pool(np.array(x), 3, 2, 1)
        assert y.shape == ref.shape
        np.testing.assert_allclose(np.array(y), ref, rtol=1e-5, atol=1e-6)

    def test_global_pooling(self, rng):
        layer, params, state = make_layer(
            'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
            'pooling_param { pool: AVE global_pooling: true }',
            [(2, 4, 6, 6)],
        )
        x = rand((2, 4, 6, 6), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        assert y.shape == (2, 4, 1, 1)
        np.testing.assert_allclose(np.array(y)[:, :, 0, 0],
                                   np.array(x).mean(axis=(2, 3)), rtol=1e-5)

    def test_output_dim_clip_guard_matches_reference(self):
        """The last-window clip applies to BOTH dims whenever EITHER pad is
        nonzero — the reference's `if (pad_h_ || pad_w_)` guard
        (pooling_layer.cpp:96-108), not a per-dim pad check."""
        import math
        from caffe_mpi_tpu.ops.pool import pool_output_dim

        def ref_dims(h, w, k, s, ph, pw):
            oh = int(math.ceil((h + 2 * ph - k) / s)) + 1
            ow = int(math.ceil((w + 2 * pw - k) / s)) + 1
            if ph or pw:
                if (oh - 1) * s >= h + ph:
                    oh -= 1
                if (ow - 1) * s >= w + pw:
                    ow -= 1
            return oh, ow

        for h in (3, 4, 6, 7):
            for w in (3, 5, 6):
                for k in (1, 2, 3):
                    for s in (1, 2, 3):
                        for ph in (0, 1):
                            for pw in (0, 1):
                                if ph >= k or pw >= k:
                                    continue  # Caffe CHECKs pad < kernel
                                any_pad = ph > 0 or pw > 0
                                got = (pool_output_dim(h, k, ph, s, any_pad),
                                       pool_output_dim(w, k, pw, s, any_pad))
                                assert got == ref_dims(h, w, k, s, ph, pw), \
                                    (h, w, k, s, ph, pw)

    def test_gradients(self, rng):
        for pool in ("MAX", "AVE"):
            layer, params, state = make_layer(
                f'name: "p" type: "Pooling" bottom: "x" top: "y"\n'
                f'pooling_param {{ pool: {pool} kernel_size: 2 stride: 2 }}',
                [(1, 2, 4, 4)],
            )
            check_gradients(layer, params, state, [rand((1, 2, 4, 4), rng)])


class TestLRN:
    def test_across_channels_formula(self, rng):
        layer, params, state = make_layer(
            'name: "n" type: "LRN" bottom: "x" top: "y"\n'
            'lrn_param { local_size: 5 alpha: 0.0001 beta: 0.75 }',
            [(1, 8, 3, 3)],
        )
        x = rand((1, 8, 3, 3), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        # naive: scale_c = k + alpha/n * sum_{c'} x^2 over window
        xn = np.array(x)
        out = np.zeros_like(xn)
        for c in range(8):
            lo, hi = max(0, c - 2), min(8, c + 3)
            s = 1.0 + (1e-4 / 5) * (xn[:, lo:hi] ** 2).sum(axis=1)
            out[:, c] = xn[:, c] * s ** -0.75
        np.testing.assert_allclose(np.array(y), out, rtol=1e-5)
        # torch cross-check: torch LRN uses the same alpha/n convention
        ref = F.local_response_norm(torch.tensor(xn), 5, alpha=1e-4, beta=0.75, k=1.0)
        np.testing.assert_allclose(np.array(y), ref.numpy(), rtol=1e-5)

    def test_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "n" type: "LRN" bottom: "x" top: "y"\n'
            'lrn_param { local_size: 3 alpha: 0.1 beta: 0.75 }',
            [(1, 4, 3, 3)],
        )
        check_gradients(layer, params, state, [rand((1, 4, 3, 3), rng)])


class TestInnerProduct:
    def test_forward_and_transpose(self, rng):
        x = rand((3, 4, 2, 2), rng)
        layer, params, state = make_layer(
            'name: "ip" type: "InnerProduct" bottom: "x" top: "y"\n'
            'inner_product_param { num_output: 5 weight_filler { type: "xavier" } }',
            [(3, 4, 2, 2)],
        )
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        ref = np.array(x).reshape(3, -1) @ np.array(params["weight"]).T + \
            np.array(params["bias"])
        np.testing.assert_allclose(np.array(y), ref, rtol=1e-4, atol=1e-5)

        layer_t, params_t, _ = make_layer(
            'name: "ip" type: "InnerProduct" bottom: "x" top: "y"\n'
            'inner_product_param { num_output: 5 transpose: true\n'
            '  weight_filler { type: "xavier" } }',
            [(3, 4, 2, 2)],
        )
        assert params_t["weight"].shape == (16, 5)
        (yt,), _ = layer_t.apply(params_t, state, [x], train=False, rng=None)
        assert yt.shape == (3, 5)

    def test_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "ip" type: "InnerProduct" bottom: "x" top: "y"\n'
            'inner_product_param { num_output: 4 weight_filler { type: "xavier" } }',
            [(2, 6)],
        )
        check_gradients(layer, params, state, [rand((2, 6), rng)])


class TestActivations:
    CASES = [
        ('type: "ReLU"', lambda x: np.maximum(x, 0)),
        ('type: "ReLU" relu_param { negative_slope: 0.1 }',
         lambda x: np.where(x > 0, x, 0.1 * x)),
        ('type: "Sigmoid"', lambda x: 1 / (1 + np.exp(-x))),
        ('type: "TanH"', np.tanh),
        ('type: "AbsVal"', np.abs),
        ('type: "BNLL"', lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)),
        ('type: "ELU"', lambda x: np.where(x > 0, x, np.exp(x) - 1)),
        ('type: "Power" power_param { power: 2 scale: 0.5 shift: 1 }',
         lambda x: (1 + 0.5 * x) ** 2),
        ('type: "Exp"', np.exp),
    ]

    @pytest.mark.parametrize("proto,ref", CASES, ids=[c[0][7:20] for c in CASES])
    def test_forward(self, proto, ref, rng):
        layer, params, state = make_layer(
            f'name: "a" {proto} bottom: "x" top: "y"', [(2, 3, 4)])
        x = rand((2, 3, 4), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        np.testing.assert_allclose(np.array(y), ref(np.array(x)), rtol=1e-5,
                                   atol=1e-6)

    def test_smooth_gradients(self, rng):
        for proto in ['type: "Sigmoid"', 'type: "TanH"', 'type: "ELU"',
                      'type: "BNLL"']:
            layer, params, state = make_layer(
                f'name: "a" {proto} bottom: "x" top: "y"', [(2, 5)])
            check_gradients(layer, params, state, [rand((2, 5), rng)])

    def test_prelu_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "a" type: "PReLU" bottom: "x" top: "y"', [(2, 3, 4)])
        assert params["slope"].shape == (3,)
        x = rand((2, 3, 4), rng) + 0.3  # keep away from the kink
        check_gradients(layer, params, state, [x])

    def test_dropout(self, rng):
        layer, params, state = make_layer(
            'name: "d" type: "Dropout" bottom: "x" top: "y"\n'
            'dropout_param { dropout_ratio: 0.4 }', [(100, 100)])
        x = jnp.ones((100, 100))
        (y_test,), _ = layer.apply(params, state, [x], train=False, rng=None)
        np.testing.assert_array_equal(np.array(y_test), np.ones((100, 100)))
        (y_train,), _ = layer.apply(params, state, [x], train=True,
                                    rng=jax.random.PRNGKey(3))
        yn = np.array(y_train)
        kept = yn != 0
        assert 0.55 < kept.mean() < 0.65
        np.testing.assert_allclose(yn[kept], 1 / 0.6, rtol=1e-5)


class TestBatchNorm:
    def test_train_normalizes_and_updates_ema(self, rng):
        layer, params, state = make_layer(
            'name: "bn" type: "BatchNorm" bottom: "x" top: "y"\n'
            'batch_norm_param { moving_average_fraction: 0.9 }',
            [(4, 3, 5, 5)],
        )
        x = rand((4, 3, 5, 5), rng, scale=2.0) + 1.0
        (y,), new_state = layer.apply(params, state, [x], train=True, rng=None)
        yn = np.array(y)
        assert abs(yn.mean(axis=(0, 2, 3))).max() < 1e-4
        np.testing.assert_allclose(yn.std(axis=(0, 2, 3)), 1.0, atol=1e-3)
        xn = np.array(x, np.float64)
        batch_mean = xn.mean(axis=(0, 2, 3))
        np.testing.assert_allclose(np.array(new_state["mean"]),
                                   0.1 * batch_mean, rtol=1e-4)

    def test_test_phase_uses_global_stats(self, rng):
        layer, params, state = make_layer(
            'name: "bn" type: "BatchNorm" bottom: "x" top: "y"',
            [(2, 3, 4, 4)], phase="TEST",
        )
        state = {"mean": jnp.array([1.0, 2.0, 3.0]),
                 "var": jnp.array([4.0, 4.0, 4.0])}
        x = rand((2, 3, 4, 4), rng)
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        expect = (np.array(x) - np.array([1, 2, 3])[None, :, None, None]) / \
            np.sqrt(4.0 + 1e-5)
        np.testing.assert_allclose(np.array(y), expect, rtol=1e-4, atol=1e-5)

    def test_scale_bias_params(self, rng):
        layer, params, state = make_layer(
            'name: "bn" type: "BatchNorm" bottom: "x" top: "y"\n'
            'batch_norm_param { scale_bias: true }',
            [(2, 3, 4, 4)],
        )
        assert set(params) == {"scale", "bias"}
        check_gradients(layer, params, state, [rand((2, 3, 4, 4), rng)],
                        bottoms_to_check=[])


class TestLosses:
    def test_softmax_loss_matches_torch(self, rng):
        layer, params, state = make_layer(
            'name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "t" top: "loss"',
            [(5, 7), (5,)],
        )
        x = rand((5, 7), rng)
        t = jnp.asarray(rng.randint(0, 7, 5))
        (loss,), _ = layer.apply(params, state, [x, t], train=True, rng=None)
        ref = F.cross_entropy(torch.tensor(np.array(x)),
                              torch.tensor(np.array(t), dtype=torch.long))
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)

    def test_softmax_loss_spatial_ignore(self, rng):
        layer, params, state = make_layer(
            'name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "t" top: "loss"\n'
            'loss_param { ignore_label: 255 }',
            [(2, 4, 3, 3), (2, 3, 3)],
        )
        x = rand((2, 4, 3, 3), rng)
        t = rng.randint(0, 4, (2, 3, 3))
        t[0, 0, :] = 255
        tj = jnp.asarray(t)
        (loss,), _ = layer.apply(params, state, [x, tj], train=True, rng=None)
        ref = F.cross_entropy(torch.tensor(np.array(x)),
                              torch.tensor(t, dtype=torch.long),
                              ignore_index=255)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)

    def test_legacy_normalize_false_is_batch_size(self, rng):
        """loss_param { normalize: false } maps to BATCH_SIZE for every
        loss (softmax_loss_layer.cpp:35-38), i.e. divide by N even when the
        target is spatial — NOT by the full count, NOT by 1."""
        x = rand((3, 4, 2, 2), rng)
        t = jnp.asarray(rng.randint(0, 4, (3, 2, 2)))
        legacy, params, state = make_layer(
            'name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "t" top: "loss"\n'
            'loss_param { normalize: false }',
            [(3, 4, 2, 2), (3,)],
        )
        (loss,), _ = legacy.apply(params, state, [x, t], train=True, rng=None)
        modern, p2, s2 = make_layer(
            'name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "t" top: "loss"\n'
            'loss_param { normalization: BATCH_SIZE }',
            [(3, 4, 2, 2), (3,)],
        )
        (ref,), _ = modern.apply(p2, s2, [x, t], train=True, rng=None)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)
        # sanity: BATCH_SIZE (sum/3) differs from VALID (sum/12) here
        valid, p3, s3 = make_layer(
            'name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "t" top: "loss"',
            [(3, 4, 2, 2), (3,)],
        )
        (lv,), _ = valid.apply(p3, s3, [x, t], train=True, rng=None)
        np.testing.assert_allclose(float(loss), 4 * float(lv), rtol=1e-5)

    def test_softmax_loss_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "l" type: "SoftmaxWithLoss" bottom: "x" bottom: "t" top: "loss"',
            [(4, 5), (4,)],
        )
        x = rand((4, 5), rng)
        t = jnp.asarray(rng.randint(0, 5, 4))
        check_gradients(layer, params, state, [x, t], bottoms_to_check=[0])

    def test_euclidean(self, rng):
        layer, params, state = make_layer(
            'name: "l" type: "EuclideanLoss" bottom: "a" bottom: "b" top: "loss"',
            [(4, 3), (4, 3)],
        )
        a, b = rand((4, 3), rng), rand((4, 3), rng)
        (loss,), _ = layer.apply(params, state, [a, b], train=True, rng=None)
        expect = ((np.array(a) - np.array(b)) ** 2).sum() / 8
        np.testing.assert_allclose(float(loss), expect, rtol=1e-5)
        check_gradients(layer, params, state, [a, b])

    def test_sigmoid_ce_matches_torch(self, rng):
        layer, params, state = make_layer(
            'name: "l" type: "SigmoidCrossEntropyLoss" bottom: "x" bottom: "t" top: "loss"',
            [(4, 6), (4, 6)],
        )
        x = rand((4, 6), rng)
        t = jnp.asarray(rng.rand(4, 6).astype(np.float32))
        (loss,), _ = layer.apply(params, state, [x, t], train=True, rng=None)
        ref = F.binary_cross_entropy_with_logits(
            torch.tensor(np.array(x)), torch.tensor(np.array(t)),
            reduction="sum") / 4
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
        check_gradients(layer, params, state, [x, t], bottoms_to_check=[0])

    def test_hinge(self, rng):
        layer, params, state = make_layer(
            'name: "l" type: "HingeLoss" bottom: "x" bottom: "t" top: "loss"',
            [(3, 4), (3,)],
        )
        x = rand((3, 4), rng)
        t = jnp.asarray(rng.randint(0, 4, 3))
        (loss,), _ = layer.apply(params, state, [x, t], train=True, rng=None)
        xn, tn = np.array(x), np.array(t)
        margins = np.maximum(0, 1 + xn)
        for i, lab in enumerate(tn):
            margins[i, lab] = max(0, 1 - xn[i, lab])
        np.testing.assert_allclose(float(loss), margins.sum() / 3, rtol=1e-5)

    def test_accuracy_topk(self, rng):
        layer, params, state = make_layer(
            'name: "a" type: "Accuracy" bottom: "x" bottom: "t" top: "acc"\n'
            'accuracy_param { top_k: 2 }',
            [(6, 5), (6,)],
        )
        x = rand((6, 5), rng)
        t = jnp.asarray(rng.randint(0, 5, 6))
        (acc,), _ = layer.apply(params, state, [x, t], train=False, rng=None)
        order = np.argsort(-np.array(x), axis=1)
        expect = np.mean([t[i] in order[i, :2] for i in range(6)])
        np.testing.assert_allclose(float(acc), expect, rtol=1e-6)


class TestShapeOps:
    def test_concat_slice_roundtrip(self, rng):
        x = rand((2, 6, 3), rng)
        sl, _, _ = make_layer(
            'name: "s" type: "Slice" bottom: "x" top: "a" top: "b" top: "c"\n'
            'slice_param { axis: 1 slice_point: 1 slice_point: 3 }',
            [(2, 6, 3)],
        )
        tops, _ = sl.apply({}, {}, [x], train=False, rng=None)
        assert [t.shape for t in tops] == [(2, 1, 3), (2, 2, 3), (2, 3, 3)]
        cat, _, _ = make_layer(
            'name: "c" type: "Concat" bottom: "a" bottom: "b" bottom: "c" top: "y"',
            [t.shape for t in tops],
        )
        (y,), _ = cat.apply({}, {}, tops, train=False, rng=None)
        np.testing.assert_array_equal(np.array(y), np.array(x))

    def test_eltwise(self, rng):
        a, b = rand((2, 3), rng), rand((2, 3), rng)
        for op, ref in [("SUM", np.array(a) + np.array(b)),
                        ("PROD", np.array(a) * np.array(b)),
                        ("MAX", np.maximum(np.array(a), np.array(b)))]:
            el, _, _ = make_layer(
                f'name: "e" type: "Eltwise" bottom: "a" bottom: "b" top: "y"\n'
                f'eltwise_param {{ operation: {op} }}',
                [(2, 3), (2, 3)],
            )
            (y,), _ = el.apply({}, {}, [a, b], train=False, rng=None)
            np.testing.assert_allclose(np.array(y), ref, rtol=1e-6)

    def test_eltwise_coeff(self, rng):
        a, b = rand((2, 3), rng), rand((2, 3), rng)
        el, _, _ = make_layer(
            'name: "e" type: "Eltwise" bottom: "a" bottom: "b" top: "y"\n'
            'eltwise_param { operation: SUM coeff: 1 coeff: -1 }',
            [(2, 3), (2, 3)],
        )
        (y,), _ = el.apply({}, {}, [a, b], train=False, rng=None)
        np.testing.assert_allclose(np.array(y), np.array(a) - np.array(b),
                                   rtol=1e-5)

    def test_flatten_reshape(self, rng):
        x = rand((2, 3, 4, 5), rng)
        fl, _, _ = make_layer(
            'name: "f" type: "Flatten" bottom: "x" top: "y"', [(2, 3, 4, 5)])
        (y,), _ = fl.apply({}, {}, [x], train=False, rng=None)
        assert y.shape == (2, 60)
        rs, _, _ = make_layer(
            'name: "r" type: "Reshape" bottom: "x" top: "y"\n'
            'reshape_param { shape { dim: 0 dim: -1 dim: 5 } }',
            [(2, 3, 4, 5)],
        )
        (z,), _ = rs.apply({}, {}, [x], train=False, rng=None)
        assert z.shape == (2, 12, 5)

    def test_argmax(self, rng):
        x = rand((3, 7), rng)
        am, _, _ = make_layer(
            'name: "a" type: "ArgMax" bottom: "x" top: "y"', [(3, 7)])
        (y,), _ = am.apply({}, {}, [x], train=False, rng=None)
        np.testing.assert_array_equal(
            np.array(y)[:, 0, 0], np.argmax(np.array(x), axis=1))

    def test_scale_bias_layers(self, rng):
        x = rand((2, 3, 4), rng)
        sc, params, _ = make_layer(
            'name: "s" type: "Scale" bottom: "x" top: "y"\n'
            'scale_param { bias_term: true }',
            [(2, 3, 4)],
        )
        params = {"operand": jnp.array([1.0, 2.0, 3.0]),
                  "bias": jnp.array([0.5, 0.0, -0.5])}
        (y,), _ = sc.apply(params, {}, [x], train=False, rng=None)
        expect = np.array(x) * np.array([1, 2, 3])[None, :, None] + \
            np.array([0.5, 0, -0.5])[None, :, None]
        np.testing.assert_allclose(np.array(y), expect, rtol=1e-5)


class TestMoreGradients:
    """Gradient checks for structural ops (reference runs GradientChecker
    on every layer; these cover the pure-movement ones)."""

    @pytest.mark.parametrize("proto,shapes", [
        ('type: "Concat" bottom: "a" bottom: "b" top: "y"',
         [(2, 3, 4), (2, 2, 4)]),
        ('type: "Slice" bottom: "x" top: "a" top: "b"\n'
         'slice_param { axis: 1 slice_point: 2 }', [(2, 5)]),
        ('type: "Flatten" bottom: "x" top: "y"', [(2, 3, 4)]),
        ('type: "Tile" bottom: "x" top: "y" tile_param { tiles: 3 }',
         [(2, 4)]),
        ('type: "Reduction" bottom: "x" top: "y"\n'
         'reduction_param { operation: SUMSQ axis: 1 }', [(3, 4)]),
        ('type: "Eltwise" bottom: "a" bottom: "b" top: "y"\n'
         'eltwise_param { operation: PROD }', [(2, 3), (2, 3)]),
        ('type: "Scale" bottom: "x" top: "y" scale_param { bias_term: true }',
         [(2, 3, 4)]),
        ('type: "Bias" bottom: "x" top: "y"', [(2, 3, 4)]),
        ('type: "MVN" bottom: "x" top: "y"', [(2, 3, 4, 4)]),
        ('type: "LRN" bottom: "x" top: "y"\n'
         'lrn_param { local_size: 3 norm_region: WITHIN_CHANNEL }',
         [(1, 2, 5, 5)]),
        ('type: "SPP" bottom: "x" top: "y" spp_param { pyramid_height: 2 }',
         [(1, 2, 6, 6)]),
    ], ids=lambda v: v[7:25] if isinstance(v, str) else "")
    def test_gradients(self, proto, shapes, rng):
        layer, params, state = make_layer(f'name: "l" {proto}', shapes)
        bottoms = [rand(s, rng) for s in shapes]
        check_gradients(layer, params, state, bottoms)

    def test_crop_gradients(self, rng):
        layer, params, state = make_layer(
            'name: "c" type: "Crop" bottom: "x" bottom: "ref" top: "y"\n'
            'crop_param { axis: 2 offset: 1 }',
            [(1, 2, 5, 5), (1, 2, 3, 3)],
        )
        check_gradients(layer, params, state,
                        [rand((1, 2, 5, 5), rng), rand((1, 2, 3, 3), rng)],
                        bottoms_to_check=[0])


class TestEmbed:
    def test_forward_and_grad(self, rng):
        layer, params, state = make_layer(
            'name: "e" type: "Embed" bottom: "i" top: "y"\n'
            'embed_param { num_output: 4 input_dim: 10\n'
            '  weight_filler { type: "gaussian" std: 1 } }',
            [(5,)],
        )
        idx = jnp.asarray(rng.randint(0, 10, 5))
        (y,), _ = layer.apply(params, state, [idx], train=False, rng=None)
        np.testing.assert_allclose(
            np.array(y), np.array(params["weight"])[np.array(idx)] +
            np.array(params["bias"]), rtol=1e-5)
        check_gradients(layer, params, state, [idx], bottoms_to_check=[])


class TestMVN:
    def test_normalizes(self, rng):
        layer, params, state = make_layer(
            'name: "m" type: "MVN" bottom: "x" top: "y"', [(3, 2, 4, 4)])
        x = rand((3, 2, 4, 4), rng, scale=3.0) + 2.0
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        yn = np.array(y)
        np.testing.assert_allclose(yn.mean(axis=(2, 3)), 0, atol=1e-5)
        np.testing.assert_allclose(yn.std(axis=(2, 3)), 1, atol=1e-2)
