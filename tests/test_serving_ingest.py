"""Serving request-ingest tests (ISSUE 14): native request decode
parity, window-fused preprocessing row-identity, the hot-content
decoded-request cache, PIL fallback for declines, the bitwise
pre-native path under CAFFE_NATIVE_DECODE=0, the typed-400 contract
for corrupt uploads, and the zero-recompile invariant held throughout.

Parity contracts under test (docs/serving.md "Native request ingest"):
  * decode — PNG bitwise vs PIL, JPEG <= 1 LSB per pixel (the decode
    plane's documented contract, data/decode.py);
  * preprocess — the native fused kernel (transform_core.h
    serve_preprocess_one: u8/255 -> PIL-convention F-mode BILINEAR
    resize -> center crop -> raw_scale/mean/input_scale) is BITWISE
    equal to the Python per-request chain (caffe_io.resize_center_crop
    + Transformer.preprocess) for the same decoded pixels;
  * scores — with a pinned single-bucket ladder (one compiled program,
    so PR 7's ~1e-15 cross-program reduction-order variance cannot
    leak in), serving the same PNG trace native vs pre-native is
    bitwise score-identical.
"""

import io
import json
import os
import subprocess
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from caffe_mpi_tpu import caffe_io, native
from caffe_mpi_tpu.data import decode as dmod
from caffe_mpi_tpu.serving import ServingEngine, ingest
from caffe_mpi_tpu.serving.http_front import make_server

DEPLOY = """
name: "toy"
layer { name: "data" type: "Input" top: "data"
        input_param { shape { dim: 4 dim: 3 dim: 8 dim: 8 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 6 kernel_size: 3
          weight_filler { type: "xavier" } } }
layer { name: "ip" type: "InnerProduct" bottom: "c" top: "score"
        inner_product_param { num_output: 5
          weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "score" top: "prob" }
"""

PRE = dict(mean=np.array([0.1, 0.2, 0.3], np.float32), raw_scale=255.0,
           channel_swap=(2, 1, 0))


@pytest.fixture(scope="module", autouse=True)
def built():
    if not native.available():
        script = os.path.join(os.path.dirname(native.__file__), "build.sh")
        try:
            subprocess.run(["sh", script], check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pytest.skip("native toolchain unavailable")
        native._TRIED = False  # re-probe
    if not (native.available() and native.decode_available()
            and native.serve_preprocess_available()):
        pytest.skip("native ingest plane unavailable (no libjpeg/libpng "
                    "at build time) — PIL fallback covers production")


@pytest.fixture(autouse=True)
def clean_env(monkeypatch):
    monkeypatch.delenv("CAFFE_NATIVE_DECODE", raising=False)
    dmod.STATS.reset()


@pytest.fixture(scope="module")
def deploy(tmp_path_factory):
    p = tmp_path_factory.mktemp("serve_ingest") / "deploy.prototxt"
    p.write_text(DEPLOY)
    return str(p)


def _encode(img_hwc_rgb, fmt, **kw):
    from PIL import Image
    b = io.BytesIO()
    Image.fromarray(img_hwc_rgb).save(b, fmt, **kw)
    return b.getvalue()


def _png(seed, hw=(12, 12)):
    rng = np.random.RandomState(seed)
    return _encode(rng.randint(0, 256, (*hw, 3), np.uint8), "PNG")


def _pil_chw(data):
    from PIL import Image
    img = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    return img[:, :, ::-1].transpose(2, 0, 1)


def _engine(deploy, **kw):
    # single-bucket ladder: one compiled program for every dispatch, so
    # cross-pass score comparisons are bitwise (see module docstring)
    kw.setdefault("buckets", "4")
    kw.setdefault("window_ms", 5)
    pre = kw.pop("pre", PRE)
    eng = ServingEngine(**kw)
    eng.load_model("m", deploy, **pre)
    return eng


class TestRequestDecodeParity:
    def test_png_bitwise_vs_pil(self):
        eng = ServingEngine(start=False)
        data = _png(0, (19, 23))
        nat = eng.decode_request(data)
        np.testing.assert_array_equal(nat, _pil_chw(data))
        assert dmod.STATS.snapshot()["native_records"] == 1
        eng.close()

    def test_jpeg_within_one_lsb(self, rng):
        eng = ServingEngine(start=False)
        data = _encode(rng.randint(0, 256, (21, 17, 3)).astype(np.uint8),
                       "JPEG", quality=90)
        nat = eng.decode_request(data).astype(np.int16)
        ref = _pil_chw(data).astype(np.int16)
        assert np.abs(nat - ref).max() <= 1
        eng.close()

    def test_forced_pil_is_prenative_bitwise(self, monkeypatch):
        data = _png(1)
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "0")
        eng = ServingEngine(start=False)
        arr = eng.decode_request(data)
        np.testing.assert_array_equal(arr, _pil_chw(data))
        snap = dmod.STATS.snapshot()
        assert snap["pil_records"] == 1 and snap["native_records"] == 0
        eng.close()


class TestFusedPreprocessParity:
    def test_native_kernel_bitwise_vs_python_chain(self, rng):
        """The load-bearing unit contract: serve_preprocess_batch ==
        the per-request Python chain (resize_center_crop + Transformer)
        BITWISE, across resize/crop/swap/raw/mean/input_scale combos —
        including the PIL-convention F-mode BILINEAR resample."""
        cases = [
            # (h, w, image_dims, crop_dims, swap_rgb, raw, mean, iscale)
            (37, 53, (24, 24), (24, 24), (2, 1, 0), 255.0,
             np.array([104., 117., 123.], np.float32), None),
            (10, 10, (8, 8), (8, 8), None, None, None, None),
            (12, 12, (12, 12), (8, 8), (2, 1, 0), 255.0, None, 0.0078125),
            (64, 48, (32, 32), (28, 28), (1, 0, 2), 128.0,
             np.array([1., 2., 3.], np.float32), 2.5),
            (8, 8, (16, 16), (16, 16), None, 255.0, None, None),
        ]
        for h, w, img_d, crop_d, swap_rgb, raw, mean, iscale in cases:
            u8 = np.ascontiguousarray(
                rng.randint(0, 256, (3, h, w)).astype(np.uint8))  # BGR CHW
            img = dmod.to_float_image(u8)
            ref = caffe_io.resize_center_crop(img, img_d, crop_d)
            ref = ref.transpose(2, 0, 1)
            if swap_rgb is not None:
                ref = ref[np.array(swap_rgb), :, :]
            if raw is not None:
                ref = ref * raw
            if mean is not None:
                ref = ref - mean.reshape(3, 1, 1)
            if iscale is not None:
                ref = ref * iscale
            sw = [2 - (swap_rgb[j] if swap_rgb else j) for j in range(3)]
            out, status = native.serve_preprocess_batch(
                [u8], img_h=img_d[0], img_w=img_d[1], crop_h=crop_d[0],
                crop_w=crop_d[1], swap=sw, raw_scale=raw, mean=mean,
                input_scale=iscale)
            assert (status == 0).all()
            np.testing.assert_array_equal(out[0],
                                          np.asarray(ref, np.float32))

    def test_window_fused_scores_bitwise_vs_prenative(self, deploy,
                                                      monkeypatch):
        """The e2e row-identity claim: the same PNG trace served through
        the native window-fused path and through the bitwise pre-native
        path (CAFFE_NATIVE_DECODE=0: PIL decode + per-request Python
        preprocess in the caller's thread) scores IDENTICALLY — resize
        engaged (12x12 uploads into the 8x8-input net)."""
        trace = [_png(i) for i in range(10)]
        eng = _engine(deploy)
        futs = [eng.submit_bytes("m", b) for b in trace]
        nat_scores = np.stack([f.result(60) for f in futs])
        st = eng.ingest.stats()
        assert st["fused_rows"] == 10 and st["immediate_rows"] == 0
        assert st["fused_fallback_rows"] == 0
        assert eng.compile_count == eng.warmed_buckets
        eng.close()

        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "0")
        eng = _engine(deploy)
        futs = [eng.submit_bytes("m", b) for b in trace]
        pil_scores = np.stack([f.result(60) for f in futs])
        st = eng.ingest.stats()
        assert st["immediate_rows"] == 10 and st["fused_rows"] == 0
        assert eng.compile_count == eng.warmed_buckets
        eng.close()

        np.testing.assert_array_equal(nat_scores, pil_scores)

    def test_prenative_path_matches_classic_submit(self, deploy,
                                                   monkeypatch):
        """CAFFE_NATIVE_DECODE=0 submit_bytes IS the pre-ISSUE-14
        pipeline: PIL float decode + engine.submit — bitwise, same
        engine, same program."""
        from PIL import Image
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "0")
        eng = _engine(deploy)
        for i in range(4):
            data = _png(20 + i)
            img = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"),
                             np.float32) / 255.0
            a = eng.submit_bytes("m", data).result(60)
            b = eng.submit("m", img).result(60)
            np.testing.assert_array_equal(a, b)
        assert eng.compile_count == eng.warmed_buckets
        eng.close()

    def test_full_image_mean_model_falls_back_classic(self, deploy):
        """A model whose preprocessing the fused kernel cannot express
        (full-image mean) keeps the classic per-request path — no plan,
        no fused rows, requests still serve."""
        full_mean = np.full((3, 8, 8), 0.25, np.float32)
        eng = _engine(deploy, pre=dict(mean=full_mean, raw_scale=255.0))
        assert eng.model("m").ingest_plan is None
        f = eng.submit_bytes("m", _png(3))
        assert f.result(60).shape == (5,)
        st = eng.ingest.stats()
        assert st["immediate_rows"] == 1 and st["fused_rows"] == 0
        assert eng.compile_count == eng.warmed_buckets
        eng.close()


class TestDecodedRequestCache:
    def test_hot_repeat_zero_decode_calls(self, deploy):
        eng = _engine(deploy, decoded_cache_mb=4)
        hot = _png(7)
        eng.submit_bytes("m", hot).result(60)
        before = dmod.STATS.snapshot()["decode_calls"]
        futs = [eng.submit_bytes("m", hot) for _ in range(5)]
        scores = np.stack([f.result(60) for f in futs])
        assert dmod.STATS.snapshot()["decode_calls"] == before
        st = eng.ingest.stats()
        assert st["cache_hits"] == 5 and st["cache_misses"] == 1
        assert st["cache_inserts"] == 1
        # cached repeats still score — and identically to each other
        assert np.array_equal(scores, np.repeat(scores[:1], 5, axis=0))
        assert eng.compile_count == eng.warmed_buckets
        eng.close()

    def test_lru_eviction_bounded_by_budget(self, deploy):
        # an entry charges decoded pixels (12x12x3 = 432) PLUS the
        # stored encoded bytes (the exact-identity check's cost); size
        # the budget to hold exactly two entries
        entries = [432 + len(_png(30 + i)) for i in range(4)]
        budget = entries[0] + entries[1] + min(entries) // 2
        eng = _engine(deploy, decoded_cache_mb=budget / 2**20)
        for i in range(4):
            eng.submit_bytes("m", _png(30 + i)).result(60)
        st = eng.ingest.stats()
        assert st["cache_inserts"] == 4 and st["cache_evictions"] == 2
        assert st["cache_bytes"] <= budget
        # the two newest stay hot, the two oldest were evicted
        before = dmod.STATS.snapshot()["decode_calls"]
        eng.submit_bytes("m", _png(33)).result(60)
        assert dmod.STATS.snapshot()["decode_calls"] == before
        eng.submit_bytes("m", _png(30)).result(60)
        assert dmod.STATS.snapshot()["decode_calls"] == before + 1
        eng.close()

    def test_oversized_record_not_cached(self, deploy):
        eng = _engine(deploy, decoded_cache_mb=100 / 2**20)  # 100 bytes
        eng.submit_bytes("m", _png(40)).result(60)
        st = eng.ingest.stats()
        assert st["cache_inserts"] == 0 and st["cache_bytes"] == 0
        eng.close()

    def test_crc_collision_never_serves_wrong_pixels(self, monkeypatch):
        """Review regression: crc32c is 32 bits (and linear — a
        colliding file is craftable), so a HIT must verify exact
        encoded-byte identity. Simulated collision: every request
        hashes to the same key; the second image must still decode to
        ITS OWN pixels, never the first's cached decode."""
        from caffe_mpi_tpu.serving import ingest as ing
        monkeypatch.setattr(ing, "_content_key", lambda data: 42)
        eng = ServingEngine(decoded_cache_mb=4, start=False)
        a, b = _png(90), _png(91)
        pix_a = eng.decode_request(a)
        pix_b = eng.decode_request(b)  # same key, different bytes
        np.testing.assert_array_equal(pix_b, _pil_chw(b))
        assert not np.array_equal(pix_a, pix_b)
        st = eng.ingest.stats()
        assert st["cache_hits"] == 0 and st["cache_misses"] == 2
        # the newer content replaced the colliding entry, bytes-exact:
        # b now hits, a now misses (decodes fresh, still correct)
        assert np.array_equal(eng.decode_request(b), pix_b)
        assert eng.ingest.stats()["cache_hits"] == 1
        np.testing.assert_array_equal(eng.decode_request(a), _pil_chw(a))
        eng.close()

    def test_negative_cache_budget_rejected(self):
        with pytest.raises(ValueError, match="serve_decoded_cache_mb"):
            ServingEngine(decoded_cache_mb=-1, start=False)

    def test_racing_duplicate_inserts_account_bytes_once(self, deploy):
        """Review regression: two handler threads missing on the same
        hot image concurrently must not double-count cache_bytes (a
        blind overwrite left phantom bytes shrinking the budget until
        the cache degraded to a 0% hit rate)."""
        eng = ServingEngine(decoded_cache_mb=4, start=False)
        data = _png(70)
        nbytes = eng.decode_request(data).nbytes
        barrier = threading.Barrier(8)

        def hammer():
            barrier.wait()
            for _ in range(20):
                eng.decode_request(data)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        st = eng.ingest.stats()
        assert st["cache_bytes"] == nbytes + len(data)
        assert st["cache_inserts"] == 1
        assert st["cache_hits"] + st["cache_misses"] == 161
        eng.close()


class TestDeclinesAndFallback:
    def test_sixteen_bit_png_declines_to_pil(self, deploy, rng):
        """An alpha/16-bit PNG is outside the native decoder's parity
        envelope — it must decline to PIL (coverage never shrinks) and
        the request must still serve through the fused window."""
        from PIL import Image
        b = io.BytesIO()
        Image.fromarray(rng.randint(0, 2**16, (12, 12)).astype(np.uint16)
                        ).save(b, "PNG")
        eng = _engine(deploy)
        f = eng.submit_bytes("m", b.getvalue())
        assert f.result(60).shape == (5,)
        snap = dmod.STATS.snapshot()
        assert snap["native_fallbacks"] == 1 and snap["pil_records"] == 1
        assert eng.compile_count == eng.warmed_buckets
        eng.close()

    def test_corrupt_bytes_raise_in_caller_thread(self, deploy):
        eng = _engine(deploy)
        with pytest.raises(Exception):
            eng.submit_bytes("m", b"these are not image bytes")
        # a truncated JPEG: valid magic, rotten entropy data — the
        # native decoder returns a status (never aborts), PIL raises
        jpeg = _encode(np.zeros((16, 16, 3), np.uint8), "JPEG")
        with pytest.raises(Exception):
            eng.submit_bytes("m", jpeg[:24])
        eng.close()


class TestHTTPFront:
    @pytest.fixture()
    def server(self, deploy):
        eng = _engine(deploy, decoded_cache_mb=2)
        srv = make_server(eng, "m", labels=[f"c{i}" for i in range(5)],
                          port=0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{srv.server_address[1]}", eng
        srv.shutdown()
        eng.close()

    def test_upload_serves_through_native_ingest(self, server):
        base, eng = server
        req = urllib.request.Request(base + "/classify", data=_png(50),
                                     headers={"Content-Type": "image/png"})
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert len(out["predictions"]) == 5
        st = eng.ingest.stats()
        assert st["requests"] == 1
        assert st["deferred_rows"] == 1  # window-fused, not per-handler
        assert st["decode_plane"]["native_records"] >= 1

    def test_corrupt_upload_typed_400_bad_request(self, server):
        """ISSUE 14 satellite: corrupt/undecodable bytes through the
        native path map to the typed 400 kind=bad_request body — never
        a 500, never a native abort."""
        base, eng = server
        for payload in (b"definitely not an image",
                        _encode(np.zeros((16, 16, 3), np.uint8),
                                "JPEG")[:24]):
            req = urllib.request.Request(
                base + "/classify", data=payload,
                headers={"Content-Type": "image/png"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=60)
            assert e.value.code == 400
            body = json.loads(e.value.read())
            assert body["kind"] == "bad_request"
        # the engine survived: a good upload still classifies, and
        # steady-state serving never compiled
        req = urllib.request.Request(base + "/classify", data=_png(51),
                                     headers={"Content-Type": "image/png"})
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert len(out["predictions"]) == 5
        assert eng.compile_count == eng.warmed_buckets

    def test_stats_reports_ingest_block(self, server):
        base, eng = server
        urllib.request.urlopen(
            urllib.request.Request(
                base + "/classify", data=_png(52),
                headers={"Content-Type": "image/png"}), timeout=60).read()
        st = json.loads(urllib.request.urlopen(base + "/stats",
                                               timeout=60).read())
        assert "ingest" in st
        assert st["ingest"]["cache_budget_mb"] == 2.0
        assert "decode_plane" in st["ingest"]


class TestShedAndHealthGates:
    def test_unhealthy_engine_sheds_before_decode(self, deploy):
        """Review regression: an open stall breaker must fast-fail
        submit_bytes BEFORE any decode cost — rejected uploads cannot
        burn host CPU during the exact overload shedding exists for."""
        from caffe_mpi_tpu.serving import EngineUnhealthyError
        eng = _engine(deploy)
        eng._healthy = False
        with pytest.raises(EngineUnhealthyError):
            eng.submit_bytes("m", _png(80))
        st = eng.ingest.stats()
        assert st["requests"] == 0  # never reached the decode plane
        eng._healthy = True
        eng.close()

    def test_shed_requests_do_not_inflate_engagement_counters(
            self, deploy):
        """Review regression: a batcher-level shed (queue limit) must
        not count deferred_rows — the request never entered the queue,
        and engagement checks compare deferred vs fused rows."""
        from caffe_mpi_tpu.serving import ShedError
        # a huge window parks the first request; limit 1 sheds the next
        eng = ServingEngine(window_ms=10_000, queue_limit=1, buckets="4")
        eng.load_model("m", deploy, **PRE)
        first = eng.submit_bytes("m", _png(81))
        shed = 0
        for i in range(3):
            try:
                eng.submit_bytes("m", _png(82 + i))
            except ShedError:
                shed += 1
        assert shed == 3
        assert eng.ingest.stats()["deferred_rows"] == 1
        eng.close()
        assert first.done()


class TestZeroRecompile:
    def test_mixed_ingest_traffic_never_recompiles(self, deploy,
                                                   monkeypatch):
        """The PR 7 invariant held across the whole ingest surface:
        mixed-size uploads, cache hits, PIL declines, env flips — the
        compiled ladder never grows past its warm count."""
        eng = ServingEngine(window_ms=2, decoded_cache_mb=2)
        eng.load_model("m", deploy, **PRE)
        warmed = eng.warmed_buckets
        futs = [eng.submit_bytes("m", _png(i % 3, hw=(10 + i % 4, 12)))
                for i in range(20)]
        monkeypatch.setenv("CAFFE_NATIVE_DECODE", "0")
        futs += [eng.submit_bytes("m", _png(60 + i)) for i in range(5)]
        for f in futs:
            assert f.result(60).shape == (5,)
        assert eng.compile_count == warmed == eng.warmed_buckets
        eng.close()
