"""ZeRO-1 optimizer-state sharding (zero_stage: 1 — TPU extension beyond
the reference's replicated-everything DP).

Invariants:
- parameter trajectories are EXACTLY those of replicated DP (the sharding
  moves where the update computes, never what it computes);
- slots whose dim 0 divides n_data actually live split over 'data';
- indivisible slots fall back to replicated;
- snapshot/restore survives with placements reapplied.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.parallel import MeshPlan
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver

NET = """
name: "zero_mlp"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 16 dim: 8 } shape { dim: 16 } } }
layer { name: "ip1" type: "InnerProduct" bottom: "x" top: "h"
        inner_product_param { num_output: 32 bias_term: true
          weight_filler { type: "xavier" } } }
layer { name: "r" type: "ReLU" bottom: "h" top: "h" }
layer { name: "ip2" type: "InnerProduct" bottom: "h" top: "y"
        inner_product_param { num_output: 5 bias_term: true
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y" bottom: "t" top: "l" }
"""


def make_solver(zero, solver_type="SGD"):
    sp = SolverParameter.from_text(
        f'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 20 '
        f'type: "{solver_type}" random_seed: 7 weight_decay: 0.001 '
        f'zero_stage: {zero}'
    )
    if solver_type == "Adam":
        sp.momentum2 = 0.999
    sp.net_param = NetParameter.from_text(NET)
    return Solver(sp, mesh=MeshPlan.data_parallel())


def feed_fn(it):
    r = np.random.RandomState(100 + it)
    return {"x": jnp.asarray(r.randn(16, 8).astype(np.float32)),
            "t": jnp.asarray(r.randint(0, 5, 16))}


def _params_np(solver):
    return {(ln, pn): np.asarray(a)
            for ln, lp in solver.params.items() for pn, a in lp.items()}


@pytest.mark.parametrize("solver_type", ["SGD", "Adam"])
def test_zero1_matches_replicated_dp(solver_type):
    base = make_solver(0, solver_type)
    zero = make_solver(1, solver_type)
    base.step(6, feed_fn)
    zero.step(6, feed_fn)
    pb, pz = _params_np(base), _params_np(zero)
    assert pb.keys() == pz.keys()
    for k in pb:
        np.testing.assert_allclose(pz[k], pb[k], rtol=2e-5, atol=2e-6,
                                   err_msg=str(k))


def test_slots_actually_sharded():
    s = make_solver(1)
    # ip1 weight (32, 8): 32 % 8 == 0 -> dim 0 split over 'data'
    (hist,) = s.opt_state["ip1"]["weight"]
    spec = hist.sharding.spec
    assert spec and spec[0] == "data", spec
    assert ("ip1", "weight") in s._zero_shardings
    # ip2 weight (5, 32): 5 % 8 != 0 -> replicated fallback
    (hist2,) = s.opt_state["ip2"]["weight"]
    assert not any(hist2.sharding.spec), hist2.sharding.spec
    assert ("ip2", "weight") not in s._zero_shardings
    # shard really is 1/8 of the slot on each device
    shard = next(iter(hist.addressable_shards)).data
    assert shard.shape[0] == hist.shape[0] // 8


def test_zero1_with_iter_size_accumulation():
    """iter_size gradient accumulation (lax.scan over microbatches)
    composes with the sharded update: same trajectory as replicated."""
    def build(zero):
        sp = SolverParameter.from_text(
            f'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 20 '
            f'type: "SGD" random_seed: 7 iter_size: 2 zero_stage: {zero}')
        sp.net_param = NetParameter.from_text(NET)
        return Solver(sp, mesh=MeshPlan.data_parallel())
    base, zero = build(0), build(1)
    base.step(4, feed_fn)
    zero.step(4, feed_fn)
    pb, pz = _params_np(base), _params_np(zero)
    for k in pb:
        np.testing.assert_allclose(pz[k], pb[k], rtol=2e-5, atol=2e-6,
                                   err_msg=str(k))


def test_zero_requires_mesh():
    sp = SolverParameter.from_text(
        'base_lr: 0.05 lr_policy: "fixed" zero_stage: 1')
    sp.net_param = NetParameter.from_text(NET)
    with pytest.raises(ValueError, match="zero_stage"):
        Solver(sp)


def test_snapshot_restore_keeps_sharding(tmp_path):
    s = make_solver(1)
    s.step(3, feed_fn)
    prefix = str(tmp_path / "zck")
    s.sp.snapshot_prefix = prefix
    s.snapshot()
    s2 = make_solver(1)
    s2.restore(f"{prefix}_iter_3.solverstate")
    (hist,) = s2.opt_state["ip1"]["weight"]
    assert hist.sharding.spec and hist.sharding.spec[0] == "data"
    # trajectories continue identically
    s.step(3, feed_fn)
    s2.step(3, feed_fn)
    p1, p2 = _params_np(s), _params_np(s2)
    for k in p1:
        np.testing.assert_allclose(p2[k], p1[k], rtol=1e-6, err_msg=str(k))
