"""Finite-difference gradient checker.

Mirrors the reference's GradientChecker
(include/caffe/test/test_gradient_check_util.hpp:18-110): perturb each input
element by ±step, compare the central difference against the analytic
gradient from jax.grad, with the same scale-relative threshold
(threshold * max(|analytic|, |numeric|, 1)).

Instead of checking every (input, output) pair exhaustively, the loss is a
fixed random linear functional of all tops — one backward pass checks the
full Jacobian action, which is what jax.grad computes anyway.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from caffe_mpi_tpu.core.types import DtypePolicy
from caffe_mpi_tpu.layers.base import create_layer
from caffe_mpi_tpu.proto.config import LayerParameter


def make_layer(prototxt: str, in_shapes, phase: str = "TRAIN",
               policy: DtypePolicy | None = None, seed: int = 0):
    """Build + setup + init a single layer from a prototxt snippet."""
    lp = LayerParameter.from_text(prototxt)
    layer = create_layer(lp, policy or DtypePolicy(), phase)
    layer.in_shapes = [tuple(s) for s in in_shapes]
    layer.out_shapes = layer.setup(layer.in_shapes)
    params = layer.init_params(jax.random.PRNGKey(seed))
    state = layer.init_state()
    return layer, params, state


def apply_layer(layer, params, state, bottoms, train=True, rng=None):
    tops, new_state = layer.apply(params, state, list(bottoms), train=train,
                                  rng=rng)
    return tops, new_state


def check_gradients(layer, params, state, bottoms, *, check_params=True,
                    bottoms_to_check=None, step=1e-2, threshold=1e-2,
                    train=True, rng=None, seed=42):
    """Assert analytic == numeric gradients for params and selected bottoms."""
    bottoms = [jnp.asarray(b) for b in bottoms]
    if bottoms_to_check is None:
        bottoms_to_check = [
            i for i, b in enumerate(bottoms)
            if jnp.issubdtype(b.dtype, jnp.floating)
        ]
    key = jax.random.PRNGKey(seed)
    tops0, _ = apply_layer(layer, params, state, bottoms, train=train, rng=rng)
    weights = [
        jax.random.normal(jax.random.fold_in(key, i), jnp.shape(t))
        for i, t in enumerate(tops0)
    ]

    def loss_fn(params_, bottoms_):
        tops, _ = apply_layer(layer, params_, state, bottoms_, train=train,
                              rng=rng)
        return sum(jnp.sum(w * t.astype(jnp.float32)) for w, t in zip(weights, tops))

    grads_p, grads_b = jax.grad(loss_fn, argnums=(0, 1),
                                allow_int=True)(params, bottoms)

    def check_array(name, arr, grad, perturb):
        arr_np = np.asarray(arr, dtype=np.float64)
        grad_np = np.asarray(grad, dtype=np.float64)
        flat = arr_np.reshape(-1)
        n_check = min(flat.size, 64)
        idxs = np.random.RandomState(seed).choice(flat.size, n_check, replace=False)
        for i in idxs:
            orig = flat[i]
            flat[i] = orig + step
            lp_ = float(loss_fn(*perturb(arr_np.reshape(arr.shape))))
            flat[i] = orig - step
            lm_ = float(loss_fn(*perturb(arr_np.reshape(arr.shape))))
            flat[i] = orig
            numeric = (lp_ - lm_) / (2 * step)
            analytic = grad_np.reshape(-1)[i]
            scale = max(abs(numeric), abs(analytic), 1.0)
            assert abs(numeric - analytic) <= threshold * scale, (
                f"{name}[{i}]: analytic {analytic:.6g} vs numeric "
                f"{numeric:.6g} (scale {scale:.3g})"
            )

    if check_params:
        for pname in params:
            def perturb_param(new, pname=pname):
                p2 = dict(params)
                p2[pname] = jnp.asarray(new, dtype=params[pname].dtype)
                return p2, bottoms
            check_array(f"param:{pname}", params[pname], grads_p[pname],
                        perturb_param)
    for bi in bottoms_to_check:
        def perturb_bottom(new, bi=bi):
            b2 = list(bottoms)
            b2[bi] = jnp.asarray(new, dtype=bottoms[bi].dtype)
            return params, b2
        check_array(f"bottom:{bi}", bottoms[bi], grads_b[bi], perturb_bottom)
