"""Persistent AOT program bank (ISSUE 17): zero-compile warm starts,
verified-atomic entry publication, corruption/fingerprint fallback, and
the netshape-planned admission path.

Reference: the reference deployment (caffe.cpp:291, classification.cpp)
has no compilation artifact to persist; this plane is TPU-native. The
behavior baseline is PR 7's zero-recompile invariant — extended here to
`compile_count == bank_misses` (unconditional) and `compile_count +
bank_hits == warmed_buckets` — plus PR 3's verified-atomic manifest
semantics applied to one standalone artifact per bucket program.
"""

import glob
import os
import threading

import numpy as np
import pytest

import caffe_mpi_tpu.pycaffe as caffe
from caffe_mpi_tpu.proto.config import NetParameter
from caffe_mpi_tpu.serving import BankStats, ProgramBank, ServingEngine
from caffe_mpi_tpu.serving.plan import plan_admission, plan_model
from caffe_mpi_tpu.serving.program_bank import fingerprint
from caffe_mpi_tpu.utils import resilience
from caffe_mpi_tpu.utils.resilience import (FAULTS, verify_file_manifest,
                                            write_file_manifest)

TOY_NET = """
name: "toy"
layer {{ name: "data" type: "Input" top: "data"
        input_param {{ shape {{ dim: {batch} dim: 3 dim: 8 dim: 8 }} }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "score"
        inner_product_param {{ num_output: 5
          weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "prob" type: "Softmax" bottom: "score" top: "prob" }}
"""


def write_toy(tmp_path, batch=8, name="deploy.prototxt"):
    model = tmp_path / name
    model.write_text(TOY_NET.format(batch=batch))
    net = caffe.Net(str(model), caffe.TEST)
    weights = str(tmp_path / (name + ".caffemodel"))
    net.save(weights)
    return str(model), weights


def imgs(n, seed=0, hw=(8, 8)):
    r = np.random.RandomState(seed)
    return [r.rand(*hw, 3).astype(np.float32) for _ in range(n)]


def start(bank_dir, model, weights, **kw):
    eng = ServingEngine(window_ms=0,
                        program_bank=str(bank_dir) if bank_dir else None,
                        **kw)
    eng.load_model("m", model, weights)
    return eng


def bank_stats(eng):
    return eng.stats()["bank"]


# ---------------------------------------------------------------------------
# the invariant, bank off and on


class TestInvariant:
    def test_bank_off_misses_equal_compiles(self, tmp_path):
        model, weights = write_toy(tmp_path)
        eng = start(None, model, weights)
        try:
            st = bank_stats(eng)
            assert not st["enabled"]
            assert st["misses"] == eng.compile_count == eng.warmed_buckets
            assert st["hits"] == st["stores"] == 0
            ok, doc = eng.ready()
            assert ok and doc["bank_misses"] == eng.compile_count
        finally:
            eng.close()

    def test_warm_start_zero_compiles_bitwise(self, tmp_path):
        model, weights = write_toy(tmp_path)
        bank = tmp_path / "bank"
        cold = start(bank, model, weights)
        try:
            st = bank_stats(cold)
            assert st["enabled"] and st["path"] == str(bank)
            assert cold.compile_count == st["misses"] == cold.warmed_buckets
            assert st["stores"] == cold.warmed_buckets
            assert st["cold_start_ms"] > 0
            ref = cold.classify("m", imgs(5, seed=3))
        finally:
            cold.close()
        warm = start(bank, model, weights)
        try:
            st = bank_stats(warm)
            assert warm.compile_count == 0
            assert st["misses"] == 0
            assert st["hits"] == warm.warmed_buckets
            ok, doc = warm.ready()
            assert ok and doc["bank_hits"] == warm.warmed_buckets
            # the deserialized program is the stored XLA program: scores
            # on the same inputs + weights are bitwise-identical
            out = warm.classify("m", imgs(5, seed=3))
            assert np.array_equal(np.asarray(ref), np.asarray(out))
            # warm events carry the per-bucket breakdown
            for ev in st["warm"]["m"]:
                assert ev["source"] == "bank"
                assert ev["compile_ms"] == 0.0
                assert ev["deserialize_ms"] > 0
        finally:
            warm.close()

    def test_repopulated_bank_serves_next_engine(self, tmp_path):
        model, weights = write_toy(tmp_path)
        bank = tmp_path / "bank"
        start(bank, model, weights).close()
        # wipe ONE entry: the next engine misses it, recompiles it, and
        # repopulates — the engine after that is fully warm again
        victim = sorted(glob.glob(str(bank / "*.xpb")))[0]
        os.remove(victim)
        os.remove(victim + ".manifest.json")
        mid = start(bank, model, weights)
        try:
            st = bank_stats(mid)
            assert mid.compile_count == st["misses"] == 1
            assert st["hits"] == mid.warmed_buckets - 1
            assert st["stores"] == 1
        finally:
            mid.close()
        warm = start(bank, model, weights)
        try:
            assert warm.compile_count == 0
            assert bank_stats(warm)["hits"] == warm.warmed_buckets
        finally:
            warm.close()


# ---------------------------------------------------------------------------
# corruption: every broken-entry shape is a counted miss, never a crash


class TestCorruption:
    def test_truncated_entry_rejected_and_repopulated(self, tmp_path):
        model, weights = write_toy(tmp_path)
        bank = tmp_path / "bank"
        start(bank, model, weights).close()
        victim = sorted(glob.glob(str(bank / "*.xpb")))[0]
        blob = open(victim, "rb").read()
        with open(victim, "wb") as f:
            f.write(blob[:len(blob) // 2])  # torn write
        eng = start(bank, model, weights)
        try:
            st = bank_stats(eng)
            assert eng.compile_count == st["misses"] == 1
            assert st["verify_rejects"] == 1
            assert st["stores"] == 1  # repopulated
            eng.classify("m", imgs(2))
        finally:
            eng.close()
        # the repopulated entry round-trips
        warm = start(bank, model, weights)
        try:
            assert warm.compile_count == 0
        finally:
            warm.close()

    def test_bank_corrupt_fault_site(self, tmp_path):
        # the registered site flips a payload byte AFTER the manifest
        # committed — the bitrot shape the crc32c verify exists for
        model, weights = write_toy(tmp_path)
        bank = tmp_path / "bank"
        FAULTS.configure("bank_corrupt:1")
        try:
            start(bank, model, weights).close()
        finally:
            FAULTS.configure("")
        eng = start(bank, model, weights)
        try:
            st = bank_stats(eng)
            assert st["verify_rejects"] == 1
            assert eng.compile_count == st["misses"] == 1
            assert st["hits"] == eng.warmed_buckets - 1
            ok, _ = eng.ready()
            assert ok
        finally:
            eng.close()

    def test_garbage_payload_with_valid_manifest(self, tmp_path):
        # a verified entry that still fails to unpickle/deserialize must
        # count deserialize_failures and recompile, never crash
        model, weights = write_toy(tmp_path)
        bank = tmp_path / "bank"
        start(bank, model, weights).close()
        victim = sorted(glob.glob(str(bank / "*.xpb")))[0]
        with open(victim, "wb") as f:
            f.write(b"not a pickled executable")
        write_file_manifest(victim)  # re-commit: crc now matches garbage
        eng = start(bank, model, weights)
        try:
            st = bank_stats(eng)
            assert st["deserialize_failures"] == 1
            assert st["verify_rejects"] == 0
            assert eng.compile_count == st["misses"] == 1
        finally:
            eng.close()

    def test_fingerprint_mismatch_spoofed_runtime(self, tmp_path,
                                                  monkeypatch):
        # a jaxlib/backend bump changes the runtime tag: every banked
        # entry silently misses (no verify_rejects — the old entries are
        # intact, just keyed away) and the zoo recompiles + repopulates
        model, weights = write_toy(tmp_path)
        bank = tmp_path / "bank"
        start(bank, model, weights).close()
        import caffe_mpi_tpu.utils.compile_cache as cc
        monkeypatch.setattr(cc, "runtime_tag",
                            lambda: "jax-9.9.9/jaxlib-9.9.9/cpu/spoof")
        eng = start(bank, model, weights)
        try:
            st = bank_stats(eng)
            assert eng.compile_count == st["misses"] == eng.warmed_buckets
            assert st["hits"] == 0 and st["verify_rejects"] == 0
            assert st["stores"] == eng.warmed_buckets
        finally:
            eng.close()
        # and the spoofed-runtime entries now warm a same-runtime engine
        eng2 = start(bank, model, weights)
        try:
            assert eng2.compile_count == 0
        finally:
            eng2.close()


# ---------------------------------------------------------------------------
# concurrency: two engines sharing one bank directory


class TestConcurrentWriters:
    def test_two_engines_same_bank(self, tmp_path):
        model, weights = write_toy(tmp_path)
        bank = tmp_path / "bank"
        engines, errors = [], []

        def boot():
            try:
                engines.append(start(bank, model, weights))
            except Exception as e:  # noqa: BLE001 — the test's assertion
                errors.append(e)

        threads = [threading.Thread(target=boot) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        try:
            assert not errors
            for eng in engines:
                st = bank_stats(eng)
                assert eng.compile_count == st["misses"]
                assert eng.compile_count + st["hits"] == eng.warmed_buckets
                assert st["store_failures"] == 0
        finally:
            for eng in engines:
                eng.close()
        # whatever interleaving happened, the committed bank is whole
        warm = start(bank, model, weights)
        try:
            assert warm.compile_count == 0
            assert bank_stats(warm)["hits"] == warm.warmed_buckets
        finally:
            warm.close()


# ---------------------------------------------------------------------------
# fingerprint semantics


class TestFingerprint:
    def _param(self, tmp_path, batch=8):
        model, _ = write_toy(tmp_path, batch=batch)
        return NetParameter.from_file(model)

    def test_stable_and_selective(self, tmp_path):
        p = self._param(tmp_path)
        kw = dict(bucket=4, dtype="f32", out_spec="prob", runtime="rt")
        base = fingerprint(p, **kw)
        assert base == fingerprint(p, **kw)  # deterministic
        assert base != fingerprint(p, **{**kw, "bucket": 8})
        assert base != fingerprint(p, **{**kw, "dtype": "bf16"})
        assert base != fingerprint(p, **{**kw, "out_spec": "env"})
        assert base != fingerprint(p, **{**kw, "runtime": "rt2"})

    def test_topology_in_weights_out(self, tmp_path):
        # the declared batch is normalized away per bucket by the warm
        # path's rewrite, but a topology edit (layer width) must re-key
        pa = self._param(tmp_path)
        pb = self._param(tmp_path)
        kw = dict(bucket=4, dtype="f32", out_spec="prob", runtime="rt")
        assert fingerprint(pa, **kw) == fingerprint(pb, **kw)
        pb.layer[1].inner_product_param.num_output = 6
        assert fingerprint(pa, **kw) != fingerprint(pb, **kw)


# ---------------------------------------------------------------------------
# standalone-artifact manifests (the PR 3 scheme, single-file form)


class TestFileManifest:
    def test_roundtrip_and_commit_record(self, tmp_path):
        p = str(tmp_path / "artifact.bin")
        with open(p, "wb") as f:
            f.write(b"payload bytes")
        mpath = write_file_manifest(p, fingerprint="abc")
        assert os.path.exists(mpath)
        doc = verify_file_manifest(p)
        assert doc is not None and doc["fingerprint"] == "abc"

    def test_missing_manifest_or_file(self, tmp_path):
        p = str(tmp_path / "artifact.bin")
        with open(p, "wb") as f:
            f.write(b"x")
        assert verify_file_manifest(p) is None  # no commit record
        write_file_manifest(p)
        os.remove(p)
        assert verify_file_manifest(p) is None  # record without artifact

    def test_size_and_crc_mismatch(self, tmp_path):
        p = str(tmp_path / "artifact.bin")
        with open(p, "wb") as f:
            f.write(b"payload")
        write_file_manifest(p)
        with open(p, "r+b") as f:
            f.write(b"PAYLOAD")  # same size, different bytes
        assert verify_file_manifest(p) is None
        with open(p, "ab") as f:
            f.write(b"tail")
        assert verify_file_manifest(p) is None


# ---------------------------------------------------------------------------
# bank internals


class TestProgramBank:
    def test_load_absent_counts_plain_miss(self, tmp_path):
        bank = ProgramBank(str(tmp_path / "bank"), BankStats())
        assert bank.load("0" * 32) is None
        st = bank.stats.snapshot()
        assert st["misses"] == 1 and st["verify_rejects"] == 0

    def test_store_unserializable_counts_failure(self, tmp_path):
        bank = ProgramBank(str(tmp_path / "bank"), BankStats())
        assert bank.store("0" * 32, object()) is False
        st = bank.stats.snapshot()
        assert st["store_failures"] == 1 and st["stores"] == 0
        assert not os.listdir(bank.path)


# ---------------------------------------------------------------------------
# the netshape plan: static bytes, admission, and telemetry surface


class TestPlan:
    def test_plan_matches_built_model(self, tmp_path):
        model, weights = write_toy(tmp_path)
        plan = plan_model(NetParameter.from_file(model))
        eng = start(None, model, weights)
        try:
            m = eng.model("m")
            assert tuple(plan["ladder"]) == tuple(m.fwd.ladder)
            assert plan["param_bytes_exact"]
            assert plan["param_bytes"] == m.param_bytes
            assert plan["peak_activation_bytes"] > 0
            # the surfaced plan in stats matches the standalone one
            surfaced = bank_stats(eng)["plan"]["models"]["m"]
            assert surfaced["param_bytes"] == plan["param_bytes"]
            assert surfaced["load_ms"] > 0
        finally:
            eng.close()

    def test_admission_plan_predicts_lru_spill(self, tmp_path):
        model, weights = write_toy(tmp_path)
        pb = plan_model(NetParameter.from_file(model))["param_bytes"]
        # budget fits one model, not two: the planner must predict the
        # load-order LRU spill the engine then actually performs
        budget_mb = pb * 1.5 / 2**20
        planned = plan_admission([("a", pb), ("b", pb)],
                                 int(budget_mb * 2**20))
        assert planned["planned_spills"] == ["a"]
        assert planned["resident"] == ["b"]
        assert not planned["over_budget"]
        eng = ServingEngine(window_ms=0, hbm_mb=budget_mb)
        try:
            eng.load_model("a", model, weights)
            eng.load_model("b", model, weights)
            assert eng.spills == len(planned["planned_spills"])
            adm = bank_stats(eng)["plan"]["admission"]
            assert adm["planned_spills"] == ["a"]
        finally:
            eng.close()

    def test_admission_over_budget_flag(self):
        planned = plan_admission([("a", 100)], 50)
        assert planned["over_budget"]
        assert planned["resident"] == ["a"]  # newest always resident

    def test_plan_bf16_halves_activation_bytes(self, tmp_path):
        model, _ = write_toy(tmp_path)
        p = NetParameter.from_file(model)
        f32 = plan_model(p, dtype="f32")
        bf16 = plan_model(p, dtype="bf16")
        assert bf16["peak_activation_bytes"] == \
            f32["peak_activation_bytes"] // 2


# ---------------------------------------------------------------------------
# the registered fault site exists (doc-drift holds the description)


def test_bank_corrupt_site_registered():
    assert "bank_corrupt" in resilience.FAULT_SITES
