"""Tests for previously-uncovered paths: DummyData, MemoryData, ArgMax axis
mode, debug_info, V1-format caffemodel parsing, HDF5 snapshot format, and
the generated deploy nets."""

import glob
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.net import Net
from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from gradcheck import make_layer


class TestDummyData:
    def test_constant_and_gaussian_fills(self):
        net = Net(NetParameter.from_text("""
        layer { name: "d" type: "DummyData" top: "a" top: "b"
          dummy_data_param {
            shape { dim: 2 dim: 3 } shape { dim: 2 dim: 3 }
            data_filler { type: "constant" value: 7 }
            data_filler { type: "gaussian" std: 1 }
          } }
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        blobs, _, _ = net.apply(params, state, {}, train=True,
                                rng=jax.random.PRNGKey(1))
        np.testing.assert_allclose(np.array(blobs["a"]), 7.0)
        assert np.array(blobs["b"]).std() > 0.1

    def test_legacy_4d_fields(self):
        net = Net(NetParameter.from_text("""
        layer { name: "d" type: "DummyData" top: "x"
          dummy_data_param { num: 2 channels: 3 height: 4 width: 5 } }
        """))
        assert net.blob_shapes["x"] == (2, 3, 4, 5)


class TestMemoryData:
    def test_feed_slot(self, rng):
        net = Net(NetParameter.from_text("""
        layer { name: "m" type: "MemoryData" top: "data" top: "label"
          memory_data_param { batch_size: 4 channels: 2 height: 3 width: 3 } }
        """))
        params, state = net.init(jax.random.PRNGKey(0))
        feeds = {"data": jnp.asarray(rng.randn(4, 2, 3, 3).astype(np.float32)),
                 "label": jnp.asarray(rng.randint(0, 5, 4))}
        blobs, _, _ = net.apply(params, state, feeds, train=False)
        assert blobs["data"].shape == (4, 2, 3, 3)


class TestArgMaxAxis:
    def test_axis_mode(self, rng):
        layer, params, state = make_layer(
            'name: "a" type: "ArgMax" bottom: "x" top: "y"\n'
            'argmax_param { axis: 1 top_k: 2 }', [(2, 5, 3)])
        x = jnp.asarray(rng.randn(2, 5, 3).astype(np.float32))
        (y,), _ = layer.apply(params, state, [x], train=False, rng=None)
        assert y.shape == (2, 2, 3)
        top1 = np.array(y)[:, 0, :]
        np.testing.assert_array_equal(top1, np.argmax(np.array(x), axis=1))


class TestDebugInfo:
    def test_smoke(self, rng, capfd):
        net = Net(NetParameter.from_text("""
        debug_info: true
        layer { name: "in" type: "Input" top: "x"
                input_param { shape { dim: 2 dim: 3 } } }
        layer { name: "r" type: "ReLU" bottom: "x" top: "y" }
        """))
        assert net.debug_info
        params, state = net.init(jax.random.PRNGKey(0))
        net.apply(params, state,
                  {"x": jnp.asarray(rng.randn(2, 3).astype(np.float32))},
                  train=False)
        jax.effects_barrier()
        out = capfd.readouterr()
        assert "[Forward]" in out.out + out.err


class TestV1Caffemodel:
    def test_v1_layers_field_parses(self):
        """Binary NetParameter with V1 `layers` (field 2, name=4, blobs=6)."""
        from caffe_mpi_tpu.io import _tag, _varint, encode_blob, parse_caffemodel
        blob = encode_blob(np.arange(6, dtype=np.float32).reshape(2, 3))
        inner = (_tag(4, 2) + _varint(len(b"old_ip")) + b"old_ip"
                 + _tag(6, 2) + _varint(len(blob)) + blob)
        buf = _tag(2, 2) + _varint(len(inner)) + inner
        weights = parse_caffemodel(buf)
        assert "old_ip" in weights
        np.testing.assert_array_equal(weights["old_ip"][0],
                                      np.arange(6).reshape(2, 3))


class TestHDF5Snapshot:
    def test_snapshot_format_hdf5(self, tmp_path, rng):
        sp = SolverParameter.from_text(
            'base_lr: 0.05 lr_policy: "fixed" max_iter: 3 type: "SGD" '
            'snapshot_format: HDF5')
        sp.snapshot_prefix = str(tmp_path / "h5snap")
        sp.net_param = NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "x" top: "t"
                input_param { shape { dim: 2 dim: 4 } shape { dim: 2 } } }
        layer { name: "ip" type: "InnerProduct" bottom: "x" top: "y"
                inner_product_param { num_output: 3
                  weight_filler { type: "xavier" } } }
        layer { name: "l" type: "SoftmaxWithLoss" bottom: "y" bottom: "t"
                top: "loss" }
        """)
        from caffe_mpi_tpu.solver import Solver
        s = Solver(sp)
        feeds = {"x": jnp.asarray(rng.randn(2, 4).astype(np.float32)),
                 "t": jnp.asarray(rng.randint(0, 3, 2))}
        s.step(2, lambda it: feeds)
        path = s.snapshot()
        assert (tmp_path / "h5snap_iter_2.caffemodel.h5").exists()
        s2 = Solver(sp)
        s2.restore(path)
        np.testing.assert_array_equal(np.array(s2.params["ip"]["weight"]),
                                      np.array(s.params["ip"]["weight"]))


class TestDeployNets:
    def test_all_deploys_build(self):
        paths = sorted(glob.glob("models/*/deploy.prototxt"))
        if not paths:
            pytest.skip("zoo not generated")
        for path in paths:
            net = Net(NetParameter.from_file(path), phase="TEST")
            name = path.split(os.sep)[1]
            if name != "rcnn":
                assert "prob" in net.blob_shapes, path
