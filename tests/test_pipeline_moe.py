"""Pipeline parallelism + Mixture-of-Experts tests on the 8-device CPU
mesh. Both are beyond-reference capabilities (SURVEY §2.7: the reference
has neither PP nor EP); the invariant throughout: the distributed
schedule must match the sequential/dense computation exactly."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from caffe_mpi_tpu.ops.moe import (
    init_moe_params,
    moe_ffn,
    moe_ffn_dense_reference,
    shard_experts,
)
from caffe_mpi_tpu.parallel.pipeline import (
    pipeline_apply,
    shard_stages,
    stack_stage_params,
)


def mlp_stages(rng, n_stages=4, f=16):
    return [{"w": jnp.asarray(rng.randn(f, f).astype(np.float32) * 0.3),
             "b": jnp.asarray(rng.randn(f).astype(np.float32) * 0.1)}
            for _ in range(n_stages)]


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def seq_apply(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


class TestPipeline:
    def _mesh(self, stages):
        return Mesh(np.array(jax.devices()).reshape(stages, -1),
                    ("stage", "tp"))

    @pytest.mark.parametrize("n_stages,n_micro", [(4, 6), (8, 8), (2, 1)])
    def test_matches_sequential(self, rng, n_stages, n_micro):
        mesh = self._mesh(n_stages)
        per_stage = mlp_stages(rng, n_stages)
        stacked = shard_stages(stack_stage_params(per_stage), mesh, "stage")
        # one stage per mesh position: model memory truly partitioned
        assert not jax.tree.leaves(stacked)[0].sharding.is_fully_replicated
        mb = jnp.asarray(rng.randn(n_micro, 4, 16).astype(np.float32))
        out = pipeline_apply(stage_fn, stacked, mb, mesh, stage_axis="stage")
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(seq_apply(per_stage, mb)),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match_sequential(self, rng):
        mesh = self._mesh(4)
        per_stage = mlp_stages(rng, 4)
        stacked_repl = stack_stage_params(per_stage)
        stacked = shard_stages(stacked_repl, mesh, "stage")
        mb = jnp.asarray(rng.randn(6, 4, 16).astype(np.float32))

        g_pp = jax.grad(lambda sp: jnp.sum(
            pipeline_apply(stage_fn, sp, mb, mesh, stage_axis="stage") ** 2
        ))(stacked)

        def seq_loss(stacked):
            x = mb
            for i in range(4):
                x = stage_fn(jax.tree.map(lambda a: a[i], stacked), x)
            return jnp.sum(x ** 2)

        g_seq = jax.grad(seq_loss)(stacked_repl)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5)

    def test_trains_under_jit(self, rng):
        """SGD on the pipelined stack reduces a teacher-student loss."""
        mesh = self._mesh(4)
        teacher = mlp_stages(rng, 4)
        mb = jnp.asarray(rng.randn(4, 8, 16).astype(np.float32))
        target = seq_apply(teacher, mb)
        student = shard_stages(stack_stage_params(mlp_stages(rng, 4)),
                               mesh, "stage")

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(lambda p: jnp.mean(
                (pipeline_apply(stage_fn, p, mb, mesh,
                                stage_axis="stage") - target) ** 2))(p)
            return jax.tree.map(lambda w, gw: w - 0.5 * gw, p, g), loss

        p = student
        l0 = None
        for i in range(40):
            p, loss = step(p)
            # block per step: on the 1-core CPU simulation, async-dispatched
            # programs each containing an 8-participant collective can
            # starve XLA's rendezvous (40s timeout -> abort). Real TPUs
            # don't hit this — every participant is its own chip.
            jax.block_until_ready(loss)
            if l0 is None:
                l0 = float(loss)
        assert float(loss) < l0 * 0.3, (l0, float(loss))

    def test_stage_count_mismatch_raises(self, rng):
        mesh = self._mesh(4)
        stacked = stack_stage_params(mlp_stages(rng, 3))
        mb = jnp.zeros((2, 4, 16), jnp.float32)
        with pytest.raises(ValueError, match="3 stages"):
            pipeline_apply(stage_fn, stacked, mb, mesh, stage_axis="stage")


class TestMoE:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_dense_reference(self, top_k):
        params = init_moe_params(jax.random.PRNGKey(0), 16, 32, 8)
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
        y, aux = moe_ffn(params, x, top_k=top_k, capacity_factor=8.0)
        ref = moe_ffn_dense_reference(params, x, top_k=top_k)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        assert np.isfinite(float(aux))

    def test_expert_parallel_matches_dense(self):
        """Experts sharded 8-way (EP): GSPMD partitions the batched expert
        einsums and inserts the token all-to-alls; results unchanged."""
        mesh = Mesh(np.array(jax.devices()), ("model",))
        params = init_moe_params(jax.random.PRNGKey(0), 16, 32, 8)
        ep_params = shard_experts(params, mesh, "model")
        assert not ep_params["w1"].sharding.is_fully_replicated
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
        y, _ = jax.jit(lambda p, x: moe_ffn(
            p, x, capacity_factor=8.0, mesh=mesh, expert_axis="model"))(
                ep_params, x)
        ref = moe_ffn_dense_reference(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_capacity_drops_overflow_tokens(self):
        """Tokens past an expert's capacity contribute zero output (GShard
        drop semantics), never garbage."""
        params = init_moe_params(jax.random.PRNGKey(0), 8, 16, 2)
        # force every token to expert 0: all-positive features so the
        # gate's logit sign is uniform across tokens
        params["gate"] = params["gate"].at[:, 0].set(10.0).at[:, 1].set(-10.0)
        x = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (16, 8))) + 0.1
        y, _ = moe_ffn(params, x, capacity_factor=0.5)  # cap = 4 of 16
        # exactly 4 tokens routed; the rest are zero rows
        nonzero = np.abs(np.asarray(y)).sum(axis=1) > 1e-9
        assert nonzero.sum() == 4
        assert nonzero[:4].all()  # first-come-first-served positions

    def test_underflowed_gate_weight_still_dispatches(self):
        """A routed, within-capacity token whose softmax gate weight
        underflows to exactly 0 must still be dispatched (it shows up in
        the aux loss's frac_tokens): dispatch derives from the routing
        decision, not from thresholding the gate-weighted combine."""
        params = init_moe_params(jax.random.PRNGKey(0), 2, 4, 2)
        # logits = x @ gate; craft gate so logits are [x0, -x0]
        params["gate"] = jnp.array([[1.0, -1.0], [0.0, 0.0]])
        x = jnp.zeros((8, 2))
        # tokens 4-7: logits (120, -120) -> P(expert1) = e^-240 == 0.0 in f32
        x = x.at[4:, 0].set(120.0)
        assert float(jax.nn.softmax(jnp.array([120.0, -120.0]))[1]) == 0.0
        _, aux = moe_ffn(params, x, top_k=2, capacity_factor=8.0)
        # every token dispatches to BOTH experts -> frac_tokens = [1, 1];
        # frac_probs = [0.75, 0.25] -> aux = (0.75 + 0.25) * 2 = 2.
        # Thresholding combine would drop tokens 4-7 from expert 1
        # (frac_tokens[1] = 0.5 -> aux = 1.75).
        np.testing.assert_allclose(float(aux), 2.0, rtol=1e-5)

    def test_gradients_flow_and_aux_balances(self):
        params = init_moe_params(jax.random.PRNGKey(0), 16, 32, 4)
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))

        def loss(p):
            y, aux = moe_ffn(p, x, capacity_factor=8.0)
            return jnp.sum(y ** 2) + 0.01 * aux

        g = jax.grad(loss)(params)
        for leaf in jax.tree.leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()
        # gate receives gradient (both through routing weights and aux)
        assert float(jnp.abs(g["gate"]).sum()) > 0