"""CLI tests: train/test/time/device_query driven through main(), including
-gpu all on the 8-virtual-device mesh, plus layer-level remat."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.tools.cli import main

NET = """
name: "clinet"
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 } shape { dim: 8 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 4 kernel_size: 3
          weight_filler { type: "xavier" } } }
layer { name: "relu" type: "ReLU" bottom: "c" top: "c" }
layer { name: "ip" type: "InnerProduct" bottom: "c" top: "score"
        inner_product_param { num_output: 5
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "score" bottom: "label"
        top: "loss" include { phase: TRAIN } }
layer { name: "acc" type: "Accuracy" bottom: "score" bottom: "label"
        top: "acc" include { phase: TEST } }
"""


@pytest.fixture
def model(tmp_path):
    p = tmp_path / "net.prototxt"
    p.write_text(NET)
    return str(p)


@pytest.fixture
def solver_file(tmp_path, model):
    p = tmp_path / "solver.prototxt"
    p.write_text(f'net: "{model}"\nbase_lr: 0.05 momentum: 0.9\n'
                 f'lr_policy: "fixed" max_iter: 6 type: "SGD"\n'
                 f'snapshot_prefix: "{tmp_path}/snap"\n')
    return str(p)


class TestCLI:
    def test_device_query(self, capsys):
        assert main(["device_query"]) == 0
        out = capsys.readouterr().out
        assert "device 0" in out and "cpu" in out

    def test_train_synthetic(self, solver_file, tmp_path):
        assert main(["train", "-solver", solver_file, "-synthetic"]) == 0
        assert (tmp_path / "snap_iter_6.caffemodel").exists()

    def test_train_gpu_all_mesh(self, solver_file):
        assert main(["train", "-solver", solver_file, "-synthetic",
                     "-gpu", "all"]) == 0

    def test_train_gpu_all_with_lmdb(self, tmp_path, monkeypatch):
        """The reference's flagship scenario end to end: a DB-backed Data
        layer feeding data-parallel training over every device of the mesh
        (LMDB -> Feeder rank striping -> batch sharded over 'data' ->
        XLA gradient allreduce), via 'caffe train -gpu all'."""
        import jax.numpy as jnp
        from caffe_mpi_tpu.data.datasets import encode_datum
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        rng = np.random.RandomState(0)
        tmpl = rng.randint(0, 256, (2, 1, 6, 6))
        labels = rng.randint(0, 2, 64)
        imgs = np.clip(tmpl[labels] + rng.randint(-30, 31, (64, 1, 6, 6)),
                       0, 255).astype(np.uint8)
        db = str(tmp_path / "train_lmdb")
        write_lmdb(db, [(f"{i:08d}".encode(), encode_datum(imgs[i],
                                                           int(labels[i])))
                        for i in range(64)])
        (tmp_path / "net.prototxt").write_text(f"""
        name: "dp_lmdb"
        layer {{ name: "data" type: "Data" top: "data" top: "label"
                data_param {{ source: "{db}" backend: LMDB batch_size: 16 }}
                transform_param {{ scale: 0.00390625 }} }}
        layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "y"
                inner_product_param {{ num_output: 2
                  weight_filler {{ type: "xavier" }} }} }}
        layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "y"
                bottom: "label" top: "l" }}
        """)
        (tmp_path / "solver.prototxt").write_text(
            f'net: "{tmp_path}/net.prototxt"\nbase_lr: 0.5\n'
            'lr_policy: "fixed"\nmax_iter: 20\ndisplay: 0\ntype: "SGD"\n'
            f'snapshot: 20\nsnapshot_prefix: "{tmp_path}/dp"\n')
        assert main(["train", "-solver", str(tmp_path / "solver.prototxt"),
                     "-gpu", "all"]) == 0
        assert (tmp_path / "dp_iter_20.caffemodel").exists()

    def test_test_with_weights(self, solver_file, model, tmp_path, capsys):
        main(["train", "-solver", solver_file, "-synthetic"])
        rc = main(["test", "-model", model,
                   "-weights", str(tmp_path / "snap_iter_6.caffemodel"),
                   "-iterations", "2"])
        assert rc == 0
        assert "acc" in capsys.readouterr().out

    def test_time(self, model, capsys):
        assert main(["time", "-model", model, "-iterations", "2",
                     "-phase", "TRAIN"]) == 0
        out = capsys.readouterr().out
        assert "whole-graph forward+backward" in out

    def test_missing_args(self):
        assert main(["train"]) == 1
        assert main(["test"]) == 1


class TestRemat:
    def test_same_grads_with_remat(self, rng):
        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter
        plain = Net(NetParameter.from_text(NET), phase="TRAIN")
        remat_text = NET.replace('name: "conv" type: "Convolution"',
                                 'name: "conv" type: "Convolution" remat: true')
        remat = Net(NetParameter.from_text(remat_text), phase="TRAIN")
        params, state = plain.init(jax.random.PRNGKey(0))
        feeds = {"data": jnp.asarray(rng.randn(8, 3, 8, 8).astype(np.float32)),
                 "label": jnp.asarray(rng.randint(0, 5, 8))}

        def loss(net):
            return jax.grad(lambda p: net.apply(p, state, feeds,
                                                train=True)[2])(params)

        g1, g2 = loss(plain), loss(remat)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=1e-5)
