"""CLI tests: train/test/time/device_query driven through main(), including
-gpu all on the 8-virtual-device mesh, plus layer-level remat."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from caffe_mpi_tpu.tools.cli import main

NET = """
name: "clinet"
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 8 dim: 3 dim: 8 dim: 8 } shape { dim: 8 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 4 kernel_size: 3
          weight_filler { type: "xavier" } } }
layer { name: "relu" type: "ReLU" bottom: "c" top: "c" }
layer { name: "ip" type: "InnerProduct" bottom: "c" top: "score"
        inner_product_param { num_output: 5
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "score" bottom: "label"
        top: "loss" include { phase: TRAIN } }
layer { name: "acc" type: "Accuracy" bottom: "score" bottom: "label"
        top: "acc" include { phase: TEST } }
"""


@pytest.fixture
def model(tmp_path):
    p = tmp_path / "net.prototxt"
    p.write_text(NET)
    return str(p)


@pytest.fixture
def solver_file(tmp_path, model):
    p = tmp_path / "solver.prototxt"
    p.write_text(f'net: "{model}"\nbase_lr: 0.05 momentum: 0.9\n'
                 f'lr_policy: "fixed" max_iter: 6 type: "SGD"\n'
                 f'snapshot_prefix: "{tmp_path}/snap"\n')
    return str(p)


class TestCLI:
    def test_device_query(self, capsys):
        assert main(["device_query"]) == 0
        out = capsys.readouterr().out
        assert "device 0" in out and "cpu" in out

    def test_train_synthetic(self, solver_file, tmp_path):
        assert main(["train", "-solver", solver_file, "-synthetic"]) == 0
        assert (tmp_path / "snap_iter_6.caffemodel").exists()

    def test_train_gpu_all_mesh(self, solver_file):
        assert main(["train", "-solver", solver_file, "-synthetic",
                     "-gpu", "all"]) == 0

    def test_train_gpu_all_with_lmdb(self, tmp_path, monkeypatch):
        """The reference's flagship scenario end to end: a DB-backed Data
        layer feeding data-parallel training over every device of the mesh
        (LMDB -> Feeder rank striping -> batch sharded over 'data' ->
        XLA gradient allreduce), via 'caffe train -gpu all'."""
        import jax.numpy as jnp
        from caffe_mpi_tpu.data.datasets import encode_datum
        from caffe_mpi_tpu.data.lmdb_io import write_lmdb
        rng = np.random.RandomState(0)
        tmpl = rng.randint(0, 256, (2, 1, 6, 6))
        labels = rng.randint(0, 2, 64)
        imgs = np.clip(tmpl[labels] + rng.randint(-30, 31, (64, 1, 6, 6)),
                       0, 255).astype(np.uint8)
        db = str(tmp_path / "train_lmdb")
        write_lmdb(db, [(f"{i:08d}".encode(), encode_datum(imgs[i],
                                                           int(labels[i])))
                        for i in range(64)])
        (tmp_path / "net.prototxt").write_text(f"""
        name: "dp_lmdb"
        layer {{ name: "data" type: "Data" top: "data" top: "label"
                data_param {{ source: "{db}" backend: LMDB batch_size: 16 }}
                transform_param {{ scale: 0.00390625 }} }}
        layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "y"
                inner_product_param {{ num_output: 2
                  weight_filler {{ type: "xavier" }} }} }}
        layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "y"
                bottom: "label" top: "l" }}
        """)
        (tmp_path / "solver.prototxt").write_text(
            f'net: "{tmp_path}/net.prototxt"\nbase_lr: 0.5\n'
            'lr_policy: "fixed"\nmax_iter: 20\ndisplay: 0\ntype: "SGD"\n'
            f'snapshot: 20\nsnapshot_prefix: "{tmp_path}/dp"\n')
        assert main(["train", "-solver", str(tmp_path / "solver.prototxt"),
                     "-gpu", "all"]) == 0
        assert (tmp_path / "dp_iter_20.caffemodel").exists()

    def test_test_with_weights(self, solver_file, model, tmp_path, capsys):
        main(["train", "-solver", solver_file, "-synthetic"])
        rc = main(["test", "-model", model,
                   "-weights", str(tmp_path / "snap_iter_6.caffemodel"),
                   "-iterations", "2"])
        assert rc == 0
        assert "acc" in capsys.readouterr().out

    def test_time(self, model, capsys):
        assert main(["time", "-model", model, "-iterations", "2",
                     "-phase", "TRAIN"]) == 0
        out = capsys.readouterr().out
        assert "whole-graph forward+backward" in out

    def test_missing_args(self):
        assert main(["train"]) == 1
        assert main(["test"]) == 1


class TestRemat:
    def test_same_grads_with_remat(self, rng):
        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter
        plain = Net(NetParameter.from_text(NET), phase="TRAIN")
        remat_text = NET.replace('name: "conv" type: "Convolution"',
                                 'name: "conv" type: "Convolution" remat: true')
        remat = Net(NetParameter.from_text(remat_text), phase="TRAIN")
        params, state = plain.init(jax.random.PRNGKey(0))
        feeds = {"data": jnp.asarray(rng.randn(8, 3, 8, 8).astype(np.float32)),
                 "label": jnp.asarray(rng.randint(0, 5, 8))}

        def loss(net):
            return jax.grad(lambda p: net.apply(p, state, feeds,
                                                train=True)[2])(params)

        g1, g2 = loss(plain), loss(remat)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.array(a), np.array(b), rtol=1e-5)


class TestMeshFlag:
    """-mesh data=N,model=M + prototxt param_sharding: the one-command
    DPxTP launch (the `mpirun -n N caffe train` analogue generalized
    beyond DP, reference README.md:40)."""

    def test_mesh_flag_parses(self):
        from caffe_mpi_tpu.tools.cli import _select_mesh
        plan = _select_mesh("", "data=4,model=2")
        assert dict(plan.mesh.shape) == {"data": 4, "model": 2}
        plan = _select_mesh("", "data=8")
        assert dict(plan.mesh.shape) == {"data": 8, "model": 1}
        for bad in ("data=4,model=x", "foo=8", "data"):
            with pytest.raises(SystemExit):
                _select_mesh("", bad)
        assert _select_mesh("", "") is None

    def test_prototxt_sharding_rules_collected_and_applied(self, tmp_path):
        """param_sharding: "rows"/"cols" in the net prototxt places the
        weights over the 'model' axis; training matches the same-mesh
        replicated (pure-DP) run."""
        from caffe_mpi_tpu.parallel import MeshPlan
        from caffe_mpi_tpu.proto import NetParameter, SolverParameter
        from caffe_mpi_tpu.solver import Solver
        net_text = """
        layer { name: "in" type: "Input" top: "x" top: "label"
                input_param { shape { dim: 16 dim: 32 } shape { dim: 16 } } }
        layer { name: "fc1" type: "InnerProduct" bottom: "x" top: "h"
                param_sharding: "rows"
                inner_product_param { num_output: 64
                  weight_filler { type: "xavier" } } }
        layer { name: "relu" type: "ReLU" bottom: "h" top: "h" }
        layer { name: "fc2" type: "InnerProduct" bottom: "h" top: "y"
                param_sharding: "cols"
                inner_product_param { num_output: 10
                  weight_filler { type: "xavier" } } }
        layer { name: "loss" type: "SoftmaxWithLoss" bottom: "y"
                bottom: "label" top: "l" }
        """

        def run(strip_rules):
            sp = SolverParameter.from_text(
                'base_lr: 0.1 lr_policy: "fixed" momentum: 0.9\n'
                'max_iter: 5 display: 0 random_seed: 3 type: "SGD"')
            sp.net_param = NetParameter.from_text(net_text)
            if strip_rules:
                for lp in sp.net_param.layer:
                    lp.param_sharding = ""
            solver = Solver(sp, mesh=MeshPlan.from_shape(4, 2))
            r = np.random.RandomState(0)
            feeds = {"x": r.randn(16, 32).astype(np.float32),
                     "label": r.randint(0, 10, 16)}
            solver.step(5, lambda it: feeds)
            return solver

        tp = run(strip_rules=False)
        assert "model" in str(tp.params["fc1"]["weight"].sharding.spec)
        assert "model" in str(tp.params["fc2"]["weight"].sharding.spec)
        # optimizer history follows the param placement
        assert (tp.opt_state["fc1"]["weight"][0].sharding
                == tp.params["fc1"]["weight"].sharding)
        dp = run(strip_rules=True)
        assert dp.params["fc1"]["weight"].sharding.is_fully_replicated
        for ln in ("fc1", "fc2"):
            np.testing.assert_allclose(
                np.asarray(tp.params[ln]["weight"]),
                np.asarray(dp.params[ln]["weight"]), atol=1e-5)

    def test_unknown_param_sharding_rejected(self):
        from caffe_mpi_tpu.parallel import MeshPlan
        from caffe_mpi_tpu.proto import NetParameter, SolverParameter
        from caffe_mpi_tpu.solver import Solver
        sp = SolverParameter.from_text('base_lr: 0.1 lr_policy: "fixed"')
        sp.net_param = NetParameter.from_text("""
        layer { name: "in" type: "Input" top: "x"
                input_param { shape { dim: 8 dim: 4 } } }
        layer { name: "fc" type: "InnerProduct" bottom: "x" top: "y"
                param_sharding: "diagonal"
                inner_product_param { num_output: 8
                  weight_filler { type: "xavier" } } }
        """)
        with pytest.raises(ValueError, match="param_sharding"):
            Solver(sp, mesh=MeshPlan.from_shape(4, 2))

    def test_resnet50_cli_mesh_tp_matches_dp(self, tmp_path, monkeypatch):
        """The north-star launch: `caffe train -mesh data=4,model=2` on
        ResNet-50 with prototxt TP rules, parameter-trajectory-matching
        the same-mesh replicated run (float-reassociation tolerance:
        sharded contractions reduce in a different order).

        One iteration, deliberately (the PR-2..PR-12 "flake", root-caused
        in ISSUE 14): the TP-vs-DP contract — same math modulo float
        reassociation — is only testable before the divergence becomes
        chaotic. Measured on this net: after 1 step every param agrees
        to 1.9e-4; after 2 steps the same comparison reads 9.7e-3 (~50x
        per-step amplification as step 1's reassociation-level deltas
        feed BatchNorm batch statistics and a 176-layer backward), which
        straddled the old 2-iter/5e-3 assert depending on XLA scheduling.
        The CLI surface exercised (sharding-rule collection, mesh launch,
        train step, snapshot) is identical at 1 iter, so this runs in
        tier-1 instead of hiding behind a slow mark."""
        import os
        from caffe_mpi_tpu.io import load_caffemodel
        from caffe_mpi_tpu.proto import NetParameter
        monkeypatch.chdir(tmp_path)
        net = NetParameter.from_file(
            os.path.join(os.path.dirname(__file__),
                         "../caffe_mpi_tpu/models/resnet50/train_val.prototxt"))
        net.layer[0].input_param.shape[0].dim = [8, 3, 48, 48]
        net.layer[0].input_param.shape[1].dim = [8]
        for lp in net.layer:
            if lp.name in ("fc", "conv1"):
                lp.param_sharding = "rows"
        (tmp_path / "net_tp.prototxt").write_text(net.to_prototxt())
        for lp in net.layer:
            lp.param_sharding = ""
        (tmp_path / "net_dp.prototxt").write_text(net.to_prototxt())
        for tag in ("tp", "dp"):
            (tmp_path / f"solver_{tag}.prototxt").write_text(
                f'net: "net_{tag}.prototxt"\nbase_lr: 0.001\n'
                'lr_policy: "fixed"\nmomentum: 0.9\nmax_iter: 1\n'
                f'display: 0\nsnapshot: 1\nsnapshot_prefix: "{tag}"\n'
                'type: "SGD"\nrandom_seed: 5\n')
            assert main(["train", "-solver", str(tmp_path / f"solver_{tag}.prototxt"),
                         "-mesh", "data=4,model=2", "-synthetic"]) == 0
        a = load_caffemodel(str(tmp_path / "tp_iter_1.caffemodel"))
        b = load_caffemodel(str(tmp_path / "dp_iter_1.caffemodel"))
        assert a.keys() == b.keys()
        for k in a:
            for x, y in zip(a[k], b[k]):
                # 5x headroom over the measured 1-step reassociation
                # envelope (1.9e-4, conv1) — see the docstring
                np.testing.assert_allclose(x, y, atol=1e-3)
