"""Mixed-precision bf16 training (ISSUE 9): the `precision` solver knob.

Four contracts, mirroring the reference's fp16 system (caffe.proto
forward_type/backward_type + solver_data_type, net.cpp:815-818 loss
scaling) as rebuilt for TPU:

1. The f32 path is UNTOUCHED: a solver that spells `precision: "f32"`
   (+ loss-scale knobs, which are bf16-only) trains bitwise-identically
   to one that predates the knob, across step_chunk {1,K} x train_guard
   x reduce_overlap.
2. Under `precision: bf16`, activations/gradients compute in bfloat16
   while params and momentum stay f32 MASTER copies updated in f32 —
   held against a torch-amp-style oracle (torch is the independent
   numerical oracle of this suite, CLAUDE.md).
3. Dynamic loss scaling (loss_scale 0) composes with the train guard: a
   fault-injected overflow becomes skip + scale-down (+ regrowth after
   loss_scale_window clean steps) instead of the exit-88 divergence
   policy, which still fires for f32 guard runs and for bf16 once the
   scale floor is reached.
4. reduce_overlap buckets pack and psum in bf16 (collective bytes
   halve) and serving's bucket programs run bf16 within tolerance of
   f32 at zero extra compiles.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from caffe_mpi_tpu.proto import NetParameter, SolverParameter
from caffe_mpi_tpu.solver import Solver
from caffe_mpi_tpu.utils import resilience

NET = """
name: "prec_net"
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 16 dim: 1 dim: 8 dim: 8 }
                      shape { dim: 16 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 4 kernel_size: 3
          weight_filler { type: "msra" } } }
layer { name: "r" type: "ReLU" bottom: "c" top: "c" }
layer { name: "ip" type: "InnerProduct" bottom: "c" top: "logits"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
        bottom: "label" top: "loss" }
"""


def _feed(rng_seed=0):
    r = np.random.RandomState(rng_seed)
    batches = [{"data": jnp.asarray(r.randn(16, 1, 8, 8).astype(np.float32)),
                "label": jnp.asarray(r.randint(0, 4, 16))}
               for _ in range(24)]
    return lambda it: batches[it % len(batches)]


def _solver(extra="", net=NET, **kw):
    sp = SolverParameter.from_text(
        'base_lr: 0.05 momentum: 0.9 lr_policy: "fixed" max_iter: 100 '
        'random_seed: 3 '
        # tmp prefix: the exit-88 path journals <prefix>.run.json — a
        # bare default would litter the repo root on every suite run
        'snapshot_prefix: "/tmp/caffe_tpu_precision/snap" ' + extra)
    sp.net_param = NetParameter.from_text(net)
    return Solver(sp, **kw)


def _params_host(s):
    return {ln: {pn: np.asarray(a) for pn, a in lp.items()}
            for ln, lp in s.params.items()}


def _assert_trees_equal(a, b):
    for ln in a:
        for pn in a[ln]:
            np.testing.assert_array_equal(
                a[ln][pn], b[ln][pn], err_msg=f"{ln}/{pn} differs")


class TestF32Bitwise:
    """Spelling the knobs at their f32 defaults must not move a bit."""

    @pytest.mark.parametrize("variant", ["plain", "chunk", "guard",
                                         "chunk_guard"])
    def test_f32_knob_is_bitwise_noop(self, variant):
        extra = {"plain": "",
                 "chunk": "step_chunk: 3",
                 "guard": "train_guard: true",
                 "chunk_guard": "step_chunk: 3 train_guard: true"}[variant]
        base = _solver(extra)
        base.step(7, _feed())
        knob = _solver(extra + ' precision: "f32" loss_scale: 128 '
                       'loss_scale_window: 7')
        knob.step(7, _feed())
        _assert_trees_equal(_params_host(base), _params_host(knob))

    def test_f32_reduce_overlap_bitwise_noop(self):
        from caffe_mpi_tpu.parallel import MeshPlan
        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")
        base = _solver("reduce_overlap: true", mesh=MeshPlan.data_parallel())
        assert base._reduction is not None, base._reduction_fallback
        base.step(5, _feed())
        knob = _solver('reduce_overlap: true precision: "f32" '
                       'loss_scale: 64', mesh=MeshPlan.data_parallel())
        knob.step(5, _feed())
        _assert_trees_equal(_params_host(base), _params_host(knob))


LINEAR_NET = """
name: "lin"
layer { name: "in" type: "Input" top: "x" top: "t"
        input_param { shape { dim: 8 dim: 16 } shape { dim: 8 dim: 4 } } }
layer { name: "fc" type: "InnerProduct" bottom: "x" top: "y"
        inner_product_param { num_output: 4 bias_term: false
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "y" bottom: "t"
        top: "l" }
"""


class TestBF16MasterWeights:
    def test_master_update_matches_torch_amp_oracle(self):
        torch = pytest.importorskip("torch")
        r = np.random.RandomState(0)
        x = r.randn(8, 16).astype(np.float32)
        t = r.randn(8, 4).astype(np.float32)

        s = _solver('precision: "bf16" loss_scale: 1024', net=LINEAR_NET,
                    )
        sp_lr = 0.05
        w0 = np.asarray(s.params["fc"]["weight"])  # (4, 16) f32 master
        assert s.params["fc"]["weight"].dtype == jnp.float32
        s.step(1, lambda it: {"x": jnp.asarray(x), "t": jnp.asarray(t)})
        assert s.params["fc"]["weight"].dtype == jnp.float32
        w1 = np.asarray(s.params["fc"]["weight"])

        # torch-amp-style oracle: bf16 forward off the f32 master, f32
        # loss, STATIC loss scale applied and unwound exactly like
        # net.cpp:815-818, SGD+momentum update applied to the f32 master
        wt = torch.tensor(w0, requires_grad=True)
        y = torch.tensor(x).bfloat16() @ wt.bfloat16().T
        loss = ((y.float() - torch.tensor(t).bfloat16().float())
                ** 2).sum() / (2 * 8)
        (loss * 1024.0).backward()
        g = wt.grad.float() / 1024.0
        w_ref = torch.tensor(w0) - sp_lr * g  # first step: momentum 0
        np.testing.assert_allclose(w1, w_ref.numpy(), rtol=2e-2,
                                   atol=2e-4)
        assert np.abs(w1 - w0).max() > 0

    def test_updates_land_in_f32_below_bf16_resolution(self):
        # an update smaller than one bf16 ulp of the weight must still
        # move the f32 master — the whole point of master weights
        s = _solver('precision: "bf16" loss_scale: 1', net=LINEAR_NET)
        s.sp.base_lr = 1e-6
        r = np.random.RandomState(1)
        feed = lambda it: {"x": jnp.asarray(r.randn(8, 16).astype(np.float32)),
                           "t": jnp.asarray(r.randn(8, 4).astype(np.float32))}
        w0 = np.asarray(s.params["fc"]["weight"])
        s.step(1, feed)
        w1 = np.asarray(s.params["fc"]["weight"])
        delta = np.abs(w1 - w0)
        assert delta.max() > 0
        # bf16 has 8 mantissa bits: ulp(w) ~ |w| * 2^-8. The moved
        # deltas must be far below that for a 1e-6 lr — i.e. a bf16
        # master copy would have rounded them away entirely.
        moved = delta[delta > 0]
        ulp = np.abs(w0[delta > 0]) * 2.0 ** -8
        assert (moved < ulp / 8).all()

    def test_activations_bf16_loss_f32(self):
        s = _solver('precision: "bf16" loss_scale: 2')
        feeds = _feed()(0)
        blobs, _, loss = s.net.apply(s.params, s.net_state, feeds,
                                     train=True, rng=jax.random.PRNGKey(0))
        assert blobs["c"].dtype == jnp.bfloat16
        assert blobs["logits"].dtype == jnp.bfloat16
        assert loss.dtype == jnp.float32
        # momentum slots stay f32
        assert all(sl.dtype == jnp.float32
                   for lp in s.opt_state.values()
                   for slots in lp.values() for sl in slots)

    def test_bf16_converges_with_dynamic_scaling(self):
        r = np.random.RandomState(2)
        templates = r.randn(4, 1, 8, 8).astype(np.float32)

        def feed(it):
            rr = np.random.RandomState(it)
            lab = rr.randint(0, 4, 16)
            return {"data": jnp.asarray(
                templates[lab] + 0.1 * rr.randn(16, 1, 8, 8).astype(
                    np.float32)),
                "label": jnp.asarray(lab)}

        s = _solver('precision: "bf16" step_chunk: 5')
        assert s._dyn_scale and s._guard_on
        l0 = s.step(5, feed)
        lN = s.step(35, feed)
        assert lN < 0.5 * l0
        assert s.overflow_steps == 0


class TestDynamicLossScale:
    def _burst_feed(self, bad_iters):
        clean = _feed(5)
        nan = {"data": jnp.asarray(np.full((16, 1, 8, 8), np.nan,
                                           np.float32)),
               "label": jnp.asarray(np.zeros(16, np.int64))}
        return lambda it: nan if it in bad_iters else clean(it)

    def test_overflow_skips_and_rescales_instead_of_exit88(self):
        s = _solver('precision: "bf16" guard_max_skips: 2 '
                    'loss_scale_window: 4')
        s.step(9, self._burst_feed({3, 4, 5}))  # burst > guard_max_skips
        assert s.skipped_steps == 3
        assert s.overflow_steps == 3
        assert s.loss_scale_value == 2.0 ** 15 / 8  # three halvings
        # clean window -> regrowth: 3 clean steps already banked after
        # the burst, 11 more = three window-4 growth events, back to the
        # 2^15 start
        s.step(11, self._burst_feed(set()))
        assert s.loss_scale_value == 2.0 ** 15
        assert s.skipped_steps == 3

    def test_f32_guard_same_burst_exits_88(self):
        s = _solver("train_guard: true guard_max_skips: 2")
        with pytest.raises(resilience.NumericAnomalyError):
            s.step(9, self._burst_feed({3, 4, 5}))

    def test_fault_injected_overflow_recovers(self):
        # the ISSUE 4 fault plane injects the NaNs (range-keyed feed
        # poisoning) — the acceptance-criteria spelling of the burst
        s = _solver('precision: "bf16" guard_max_skips: 2')
        resilience.FAULTS.configure("nan_grad:2:0:3")  # iters 3,4 bad
        try:
            s.step(8, _feed(7))
        finally:
            resilience.FAULTS.configure("")
        assert s.skipped_steps == 2
        assert s.overflow_steps == 2
        assert s.loss_scale_value == 2.0 ** 15 / 4

    def test_scale_floor_still_trips_divergence_policy(self):
        # a run that is ACTUALLY divergent (every step non-finite)
        # halves to the floor and then the exit-88 policy fires — the
        # self-healing contract survives under bf16
        s = _solver('precision: "bf16" guard_max_skips: 2 '
                    'step_chunk: 5')
        bad = self._burst_feed(set(range(100)))
        with pytest.raises(resilience.NumericAnomalyError):
            s.step(30, bad)

    def test_finite_spike_skips_without_touching_scale(self):
        # review finding (ISSUE 9): a guard_loss_spike skip on a FINITE
        # loss is a real anomaly, not an overflow — it must not halve
        # the loss scale, must not count as an overflow, and must feed
        # the guard_max_skips divergence counter immediately (no
        # waiting for the scale floor)
        s = _solver('precision: "bf16" guard_loss_spike: 3.0 '
                    'guard_max_skips: 2')
        clean = _feed(5)
        spike = {"data": clean(0)["data"] * 60.0,
                 "label": jnp.asarray((np.asarray(clean(0)["label"]) + 2)
                                      % 4)}
        s.step(6, clean)  # build the accepted-loss EMA
        assert s.skipped_steps == 0
        scale0, ov0 = s.loss_scale_value, s.overflow_steps
        s.step(1, lambda it: spike)
        assert s.skipped_steps == 1          # the spike was skipped...
        assert s.overflow_steps == ov0       # ...but is NOT an overflow
        assert s.loss_scale_value == scale0  # and the scale is untouched
        # two consecutive finite spikes trip the divergence policy even
        # though the scale never reached its floor
        with pytest.raises(resilience.NumericAnomalyError):
            s.step(2, lambda it: spike)

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="precision"):
            _solver('precision: "fp8"')
        with pytest.raises(ValueError, match="loss_scale"):
            _solver('precision: "bf16" loss_scale: -1')
        with pytest.raises(ValueError, match="loss_scale_window"):
            _solver('precision: "bf16" loss_scale_window: 0')
        with pytest.raises(ValueError, match="gpipe"):
            _solver('precision: "bf16"', gpipe=2)


class TestBF16Reduction:
    def test_bucket_bytes_halve_and_training_runs(self):
        from caffe_mpi_tpu.parallel import MeshPlan
        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")
        f32 = _solver("reduce_overlap: true", mesh=MeshPlan.data_parallel())
        b16 = _solver('reduce_overlap: true precision: "bf16"',
                      mesh=MeshPlan.data_parallel())
        sf, sb = f32.reduction_stats(), b16.reduction_stats()
        assert sf["mode"] == "bucketed" and sb["mode"] == "bucketed"
        assert sb["wire_dtype"] == "bfloat16"
        assert "wire_dtype" not in sf
        assert sum(sb["bucket_bytes"]) * 2 == sum(sf["bucket_bytes"])
        loss = b16.step(4, _feed())
        assert np.isfinite(loss)
        assert b16.params["conv"]["weight"].dtype == jnp.float32

    def test_bf16_fused_eval_runs(self):
        sp = SolverParameter.from_text(
            'base_lr: 0.05 max_iter: 20 precision: "bf16" test_iter: 4 '
            'test_interval: 10 test_initialization: false test_chunk: 2')
        sp.net_param = NetParameter.from_text(NET)
        s = Solver(sp)
        scores = s.test_all([_feed(9)])
        assert scores and np.isfinite(scores[0]["loss"])


LRN_NET = """
name: "lrn_net"
layer { name: "in" type: "Input" top: "data" top: "label"
        input_param { shape { dim: 4 dim: 8 dim: 6 dim: 6 }
                      shape { dim: 4 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 8 kernel_size: 3 pad: 1
          weight_filler { type: "msra" } } }
layer { name: "norm" type: "LRN" bottom: "c" top: "n"
        lrn_param { local_size: 5 alpha: 0.0001 beta: 0.75 } }
layer { name: "ip" type: "InnerProduct" bottom: "n" top: "logits"
        inner_product_param { num_output: 4
          weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "logits"
        bottom: "label" top: "loss" }
"""


class TestPallasLRN:
    """ops/lrn.py wired behind the precision policy (ISSUE 9)."""

    def _feed(self):
        r = np.random.RandomState(4)
        return {"data": jnp.asarray(r.randn(4, 8, 6, 6).astype(np.float32)),
                "label": jnp.asarray(r.randint(0, 4, 4))}

    def test_kernel_matches_lax_fwd_and_bwd(self):
        from jax import lax
        from caffe_mpi_tpu.ops.lrn import lrn_across_channels
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(2, 16, 7, 9).astype(np.float32)) * 2

        def ref(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
            half = (size - 1) // 2
            ws = lax.reduce_window(
                jnp.square(x), np.zeros((), np.dtype(x.dtype))[()],
                lax.add, window_dimensions=(1, size, 1, 1),
                window_strides=(1, 1, 1, 1),
                padding=((0, 0), (half, half), (0, 0), (0, 0)))
            return x * jnp.power(k + ws * (alpha / size), -beta)

        np.testing.assert_allclose(
            lrn_across_channels(x, 5, 1e-4, 0.75, 1.0), ref(x),
            rtol=1e-5, atol=1e-6)
        g_ker = jax.grad(lambda x: jnp.sum(
            lrn_across_channels(x, 5, 1e-4, 0.75, 1.0) ** 2))(x)
        g_ref = jax.grad(lambda x: jnp.sum(ref(x) ** 2))(x)
        np.testing.assert_allclose(g_ker, g_ref, rtol=1e-4, atol=1e-5)

    def test_bf16_routes_through_pallas_f32_does_not(self, monkeypatch):
        monkeypatch.delenv("CAFFE_LRN_PALLAS", raising=False)
        for precision, expect_pallas in (("bf16", True), ("", False)):
            s = _solver('precision: "bf16"' if precision else "",
                        net=LRN_NET)
            jaxpr = jax.make_jaxpr(
                lambda p, st, f: s.net.apply(p, st, f, train=True,
                                             rng=jax.random.PRNGKey(0)))(
                s.params, s.net_state, self._feed())
            has_pallas = "pallas" in str(jaxpr)
            assert has_pallas == expect_pallas, (precision, has_pallas)
        # CAFFE_LRN_PALLAS=0 opts the bf16 path back out
        monkeypatch.setenv("CAFFE_LRN_PALLAS", "0")
        s = _solver('precision: "bf16"', net=LRN_NET)
        jaxpr = jax.make_jaxpr(
            lambda p, st, f: s.net.apply(p, st, f, train=True,
                                         rng=jax.random.PRNGKey(0)))(
            s.params, s.net_state, self._feed())
        assert "pallas" not in str(jaxpr)

    def test_bf16_lrn_net_trains(self, monkeypatch):
        monkeypatch.delenv("CAFFE_LRN_PALLAS", raising=False)
        s = _solver('precision: "bf16" step_chunk: 3', net=LRN_NET)
        loss = s.step(6, lambda it: self._feed())
        assert np.isfinite(loss)
        assert s.skipped_steps == 0

    def test_forced_pallas_matches_stock_f32_training(self, monkeypatch):
        # CAFFE_LRN_PALLAS=1: the kernels under the plain f32 path must
        # track the stock lax program to f32 tolerance over real steps
        monkeypatch.setenv("CAFFE_LRN_PALLAS", "0")
        a = _solver("", net=LRN_NET)
        a.step(4, lambda it: self._feed())
        monkeypatch.setenv("CAFFE_LRN_PALLAS", "1")
        b = _solver("", net=LRN_NET)
        b.step(4, lambda it: self._feed())
        for ln in a.params:
            for pn in a.params[ln]:
                np.testing.assert_allclose(
                    np.asarray(a.params[ln][pn]),
                    np.asarray(b.params[ln][pn]), rtol=1e-4, atol=1e-6,
                    err_msg=f"{ln}/{pn}")


class TestBF16Serving:
    def _deploy(self, tmp_path):
        text = """
name: "srv"
layer { name: "in" type: "Input" top: "data"
        input_param { shape { dim: 4 dim: 1 dim: 8 dim: 8 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 4 kernel_size: 3
          weight_filler { type: "msra" } } }
layer { name: "ip" type: "InnerProduct" bottom: "c" top: "logits"
        inner_product_param { num_output: 3
          weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "logits" top: "prob" }
"""
        p = tmp_path / "deploy.prototxt"
        p.write_text(text)
        return str(p)

    def test_scores_close_and_zero_extra_compiles(self, tmp_path):
        from caffe_mpi_tpu.serving.engine import BucketedForward
        path = self._deploy(tmp_path)
        param = NetParameter.from_file(path)
        f32 = BucketedForward(param, ladder=(1, 4))
        b16 = BucketedForward(param, ladder=(1, 4), dtype="bf16")
        params, state = f32.init(seed=0)
        f32.warm(params, state)
        b16.warm(params, state)
        assert f32.counter.count == 2 and b16.counter.count == 2
        r = np.random.RandomState(0)
        for n in (1, 3, 4, 2):  # mixed arrival sizes
            data = r.randn(n, 1, 8, 8).astype(np.float32)
            sf = f32.forward(params, state, data)
            sb = b16.forward(params, state, data)
            assert sf.dtype == np.float32 and sb.dtype == np.float32
            np.testing.assert_allclose(sb, sf, rtol=5e-2, atol=5e-3)
        # steady state compiled nothing new on either path
        assert f32.counter.count == 2 and b16.counter.count == 2

    def test_engine_serve_dtype_knob(self, tmp_path):
        from caffe_mpi_tpu.proto.config import ServingParameter
        from caffe_mpi_tpu.serving import ServingEngine
        path = self._deploy(tmp_path)
        spp = ServingParameter()
        spp.serve_dtype = "bf16"
        eng = ServingEngine(spp, start=False)
        try:
            eng.load_model("m", path)
            assert eng.compile_count == eng.warmed_buckets
        finally:
            eng.close()
        with pytest.raises(ValueError, match="serve_dtype"):
            spp2 = ServingParameter()
            spp2.serve_dtype = "fp8"
            ServingEngine(spp2, start=False)
