"""DetectNet pipeline tests: coverage-grid generation, augmentation bbox
consistency, feeder batches."""

import numpy as np
import pytest

from caffe_mpi_tpu.data.detectnet import (
    DetectNetAugmenter,
    DetectNetFeeder,
    coverage_label,
)
from caffe_mpi_tpu.proto import LayerParameter
from caffe_mpi_tpu.proto.config import (
    DetectNetAugmentationParameter,
    DetectNetGroundTruthParameter,
)


def gt_param(**kw):
    g = DetectNetGroundTruthParameter(image_size_x=64, image_size_y=32,
                                      stride=4, **kw)
    return g


class TestCoverage:
    def test_coverage_region_and_offsets(self):
        gt = gt_param(scale_cvg=1.0)
        bboxes = np.array([[0, 8, 8, 24, 16]], np.float32)
        lab = coverage_label(bboxes, gt, num_classes=1)
        assert lab.shape == (5, 8, 16)
        cov = lab[0]
        # covered cells span the bbox on the stride-4 grid
        assert cov[2:4, 2:6].all() and cov.sum() == 8
        # offset channel at cell (2,2): center=(10,10); dx1 = 8-10 = -2
        assert lab[1, 2, 2] == pytest.approx(-2.0)
        assert lab[3, 2, 2] == pytest.approx(24 - 10)

    def test_scale_cvg_shrinks(self):
        full = coverage_label(np.array([[0, 0, 0, 63, 31]]), gt_param(scale_cvg=1.0))
        half = coverage_label(np.array([[0, 0, 0, 63, 31]]), gt_param(scale_cvg=0.4))
        assert half[0].sum() < full[0].sum()

    def test_multi_class_channels(self):
        gt = gt_param()
        bboxes = np.array([[1, 8, 8, 24, 16]], np.float32)
        lab = coverage_label(bboxes, gt, num_classes=2)
        assert lab.shape == (10, 8, 16)
        assert lab[0].sum() == 0 and lab[5].sum() > 0


class TestAugmenter:
    def test_test_phase_deterministic_center(self):
        gt = gt_param()
        aug = DetectNetAugmenter(None, gt, phase="TEST")
        img = np.random.RandomState(0).randint(
            0, 256, (3, 48, 96)).astype(np.uint8)
        boxes = np.array([[0, 30, 10, 60, 30]], np.float32)
        rng = np.random.default_rng(0)
        out1, b1 = aug(img, boxes, rng)
        out2, b2 = aug(img, boxes, np.random.default_rng(99))
        assert out1.shape == (3, 32, 64)
        np.testing.assert_array_equal(out1, out2)  # TEST: no randomness
        # center crop offset: (96-64)/2=16, (48-32)/2=8
        np.testing.assert_allclose(b1[0], [0, 14, 2, 44, 22])

    def test_flip_transforms_boxes(self):
        gt = gt_param()
        a = DetectNetAugmentationParameter(flip_prob=1.0, crop_prob=0.0,
                                           scale_prob=0.0,
                                           hue_rotation_prob=0.0,
                                           desaturation_prob=0.0)
        aug = DetectNetAugmenter(a, gt, phase="TRAIN")
        img = np.zeros((3, 32, 64), np.uint8)
        img[:, :, 0] = 255  # marker column at x=0
        boxes = np.array([[0, 0, 0, 9, 9]], np.float32)
        out, b = aug(img, boxes, np.random.default_rng(0))
        assert out[0, 0, -1] == 255  # marker moved to the right edge
        np.testing.assert_allclose(b[0], [0, 63 - 9, 0, 63, 9])


class _ToyDetDataset:
    def __init__(self, n=16):
        self.n = n

    def __len__(self):
        return self.n

    def get(self, i):
        r = np.random.RandomState(i)
        img = r.randint(0, 256, (3, 32, 64)).astype(np.uint8)
        boxes = np.array([[0, 10, 10, 30, 25]], np.float32)
        return img, boxes


class TestFeeder:
    def test_batches(self):
        lp = LayerParameter.from_text("""
        name: "d" type: "Data" top: "data" top: "label"
        data_param { batch_size: 4 }
        detectnet_groundtruth_param { image_size_x: 64 image_size_y: 32 stride: 4 }
        detectnet_augmentation_param { flip_prob: 0.5 }
        """)
        feeder = DetectNetFeeder(_ToyDetDataset(), lp, "TRAIN")
        batch = feeder(0)
        assert batch["data"].shape == (4, 3, 32, 64)
        assert batch["label"].shape == (4, 5, 8, 16)
        assert batch["label"][:, 0].sum() > 0  # coverage present
        np.testing.assert_array_equal(feeder(3)["data"], feeder(3)["data"])
