"""DetectNet pipeline tests: coverage-grid generation, augmentation bbox
consistency, feeder batches."""

import numpy as np
import pytest

from caffe_mpi_tpu.data.detectnet import (
    DetectNetAugmenter,
    DetectNetFeeder,
    coverage_label,
)
from caffe_mpi_tpu.proto import LayerParameter
from caffe_mpi_tpu.proto.config import (
    DetectNetAugmentationParameter,
    DetectNetGroundTruthParameter,
)


def gt_param(**kw):
    g = DetectNetGroundTruthParameter(image_size_x=64, image_size_y=32,
                                      stride=4, **kw)
    return g


class TestCoverage:
    def test_coverage_region_and_offsets(self):
        gt = gt_param(scale_cvg=1.0)
        bboxes = np.array([[0, 8, 8, 24, 16]], np.float32)
        lab = coverage_label(bboxes, gt, num_classes=1)
        assert lab.shape == (5, 8, 16)
        cov = lab[0]
        # covered cells span the bbox on the stride-4 grid
        assert cov[2:4, 2:6].all() and cov.sum() == 8
        # offset channel at cell (2,2): center=(10,10); dx1 = 8-10 = -2
        assert lab[1, 2, 2] == pytest.approx(-2.0)
        assert lab[3, 2, 2] == pytest.approx(24 - 10)

    def test_scale_cvg_shrinks(self):
        full = coverage_label(np.array([[0, 0, 0, 63, 31]]), gt_param(scale_cvg=1.0))
        half = coverage_label(np.array([[0, 0, 0, 63, 31]]), gt_param(scale_cvg=0.4))
        assert half[0].sum() < full[0].sum()

    def test_multi_class_channels(self):
        gt = gt_param()
        bboxes = np.array([[1, 8, 8, 24, 16]], np.float32)
        lab = coverage_label(bboxes, gt, num_classes=2)
        assert lab.shape == (10, 8, 16)
        assert lab[0].sum() == 0 and lab[5].sum() > 0


class TestAugmenter:
    def test_test_phase_deterministic_center(self):
        gt = gt_param()
        aug = DetectNetAugmenter(None, gt, phase="TEST")
        img = np.random.RandomState(0).randint(
            0, 256, (3, 48, 96)).astype(np.uint8)
        boxes = np.array([[0, 30, 10, 60, 30]], np.float32)
        rng = np.random.default_rng(0)
        out1, b1 = aug(img, boxes, rng)
        out2, b2 = aug(img, boxes, np.random.default_rng(99))
        assert out1.shape == (3, 32, 64)
        np.testing.assert_array_equal(out1, out2)  # TEST: no randomness
        # center crop offset: (96-64)/2=16, (48-32)/2=8
        np.testing.assert_allclose(b1[0], [0, 14, 2, 44, 22])

    def test_flip_transforms_boxes(self):
        gt = gt_param()
        a = DetectNetAugmentationParameter(flip_prob=1.0, crop_prob=0.0,
                                           scale_prob=0.0,
                                           hue_rotation_prob=0.0,
                                           desaturation_prob=0.0)
        aug = DetectNetAugmenter(a, gt, phase="TRAIN")
        img = np.zeros((3, 32, 64), np.uint8)
        img[:, :, 0] = 255  # marker column at x=0
        boxes = np.array([[0, 0, 0, 9, 9]], np.float32)
        out, b = aug(img, boxes, np.random.default_rng(0))
        assert out[0, 0, -1] == 255  # marker moved to the right edge
        np.testing.assert_allclose(b[0], [0, 63 - 9, 0, 63, 9])


class _ToyDetDataset:
    def __init__(self, n=16):
        self.n = n

    def __len__(self):
        return self.n

    def get(self, i):
        r = np.random.RandomState(i)
        img = r.randint(0, 256, (3, 32, 64)).astype(np.uint8)
        boxes = np.array([[0, 10, 10, 30, 25]], np.float32)
        return img, boxes


class TestFeeder:
    def test_batches(self):
        lp = LayerParameter.from_text("""
        name: "d" type: "Data" top: "data" top: "label"
        data_param { batch_size: 4 }
        detectnet_groundtruth_param { image_size_x: 64 image_size_y: 32 stride: 4 }
        detectnet_augmentation_param { flip_prob: 0.5 }
        """)
        feeder = DetectNetFeeder(_ToyDetDataset(), lp, "TRAIN")
        batch = feeder(0)
        assert batch["data"].shape == (4, 3, 32, 64)
        assert batch["label"].shape == (4, 5, 8, 16)
        assert batch["label"][:, 0].sum() > 0  # coverage present
        np.testing.assert_array_equal(feeder(3)["data"], feeder(3)["data"])


class TestDetectNetTransformationLayer:
    """The net-layer binding (layers/detection.py): the reference's
    examples/kitti prototxt builds, and the layer's pure_callback forward
    reproduces the host pipeline exactly."""

    NET = """
    name: "det"
    layer { name: "in" type: "Input" top: "data" top: "label"
            input_param { shape { dim: 2 dim: 3 dim: 32 dim: 64 }
                          shape { dim: 2 dim: 1 dim: 5 dim: 16 } } }
    layer { name: "xf" type: "DetectNetTransformation"
            bottom: "data" bottom: "label"
            top: "tdata" top: "tlabel"
            detectnet_groundtruth_param { stride: 4 scale_cvg: 1.0
              gridbox_type: GRIDBOX_MIN min_cvg_len: 1
              image_size_x: 64 image_size_y: 32
              object_class: { src: 1 dst: 0 } }
            transform_param { mean_value: 127 } }
    """

    def test_label_blob_roundtrip(self):
        from caffe_mpi_tpu.layers.detection import (encode_label_blob,
                                                    parse_label_blob)
        boxes = np.array([[1, 4, 6, 20, 18], [2, 0, 0, 10, 10]], np.float32)
        blob = encode_label_blob(boxes, max_bboxes=4)
        assert blob.shape == (1, 5, 16)
        np.testing.assert_allclose(parse_label_blob(blob), boxes)

    def test_forward_matches_host_pipeline(self):
        import jax
        import jax.numpy as jnp
        from caffe_mpi_tpu.layers.detection import encode_label_blob
        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter

        net = Net(NetParameter.from_text(self.NET), phase="TEST")
        assert net.blob_shapes["tdata"] == (2, 3, 32, 64)
        assert net.blob_shapes["tlabel"] == (2, 5, 8, 16)
        r = np.random.RandomState(0)
        data = r.randint(0, 256, (2, 3, 32, 64)).astype(np.float32)
        # class 1 maps to coverage 0; class 7 is unmapped and must drop
        boxes = [np.array([[1, 8, 8, 24, 16]], np.float32),
                 np.array([[1, 0, 4, 60, 28], [7, 0, 0, 30, 30]], np.float32)]
        label = np.stack([encode_label_blob(b, 4) for b in boxes])
        params, state = net.init(jax.random.PRNGKey(0))
        blobs, _, _ = jax.jit(
            lambda p, s, f: net.apply(p, s, f, train=False))(
                params, state,
                {"data": jnp.asarray(data), "label": jnp.asarray(label)})
        # TEST phase: no augmentation (images already at network size),
        # so output = data - mean and label = coverage_label(bboxes)
        np.testing.assert_allclose(np.asarray(blobs["tdata"]), data - 127.0,
                                   atol=1e-5)
        gt = DetectNetGroundTruthParameter(
            stride=4, scale_cvg=1.0, gridbox_type="GRIDBOX_MIN",
            min_cvg_len=1, image_size_x=64, image_size_y=32)
        want = np.stack([coverage_label(b[b[:, 0] == 1] * [0, 1, 1, 1, 1],
                                        gt, 1) for b in boxes])
        np.testing.assert_allclose(np.asarray(blobs["tlabel"]), want,
                                   atol=1e-5)

    def test_train_phase_augments_deterministically(self):
        import jax
        import jax.numpy as jnp
        from caffe_mpi_tpu.layers.detection import encode_label_blob
        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter

        aug_net = self.NET.replace(
            "transform_param { mean_value: 127 } }",
            "detectnet_augmentation_param { flip_prob: 1.0 crop_prob: 0\n"
            "              hue_rotation_prob: 0 desaturation_prob: 0\n"
            "              scale_prob: 0 }\n"
            "            transform_param { mean_value: 127 } }")
        net = Net(NetParameter.from_text(aug_net), phase="TRAIN")
        params, state = net.init(jax.random.PRNGKey(0))
        r = np.random.RandomState(0)
        feeds = {"data": jnp.asarray(
                     r.randint(0, 256, (2, 3, 32, 64)).astype(np.float32)),
                 "label": jnp.asarray(np.stack(
                     [encode_label_blob(
                         np.array([[1, 8, 8, 24, 16]], np.float32), 4)] * 2))}
        rng = jax.random.PRNGKey(42)
        a1, _, _ = net.apply(params, state, feeds, train=True, rng=rng)
        a2, _, _ = net.apply(params, state, feeds, train=True, rng=rng)
        np.testing.assert_array_equal(np.asarray(a1["tdata"]),
                                      np.asarray(a2["tdata"]))
        # flip_prob 1: the image is mirrored (after mean subtraction)
        np.testing.assert_allclose(
            np.asarray(a1["tdata"]),
            np.asarray(feeds["data"])[:, :, :, ::-1] - 127.0, atol=1e-5)
        assert np.asarray(a1["tlabel"])[:, 0].sum() > 0

    @pytest.mark.parametrize("phase,stages", [("TRAIN", ())])
    def test_reference_kitti_prototxt_builds(self, phase, stages):
        """The REAL examples/kitti/detectnet_network.prototxt builds as a
        Net — every layer type it uses is registered, incl. the transform
        (reference detectnet_transform_layer.cpp). TRAIN only: every TEST
        variant includes DIGITS Python layers (module
        caffe.layers.detectnet, shipped by DIGITS, not the reference), so
        a reference build without DIGITS cannot construct TEST either."""
        import os

        from caffe_mpi_tpu.net import Net
        from caffe_mpi_tpu.proto import NetParameter

        ref = "/root/reference/examples/kitti/detectnet_network.prototxt"
        if not os.path.exists(ref):
            # the read-only reference checkout is an environment fixture,
            # not repo data — its absence is a skip, not a failure
            pytest.skip(f"reference test data absent: {ref}")

        def probe(lp):
            return ((3, 384, 1248) if "data" in lp.top[0]
                    else (1, 16, 16))

        net = Net(NetParameter.from_file(ref),
                  phase=phase, stages=stages, data_shape_probe=probe,
                  device_transform=False)
        batch = net.blob_shapes["data"][0]
        assert net.blob_shapes["transformed_data"] == (batch, 3, 384, 1248)
        # coverage head: 1 class -> 5 grid channels at stride 16
        assert net.blob_shapes["transformed_label"][1:] == (5, 24, 78)
