"""pycaffe-compat API tests (reference python/caffe/test/test_net.py,
test_solver.py scope)."""

import numpy as np
import pytest

import caffe_mpi_tpu.pycaffe as caffe


@pytest.fixture
def model(tmp_path):
    p = tmp_path / "net.prototxt"
    p.write_text("""
    name: "pynet"
    layer { name: "data" type: "Input" top: "data" top: "label"
            input_param { shape { dim: 4 dim: 3 dim: 8 dim: 8 }
                          shape { dim: 4 } } }
    layer { name: "conv" type: "Convolution" bottom: "data" top: "c"
            convolution_param { num_output: 2 kernel_size: 3
              weight_filler { type: "xavier" } } }
    layer { name: "ip" type: "InnerProduct" bottom: "c" top: "score"
            inner_product_param { num_output: 5
              weight_filler { type: "xavier" } } }
    layer { name: "loss" type: "SoftmaxWithLoss" bottom: "score"
            bottom: "label" top: "loss" }
    """)
    return str(p)


class TestNet:
    def test_forward_kwargs(self, model, rng):
        net = caffe.Net(model, caffe.TEST)
        assert net.inputs == ["data", "label"]
        assert "loss" in net.outputs
        out = net.forward(data=rng.randn(4, 3, 8, 8).astype(np.float32),
                          label=rng.randint(0, 5, 4))
        assert out["loss"].shape == ()
        assert net.blobs["score"].data.shape == (4, 5)

    def test_params_and_backward(self, model, rng):
        net = caffe.Net(model, caffe.TRAIN)
        w = net.params["conv"][0]
        assert w.data.shape == (2, 3, 3, 3)
        net.forward(data=rng.randn(4, 3, 8, 8).astype(np.float32),
                    label=rng.randint(0, 5, 4))
        net.backward()
        g = net.params["conv"][0].diff
        assert g.shape == (2, 3, 3, 3) and np.abs(g).sum() > 0

    def test_save_copy_from(self, model, tmp_path, rng):
        net = caffe.Net(model, caffe.TEST)
        x = rng.randn(4, 3, 8, 8).astype(np.float32)
        lab = rng.randint(0, 5, 4)
        y1 = net.forward(data=x, label=lab)["loss"]
        wpath = str(tmp_path / "w.caffemodel")
        net.save(wpath)
        net2 = caffe.Net(model, wpath, caffe.TEST)
        y2 = net2.forward(data=x, label=lab)["loss"]
        np.testing.assert_allclose(y1, y2, rtol=1e-6)

    def test_layer_type_list(self):
        types = caffe.layer_type_list()
        for t in ("Convolution", "Pooling", "InnerProduct", "ReLU",
                  "SoftmaxWithLoss", "BatchNorm", "LRN"):
            assert t in types


class TestSolver:
    def test_step_with_memory_inputs(self, model, tmp_path, rng):
        sp = tmp_path / "solver.prototxt"
        sp.write_text(f'net: "{model}"\nbase_lr: 0.05 momentum: 0.9\n'
                      'lr_policy: "fixed" max_iter: 20 type: "SGD"\n')
        solver = caffe.SGDSolver(str(sp))
        net = solver.net
        net.blobs["data"].data = rng.randn(4, 3, 8, 8).astype(np.float32)
        net.blobs["label"].data = rng.randint(0, 5, 4)
        w0 = solver.net.params["conv"][0].data.copy()
        solver.step(5)
        assert solver.iter == 5
        w1 = solver.net.params["conv"][0].data
        assert not np.allclose(w0, w1)
