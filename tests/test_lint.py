"""tpulint framework (ISSUE 5): every pass catches its seeded bug,
honors its waiver, and a misspelled waiver still fails; the shipped
tree is lint-clean, fast, and checkable without jax (the suite must
survive a dead tunnel).

Fixture convention: per pass, one file seeding a known violation and
one seeding the same pattern waived with
`# lint: ok(<pass>) — reason`.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

from caffe_mpi_tpu.tools import lint

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_PASSES = ("host-sync", "traced-control-flow", "concrete-init",
              "gated-imports", "reference-citation", "doc-drift",
              "knob-drift", "lock-order", "blocking-under-lock",
              "thread-shared-mutation",
              # ISSUE 15: model-level passes (tests/test_netlint.py)
              "net-wiring", "net-shape", "net-params", "net-dtype",
              "net-serve", "net-footprint",
              # ISSUE 20: failure-path family
              "future-resolution", "typed-failure", "thread-crash",
              "deadline-discipline")


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return str(p)


def _run(paths, select, root=None):
    return lint.run_lint(paths=paths, select=list(select),
                         root=root or _ROOT)


def _names(findings):
    return sorted({f.pass_name for f in findings})


# ---------------------------------------------------------------------------
# registry + CLI surface

def test_all_tentpole_passes_registered():
    lint._load_passes()
    for name in ALL_PASSES:
        assert name in lint.REGISTRY, name
        assert lint.REGISTRY[name].description
    # the documented suite size (CLAUDE.md / docs/static_analysis.md):
    # ten code passes + six net-* model passes + the four ISSUE 20
    # failure-path passes, nothing registered twice or forgotten
    assert len(lint.REGISTRY) == 20, sorted(lint.REGISTRY)


def test_shipped_tree_is_clean_fast_and_jax_free():
    """`python -m caffe_mpi_tpu.tools.lint` exits 0 on the shipped
    tree, in under 5 s, with jax imports poisoned — the whole suite
    stays usable while the tunnel is down."""
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "for m in ('jax', 'jaxlib'):\n"
         "    sys.modules[m] = None\n"  # any `import jax` now raises
         "from caffe_mpi_tpu.tools.lint import main\n"
         "raise SystemExit(main([]))"],
        cwd=_ROOT, capture_output=True, text=True, timeout=60,
        env=dict(os.environ, PYTHONPATH=_ROOT))
    elapsed = time.monotonic() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert elapsed < 5.0, f"lint took {elapsed:.1f}s (budget 5s)"


def test_cli_select_unknown_pass_is_usage_error():
    assert lint.main(["--select", "no-such-pass"]) == 2


def test_cli_nonexistent_path_is_usage_error_not_false_clean(capsys):
    """A typo'd path must NOT exit 0 ('clean') — that is the one
    failure mode a tripwire cannot afford — nor crash with a raw
    traceback."""
    assert lint.main(["caffe_mpi_tpuu"]) == 2       # typo'd dir
    assert lint.main(["no_such_file.py"]) == 2
    err = capsys.readouterr().err
    assert "do not exist" in err


def test_default_scan_tolerates_roots_without_bench(tmp_path):
    """run_lint(root=fixture) must not crash when the root lacks
    DEFAULT_SCAN entries like bench.py."""
    _write(tmp_path, "caffe_mpi_tpu/ok.py", """
        '''Replaces nothing.py:1 — fixture.'''
    """)
    assert lint.run_lint(root=str(tmp_path)) == []


def test_cli_json_output(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", """
        def f(xs):
            return [float(x) for x in xs]
    """)
    rc = lint.main(["--select", "host-sync", "--json", bad])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out and out[0]["pass"] == "host-sync"
    assert out[0]["line"] == 3


def test_syntax_error_is_surfaced_not_swallowed(tmp_path):
    p = _write(tmp_path, "broken.py", "def oops(:\n")
    findings = _run([p], ["host-sync"])
    assert len(findings) == 1
    assert findings[0].pass_name == "syntax"
    assert "SYNTAX ERROR" in findings[0].message


# ---------------------------------------------------------------------------
# host-sync

def test_host_sync_catches_seeded_bug(tmp_path):
    p = _write(tmp_path, "hot.py", """
        import numpy as np

        def train(losses):
            total = 0.0
            for l in losses:
                total += float(l)
            while losses:
                x = np.asarray(losses.pop())
                y = losses[0].item()
            return total, float(total)     # outside any loop: clean
    """)
    kinds = sorted(f.detail for f in _run([p], ["host-sync"]))
    assert kinds == [".item()", "float", "np.asarray"]


def test_host_sync_honors_waiver_and_legacy_spelling(tmp_path):
    p = _write(tmp_path, "waived.py", """
        import numpy as np

        def display(window):
            for l in window:
                s = float(l)  # lint: ok(host-sync) — display boundary
                v = np.asarray(l)  # host-sync: ok (legacy spelling)
    """)
    assert _run([p], ["host-sync"]) == []


def test_host_sync_scope_aware(tmp_path):
    """A function/lambda DEFINED inside a loop is a new dynamic scope
    (not executed per iteration at def time), and a for-loop's iterable
    is evaluated once — neither is a per-iteration sync. Calls inside
    the defined function still count when IT loops."""
    p = _write(tmp_path, "scopes.py", """
        import numpy as np

        def build(schedule, blobs):
            cbs = []
            for s in schedule:
                def cb(v):
                    return float(v)        # def-time: not in the loop
                cbs.append(cb)
            for row in np.asarray(blobs):  # iterable: evaluated once
                pass
            def worker(vals):
                return [v.item() for v in vals]   # still a real loop
            return cbs, worker
    """)
    findings = _run([p], ["host-sync"])
    assert [(f.line, f.detail) for f in findings] == [(13, ".item()")]


def test_host_sync_comprehension_as_for_iterable_still_counts(tmp_path):
    """A comprehension used AS a for-loop's iterable is evaluated once
    but still loops over its own elements — the per-element sync must
    not escape through the for-header position."""
    p = _write(tmp_path, "itercomp.py", """
        def drain(losses):
            total = 0.0
            for l in [float(x) for x in losses]:
                total += l
            for l in sum(v.item() for v in losses):   # nested in call
                total += l
            return total
    """)
    kinds = sorted(f.detail for f in _run([p], ["host-sync"]))
    assert kinds == [".item()", "float"]


# ---------------------------------------------------------------------------
# traced-control-flow

_TRACED_BAD = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        if jnp.sum(x) > 0:
            x = x + 1
        n = int(jnp.max(x))
        return helper(x), n

    def helper(x):
        while jnp.any(x > 0):
            x = x - 1
        return x

    def host_only(x):
        if jnp.sum(x) > 0:     # not reachable from any traced root
            return x
        return -x
"""


def test_traced_control_flow_catches_seeded_bug(tmp_path):
    p = _write(tmp_path, "traced.py", _TRACED_BAD)
    findings = _run([p], ["traced-control-flow"])
    lines = sorted(f.line for f in findings)
    assert lines == [7, 9, 13]   # if, int(), while-in-callee; host_only clean


def test_traced_control_flow_honors_waiver_and_whitelist(tmp_path):
    p = _write(tmp_path, "waived.py", """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, training):
            # lint: ok(traced-control-flow) — static arg, concrete at trace
            if jnp.asarray(training):
                x = x + 1
            if jnp.issubdtype(x.dtype, jnp.floating):  # metadata: fine
                x = x * 2
            return x
    """)
    assert _run([p], ["traced-control-flow"]) == []


def test_traced_control_flow_sees_scan_bodies(tmp_path):
    p = _write(tmp_path, "scanbody.py", """
        from jax import lax
        import jax.numpy as jnp

        def outer(xs):
            def body(carry, x):
                if jnp.abs(x) > 1:       # traced: scan body
                    carry = carry + x
                return carry, x
            return lax.scan(body, 0.0, xs)
    """)
    findings = _run([p], ["traced-control-flow"])
    assert [f.line for f in findings] == [7]


# ---------------------------------------------------------------------------
# concrete-init

def test_concrete_init_catches_seeded_bug(tmp_path):
    p = _write(tmp_path, "init.py", """
        import numpy as np
        import jax.numpy as jnp
        from jax import lax

        def bad_pool(x):
            return lax.reduce_window(x, jnp.zeros(()), lax.add,
                                     window_dimensions=(1,),
                                     window_strides=(1,),
                                     padding=((0, 0),))

        def good_pool(x):
            return lax.reduce_window(x, np.zeros((), x.dtype)[()],
                                     lax.add, window_dimensions=(1,),
                                     window_strides=(1,),
                                     padding=((0, 0),))

        def bad_scan(xs):
            return lax.scan(lambda c, x: (c + x, c), jnp.zeros(()), xs)

        def good_scan(acc0, xs):
            return lax.scan(lambda c, x: (c + x, c), acc0, xs)
    """)
    findings = _run([p], ["concrete-init"])
    assert sorted(f.line for f in findings) == [7, 19]


def test_concrete_init_honors_waiver(tmp_path):
    p = _write(tmp_path, "waived.py", """
        import jax.numpy as jnp
        from jax import lax

        def pool(x):
            # lint: ok(concrete-init) — forward-only op, never differentiated
            return lax.reduce_window(x, jnp.zeros(()), lax.max,
                                     window_dimensions=(1,),
                                     window_strides=(1,),
                                     padding=((0, 0),))
    """)
    assert _run([p], ["concrete-init"]) == []


# ---------------------------------------------------------------------------
# gated-imports

def test_gated_imports_catches_seeded_bug(tmp_path):
    p = _write(tmp_path, "db.py", """
        import lmdb

        def open_db(path):
            return lmdb.open(path)
    """)
    findings = _run([p], ["gated-imports"])
    assert len(findings) == 1 and findings[0].line == 2


def test_gated_imports_honors_gate_waiver_and_tests_exemption(tmp_path):
    gated = _write(tmp_path, "gated.py", """
        try:
            import lmdb
        except ImportError:
            lmdb = None

        import flask  # lint: ok(gated-imports) — demo-only module

        def ready():
            return lmdb is not None
    """)
    in_tests = _write(tmp_path, "tests/test_oracle.py", """
        import torch

        def test_x():
            assert torch is not None
    """)
    assert _run([gated, in_tests], ["gated-imports"]) == []


# ---------------------------------------------------------------------------
# reference-citation

def test_reference_citation_catches_seeded_bug(tmp_path):
    p = _write(tmp_path, "mod.py", '''
        """A module docstring that cites nothing."""

        def f():
            return 1
    ''')
    findings = _run([p], ["reference-citation"])
    assert len(findings) == 1 and findings[0].line == 2


def test_reference_citation_honors_waiver_citation_and_trivial(tmp_path):
    waived = _write(tmp_path, "native.py", '''
        # lint: ok(reference-citation) — TPU-native, no reference analogue
        """A genuinely new subsystem."""

        def f():
            return 1
    ''')
    cited = _write(tmp_path, "cited.py", '''
        """Replaces src/caffe/solver.cpp:187-351 with a fused step.

        Brace-group citations like src/caffe/layers/{relu,elu}_layer.{cpp,cu}
        count too.
        """

        def f():
            return 1
    ''')
    trivial = _write(tmp_path, "__init__.py", """
        from os import path
        X = 1
    """)
    assert _run([waived, cited, trivial], ["reference-citation"]) == []


# ---------------------------------------------------------------------------
# doc-drift (needs a mini tree: registry + docs + call sites)

def _mini_tree(tmp_path, extra_call="", ghost_entry=False):
    ghost = '\n            "ghost_site": "never fired",' if ghost_entry \
        else ""
    _write(tmp_path, "caffe_mpi_tpu/utils/resilience.py", f"""
        FAULT_SITES = {{
            "feeder_read": "reader raises once",{ghost}
        }}
    """)
    _write(tmp_path, "docs/robustness.md", """
        Fault plane. Sites: `feeder_read`. More prose.
    """)
    _write(tmp_path, "caffe_mpi_tpu/runtime.py", f"""
        def read(faults, i):
            faults.fire("feeder_read")
            {extra_call}
            return i
    """)
    return str(tmp_path)


def test_doc_drift_catches_undocumented_call_site(tmp_path):
    root = _mini_tree(tmp_path, 'faults.fire("surprise_site")')
    findings = _run([os.path.join(root, "caffe_mpi_tpu")],
                    ["doc-drift"], root=root)
    assert len(findings) == 1
    assert "surprise_site" in findings[0].message


def test_doc_drift_catches_dead_registry_entry(tmp_path):
    root = _mini_tree(tmp_path, ghost_entry=True)
    findings = _run([os.path.join(root, "caffe_mpi_tpu")],
                    ["doc-drift"], root=root)
    msgs = "\n".join(f.message for f in findings)
    assert "ghost_site" in msgs


def test_doc_drift_honors_waiver(tmp_path):
    root = _mini_tree(
        tmp_path,
        'faults.fire("surprise_site")  '
        "# lint: ok(doc-drift) — staged rollout, registered next PR")
    findings = _run([os.path.join(root, "caffe_mpi_tpu")],
                    ["doc-drift"], root=root)
    assert findings == []


def test_doc_drift_registry_waiver_agrees_across_entry_points(tmp_path):
    """A waived dead registry entry (staged rollout: call site lands
    next PR) must be clean via BOTH explicit paths and paths=[]."""
    root = _mini_tree(
        tmp_path,
        ghost_entry=True)
    # waive the ghost entry on its registry line
    reg = os.path.join(root, "caffe_mpi_tpu/utils/resilience.py")
    src = open(reg).read().replace(
        '"ghost_site": "never fired",',
        '"ghost_site": "never fired",  '
        "# lint: ok(doc-drift) — call site lands next PR")
    open(reg, "w").write(src)
    for paths in ([os.path.join(root, "caffe_mpi_tpu")], []):
        assert _run(paths, ["doc-drift"], root=root) == [], paths


def test_doc_drift_clean_tree_is_clean(tmp_path):
    root = _mini_tree(tmp_path)
    assert _run([os.path.join(root, "caffe_mpi_tpu")],
                ["doc-drift"], root=root) == []


# ---------------------------------------------------------------------------
# knob-drift (ISSUE 6): accepted-but-ignored perf knobs must fail

def _knob_tree(tmp_path, *, consume_all=True):
    """Minimal root satisfying all four legs for every registered knob;
    consume_all=False drops reduce_buckets' consumer (the seeded
    accept-and-ignore bug this pass exists to catch)."""
    from caffe_mpi_tpu.tools.lint.knob_drift import KNOBS
    fields = "\n".join(f"    {k}: int = 0" for k in KNOBS)
    _write(tmp_path, "caffe_mpi_tpu/proto/config.py",
           f"class SolverParameter:\n{fields}\n")
    _write(tmp_path, "caffe_mpi_tpu/tools/cli.py",
           "FLAGS = " + repr(list(KNOBS)) + "\n")
    _write(tmp_path, "docs/benchmarks.md",
           " ".join(f"`{k}`" for k in KNOBS) + "\n")
    reads = [k for k in KNOBS
             if consume_all or k != "reduce_buckets"]
    _write(tmp_path, "caffe_mpi_tpu/solver.py",
           "def f(sp):\n" + "".join(f"    sp.{k}\n" for k in reads)
           + "    return sp\n")
    return str(tmp_path)


def test_knob_drift_clean_tree_is_clean(tmp_path):
    root = _knob_tree(tmp_path)
    assert _run([os.path.join(root, "caffe_mpi_tpu")],
                ["knob-drift"], root=root) == []


def test_knob_drift_catches_accepted_but_ignored(tmp_path):
    root = _knob_tree(tmp_path, consume_all=False)
    findings = _run([os.path.join(root, "caffe_mpi_tpu")],
                    ["knob-drift"], root=root)
    assert len(findings) == 1
    assert "reduce_buckets" in findings[0].message
    assert "IGNORED" in findings[0].message


def test_knob_drift_honors_waiver(tmp_path):
    # the waiver sits on the field's line in the schema — the knob's
    # one stable anchor (fields here are emitted one per line, so the
    # trailing comment lands on the last field's line; waive ALL by
    # putting it above the class instead would hide real findings)
    from caffe_mpi_tpu.tools.lint.knob_drift import KNOBS
    root = _knob_tree(tmp_path, consume_all=False)
    cfg = os.path.join(root, "caffe_mpi_tpu/proto/config.py")
    src = open(cfg).read().replace(
        "    reduce_buckets: int = 0",
        "    reduce_buckets: int = 0  "
        "# lint: ok(knob-drift) — consumer lands next PR")
    open(cfg, "w").write(src)
    assert _run([os.path.join(root, "caffe_mpi_tpu")],
                ["knob-drift"], root=root) == []
    assert len(KNOBS) >= 5  # the ISSUE-6 knobs are registered


def test_knob_drift_write_is_not_consumption(tmp_path):
    # bench/CLI-style plumbing `sp.knob = v` is a Store-context
    # attribute — it must NOT satisfy the consumed leg, or deleting
    # every real reader would still ship lint-clean
    root = _knob_tree(tmp_path, consume_all=False)
    _write(tmp_path, "caffe_mpi_tpu/plumbing.py",
           "def f(sp, v):\n    sp.reduce_buckets = v\n")
    findings = _run([os.path.join(root, "caffe_mpi_tpu")],
                    ["knob-drift"], root=root)
    assert len(findings) == 1
    assert "reduce_buckets" in findings[0].message


def test_knob_drift_registry_and_docstrings_are_not_consumption(tmp_path):
    # the pass's own KNOBS tuple (anything under tools/lint/) and bare
    # docstring mentions must not neuter the consumed leg — only a
    # Load-context read or a call-argument string counts
    root = _knob_tree(tmp_path, consume_all=False)
    _write(tmp_path, "caffe_mpi_tpu/tools/lint/registry.py",
           "KNOBS = ('reduce_buckets',)\n")
    _write(tmp_path, "caffe_mpi_tpu/docmention.py",
           '"""module that merely talks about reduce_buckets"""\n')
    findings = _run([os.path.join(root, "caffe_mpi_tpu")],
                    ["knob-drift"], root=root)
    assert len(findings) == 1
    assert "reduce_buckets" in findings[0].message


def test_knob_drift_getattr_string_is_consumption(tmp_path):
    root = _knob_tree(tmp_path, consume_all=False)
    _write(tmp_path, "caffe_mpi_tpu/reader.py",
           "def f(sp):\n    return getattr(sp, 'reduce_buckets', 0)\n")
    assert _run([os.path.join(root, "caffe_mpi_tpu")],
                ["knob-drift"], root=root) == []


def test_doc_drift_waiver_honored_on_empty_path_selection(tmp_path):
    """The tier-1 wrapper (tests/test_doc_drift.py) runs the pass with
    paths=[]; waivers must hold there too, not only when the call-site
    file happens to be in the scanned selection — one enforcement
    path, two entry points."""
    root = _mini_tree(
        tmp_path,
        'faults.fire("surprise_site")  '
        "# lint: ok(doc-drift) — staged rollout, registered next PR")
    assert _run([], ["doc-drift"], root=root) == []
    # and the finding still fires without the waiver via paths=[]
    root2 = _mini_tree(tmp_path / "b", 'faults.fire("surprise_site")')
    findings = lint.run_lint(paths=[], select=["doc-drift"], root=root2)
    assert len(findings) == 1 and "surprise_site" in findings[0].message


# ---------------------------------------------------------------------------
# waiver grammar hard cases

def test_misspelled_waiver_still_fails(tmp_path):
    """A typo'd pass name neither suppresses the finding NOR passes
    silently: the finding survives and the bad waiver is itself
    reported."""
    p = _write(tmp_path, "typo.py", """
        def f(xs):
            out = []
            for x in xs:
                out.append(float(x))  # lint: ok(host-sink) — oops
            return out
    """)
    findings = _run([p], ["host-sync"])
    names = _names(findings)
    assert names == ["bad-waiver", "host-sync"], findings


def test_waiver_for_other_pass_does_not_suppress(tmp_path):
    p = _write(tmp_path, "wrongpass.py", """
        def f(xs):
            out = []
            for x in xs:
                out.append(float(x))  # lint: ok(gated-imports) — wrong pass
            return out
    """)
    findings = _run([p], ["host-sync"])
    assert _names(findings) == ["host-sync"]


def test_waiver_grammar_inside_a_string_does_not_suppress(tmp_path):
    """Text that merely QUOTES the waiver grammar (a message string, a
    docstring) must not register as a waiver — only real comment
    tokens count; otherwise a pass whose error message cites the
    grammar would self-waive."""
    p = _write(tmp_path, "quoted.py", """
        def f(losses):
            out = []
            for l in losses:
                out.append(("use # lint: ok(host-sync) to waive",
                            float(l)))
            return out
    """)
    findings = _run([p], ["host-sync"])
    assert [f.detail for f in findings] == ["float"]


def test_cli_non_py_file_is_usage_error_not_false_clean(tmp_path):
    doc = tmp_path / "notes.md"
    doc.write_text("# notes\n")
    assert lint.main([str(doc)]) == 2


def test_traced_control_flow_flags_lambda_body(tmp_path):
    p = _write(tmp_path, "lam.py", """
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: bool(jnp.any(x)))
    """)
    findings = _run([p], ["traced-control-flow"])
    assert [f.line for f in findings] == [5]


def test_traced_control_flow_lambda_finding_is_waivable(tmp_path):
    """A lambda body has no statements of its own; its findings anchor
    waivers on the enclosing statement, so the documented grammar
    works on jit-wrapped lambdas too."""
    p = _write(tmp_path, "lamw.py", """
        import jax
        import jax.numpy as jnp

        # lint: ok(traced-control-flow) — scalar pred, concrete at trace
        f = jax.jit(lambda x: bool(jnp.any(x)))
    """)
    assert _run([p], ["traced-control-flow"]) == []


def test_doc_drift_waiver_on_multiline_statement_span(tmp_path):
    """The waiver grammar promises the whole statement span; a
    trailing waiver on a multi-line fire(...) call must hold."""
    root = _mini_tree(
        tmp_path,
        'faults.fire("surprise_site",\n'
        '                        0)  '
        "# lint: ok(doc-drift) — staged rollout")
    assert _run([], ["doc-drift"], root=root) == []


def test_gated_imports_type_checking_else_branch_not_gated(tmp_path):
    p = _write(tmp_path, "tc.py", """
        from typing import TYPE_CHECKING

        if TYPE_CHECKING:
            import lmdb          # never runs: gated
        else:
            import flask         # ALWAYS runs: must be flagged

        def f():
            return 0
    """)
    findings = _run([p], ["gated-imports"])
    assert len(findings) == 1 and "flask" in findings[0].message


def test_trailing_waiver_does_not_leak_to_next_statement(tmp_path):
    """A trailing waiver belongs to ITS statement; the next statement's
    'line directly above' placement only counts for comment-only
    lines — otherwise one waiver silently suppresses two findings."""
    p = _write(tmp_path, "leak.py", """
        import numpy as np

        def f(ls, ms):
            out = []
            for l, m in zip(ls, ms):
                a = float(l)  # lint: ok(host-sync) — boundary
                b = np.asarray(m)
                out.append((a, b))
            return out
    """)
    findings = _run([p], ["host-sync"])
    assert [(f.line, f.detail) for f in findings] == [(8, "np.asarray")]


def test_gated_imports_handler_and_finally_not_gated(tmp_path):
    """Only the try BODY is protected by an ImportError handler; an
    unguarded gated import in the except/finally blocks raises at
    module-import time and must be flagged."""
    p = _write(tmp_path, "tryparts.py", """
        try:
            import lmdb                  # gated: fine
        except ImportError:
            import torch                 # NOT protected: flagged
        finally:
            import flask                 # NOT protected: flagged

        def f():
            return 0
    """)
    findings = _run([p], ["gated-imports"])
    assert sorted(f.line for f in findings) == [5, 7]


def test_traced_control_flow_bool_in_test_reports_once(tmp_path):
    """`if bool(jnp.any(x)):` is ONE defect — the branch flag consumes
    the test subtree so the nested bool() does not double-report."""
    p = _write(tmp_path, "dup.py", """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            if bool(jnp.any(x)):
                x = x + 1
            return x
    """)
    findings = _run([p], ["traced-control-flow"])
    assert len(findings) == 1 and "`if`" in findings[0].message


def test_doc_drift_unrelated_trailing_waiver_does_not_leak(tmp_path):
    """A doc-drift waiver trailing the PREVIOUS statement must not
    suppress a call-site finding on the next line — and both entry
    points (explicit paths vs paths=[]) must agree."""
    root = _mini_tree(
        tmp_path,
        "x = 1  # lint: ok(doc-drift) — unrelated\n"
        '            faults.fire("surprise_site")')
    for paths in ([os.path.join(root, "caffe_mpi_tpu")], []):
        findings = _run(paths, ["doc-drift"], root=root)
        assert len(findings) == 1, (paths, findings)
        assert "surprise_site" in findings[0].message


def test_traced_control_flow_partial_jit_is_a_root(tmp_path):
    p = _write(tmp_path, "pjit.py", """
        from functools import partial
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnums=0)
        def step(n, x):
            if jnp.sum(x) > 0:
                x = x + n
            return x
    """)
    findings = _run([p], ["traced-control-flow"])
    assert [f.line for f in findings] == [8]


def test_nested_waiver_does_not_suppress_header_finding(tmp_path):
    """A finding anchored to a compound statement (if/while header)
    spans only the HEADER — a waiver on some statement nested in the
    body must not silently suppress it."""
    p = _write(tmp_path, "hdr.py", """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, idx):
            if jnp.sum(x) > 0:
                # lint: ok(traced-control-flow) — static index
                y = int(jnp.argmax(x))
                x = x + y
            return x
    """)
    findings = _run([p], ["traced-control-flow"])
    assert len(findings) == 1 and "`if`" in findings[0].message


def test_doc_drift_sees_wrapped_call_sites(tmp_path):
    """`fire(\\n \"site\")` wrapped across lines must still register as
    a call site (whole-text scan, as the pre-framework test did)."""
    root = _mini_tree(
        tmp_path,
        'faults.fire(\n                "surprise_site")')
    findings = _run([], ["doc-drift"], root=root)
    assert len(findings) == 1
    assert "surprise_site" in findings[0].message


def test_multi_pass_waiver(tmp_path):
    p = _write(tmp_path, "multi.py", """
        def f(xs):
            out = []
            for x in xs:
                # lint: ok(host-sync, traced-control-flow) — host floats
                out.append(float(x))
            return out
    """)
    assert _run([p], ["host-sync", "traced-control-flow"]) == []


# ---------------------------------------------------------------------------
# blocking-under-lock (ISSUE 13): the PR 7 / PR 11 regression shapes

_PR7_SET_RESULT_UNDER_REC_LOCK = """
    import threading

    class Batcher:
        def __init__(self):
            self._rec_lock = threading.Lock()
            self._records = []

        def harvest(self, group, scores):
            with self._rec_lock:
                self._records.append(len(group))
                for i, r in enumerate(group):
                    r.future.set_result(scores[i])
"""

_PR11_UPLOAD_UNDER_UPLOAD_LOCK = """
    import threading

    class InferenceModel:
        def __init__(self):
            self._upload_lock = threading.Lock()
            self._resident = None

        def ensure_resident(self, host):
            import jax
            with self._upload_lock:
                if self._resident is None:
                    self._resident = jax.device_put(host)
                return self._resident
"""


def test_blocking_catches_pr7_set_result_under_rec_lock(tmp_path):
    """The PR 7 second-round deadlock shape: a Future resolved under
    the non-reentrant records lock (done-callbacks run synchronously
    in the resolving thread)."""
    p = _write(tmp_path, "b.py", _PR7_SET_RESULT_UNDER_REC_LOCK)
    findings = _run([p], ["blocking-under-lock"], root=str(tmp_path))
    assert len(findings) == 1
    assert "Future.set_result" in findings[0].message
    assert "_rec_lock" in findings[0].message


def test_blocking_catches_pr11_upload_under_upload_lock(tmp_path):
    """The PR 11 shape: a tunnel-length device upload inside a held
    lock span."""
    p = _write(tmp_path, "m.py", _PR11_UPLOAD_UNDER_UPLOAD_LOCK)
    findings = _run([p], ["blocking-under-lock"], root=str(tmp_path))
    assert len(findings) == 1
    assert "jax.device_put" in findings[0].message
    assert "_upload_lock" in findings[0].message


def test_blocking_honors_waiver(tmp_path):
    p = _write(tmp_path, "w.py", """
        import threading

        class InferenceModel:
            def __init__(self):
                self._upload_lock = threading.Lock()

            def ensure_resident(self, host):
                import jax
                with self._upload_lock:
                    # lint: ok(blocking-under-lock) — upload serialization
                    # is this lock's purpose; no other lock is held here
                    return jax.device_put(host)
    """)
    assert _run([p], ["blocking-under-lock"], root=str(tmp_path)) == []


def test_blocking_flags_unbounded_waits_but_not_condition_wait(tmp_path):
    """queue.get()/join()/result() with no timeout block forever under
    a lock; a Condition's own .wait() under its lock is the sanctioned
    pattern (it RELEASES the lock) and must not be flagged."""
    p = _write(tmp_path, "u.py", """
        import threading

        class Pump:
            def __init__(self):
                self._cv = threading.Condition()
                self._q = None
                self._t = None

            def run_ok(self):
                with self._cv:
                    while self._q is None:
                        self._cv.wait()          # sanctioned

            def run_bad(self, fut):
                with self._cv:
                    item = self._q.get()         # unbounded
                    self._t.join()               # unbounded
                    return fut.result(), item    # unbounded
    """)
    findings = _run([p], ["blocking-under-lock"], root=str(tmp_path))
    kinds = sorted(f.message.split(" inside")[0] for f in findings)
    assert kinds == [".get() without timeout", ".join() without timeout",
                     ".result() without timeout"]


def test_blocking_outside_lock_is_clean(tmp_path):
    """The fixed shapes — snapshot under the lock, resolve outside —
    must be clean (the diff that fixed PR 7 has to lint clean)."""
    p = _write(tmp_path, "ok.py", """
        import threading

        class Batcher:
            def __init__(self):
                self._rec_lock = threading.Lock()
                self._records = []

            def harvest(self, group, scores):
                with self._rec_lock:
                    self._records.append(len(group))
                for i, r in enumerate(group):
                    r.future.set_result(scores[i])
    """)
    assert _run([p], ["blocking-under-lock"], root=str(tmp_path)) == []


# ---------------------------------------------------------------------------
# lock-order (ISSUE 13): nesting vs the declared LOCK_ORDER

_TWO_LOCK_CLASSES = """
    import threading

    class InferenceModel:
        def __init__(self):
            self._upload_lock = threading.Lock()

    class ServingEngine:
        def __init__(self):
            self._lock = threading.Lock()

        def swap(self, model):
            with model._upload_lock:
                with self._lock:
                    pass
"""


def _lock_registry(tmp_path, body):
    return _write(tmp_path, "caffe_mpi_tpu/serving/locks.py", body)


def test_lock_order_undeclared_nesting_is_a_finding(tmp_path):
    p = _write(tmp_path, "eng.py", _TWO_LOCK_CLASSES)
    findings = _run([p], ["lock-order"], root=str(tmp_path))
    assert len(findings) == 1
    assert "undeclared lock nesting" in findings[0].message
    assert "InferenceModel._upload_lock" in findings[0].message


def test_lock_order_declared_nesting_is_clean(tmp_path):
    _lock_registry(tmp_path, """
        LOCK_ORDER = (
            ("InferenceModel._upload_lock", "ServingEngine._lock"),
        )
    """)
    p = _write(tmp_path, "eng.py", _TWO_LOCK_CLASSES)
    assert _run([p], ["lock-order"], root=str(tmp_path)) == []


def test_lock_order_catches_inverted_upload_engine_nesting(tmp_path):
    """The acceptance shape: LOCK_ORDER declares _upload_lock ->
    engine._lock; code that nests engine._lock -> _upload_lock is the
    PR 11 deadlock inversion and must fail LOUDLY."""
    _lock_registry(tmp_path, """
        LOCK_ORDER = (
            ("InferenceModel._upload_lock", "ServingEngine._lock"),
        )
    """)
    p = _write(tmp_path, "eng.py", """
        import threading

        class InferenceModel:
            def __init__(self):
                self._upload_lock = threading.Lock()

        class ServingEngine:
            def __init__(self):
                self._lock = threading.Lock()

            def bad_swap(self, model):
                with self._lock:
                    with model._upload_lock:
                        pass
    """)
    findings = _run([p], ["lock-order"], root=str(tmp_path))
    assert len(findings) == 1
    assert "INVERTED" in findings[0].message


def test_lock_order_sees_nesting_through_resolvable_calls(tmp_path):
    """Holding lock A while CALLING a method that acquires lock B is
    the same nesting as a syntactic with-in-with — the PR 7 dispatcher
    shape (engine.model under the batcher's condition variable)."""
    p = _write(tmp_path, "call.py", """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def model(self, name):
                with self._lock:
                    return name

        class Batcher:
            def __init__(self):
                self._cv = threading.Condition()
                self._engine = Engine()

            def dispatch(self, name):
                with self._cv:
                    return self._engine.model(name)
    """)
    findings = _run([p], ["lock-order"], root=str(tmp_path))
    assert len(findings) == 1
    assert "Batcher._cv" in findings[0].message
    assert "Engine._lock" in findings[0].message
    assert "call to Engine.model" in findings[0].message


def test_lock_order_reacquire_nonreentrant_flagged_rlock_clean(tmp_path):
    p = _write(tmp_path, "re.py", """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._rlock = threading.RLock()

            def bad(self):
                with self._lock:
                    with self._lock:
                        pass

            def fine(self):
                with self._rlock:
                    with self._rlock:
                        pass
    """)
    findings = _run([p], ["lock-order"], root=str(tmp_path))
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


def test_lock_order_registry_drift_unknown_lock_fails(tmp_path):
    """A LOCK_ORDER entry naming a lock that no longer exists in the
    tree is itself a finding — the registry cannot outlive the code
    (the acceptance's seeded-mismatch case)."""
    _lock_registry(tmp_path, """
        LOCK_ORDER = (
            ("Ghost._lock", "AlsoGhost._lock"),
        )
    """)
    p = _write(tmp_path, "code.py", """
        def f():
            return 1
    """)
    findings = _run([p], ["lock-order"], root=str(tmp_path))
    msgs = "\\n".join(f.message for f in findings)
    assert "unknown lock 'Ghost._lock'" in msgs
    assert "unknown lock 'AlsoGhost._lock'" in msgs


def test_lock_order_registry_cycle_fails(tmp_path):
    _lock_registry(tmp_path, """
        LOCK_ORDER = (
            ("A._lock", "B._lock"),
            ("B._lock", "A._lock"),
        )
    """)
    p = _write(tmp_path, "code.py", """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

        class B:
            def __init__(self):
                self._lock = threading.Lock()
    """)
    findings = _run([p], ["lock-order"], root=str(tmp_path))
    assert any("cycle" in f.message for f in findings)


def test_lock_order_honors_waiver(tmp_path):
    p = _write(tmp_path, "w.py", """
        import threading

        class A:
            def __init__(self):
                self._outer = threading.Lock()
                self._inner = threading.Lock()

            def f(self):
                with self._outer:
                    # lint: ok(lock-order) — fixture: deliberate nesting
                    with self._inner:
                        pass
    """)
    assert _run([p], ["lock-order"], root=str(tmp_path)) == []


def test_shipped_lock_order_registry_matches_tree():
    """The real registry drift-holds against the real tree: every
    LOCK_ORDER node and ATTR_TYPES entry must resolve (a rename that
    misses serving/locks.py fails here and in the CLI)."""
    findings = _run([], ["lock-order"], root=_ROOT)
    assert findings == [], [f.format(_ROOT) for f in findings]


# ---------------------------------------------------------------------------
# thread-shared-mutation (ISSUE 13)

def test_thread_shared_mutation_catches_seeded_race(tmp_path):
    p = _write(tmp_path, "race.py", """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self._count += 1

            def bump(self):
                with self._lock:
                    self._count += 1
    """)
    findings = _run([p], ["thread-shared-mutation"], root=str(tmp_path))
    assert len(findings) == 1
    assert "self._count" in findings[0].message
    assert "Worker._run" in findings[0].message


def test_thread_shared_mutation_both_locked_is_clean(tmp_path):
    p = _write(tmp_path, "ok.py", """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                with self._lock:
                    self._count += 1

            def bump(self):
                with self._lock:
                    self._count += 1
    """)
    assert _run([p], ["thread-shared-mutation"],
                root=str(tmp_path)) == []


def test_thread_shared_mutation_honors_waiver_and_init_exempt(tmp_path):
    """__init__ mutations don't count (no thread exists yet), and the
    waiver-with-reason contract holds — PER SITE: every unlocked racy
    mutation site is its own finding, so each carries its own waiver
    (one waived anchor must not silence a race added elsewhere)."""
    p = _write(tmp_path, "w.py", """
        import threading

        class Worker:
            def __init__(self):
                self._state = 0     # pre-thread: exempt
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                # lint: ok(thread-shared-mutation) — reset() is only
                # called after join() in this fixture's lifecycle
                self._state = 1

            def reset(self):
                # lint: ok(thread-shared-mutation) — only called after
                # join(), same lifecycle contract as _run above
                self._state = 0
    """)
    assert _run([p], ["thread-shared-mutation"],
                root=str(tmp_path)) == []


def test_thread_shared_mutation_reports_every_unlocked_site(tmp_path):
    """A waiver on one racy site must not silence a DIFFERENT unlocked
    site of the same attribute — each gets its own finding."""
    p = _write(tmp_path, "two.py", """
        import threading

        class Worker:
            def __init__(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                # lint: ok(thread-shared-mutation) — fixture: waived site
                self._state = 1

            def reset(self):
                self._state = 0
    """)
    findings = _run([p], ["thread-shared-mutation"], root=str(tmp_path))
    assert len(findings) == 1
    assert "reset" in findings[0].message


def test_thread_shared_mutation_pool_submit_is_an_entry(tmp_path):
    """A ThreadPoolExecutor.submit callee is a thread body too (the
    feeder's pool workers)."""
    p = _write(tmp_path, "pool.py", """
        from concurrent.futures import ThreadPoolExecutor

        class Feeder:
            def __init__(self):
                self.pool = ThreadPoolExecutor(2)
                self._mode = None

            def schedule(self, it):
                return self.pool.submit(self._build, it)

            def _build(self, it):
                self._mode = "fused"
                return it

            def retune(self):
                self._mode = "classic"
    """)
    findings = _run([p], ["thread-shared-mutation"], root=str(tmp_path))
    # per-site reporting: the pool-worker write AND the public write
    # are each their own finding
    assert len(findings) == 2
    assert all("self._mode" in f.message for f in findings)


# ---------------------------------------------------------------------------
# exit-code drift (ISSUE 13 satellite, folded into doc-drift)

def _exit_tree(tmp_path, *, doc_code=86, call="os._exit(EXIT_WATCHDOG)"):
    _write(tmp_path, "caffe_mpi_tpu/utils/resilience.py", f"""
        import os
        EXIT_WATCHDOG = 86
        EXIT_FAULT = 87
        EXIT_CLUSTER = EXIT_FAULT

        def die():
            {call}
    """)
    _write(tmp_path, "docs/robustness.md", f"""
        Exit codes:

        | code | name | meaning |
        |---|---|---|
        | **{doc_code}** | `EXIT_WATCHDOG` | watchdog trip |
        | **87** | `EXIT_CLUSTER` / `EXIT_FAULT` | cluster loss |
    """)
    return str(tmp_path)


def test_exit_drift_clean_tree_is_clean(tmp_path):
    root = _exit_tree(tmp_path)
    assert _run([], ["doc-drift"], root=root) == []


def test_exit_drift_docs_code_mismatch_fails(tmp_path):
    """The PR 11 rot class: the docs table claiming a different number
    than the registry sends operators hunting a death that never
    happened."""
    root = _exit_tree(tmp_path, doc_code=96)
    findings = _run([], ["doc-drift"], root=root)
    msgs = "\\n".join(f.message for f in findings)
    assert "EXIT_WATCHDOG" in msgs and "96" in msgs


def test_exit_drift_bare_literal_exit_fails(tmp_path):
    root = _exit_tree(tmp_path, call="os._exit(86)")
    findings = _run([], ["doc-drift"], root=root)
    assert len(findings) == 1
    assert "bare literal exit 86" in findings[0].message
    assert "EXIT_WATCHDOG" in findings[0].message


def test_exit_drift_unregistered_symbol_fails(tmp_path):
    root = _exit_tree(tmp_path, call="os._exit(EXIT_BOGUS)")
    findings = _run([], ["doc-drift"], root=root)
    assert len(findings) == 1
    assert "EXIT_BOGUS" in findings[0].message


def test_exit_drift_missing_docs_entry_fails(tmp_path):
    root = _exit_tree(tmp_path)
    docs = os.path.join(root, "docs/robustness.md")
    src = open(docs).read().replace(
        "| **87** | `EXIT_CLUSTER` / `EXIT_FAULT` | cluster loss |", "")
    open(docs, "w").write(src)
    findings = _run([], ["doc-drift"], root=root)
    msgs = "\\n".join(f.message for f in findings)
    assert "EXIT_FAULT" in msgs and "EXIT_CLUSTER" in msgs


def test_exit_drift_bare_literal_waivable(tmp_path):
    root = _exit_tree(
        tmp_path,
        call="os._exit(86)  # lint: ok(doc-drift) — pre-registry shim")
    assert _run([], ["doc-drift"], root=root) == []


def test_exit_drift_waiver_in_comment_block_above_binds(tmp_path):
    """The documented contiguous-comment-block binding holds for the
    self-applied exit-call waivers too — a multi-line reason must not
    detach the waiver from its statement."""
    _write(tmp_path, "caffe_mpi_tpu/utils/resilience.py", """
        import os
        EXIT_WATCHDOG = 86

        def die():
            # lint: ok(doc-drift) — pre-registry shim kept for one
            # release so old supervisors keep matching on the number
            os._exit(86)
    """)
    _write(tmp_path, "docs/robustness.md", """
        | **86** | `EXIT_WATCHDOG` | watchdog trip |
    """)
    assert _run([], ["doc-drift"], root=str(tmp_path)) == []


# ---------------------------------------------------------------------------
# stale-waiver detection (ISSUE 13 satellite)

def test_stale_waiver_reported_when_pass_no_longer_fires(tmp_path):
    p = _write(tmp_path, "stale.py", """
        import numpy as np

        def f(x):
            # not in a loop: host-sync has nothing to say here
            return float(x)  # lint: ok(host-sync) — display boundary
    """)
    findings = lint.run_lint([p], select=["host-sync"],
                             root=str(tmp_path), stale=True)
    assert len(findings) == 1
    assert findings[0].pass_name == "stale-waiver"
    assert "host-sync" in findings[0].message


def test_stale_waiver_not_reported_for_honored_waiver(tmp_path):
    p = _write(tmp_path, "honored.py", """
        def f(xs):
            out = []
            for x in xs:
                out.append(float(x))  # lint: ok(host-sync) — fixture
            return out
    """)
    assert lint.run_lint([p], select=["host-sync"],
                         root=str(tmp_path), stale=True) == []


def test_stale_waiver_off_by_default_in_library_api(tmp_path):
    p = _write(tmp_path, "stale.py", """
        def f(x):
            return float(x)  # lint: ok(host-sync) — fixture
    """)
    assert lint.run_lint([p], select=["host-sync"],
                         root=str(tmp_path)) == []


def test_stale_waiver_only_judges_selected_passes(tmp_path):
    """A --select run must not call waivers for UNSELECTED passes
    stale — those passes never got the chance to fire."""
    p = _write(tmp_path, "other.py", """
        def f(x):
            return float(x)  # lint: ok(host-sync) — fixture
    """)
    assert lint.run_lint([p], select=["gated-imports"],
                         root=str(tmp_path), stale=True) == []


def test_stale_waiver_multiline_comment_block_binds_to_statement(tmp_path):
    """A waiver anywhere in the contiguous comment block directly above
    the statement is honored (multi-line reasons are encouraged, not
    punished)."""
    p = _write(tmp_path, "block.py", """
        def f(xs):
            out = []
            for x in xs:
                # lint: ok(host-sync) — the reason here is long enough
                # to need a second comment line, which must not detach
                # the waiver from its statement
                out.append(float(x))
            return out
    """)
    assert lint.run_lint([p], select=["host-sync"],
                         root=str(tmp_path), stale=True) == []


# ---------------------------------------------------------------------------
# --changed CLI mode (ISSUE 13 satellite)

def test_changed_mode_typod_ref_is_usage_error():
    """A typo'd git ref must exit 2 (usage error), NEVER a false-clean
    exit 0 with zero files scanned."""
    assert lint.main(["--changed", "no-such-ref-xyz"]) == 2


def test_changed_mode_valid_ref_is_not_a_usage_error():
    assert lint.main(["--changed", "HEAD", "--no-stale"]) != 2


def test_changed_mode_explicit_paths_still_lint(tmp_path):
    bad = _write(tmp_path, "bad.py", """
        def f(xs):
            return [float(x) for x in xs]
    """)
    assert lint.main(["--changed", "HEAD", "--select", "host-sync",
                      "--no-stale", bad]) == 1


def test_changed_mode_skips_files_outside_the_scanned_tree(monkeypatch):
    """tests/ and examples/ are deliberately outside the lint contract
    (torch-oracle host syncs etc.) — a commit touching only such files
    must not fail the pre-commit run on code the full scan exempts."""
    import subprocess

    real_run = subprocess.run

    def fake_run(cmd, **kw):
        if cmd[:3] == ["git", "diff", "--name-only"]:
            class R:
                returncode = 0
                stdout = "tests/test_multistep.py\nexamples/mnist/run.py\n"
                stderr = ""
            return R()
        return real_run(cmd, **kw)

    monkeypatch.setattr(subprocess, "run", fake_run)
    assert lint.main(["--changed", "HEAD", "--select", "host-sync",
                      "--no-stale"]) == 0


def test_changed_mode_wedged_git_is_usage_error(monkeypatch):
    """A git that never answers (dead NFS, lock contention) must turn
    into exit 2, not hang the pre-commit hook forever — the diff query
    itself obeys deadline discipline."""
    import subprocess

    real_run = subprocess.run

    def fake_run(cmd, **kw):
        if cmd[:3] == ["git", "diff", "--name-only"]:
            raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 60))
        return real_run(cmd, **kw)

    monkeypatch.setattr(subprocess, "run", fake_run)
    assert lint.main(["--changed", "HEAD", "--no-stale"]) == 2


def test_precommit_script_propagates_typod_ref_exit_2():
    """tools/precommit.sh (ISSUE 20 satellite) rides tpulint's
    --changed contract: a typo'd ref exits 2 through the whole script
    (set -e stops before pytest ever runs) — never a false-clean 0."""
    r = subprocess.run(
        ["sh", os.path.join(_ROOT, "tools", "precommit.sh"),
         "no-such-ref-xyz"],
        capture_output=True, text=True, timeout=120, cwd=_ROOT)
    assert r.returncode == 2, (r.stdout, r.stderr)


# ---------------------------------------------------------------------------
# failure-path family (ISSUE 20): future-resolution

def test_future_resolution_catches_pr7_create_then_raise(tmp_path):
    """The PR 7 regression shape: Batcher.submit created the Future
    BEFORE the admission checks, so a shed/closed raise left the caller
    holding a reference nobody would ever resolve."""
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        from concurrent.futures import Future

        class Batcher:
            def submit(self, item, closed, backlog, limit):
                fut = Future()
                if closed:
                    raise RuntimeError("engine closed")
                if backlog > limit:
                    raise RuntimeError("shed")
                self._queue.append((item, fut))
                return fut
    """)
    findings = _run([p], ["future-resolution"], root=str(tmp_path))
    # one finding per stranded future (the first raise edge reports
    # it; linear flow then treats it as judged)
    assert len(findings) == 1
    assert "PR 7" in findings[0].message
    assert "'fut'" in findings[0].message


def test_future_resolution_clean_when_created_after_admission(tmp_path):
    """The shipped fix for the PR 7 shape: run every raise-path check
    first, create the Future only once admission is certain."""
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        from concurrent.futures import Future

        class Batcher:
            def submit(self, item, closed, backlog, limit):
                if closed:
                    raise RuntimeError("engine closed")
                if backlog > limit:
                    raise RuntimeError("shed")
                fut = Future()
                self._queue.append((item, fut))
                return fut
    """)
    assert _run([p], ["future-resolution"], root=str(tmp_path)) == []


def test_future_resolution_resolved_on_error_path_is_clean(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        from concurrent.futures import Future

        class Batcher:
            def submit(self, item):
                fut = Future()
                try:
                    self._enqueue(item, fut)
                except Exception as e:
                    fut.set_exception(e)
                    raise
                return fut
    """)
    assert _run([p], ["future-resolution"], root=str(tmp_path)) == []


def test_future_resolution_out_of_scope_path_is_clean(tmp_path):
    """The pass is scoped to serving/ + solver/ — a data-pipeline
    helper juggling futures is not on the request path."""
    p = _write(tmp_path, "caffe_mpi_tpu/data/feeder.py", """
        from concurrent.futures import Future

        def stage(closed):
            fut = Future()
            if closed:
                raise RuntimeError("closed")
            return fut
    """)
    assert _run([p], ["future-resolution"], root=str(tmp_path)) == []


def test_future_resolution_honors_waiver(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        from concurrent.futures import Future

        class Batcher:
            def submit(self, item, closed):
                fut = Future()
                if closed:
                    # lint: ok(future-resolution) — fixture: ownership
                    # is provably elsewhere in this contrived shape
                    raise RuntimeError("closed")
                self._queue.append(fut)
                return fut
    """)
    assert _run([p], ["future-resolution"], root=str(tmp_path)) == []


def test_future_resolution_stale_waiver_reported(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        from concurrent.futures import Future

        class Batcher:
            def submit(self, item):
                # lint: ok(future-resolution) — fixture: nothing fires
                fut = Future()
                self._queue.append(fut)
                return fut
    """)
    findings = lint.run_lint([p], select=["future-resolution"],
                             root=str(tmp_path), stale=True)
    assert len(findings) == 1
    assert findings[0].pass_name == "stale-waiver"
    assert "future-resolution" in findings[0].message


# ---------------------------------------------------------------------------
# failure-path family (ISSUE 20): typed-failure

def test_typed_failure_catches_log_and_continue(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/solver/loop.py", """
        import logging
        log = logging.getLogger(__name__)

        def step(net):
            try:
                net.dispatch()
            except Exception:
                log.warning("dispatch failed")
    """)
    findings = _run([p], ["typed-failure"], root=str(tmp_path))
    assert len(findings) == 1
    assert "swallows the failure UNTYPED" in findings[0].message


def test_typed_failure_bare_except_pass_fails(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/router.py", """
        def route(req, engine):
            try:
                return engine.submit(req)
            except:
                pass
    """)
    findings = _run([p], ["typed-failure"], root=str(tmp_path))
    assert len(findings) == 1
    assert "bare except" in findings[0].message


def test_typed_failure_reraise_and_journal_are_clean(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/router.py", """
        def route(req, engine):
            try:
                return engine.submit(req)
            except Exception as e:
                engine.journal("route_failed", error=str(e))

        def close(engine):
            try:
                engine.drain()
            except Exception:
                raise
    """)
    assert _run([p], ["typed-failure"], root=str(tmp_path)) == []


def test_typed_failure_resolving_future_with_error_is_clean(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/router.py", """
        def route(req, fut, engine):
            try:
                fut.set_result(engine.submit(req))
            except Exception as e:
                fut.set_exception(e)
    """)
    assert _run([p], ["typed-failure"], root=str(tmp_path)) == []


def test_typed_failure_out_of_scope_path_is_clean(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/data/reader.py", """
        def read(db):
            try:
                return db.get()
            except Exception:
                return None
    """)
    assert _run([p], ["typed-failure"], root=str(tmp_path)) == []


def test_typed_failure_honors_waiver(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/parallel/mesh_fx.py", """
        def teardown(svc):
            try:
                svc.shutdown()
            # lint: ok(typed-failure) — fixture: already-down IS the
            # goal state of a teardown
            except Exception:
                pass
    """)
    assert _run([p], ["typed-failure"], root=str(tmp_path)) == []


def test_typed_failure_stale_waiver_reported(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/parallel/mesh_fx.py", """
        def teardown(svc):
            try:
                svc.shutdown()
            # lint: ok(typed-failure) — fixture: nothing fires here
            except Exception:
                raise
    """)
    findings = lint.run_lint([p], select=["typed-failure"],
                             root=str(tmp_path), stale=True)
    assert len(findings) == 1
    assert findings[0].pass_name == "stale-waiver"
    assert "typed-failure" in findings[0].message


# ---------------------------------------------------------------------------
# failure-path family (ISSUE 20): thread-crash

def test_thread_crash_catches_unguarded_target(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/monitor.py", """
        import threading

        class Monitor:
            def start(self):
                threading.Thread(target=self._loop,
                                 daemon=True).start()

            def _loop(self):
                while True:
                    self.poll()
    """)
    findings = _run([p], ["thread-crash"], root=str(tmp_path))
    assert len(findings) == 1
    assert "kills the worker SILENTLY" in findings[0].message
    assert "_loop" in findings[0].message


def test_thread_crash_guarded_target_is_clean(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/monitor.py", """
        import threading

        class Monitor:
            def start(self):
                threading.Thread(target=self._loop,
                                 daemon=True).start()

            def _loop(self):
                try:
                    while True:
                        self.poll()
                except Exception as e:
                    self.journal("monitor_crash", error=str(e))
    """)
    assert _run([p], ["thread-crash"], root=str(tmp_path)) == []


def test_thread_crash_catches_pr11_dispatcher_via_local_tuple(tmp_path):
    """The PR 11 regression shape: the dispatcher worker loop reaches
    Thread() through a local (name, target) tuple, so a target= match
    alone misses it — the escaping worker-loop reference must flag."""
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        import threading

        class Batcher:
            def ensure_threads(self):
                specs = [("dispatch", self._dispatch_loop),
                         ("harvest", self._harvest_loop)]
                for name, target in specs:
                    t = threading.Thread(target=target, name=name,
                                         daemon=True)
                    t.start()

            def _dispatch_loop(self):
                while not self._closed:
                    self._dispatch_once()

            def _harvest_loop(self):
                try:
                    while not self._closed:
                        self._harvest_once()
                except Exception as e:
                    self._journal("harvest_crash", error=str(e))
    """)
    findings = _run([p], ["thread-crash"], root=str(tmp_path))
    assert len(findings) == 1
    assert "_dispatch_loop" in findings[0].message


def test_thread_crash_discarded_pool_submit_flagged_kept_clean(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/workers.py", """
        def fan_out(pool, records):
            for r in records:
                pool.submit(_render, r)

        def fan_out_kept(pool, records):
            futs = [pool.submit(_render, r) for r in records]
            return futs

        def _render(r):
            return r.decode()
    """)
    findings = _run([p], ["thread-crash"], root=str(tmp_path))
    assert len(findings) == 1
    assert "discards its future" in findings[0].message


def test_thread_crash_honors_waiver(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/beat.py", """
        import threading

        class Beat:
            def start(self):
                threading.Thread(target=self._loop,
                                 daemon=True).start()

            # lint: ok(thread-crash) — fixture: a dead beat IS the
            # failure signal; the supervisor mourns the silence
            def _loop(self):
                while True:
                    self.publish()
    """)
    assert _run([p], ["thread-crash"], root=str(tmp_path)) == []


def test_thread_crash_stale_waiver_reported(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/beat.py", """
        import threading

        class Beat:
            def start(self):
                threading.Thread(target=self._loop,
                                 daemon=True).start()

            # lint: ok(thread-crash) — fixture: nothing fires here
            def _loop(self):
                try:
                    while True:
                        self.publish()
                except Exception as e:
                    self.journal("beat_crash", error=str(e))
    """)
    findings = lint.run_lint([p], select=["thread-crash"],
                             root=str(tmp_path), stale=True)
    assert len(findings) == 1
    assert findings[0].pass_name == "stale-waiver"
    assert "thread-crash" in findings[0].message


# ---------------------------------------------------------------------------
# failure-path family (ISSUE 20): deadline-discipline

def test_deadline_catches_unbounded_subprocess_and_result(tmp_path):
    p = _write(tmp_path, "tools/probe.py", """
        import subprocess

        def probe(cmd, fut):
            subprocess.run(cmd, capture_output=True)
            return fut.result()
    """)
    findings = _run([p], ["deadline-discipline"], root=str(tmp_path))
    assert len(findings) == 2
    msgs = " ".join(f.message for f in findings)
    assert "hang no" in msgs


def test_deadline_bounded_calls_are_clean(tmp_path):
    p = _write(tmp_path, "tools/probe.py", """
        import subprocess

        def probe(cmd, fut):
            subprocess.run(cmd, capture_output=True, timeout=60)
            return fut.result(timeout=30)
    """)
    assert _run([p], ["deadline-discipline"], root=str(tmp_path)) == []


def test_deadline_module_level_call_is_covered(tmp_path):
    """Smoke scripts run subprocess at module/__main__ level, outside
    any function the model walks — those statements must not escape."""
    p = _write(tmp_path, "tools/smoke.py", """
        import subprocess

        r = subprocess.run(["python", "-c", "pass"],
                           capture_output=True)
    """)
    findings = _run([p], ["deadline-discipline"], root=str(tmp_path))
    assert len(findings) == 1
    assert "subprocess.run" in findings[0].message


def test_deadline_out_of_scope_path_is_clean(tmp_path):
    """data/ is host-side io with no device adjacency — unbounded
    waits there are blocking-under-lock's business only when a lock
    is held."""
    p = _write(tmp_path, "caffe_mpi_tpu/data/prefetch.py", """
        def drain(q):
            return q.get()
    """)
    assert _run([p], ["deadline-discipline"], root=str(tmp_path)) == []


def test_deadline_honors_waiver(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        def harvest(q):
            while True:
                # lint: ok(deadline-discipline) — fixture: sentinel-
                # woken idle park; close() enqueues None
                item = q.get()
                if item is None:
                    return
    """)
    assert _run([p], ["deadline-discipline"], root=str(tmp_path)) == []


def test_deadline_stale_waiver_reported(tmp_path):
    p = _write(tmp_path, "caffe_mpi_tpu/serving/batching.py", """
        def harvest(q):
            while True:
                # lint: ok(deadline-discipline) — fixture: stale
                item = q.get(timeout=5.0)
                if item is None:
                    return
    """)
    findings = lint.run_lint([p], select=["deadline-discipline"],
                             root=str(tmp_path), stale=True)
    assert len(findings) == 1
    assert findings[0].pass_name == "stale-waiver"
    assert "deadline-discipline" in findings[0].message


# ---------------------------------------------------------------------------
# --profile (ISSUE 20 satellite)

_INTERPROCEDURAL = ("lock-order", "blocking-under-lock",
                    "thread-shared-mutation", "future-resolution",
                    "typed-failure", "thread-crash",
                    "deadline-discipline")


def test_profile_one_shared_model_build(tmp_path):
    """All seven interprocedural passes must share ONE tree_model
    build per run — per-pass rebuilds are how the 5 s budget dies."""
    _write(tmp_path, "caffe_mpi_tpu/serving/engine_fx.py", """
        import threading

        class E:
            def start(self):
                threading.Thread(target=self._loop,
                                 daemon=True).start()

            def _loop(self):
                try:
                    while True:
                        self.step()
                except Exception as e:
                    self.journal("crash", error=str(e))
    """)
    profile = {}
    lint.run_lint(paths=None, select=list(_INTERPROCEDURAL),
                  root=str(tmp_path), profile=profile)
    assert profile["model_builds"] == 1, profile
    for name in _INTERPROCEDURAL:
        assert name in profile["passes"], profile


def test_profile_text_table_on_stderr(tmp_path, capsys):
    _write(tmp_path, "ok.py", """
        '''Replaces nothing.py:1 — fixture.'''
    """)
    rc = lint.main(["--profile", "--no-stale", "--select", "host-sync",
                    str(tmp_path / "ok.py")])
    assert rc == 0
    err = capsys.readouterr().err
    assert "lint --profile:" in err
    assert "host-sync" in err
    assert "shared model build(s)" in err


def test_profile_json_envelope_and_bare_json_unchanged(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", """
        def f(xs):
            return [float(x) for x in xs]
    """)
    rc = lint.main(["--profile", "--json", "--no-stale",
                    "--select", "host-sync", bad])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    # --profile + --json opts into the envelope...
    assert set(out) == {"findings", "profile"}
    assert out["findings"][0]["pass"] == "host-sync"
    assert "passes" in out["profile"]
    assert "model_builds" in out["profile"]
    # ...while plain --json keeps the bare-array contract
    rc = lint.main(["--json", "--no-stale", "--select", "host-sync", bad])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert isinstance(out, list) and out[0]["pass"] == "host-sync"
